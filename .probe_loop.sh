#!/bin/bash
# Re-probe the axon TPU tunnel every 10 min; leave a marker when up.
cd /root/repo
for i in $(seq 1 70); do
  timeout -k 10 120 python -c "import jax; d=jax.devices(); print('BACKEND_OK', [str(x) for x in d])" > /root/repo/.tpu_probe_out 2>&1
  if grep -q BACKEND_OK /root/repo/.tpu_probe_out; then
    date -u +%FT%TZ > /root/repo/.tpu_up
    cat /root/repo/.tpu_probe_out >> /root/repo/.tpu_up
    exit 0
  fi
  date -u +%FT%TZ >> /root/repo/.tpu_probe_log
  sleep 600
done
