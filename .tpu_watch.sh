#!/bin/bash
# Round-5 TPU tunnel watcher: probe every 10 min; when the tunnel is up,
# touch .tpu_up and run bench.py (real chip) capturing output.
cd /root/repo
while true; do
  date -u +%Y-%m-%dT%H:%M:%SZ >> .tpu_probe_log
  if timeout 150 python -c "import jax; d=jax.devices(); assert any('cpu' not in str(x).lower() for x in d); print('TPU_OK', d)" > /tmp/tpu_probe_out 2>&1; then
    touch .tpu_up
    echo "TPU UP at $(date -u +%Y-%m-%dT%H:%M:%SZ)" >> .tpu_probe_log
    timeout 1800 python bench.py > BENCH_tpu_live.json 2> /tmp/bench_tpu_err.log
    echo "bench rc=$? at $(date -u +%Y-%m-%dT%H:%M:%SZ)" >> .tpu_probe_log
    sleep 1800
  fi
  sleep 600
done
