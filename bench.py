"""Benchmark entry: prints one JSON line PER METRIC
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N};
the LAST line is the headline (q3 — the join+agg+TopN pipeline).

Measures TPC-H q1 (pre-generated pages; host->device upload + fused
filter/project + sort-based group aggregation) and q3 (customer/orders
builds, semi + inner sorted-index joins, aggregation, TopN) in lineitem
rows/sec on the real TPU chip. vs_baseline = TPU rate / single-CPU rate
of the IDENTICAL pipeline (cached per query:schema in the committed
.bench_cpu_cache.json) — the "vs CPU at equal node count" framing of
BASELINE.md. Reference harness analog:
testing/trino-benchmark/.../HandTpchQuery1.java (rows/s via
LocalQueryRunner).

Hardening (rounds 1+2 produced no number: rc=1 backend crash, then rc=124
hang *after* a successful probe):
  * the parent process never imports the trino_tpu package or initializes
    a jax backend (subproc.py is loaded by file path, skipping the package
    __init__) — it cannot hang;
  * measurement children run via GuardedChild (own process group,
    stdout->file, group-killed on timeout);
  * phase 1 runs the CPU fallback child SOLO (~25 s) and prints its
    _cpu_fallback line immediately — the driver's outer timeout is unknown,
    so a parseable line must exist on stdout early; phase 2 then runs the
    TPU child SOLO (no host contention) and its _per_chip line supersedes;
    an early TPU crash (transient chip lock) gets one respawn;
  * a watchdog kills the live child group, prints the best-known JSON, and
    exits 0 at BENCH_DEADLINE (default 520 s) no matter what;
  * CPU rates are never persisted to the cache at bench time — the
    committed cache is seeded solo; an uncached schema falls back to the
    phase-1 solo rate for the ratio.

Env: BENCH_SCHEMA (micro|tiny|sf1; default tiny), BENCH_DEADLINE (s),
BENCH_TPU_BUDGET (s), BENCH_QUERIES (comma list of q1|q3|q18; default
"q1,q3" — q18 is the large-group aggregation stressor). Each rate line
is preceded by a ``*_stage_wall_ms`` line carrying the per-stage
(scan/filter-project/agg/join/exchange/sort) wall-time breakdown of the
final repeat and the query's per-kernel jit-trace deltas (all repeats
of that query; the first pays them). Internal: BENCH_ROLE=measure
BENCH_PLATFORM=cpu|default; BENCH_ROLE=chaos (fault-injection smoke,
CHAOS_RESULT line); BENCH_ROLE=memory (memory-governance smoke:
forced host+disk spill oracle + killer determinism, MEMORY_RESULT
line with spill/kill counters, rc=5 on mismatch); BENCH_ROLE=skew
(adversarial-skew smoke: zipf-keyed device exchange with
hot-partition splitting vs the unsplit oracle + scaled-writer CTAS
vs the unscaled oracle, SKEW_RESULT line with split/rebalance
counters and rows/s, rc=6 on mismatch); BENCH_ROLE=kernels (kernel-
strategy NDV sweep: matmul join vs sorted-index byte-equal + the three
SQL join strategies agree, global-hash aggregation vs exchange+scatter
vs host oracle, KERNELS_RESULT line with per-NDV rows/s and the
measured crossover NDVs, rc=9 on mismatch); BENCH_ROLE=trace / BENCH_TRACE=1
(distributed-tracing smoke: 2-worker ProcessQueryRunner join with
query tracing, writes the Perfetto-loadable Chrome-trace artifact to
BENCH_TRACE_PATH [default ./BENCH_TRACE.json], emits a
trace_stage_overlap metric line + TRACE_RESULT, rc=7 on a
disconnected/empty trace tree; ALSO the flight recorder: the run
executes with query_profiling_enabled so every process records
per-program trace/compile wall + XLA cost analysis, the merged
cluster table writes to BENCH_PROFILE_PATH [default
./BENCH_PROFILE.json], a differ vs the committed artifact names any
kernel that moved [profile_moved metric line], the total
compile-seconds ratchet gates against profile_compile_s:trace x
BENCH_PROFILE_COMPILE_FACTOR [default 2.0], and rc=11 flags an
empty/disconnected profile or a compile-budget breach — distinct
from rc=7 so trace-tree and profile failures triage separately);
BENCH_ROLE=qps (multi-tenant
throughput smoke: N concurrent HTTP protocol clients, zipf tenants,
repeat-heavy tiny/medium mix, cache-disabled vs cache-enabled phases
reporting p50/p99 + queries/sec, QPS_RESULT line, rc=10 unless the
cached phase shows plan-cache hits, zero retraces on a repeat
statement, bounded _QueryState growth, and >= 1.5x the uncached QPS;
the committed qps_speedup:<schema> baseline is ratcheted — absolute
qps:<schema> is reported, not gated, being ~2x host-noisy);
BENCH_ROLE=hbo (history-based-statistics report: tiny q1+q3 twice
with recording, hbo_qerror_p50/p90 metric lines [ratchet-ready for
the next baseline commit] + the lying-connector matmul-flip witness,
HBO_RESULT line, rc=13 when the flip or byte-equality fails);
BENCH_ROLE=elastic (elastic-cluster smoke: a queue-depth burst of 12
concurrent queries against a max_concurrency=2 resource group makes
the autoscaler grow the membership 2 -> 4 mid-burst, the grown
cluster places tasks on the joiners, idle drains back down to the
floor with zero lost rows, ELASTIC_RESULT line carrying every
autoscaler decision, rc=14 on a missed scale event or row loss). The
parent runs the qlint static
analyzer as a pre-flight before spawning any child (rc=8 on
non-baselined findings: retrace-hazardous code must not burn the TPU
budget; BENCH_SKIP_QLINT=1 skips). Every rate line carries
backend/device_kind provenance so a CPU fallback can never masquerade
as a TPU number.
"""

import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))
CACHE_PATH = os.path.join(REPO, ".bench_cpu_cache.json")


# ----------------------------------------------------------------- child ----

def _measure_child():
    """BENCH_ROLE=measure: pin platform, run q1 then q3, printing one
    'RESULT {json}' line per query (q1 first so a partial kill still
    leaves a result)."""
    schema = os.environ.get("BENCH_SCHEMA", "tiny")
    platform = os.environ.get("BENCH_PLATFORM", "default")
    queries = [q.strip()
               for q in os.environ.get("BENCH_QUERIES", "q1,q3").split(",")]
    unknown = [q for q in queries if q not in ("q1", "q3", "q18")]
    if unknown:
        raise SystemExit(f"unknown BENCH_QUERIES entries: {unknown}")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/trino_tpu_jax_cache")
    t0 = time.time()

    # backend-init watchdog: with the axon tunnel down, `import jax` /
    # `jax.devices()` can hang FOREVER (round 5 burned the entire 380 s
    # TPU budget exactly there). Fail fast with a distinct exit code so
    # the parent's respawn logic gets a second attempt while the budget
    # is still mostly intact. Armed BEFORE import (the axon
    # sitecustomize initializes jax at interpreter startup).
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "75"))
    init_done = threading.Event()

    def _init_watchdog():
        if not init_done.wait(init_timeout):
            sys.stderr.write(
                f"child[{platform}]: backend init exceeded "
                f"{init_timeout:.0f}s (tunnel down?) — failing fast\n")
            sys.stderr.flush()
            os._exit(3)

    threading.Thread(target=_init_watchdog, daemon=True).start()
    import jax

    if platform == "cpu":
        # env vars are not enough: the axon sitecustomize pins the platform
        # in live config at interpreter startup, so mutate the live config
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/trino_tpu_jax_cache")
    sys.stderr.write(f"child[{platform}]: jax ready {time.time() - t0:.1f}s\n")
    devs = jax.devices()
    init_done.set()
    sys.stderr.write(f"child[{platform}]: devices {devs} "
                     f"{time.time() - t0:.1f}s\n")

    from trino_tpu.benchmarks import (build_q1_driver, build_q3_drivers,
                                      build_q18_driver, scan_q1_pages,
                                      scan_q18_pages, scan_q3_pages,
                                      stage_breakdown)
    from trino_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(page_rows=1 << 16)
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    for query in queries:
        if query == "q1":
            pages = scan_q1_pages(conn, schema, desired_splits=8)
            total_rows = sum(p.num_rows for p in pages)

            def make_drivers(stats=False):
                return [build_q1_driver(conn, schema,
                                        source_pages=list(pages),
                                        collect_stats=stats)[0]]
        elif query == "q18":
            li18 = scan_q18_pages(conn, schema, desired_splits=8)
            total_rows = sum(p.num_rows for p in li18)

            def make_drivers(stats=False):
                return [build_q18_driver(li18, collect_stats=stats)[0]]
        else:
            cust, orders, li = scan_q3_pages(conn, schema,
                                             desired_splits=8)
            total_rows = sum(p.num_rows for p in li)

            def make_drivers(stats=False):
                return build_q3_drivers(cust, orders, li,
                                        collect_stats=stats)[0]
        sys.stderr.write(f"child[{platform}]: {query} {total_rows} rows "
                         f"generated {time.time() - t0:.1f}s\n")
        from trino_tpu import jit_stats

        traces_before = jit_stats.counts()
        times = []
        breakdown = None
        for i in range(repeats):
            # the last repeat collects per-operator stats: its stage
            # breakdown ships with the RESULT line (timing overhead is
            # two clock reads per page move — noise); compile counts on
            # it are ~0 since earlier repeats paid the traces
            stats = i == repeats - 1
            drivers = make_drivers(stats=stats)
            r0 = time.perf_counter()
            for d in drivers:
                d.run_to_completion()
            times.append(time.perf_counter() - r0)
            if stats:
                breakdown = stage_breakdown(drivers)
            sys.stderr.write(f"child[{platform}]: {query} run "
                             f"{i + 1}/{repeats} {times[-1]:.3f}s\n")
        # per-query trace delta (all repeats of THIS query; the first
        # repeat pays them, later same-shape repeats must add none)
        traces = {k: v - traces_before.get(k, 0)
                  for k, v in jit_stats.counts().items()
                  if v != traces_before.get(k, 0)}
        # first run pays compilation; take the best of the rest
        best = min(times[1:]) if len(times) > 1 else times[0]
        print("RESULT " + json.dumps({
            "query": query, "schema": schema, "platform": platform,
            "device": str(devs[0]), "rows": total_rows,
            "secs": best, "rate": total_rows / best,
            "stages": breakdown, "jit_traces": traces,
        }), flush=True)


def _chaos_smoke(n_workers: int = 2, seed: int = 7) -> dict:
    """BENCH_ROLE=chaos: deterministic fault-injection smoke over the
    multi-process runtime — kill a worker mid-query under
    retry_policy=TASK and assert the answer matches the fault-free run,
    so the recovery code paths (taxonomy, retry-from-spool, worker
    replacement) cannot silently rot outside the test suite. Returns
    the result dict (also printed as a CHAOS_RESULT line)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from trino_tpu.parallel.process_runner import ProcessQueryRunner
    from trino_tpu.sql.analyzer import Session

    sql = ("select l_returnflag, l_linestatus, count(*), "
           "sum(l_quantity) from lineitem "
           "group by l_returnflag, l_linestatus")
    s = Session(catalog="tpch", schema="micro")
    s.properties["streaming_execution"] = False
    s.properties["retry_policy"] = "TASK"
    with ProcessQueryRunner(
            {"tpch": {"connector": "tpch", "page_rows": 4096}}, s,
            n_workers=n_workers, desired_splits=4,
            heartbeat_interval=0.25) as c:
        c.fault_schedule.seed = seed
        clean = sorted(c.execute(sql).rows)
        qid = f"q{c._task_seq + 1}a0"
        c.fault_schedule.add(f"{qid}.f1", "kill-worker")
        res = c.execute(sql)
        out = {
            "ok": sorted(res.rows) == clean,
            "recovery": res.stats["recovery"],
            "workers_alive": c.heal(),
        }
    print("CHAOS_RESULT " + json.dumps(out), flush=True)
    if not out["ok"]:
        raise SystemExit(4)
    return out


def _elastic_smoke() -> dict:
    """BENCH_ROLE=elastic: elastic-cluster smoke — a queue-depth burst
    (12 concurrent queries against a max_concurrency=2 resource group)
    must make the autoscaler grow the membership 2 -> 4 mid-burst; the
    grown cluster takes new tasks (width-4 plans place .t2/.t3); idle
    then drains workers back down to the floor one at a time with zero
    lost rows anywhere. Every decision the policy took is printed on
    the ELASTIC_RESULT line. rc=14 on any violated invariant."""
    _qlint_preflight()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from trino_tpu.parallel.process_runner import ProcessQueryRunner
    from trino_tpu.resource_groups import ResourceGroupManager
    from trino_tpu.sql.analyzer import Session

    sql = ("select l_returnflag, l_linestatus, count(*), "
           "sum(l_quantity) from lineitem "
           "group by l_returnflag, l_linestatus")
    rg = ResourceGroupManager.from_config({"groups": [
        {"name": "global", "max_concurrency": 2,
         "max_queued": 10_000}]})
    s = Session(catalog="tpch", schema="micro")
    s.properties.update({
        "retry_policy": "QUERY",
        "partial_stage_retry": True,
        "autoscale_enabled": True,
        "autoscale_min_workers": 2,
        "autoscale_max_workers": 4,
        "autoscale_cooldown_s": 0.5,
        "autoscale_up_queue_depth": 1,
        "autoscale_down_idle_ticks": 4,
    })
    failures: list = []
    with ProcessQueryRunner(
            {"tpch": {"connector": "tpch", "page_rows": 4096}}, s,
            n_workers=2, desired_splits=4, heartbeat_interval=0.25,
            resource_groups=rg) as c:
        clean = sorted(c.execute(sql).rows)
        lock = threading.Lock()
        burst: list = []
        # burst threads keep the queue pressed until the membership
        # actually grows — worker spawn latency must not let the queue
        # drain before the scale-up decision lands
        grown = threading.Event()

        def one():
            for _ in range(40):
                if grown.is_set():
                    return
                try:
                    r = c.execute(sql)
                    with lock:
                        burst.append(
                            (sorted(r.rows) == clean,
                             r.stats["recovery"]["query_retries"]))
                except Exception as e:
                    with lock:
                        failures.append(repr(e))
                    return

        t0 = time.time()
        threads = [threading.Thread(target=one) for _ in range(12)]
        for t in threads:
            t.start()
        peak = len(c.workers)
        grow_deadline = time.time() + 90
        while any(t.is_alive() for t in threads):
            peak = max(peak, len(c.workers))
            if peak >= 4 or time.time() > grow_deadline:
                grown.set()
            time.sleep(0.05)
        for t in threads:
            t.join()
        burst_wall = time.time() - t0
        # the grown membership must actually take new tasks: a query
        # planned at the scaled width places .t2/.t3 on the joiners
        mark = len(c.task_launches)
        post = c.execute(sql)
        post_ok = sorted(post.rows) == clean
        wide = any(".t2" in t for t in c.task_launches[mark:])
        # idle: drain-based scale-down back to the floor, one at a time
        deadline = time.time() + 120
        while time.time() < deadline and len(c.workers) > 2:
            time.sleep(0.2)
        final_ok = sorted(c.execute(sql).rows) == clean
        snap = c.autoscaler.snapshot()
        out = {
            "ok": (not failures and len(burst) >= 4
                   and all(eq for eq, _ in burst)
                   and all(qr == 0 for _, qr in burst)
                   and peak >= 4 and wide and post_ok and final_ok
                   and len(c.workers) == 2
                   and snap["scale_ups"] >= 1
                   and snap["scale_downs"] >= 2),
            "peak_workers": peak,
            "final_workers": len(c.workers),
            "burst_queries": len(burst),
            "burst_wall_s": round(burst_wall, 2),
            "burst_qps": round(len(burst) / max(burst_wall, 1e-9), 2),
            "scaled_width_tasks": wide,
            "decisions": snap["decisions"],
            "failures": failures,
        }
    print("ELASTIC_RESULT " + json.dumps(out), flush=True)
    if not out["ok"]:
        raise SystemExit(14)
    return out


def _memory_smoke() -> dict:
    """BENCH_ROLE=memory: memory-governance smoke — run the q18-shaped
    aggregation under a cap that forces host-RAM AND disk spill, assert
    the rows byte-equal the unconstrained run, and emit the spill/kill
    counters as a MEMORY_RESULT line so governance regressions show up
    in BENCH_*.json. rc=5 on mismatch."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.parallel.cluster_memory import ClusterMemoryManager
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.sql.analyzer import Session

    sql = ("select l_orderkey, sum(l_quantity) qty from lineitem "
           "group by l_orderkey order by qty desc, l_orderkey limit 10")

    def run(**props):
        s = Session(catalog="tpch", schema="micro")
        s.properties.update(props)
        return LocalQueryRunner(
            {"tpch": TpchConnector(page_rows=1024)}, s,
            desired_splits=8).execute(sql)

    t0 = time.time()
    clean = run()
    spilled = run(query_max_memory_bytes=600_000, spill_enabled=True,
                  spill_to_disk_enabled=True, spill_host_memory_bytes=0)
    mem = spilled.stats["memory"]
    # killer determinism rides along: a synthetic blocked-node snapshot
    # must always name the same victim
    mgr = ClusterMemoryManager("total-reservation-on-blocked-nodes")
    mgr.update(0, {"max_bytes": 100, "reserved_bytes": 100,
                   "blocked_events": 1,
                   "queries": {"qa": {"reserved": 70, "peak": 70},
                               "qb": {"reserved": 30, "peak": 30}}})
    victim = mgr.maybe_kill()

    # -- hybrid hash join under a shrinking budget --------------------
    # q3-shaped join (no aggregation: the agg finish-merge transient
    # has its own cliff and would mask the join's behavior) at 100% /
    # 50% / 25% of its own unconstrained peak: graceful degradation
    # means the engine trades throughput for residency — partition
    # demotions GROW down the ladder, rows/s shrinks smoothly — and
    # NOTHING is killed.  A MemoryExceededError at any rung is rc=5,
    # the same failure class as a row mismatch.
    jsql = ("select o_orderdate, o_shippriority, l_extendedprice "
            "from orders o, lineitem l "
            "where o.o_orderkey = l.l_orderkey "
            "order by l_extendedprice desc, o_orderdate limit 10")

    def jrun(cap=None):
        s = Session(catalog="tpch", schema="micro")
        s.properties["hbo_enabled"] = False
        if cap is not None:
            s.properties.update(query_max_memory_bytes=cap,
                                spill_enabled=True,
                                spill_to_disk_enabled=True)
        r = LocalQueryRunner({"tpch": TpchConnector(page_rows=256)},
                             s, desired_splits=8)
        t = time.time()
        res = r.execute(jsql)
        return res, time.time() - t

    jclean, _ = jrun()
    peak = jclean.stats["memory"]["peak_bytes"]
    jrun(peak)  # warm the capped/spill code paths off the clock
    probe_rows = LocalQueryRunner(
        {"tpch": TpchConnector(page_rows=256)},
        Session(catalog="tpch", schema="micro")).execute(
            "select count(*) from lineitem").rows[0][0]
    levels, kills, jok = {}, 0, True
    for pct in (100, 50, 25):
        cap = max(1, peak * pct // 100)
        try:
            res, wall = jrun(cap)
        except Exception:
            kills += 1
            jok = False
            levels[str(pct)] = {"cap_bytes": cap, "killed": True}
            continue
        m = res.stats["memory"]
        levels[str(pct)] = {
            "cap_bytes": cap,
            "rows_s": round(probe_rows / max(wall, 1e-9), 1),
            "partition_spills": m.get("partition_spills", 0),
            "spill_events": m.get("spill_events", 0),
        }
        jok = jok and res.rows == jclean.rows
    slope = None
    if jok and kills == 0:
        # the smallest budget must still run PARTITIONED (the matrix's
        # bottom row), not complete by luck of a roomy plan
        jok = levels["25"]["partition_spills"] > 0
        slope = round(levels["25"]["rows_s"]
                      / max(levels["100"]["rows_s"], 1e-9), 3)
    out = {
        "ok": (spilled.rows == clean.rows and victim == "qa"
               and jok and kills == 0),
        "hybrid_join": {"peak_bytes": peak, "levels": levels,
                        "rows_s_slope": slope, "kills": kills},
        "spill_events": mem.get("spill_events", 0),
        "spilled_bytes": mem.get("spilled_bytes", 0),
        "disk_spill_events": mem.get("disk_spill_events", 0),
        "disk_spilled_bytes": mem.get("disk_spilled_bytes", 0),
        "killer_victim": victim,
        "wall_s": round(time.time() - t0, 2),
    }
    print("MEMORY_RESULT " + json.dumps(out), flush=True)
    if not out["ok"]:
        raise SystemExit(5)
    return out


def _skew_smoke() -> dict:
    """BENCH_ROLE=skew: adversarial-skew smoke for the exchange layer.

    Part A — the device collective: a zipf-distributed join key (one
    dominant partition) exchanged with hot-partition splitting vs the
    unsplit oracle (threshold=1.0); per-partition row multisets must be
    identical, the hot partition must spread over >= 2 receiver lanes
    with zero overflow retries, and the split run's rows/s rides along.
    Part B — the write path: CTAS over the same zipf keys with
    scale_writers_enabled vs the unscaled plan; written rows must
    match and the rebalancer must have re-assigned at least once.
    rc=6 on any mismatch so skew regressions fail loudly in CI."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # splitting needs >= 2 receiver devices; mirror tests/conftest
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.block import DevicePage, Page
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.parallel.device_exchange import (DeviceExchange,
                                                    SIZING_HISTORY)
    from trino_tpu.parallel.distributed import DistributedQueryRunner
    from trino_tpu.parallel.rebalancer import UniformPartitionRebalancer
    from trino_tpu.sql.analyzer import Session
    import jax

    t0 = time.time()
    rng = np.random.default_rng(17)
    n_tasks, rows_per_task = 4, 20_000
    # zipf(2.0): the rank-1 key alone carries ~60% of rows — one hot
    # partition, plus a long tail exercising the cold lanes
    zkeys = rng.zipf(2.0, size=n_tasks * rows_per_task) % 4096
    zvals = rng.integers(0, 1000, n_tasks * rows_per_task)

    def exchange(threshold):
        SIZING_HISTORY.reset()
        ex = DeviceExchange(n_tasks, jax.devices(), sizing="exact",
                            hot_split_threshold=threshold)
        ex.configure([T.BIGINT, T.BIGINT], [0])
        for t in range(n_tasks):
            lo, hi = t * rows_per_task, (t + 1) * rows_per_task
            ex.add_page(t, DevicePage.from_page(Page.from_pylists(
                [T.BIGINT, T.BIGINT],
                [zkeys[lo:hi].tolist(), zvals[lo:hi].tolist()])))
        ex.set_no_more_pages()
        start = time.perf_counter()
        parts = []
        for p in range(n_tasks):
            rows = []
            for pg in ex.pages(p):
                v = np.asarray(pg.valid)
                rows.extend(zip(np.asarray(pg.cols[0])[v].tolist(),
                                np.asarray(pg.cols[1])[v].tolist()))
            parts.append(sorted(rows))
        wall = time.perf_counter() - start
        return ex, parts, wall

    ex_split, parts_split, wall_split = exchange(0.5)
    ex_plain, parts_plain, _ = exchange(1.0)
    s = ex_split.stats
    exchange_ok = (
        parts_split == parts_plain
        and s["splits"] >= 1
        and max(s["hot_spread"].values(), default=0) >= 2
        and ex_split.a2a_retries == 0
        and s["lane_skew_ratio"] < ex_plain.stats["lane_skew_ratio"])

    def write(scale):
        SIZING_HISTORY.reset()
        sess = Session(catalog="mem", schema="default")
        sess.properties["scale_writers_enabled"] = scale
        r = DistributedQueryRunner({"mem": MemoryConnector()}, sess,
                                   n_workers=4, desired_splits=4)
        r.execute("create table z (k bigint, v bigint)")
        conn = r.metadata.connectors["mem"]
        h = conn.metadata().get_table_handle("default", "z")
        sink = conn.page_sink(h, conn.metadata().get_columns(h))
        sink.append_page(Page.from_pylists(
            [T.BIGINT, T.BIGINT],
            [zkeys[:rows_per_task].tolist(),
             zvals[:rows_per_task].tolist()]))
        sink.finish()
        r.execute("create table out as select k, v from z")
        return sorted(r.execute("select k, v from out").rows)

    reb_before = UniformPartitionRebalancer.total_rebalances
    rows_plain = write(False)
    rows_scaled = write(True)
    rebalances = UniformPartitionRebalancer.total_rebalances - reb_before
    writer_ok = rows_scaled == rows_plain and rebalances >= 1

    out = {
        "ok": exchange_ok and writer_ok,
        "exchange_ok": exchange_ok,
        "writer_ok": writer_ok,
        "splits": s["splits"],
        "hot_spread": s["hot_spread"],
        "per_dest_split": s["per_dest"],
        "per_dest_unsplit": ex_plain.stats["per_dest"],
        "lane_skew_split": s["lane_skew_ratio"],
        "lane_skew_unsplit": ex_plain.stats["lane_skew_ratio"],
        "a2a_retries": ex_split.a2a_retries,
        "rebalances": rebalances,
        "rows_per_s": round(n_tasks * rows_per_task / wall_split, 1),
        "wall_s": round(time.time() - t0, 2),
    }
    print("SKEW_RESULT " + json.dumps(out), flush=True)
    if not out["ok"]:
        raise SystemExit(6)
    return out


def _kernels_smoke() -> dict:
    """BENCH_ROLE=kernels: NDV-sweep microbench of the kernel-strategy
    matrix (round 12).

    Join: over low->high NDV, the matmul strategy (blocked one-hot
    probe, ops/matmul_join.py) must produce byte-identical rows to the
    sorted-index oracle, and the three SQL-level strategies — broadcast
    sorted-index, partitioned sorted-index, matmul — must agree on a
    real distributed join.  Aggregation: the global-hash replicated
    table (ops/global_hash_agg.py) must match the exchange+scatter
    shape and the host oracle at every NDV.  Reports per-NDV rows/s
    for both strategies and the measured crossover (largest NDV where
    the new kernel still wins ON THIS HOST — the number the cost-model
    thresholds are judged against).  rc=9 on any mismatch."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from functools import partial

    from trino_tpu import types as T
    from trino_tpu.block import DevicePage, Page, padded_size
    from trino_tpu.ops.join import (HashBuilderOperator, JoinBridge,
                                    LookupJoinOperator)
    from trino_tpu.ops.matmul_join import MatmulJoinOperator
    from trino_tpu.ops.global_hash_agg import (EMPTY, global_hash_insert,
                                               global_hash_reduce,
                                               pack_keys)
    from trino_tpu.parallel.exchange import (hash_partition_ids,
                                             repartition_a2a, shard_map)

    t0 = time.time()
    rng = np.random.default_rng(11)
    ok = True

    # --- join sweep -------------------------------------------------
    def run_join(op_cls, bkeys, bvals, pkeys, pvals, **kw):
        bridge = JoinBridge()
        build = HashBuilderOperator([T.BIGINT, T.BIGINT], [0], bridge)
        build.add_input(DevicePage.from_page(Page.from_pylists(
            [T.BIGINT, T.BIGINT], [bkeys, bvals])))
        build.finish()
        build.get_output()
        op = op_cls([T.BIGINT, T.BIGINT], [0], bridge, "inner", **kw)

        def probe():
            rows = 0
            for lo in range(0, len(pkeys), 16384):
                op.add_input(DevicePage.from_page(Page.from_pylists(
                    [T.BIGINT, T.BIGINT],
                    [pkeys[lo:lo + 16384], pvals[lo:lo + 16384]])))
                while True:
                    p = op.get_output()
                    if p is None:
                        break
                    rows += p.count()
            return rows

        t = time.perf_counter()
        n_out = probe()
        wall = time.perf_counter() - t
        op.finish()
        tail = []
        while not op.is_finished():
            p = op.get_output()
            if p is not None:
                tail.append(p)
        n_out += sum(p.count() for p in tail)
        return n_out, wall, op

    join_sweep = []
    join_crossover = 0
    n_build, n_probe = 20_000, 32_768
    for ndv in (16, 512, 8192):
        bkeys = rng.integers(0, ndv, n_build).tolist()
        bvals = rng.integers(0, 1000, n_build).tolist()
        pkeys = rng.integers(0, int(ndv * 1.2) + 2, n_probe).tolist()
        pvals = rng.integers(0, 1000, n_probe).tolist()
        # warm both compile caches, then measure
        for _ in range(2):
            n_si, w_si, _ = run_join(LookupJoinOperator, bkeys, bvals,
                                     pkeys, pvals)
            n_mm, w_mm, mm = run_join(MatmulJoinOperator, bkeys, bvals,
                                      pkeys, pvals,
                                      max_key_range=1 << 15)
        if mm.metrics().get("strategy") != "matmul" or n_mm != n_si:
            ok = False
        rate_si, rate_mm = n_probe / w_si, n_probe / w_mm
        join_sweep.append({"ndv": ndv,
                           "sorted_rows_per_s": round(rate_si, 1),
                           "matmul_rows_per_s": round(rate_mm, 1),
                           "out_rows": n_mm})
        if rate_mm >= rate_si:
            join_crossover = ndv

    # the three SQL-level join strategies agree on a real distributed
    # join (broadcast / partitioned sorted-index vs forced matmul)
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.parallel.distributed import DistributedQueryRunner
    from trino_tpu.sql.analyzer import Session

    sql = ("select c.c_custkey, o.o_orderkey from customer c "
           "join orders o on c.c_custkey = o.o_custkey")

    def run_sql(**props):
        s = Session(catalog="tpch", schema="micro")
        s.properties.update(props)
        r = DistributedQueryRunner(
            {"tpch": TpchConnector(page_rows=4096)}, s, n_workers=2,
            desired_splits=4)
        return sorted(r.execute(sql).rows)

    via_broadcast = run_sql(join_distribution_type="BROADCAST",
                            join_strategy="SORTED_INDEX")
    via_partitioned = run_sql(join_distribution_type="PARTITIONED",
                              join_strategy="SORTED_INDEX")
    via_matmul = run_sql(join_strategy="MATMUL")
    join_sql_ok = via_broadcast == via_partitioned == via_matmul \
        and len(via_matmul) > 0
    ok = ok and join_sql_ok

    # --- aggregation sweep ------------------------------------------
    n_dev = 8
    devices = jax.devices()[:n_dev]
    mesh = Mesh(np.asarray(devices), ("x",))
    rows_per_dev = 16_384

    def agg_programs(ndv, per_dest):
        table_size = padded_size(2 * ndv, minimum=max(16, n_dev))

        @partial(shard_map, mesh=mesh, in_specs=(P("x"),) * 3,
                 out_specs=(P("x"),) * 3, check_vma=False)
        def via_global_hash(k, v, va):
            k, v, va = k[0], v[0], va[0]
            packed = pack_keys([k], [None], (32,))
            table, slot_of, resolved, _unres = global_hash_insert(
                packed, va, table_size, axis_name="x")
            sums, cnts = global_hash_reduce(
                slot_of, resolved, va,
                (jnp.where(va, v, 0), va.astype(jnp.int64)),
                ("sum", "sum"), table_size, axis_name="x")
            i = jax.lax.axis_index("x")
            sh = table_size // n_dev
            sl = lambda a: jax.lax.dynamic_slice(a, (i * sh,), (sh,))  # noqa: E731
            return sl(table)[None], sl(sums)[None], sl(cnts)[None]

        @partial(shard_map, mesh=mesh, in_specs=(P("x"),) * 3,
                 out_specs=(P("x"),) * 3, check_vma=False)
        def via_exchange(k, v, va):
            k, v, va = k[0], v[0], va[0]
            part = hash_partition_ids([k.astype(jnp.int64)
                                       .view(jnp.uint64)], n_dev)
            (rk, rv), (_nk, _nv), rva, _ovf = repartition_a2a(
                (k, jnp.where(va, v, 0)),
                (jnp.zeros(k.shape, bool), jnp.zeros(v.shape, bool)),
                va, part, num_partitions=n_dev, per_dest=per_dest)
            # received rows group into the dense key table (keys are
            # [0, ndv) in this bench — the merge-final analog)
            idx = jnp.where(rva, rk, ndv).astype(jnp.int32)
            sums = jnp.zeros((ndv + 1,), jnp.int64).at[idx].add(rv)
            cnts = jnp.zeros((ndv + 1,), jnp.int64).at[idx].add(
                rva.astype(jnp.int64))
            return (sums[:ndv][None], cnts[:ndv][None],
                    jnp.sum(rva.astype(jnp.int32))[None])

        return jax.jit(via_global_hash), jax.jit(via_exchange)

    agg_sweep = []
    agg_crossover = 0
    for ndv in (16, 1024, 16384):
        keys = rng.integers(0, ndv, (n_dev, rows_per_dev))
        vals = rng.integers(0, 1000,
                            (n_dev, rows_per_dev)).astype(np.int64)
        valid = np.ones((n_dev, rows_per_dev), dtype=bool)
        want_sum = np.zeros(ndv, np.int64)
        want_cnt = np.zeros(ndv, np.int64)
        np.add.at(want_sum, keys.reshape(-1), vals.reshape(-1))
        np.add.at(want_cnt, keys.reshape(-1), 1)
        # per_dest: exact max (sender, dest) load, computed on host —
        # the count-first sizing pass for free (keys are host-side)
        h = np.zeros((n_dev, n_dev), np.int64)
        part_host = np.asarray(hash_partition_ids(
            [jnp.asarray(keys.reshape(-1)).astype(jnp.int64)
             .view(jnp.uint64)], n_dev)).reshape(n_dev, rows_per_dev)
        for d in range(n_dev):
            for p_ in range(n_dev):
                h[d, p_] = int(np.sum(part_host[d] == p_))
        per_dest = padded_size(int(h.max()))
        gh, ex = agg_programs(ndv, per_dest)
        k_j, v_j, va_j = (jnp.asarray(keys), jnp.asarray(vals),
                          jnp.asarray(valid))
        for _ in range(2):  # warm, then measure
            tg = time.perf_counter()
            t_, s_, c_ = gh(k_j, v_j, va_j)
            jax.block_until_ready(s_)
            w_gh = time.perf_counter() - tg
            te = time.perf_counter()
            es, ec, _rows = ex(k_j, v_j, va_j)
            jax.block_until_ready(es)
            w_ex = time.perf_counter() - te
        # verify both against the host oracle
        t_, s_, c_ = (np.asarray(t_).reshape(-1),
                      np.asarray(s_).reshape(-1),
                      np.asarray(c_).reshape(-1))
        gh_sum = np.zeros(ndv, np.int64)
        gh_cnt = np.zeros(ndv, np.int64)
        occ = t_ != np.uint64(EMPTY)
        kslot = ((t_[occ] & np.uint64(0xFFFFFFFF)) - 1).astype(np.int64)
        gh_sum[kslot] = s_[occ]
        gh_cnt[kslot] = c_[occ]
        es, ec = (np.asarray(es).reshape(n_dev, ndv),
                  np.asarray(ec).reshape(n_dev, ndv))
        ex_sum, ex_cnt = es.sum(axis=0), ec.sum(axis=0)
        if not (np.array_equal(gh_sum, want_sum)
                and np.array_equal(gh_cnt, want_cnt)
                and np.array_equal(ex_sum, want_sum)
                and np.array_equal(ex_cnt, want_cnt)):
            ok = False
        total = n_dev * rows_per_dev
        agg_sweep.append({"ndv": ndv,
                          "global_hash_rows_per_s":
                              round(total / w_gh, 1),
                          "exchange_rows_per_s":
                              round(total / w_ex, 1)})
        if w_gh <= w_ex:
            agg_crossover = ndv

    out = {
        "ok": ok,
        "join_sql_three_strategies_equal": join_sql_ok,
        "join_sweep": join_sweep,
        "join_crossover_ndv": join_crossover,
        "agg_sweep": agg_sweep,
        "agg_crossover_ndv": agg_crossover,
        "wall_s": round(time.time() - t0, 2),
    }
    print("KERNELS_RESULT " + json.dumps(out), flush=True)
    if not ok:
        raise SystemExit(9)
    return out


def _trace_smoke() -> dict:
    """BENCH_ROLE=trace (BENCH_TRACE=1): run a distributed join under
    ProcessQueryRunner with tracing on, write the Perfetto-loadable
    Chrome-trace artifact next to BENCH_*.json, and report the
    stage_overlap fraction from the span timelines — the metric the
    streaming-pipeline ROADMAP item will ratchet. rc=7 when the trace
    tree is disconnected (orphan spans) or empty."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from trino_tpu.parallel.process_runner import ProcessQueryRunner
    from trino_tpu.sql.analyzer import Session
    from trino_tpu.telemetry.tracing import (span_tree, stage_overlap,
                                             to_chrome_trace)

    from trino_tpu.telemetry import profiler as profiler_mod

    sql = ("select c.c_custkey, o.o_orderkey from customer c "
           "join orders o on c.c_custkey = o.o_custkey "
           "where c.c_mktsegment = 'BUILDING' "
           "order by o.o_orderkey limit 10")
    from trino_tpu.resources.tpch_queries import TPCH_QUERIES

    t0 = time.time()
    with ProcessQueryRunner(
            {"tpch": {"connector": "tpch", "page_rows": 4096}},
            # the flight recorder: profiling ON end to end, so every
            # process (coordinator + workers) records per-program
            # trace/compile wall + XLA cost analysis as it compiles
            Session(catalog="tpch", schema="micro",
                    properties={"query_profiling_enabled": True}),
            n_workers=2, desired_splits=4,
            broadcast_threshold=300.0) as c:
        res = c.execute(sql)
        # q3 multi-stage wall-clock (scan -> join -> agg -> TopN over
        # 4 fragments): the number streaming pipelining moves — the
        # first run warms compile caches, the second is the measurement
        c.execute(TPCH_QUERIES[3])
        t_q3 = time.time()
        c.execute(TPCH_QUERIES[3])
        q3_wall = round(time.time() - t_q3, 3)
        profile = c.profile_snapshot()
    spans = (res.stats or {}).get("trace") or []
    roots, _children, orphans = span_tree(spans)
    artifact = os.environ.get("BENCH_TRACE_PATH",
                              os.path.join(REPO, "BENCH_TRACE.json"))
    with open(artifact, "w") as f:
        json.dump(to_chrome_trace(spans), f)
    overlap = stage_overlap(spans)
    workers = {s["process"] for s in spans
               if s["process"].startswith("worker")}
    # the RATCHET (round 9): stage_overlap is regression-guarded like
    # the rows/s rates — a change that re-introduces a stage barrier
    # (overlap collapsing toward 0) fails the check loudly instead of
    # sliding by as a perf note
    base = _load_cache().get("trace_stage_overlap")
    ratio = round(overlap / base, 3) if base else 0.0
    floor = float(os.environ.get("BENCH_TRACE_RATCHET_MIN", "0.8"))
    regressed = bool(base) and ratio < floor
    # -- flight recorder: artifact + validation + differ + ratchet ----
    # the cluster-merged table is the artifact body (the coordinator's
    # own registry alone would miss every worker-compiled kernel)
    profile_doc = profiler_mod.profile_document(
        "trace", extra={"device_memory": profile["device_memory"]},
        kernels=profile["kernels"], table_totals=profile["totals"])
    profile_path = os.environ.get(
        "BENCH_PROFILE_PATH", os.path.join(REPO, "BENCH_PROFILE.json"))
    baseline_doc = None
    try:
        baseline_doc = json.load(open(profile_path))
    except Exception:
        pass
    with open(profile_path, "w") as f:
        json.dump(profile_doc, f, indent=1)
    problems = profiler_mod.validate_profile(profile_doc)
    compile_s = round(profile_doc["totals"]["compile_ms"] / 1e3, 3)
    base_compile = _load_cache().get("profile_compile_s:trace")
    factor = float(os.environ.get("BENCH_PROFILE_COMPILE_FACTOR",
                                  "2.0"))
    budget = round(base_compile * factor, 3) if base_compile else None
    compile_breach = budget is not None and compile_s > budget
    moved = profiler_mod.diff_profiles(baseline_doc, profile_doc) \
        if baseline_doc and not problems else []
    print(json.dumps({
        "metric": "profile_compile_s", "value": compile_s, "unit": "s",
        "vs_baseline": round(compile_s / base_compile, 3)
        if base_compile else 0.0,
        "budget_s": budget, "programs":
            profile_doc["totals"]["programs"],
        "artifact": profile_path,
    }), flush=True)
    if moved:
        # regression attribution: NAME the kernels that moved since
        # the committed artifact (informational — the compile ratchet
        # gates; a differ hit on a fresh baseline would be noise)
        print(json.dumps({
            "metric": "profile_moved", "value": len(moved),
            "unit": "kernels", "vs_baseline": 0.0,
            "moved": moved[:8],
        }), flush=True)
    out = {
        "ok": bool(spans) and len(roots) == 1 and not orphans
        and len(workers) >= 2 and not regressed,
        "spans": len(spans), "orphans": len(orphans),
        "worker_lanes": len(workers),
        "stage_overlap": round(overlap, 4),
        "artifact": artifact,
        "profile_artifact": profile_path,
        "profile_ok": not problems and not compile_breach,
        "profile_problems": problems or None,
        "profile_compile_s": compile_s,
        "profile_compile_budget_s": budget,
        "profile_kernels": len(profile_doc["kernels"]),
        "q3_wall_s": q3_wall,
        "wall_s": round(time.time() - t0, 2),
    }
    print(json.dumps({
        "metric": "trace_q3_wall_s", "value": q3_wall, "unit": "s",
        "vs_baseline": 0.0,
    }), flush=True)
    print(json.dumps({
        "metric": "trace_stage_overlap", "value": out["stage_overlap"],
        "unit": "fraction", "vs_baseline": ratio,
        "spans": out["spans"], "artifact": artifact,
    }), flush=True)
    if regressed:
        print(json.dumps({
            "metric": "trace_stage_overlap_regressed", "value": ratio,
            "unit": "x_vs_baseline", "vs_baseline": ratio,
        }), flush=True)
    print("TRACE_RESULT " + json.dumps(out), flush=True)
    if not out["ok"]:
        raise SystemExit(7)
    if not out["profile_ok"]:
        # DISTINCT rc: an empty/disconnected profile (the recorder
        # never engaged) or a compile-seconds budget breach must not
        # masquerade as a trace-tree failure
        raise SystemExit(11)
    return out


def _hbo_smoke() -> dict:
    """BENCH_ROLE=hbo: qlint-pre-flighted history-based-statistics
    report.  Part A runs the tiny TPC-H suite (q1 + q3) twice through
    the local engine with HBO recording, then emits the misestimate
    distribution as ``hbo_qerror_p50`` / ``hbo_qerror_p90`` metric
    lines (ratchet-ready: once a baseline commits, a optimizer change
    that degrades estimate quality shows up as a quantile jump).
    Part B is the closed-loop witness: a join whose connector
    statistics lie by 7 orders of magnitude must flip to the matmul
    strategy on its second run via recorded history, byte-equal.
    Part C is the distribution witness: a distributed join whose
    connector UNDER-estimates the build (broadcast territory) must
    re-plan to ``distribution=partitioned [source=hbo]`` on its second
    run after the material misestimate invalidates the cached fragment
    plan — byte-equal, with the ``hbo_plan_flips`` counters emitted as
    a metric line.  rc=13 when any flip or equality fails.

    The quantiles RATCHET against the committed ``hbo_qerror_p50`` /
    ``hbo_qerror_p90`` cache entries: the workload is deterministic
    (Q-error measures row counts, not wall time), so an optimizer
    change that degrades estimate quality moves the quantiles — a
    value above baseline x BENCH_HBO_RATCHET_MAX (default 1.25) emits
    an ``hbo_qerror_*_regressed`` line and fails the run (same rc)."""
    _qlint_preflight()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import (ColumnStatistics,
                                          TableStatistics)
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.resources.tpch_queries import TPCH_QUERIES
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.sql.analyzer import Session
    from trino_tpu.telemetry import stats_store

    t0 = time.time()
    stats_store.store().clear()
    tiny = LocalQueryRunner({"tpch": TpchConnector(page_rows=2048)},
                            Session(catalog="tpch", schema="tiny"))
    for _run in range(2):
        for q in (1, 3):
            tiny.execute(TPCH_QUERIES[q])
    p50 = stats_store.store().qerror_quantile(0.5) or 0.0
    p90 = stats_store.store().qerror_quantile(0.9) or 0.0
    counters = stats_store.store().counters()

    # Part B: the flip (the lying-statistics connector of the e2e test)
    class _LyingMetadata:
        def __init__(self, inner, lies):
            self._inner = inner
            self._lies = lies

        def get_statistics(self, table):
            return self._lies.get((table.schema, table.table)) \
                or self._inner.get_statistics(table)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    class _Lying(MemoryConnector):
        lies = {
            ("default", "dim"): TableStatistics(
                row_count=50_000_000.0,
                columns={"k": ColumnStatistics(
                    distinct_count=16.0, min_value=0, max_value=127)}),
            ("default", "fact"): TableStatistics(
                row_count=500_000_000.0),
        }

        def metadata(self):
            return _LyingMetadata(super().metadata(), self.lies)

    r = LocalQueryRunner({"memory": _Lying()},
                         Session(catalog="memory", schema="default"))
    r.execute("create table fact (fk bigint, amt bigint)")
    r.execute("create table dim (k bigint, name bigint)")
    r.execute("insert into fact values (1, 10), (2, 20), (3, 30)")
    r.execute("insert into dim values (1, 100), (2, 200), (3, 300)")
    sql = ("select f.fk, d.name from fact f join dim d on f.fk = d.k "
           "order by f.fk")
    first = r.execute(sql)
    flipped = "strategy=matmul" in r.explain(sql)
    second = r.execute(sql)

    # Part C: exchange-distribution flip (broadcast -> partitioned),
    # end-to-end through the distributed runner's fragment-plan cache
    from trino_tpu.parallel.distributed import DistributedQueryRunner

    class _LyingSmall(MemoryConnector):
        lies = {
            ("default", "probe"): TableStatistics(row_count=100_000.0),
            ("default", "build"): TableStatistics(row_count=2.0),
        }

        def metadata(self):
            return _LyingMetadata(super().metadata(), self.lies)

    dconn = _LyingSmall()
    ds = Session(catalog="memory", schema="default")
    # pin join ORDER to connector estimates: the witness isolates the
    # distribution decision
    ds.properties["hbo_reorder_joins_enabled"] = False
    dl = LocalQueryRunner({"memory": dconn}, ds)
    dl.execute("create table probe (k bigint, v bigint)")
    dl.execute("create table build (k bigint, w bigint)")
    dl.execute("insert into probe values " + ", ".join(
        f"({i % 200 + 1}, {i})" for i in range(40)))
    dl.execute("insert into build values " + ", ".join(
        f"({i + 1}, {i * 3})" for i in range(200)))
    dr = DistributedQueryRunner({"memory": dconn}, ds, n_workers=2,
                                desired_splits=2, broadcast_threshold=50)
    dsql = ("select probe.k, probe.v, build.w from probe "
            "join build on probe.k = build.k order by probe.v")
    dist_before = "distribution=broadcast [source=connector]" \
        in dr.explain(dsql)
    dfirst = dr.execute(dsql)
    dist_after = "distribution=partitioned [source=hbo]" \
        in dr.explain(dsql)
    dsecond = dr.execute(dsql)
    dist_flipped = bool(dist_before and dist_after
                        and dr.plan_cache.hbo_invalidations >= 1)
    plan_flips = dict(stats_store.store().plan_flips)

    ratios, regressed = _qerror_ratchet(p50, p90, _load_cache())
    out = {
        "ok": bool(flipped and second.rows == first.rows
                   and dist_flipped and dsecond.rows == dfirst.rows
                   and plan_flips.get("distribution", 0) >= 1
                   and counters["records"] >= 4 and not regressed),
        "qerror_p50": p50, "qerror_p90": p90,
        "qerror_regressed": regressed,
        "records": counters["records"],
        "nodes": counters["nodes"],
        "flipped": flipped,
        "byte_equal": second.rows == first.rows,
        "dist_flipped": dist_flipped,
        "dist_byte_equal": dsecond.rows == dfirst.rows,
        "plan_flips": plan_flips,
        "wall_s": round(time.time() - t0, 2),
    }
    print(json.dumps({"metric": "hbo_qerror_p50", "value": p50,
                      "unit": "qerror",
                      "vs_baseline": ratios["hbo_qerror_p50"]}),
          flush=True)
    print(json.dumps({"metric": "hbo_qerror_p90", "value": p90,
                      "unit": "qerror",
                      "vs_baseline": ratios["hbo_qerror_p90"]}),
          flush=True)
    for kind in ("join_order", "distribution"):
        print(json.dumps({"metric": "hbo_plan_flips",
                          "value": plan_flips.get(kind, 0),
                          "unit": "flips", "kind": kind}), flush=True)
    for name in regressed:
        print(json.dumps({"metric": f"{name}_regressed",
                          "value": ratios[name],
                          "unit": "x_vs_baseline",
                          "vs_baseline": ratios[name]}), flush=True)
    print("HBO_RESULT " + json.dumps(out), flush=True)
    if not out["ok"]:
        raise SystemExit(13)
    return out


def _qerror_ratchet(p50: float, p90: float, cache: dict):
    """(vs-baseline ratios, regressed metric names) for the HBO
    quantiles. Q-error is lower-better, so the check is an UPPER
    bound: a quantile above its committed baseline x the tolerance
    (BENCH_HBO_RATCHET_MAX, default 1.25) is an estimate-quality
    regression. The workload is deterministic — Q-error measures row
    counts, not wall time — so these cannot flake with host load.
    No committed baseline -> ratio 0.0, never regressed."""
    ceiling = float(os.environ.get("BENCH_HBO_RATCHET_MAX", "1.25"))
    regressed = []
    ratios = {}
    for name, value in (("hbo_qerror_p50", p50),
                        ("hbo_qerror_p90", p90)):
        base = cache.get(name)
        ratios[name] = round(value / base, 3) if base else 0.0
        if base and ratios[name] > ceiling:
            regressed.append(name)
    return ratios, regressed


def _qps_smoke():
    """BENCH_ROLE=qps: concurrent multi-tenant throughput over the REAL
    HTTP protocol surface — N client threads POST /v1/statement and
    follow nextUris against a ProtocolServer + LocalQueryRunner with
    resource groups, a zipf tenant distribution, and a repeat-heavy
    tiny/medium statement mix.  Phase A runs with the plan/result
    caches and admission batching DISABLED (every submission re-pays
    parse/plan/trace), phase B with them ON; both report p50/p99
    latency and queries/sec.  The run fails (rc=10) unless phase B
    shows plan-cache hits, a repeat statement performs ZERO jit traces,
    the _QueryState table stays bounded, and QPS reaches
    BENCH_QPS_MIN_SPEEDUP (default 1.5) x the uncached phase.  The
    cached-baseline ratchet gates on the committed SPEEDUP
    (qps_speedup:<schema> — self-normalizing; absolute qps:<schema>
    rides the metric line as reported context, since wall-clock QPS on
    a shared host swings ~2x between identical runs).
    Env: BENCH_QPS_SCHEMA (micro|tiny, default tiny), BENCH_QPS_CLIENTS
    (default 8), BENCH_QPS_QUERIES (per client, default 25),
    BENCH_QPS_TENANTS (default 12), BENCH_QPS_RATCHET_MIN (default
    0.6, applied to the speedup ratio).  Round 16 adds the
    ``batch_launch_depth:<schema>`` ratchet: profiler-counted device
    launches per statement for an 8-statement same-shape burst through
    ``execute_batch`` — the single-launch vmapped path must keep this
    under 1.0, and the committed baseline may only shrink.  Round 17
    adds ``batch_launch_depth_agg:<schema>`` with the same strict
    rules for an aggregating (GROUP BY) 8-burst riding the masked
    vmapped agg barrier, which must actually engage
    (``agg_stage_vmapped`` > 0 — serial fallback would fail the run
    even below 1.0)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/trino_tpu_jax_cache")
    import numpy as np

    from trino_tpu import jit_stats
    from trino_tpu.client import Client
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.resource_groups import ResourceGroupManager
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.server.protocol import ProtocolServer
    from trino_tpu.sql.analyzer import Session

    schema = os.environ.get("BENCH_QPS_SCHEMA", "tiny")
    n_clients = int(os.environ.get("BENCH_QPS_CLIENTS", "8"))
    per_client = int(os.environ.get("BENCH_QPS_QUERIES", "25"))
    n_tenants = int(os.environ.get("BENCH_QPS_TENANTS", "12"))
    min_speedup = float(os.environ.get("BENCH_QPS_MIN_SPEEDUP", "1.5"))

    rg = ResourceGroupManager.from_config({"groups": [
        {"name": "tenants", "user": "tenant-.*", "max_concurrency": 8,
         "max_queued": 10_000},
        {"name": "global", "max_concurrency": 8, "max_queued": 10_000},
    ]})
    runner = LocalQueryRunner({"tpch": TpchConnector()},
                              Session(catalog="tpch", schema=schema),
                              resource_groups=rg)
    srv = ProtocolServer(runner).start()
    t_start = time.time()

    tiny_templates = [
        "select count(*) c, sum(o_totalprice) s from orders "
        "where o_custkey % 64 = {t}",
        "select count(*) c, sum(l_quantity) q from lineitem "
        "where l_partkey % 128 = {t}",
    ]
    medium_templates = [
        "select l_returnflag, l_linestatus, count(*) c, "
        "sum(l_quantity) q from lineitem "
        "group by l_returnflag, l_linestatus",
        "select o_orderpriority, count(*) c from orders "
        "group by o_orderpriority",
    ]

    def workload(seed: int):
        """Deterministic per-client statement list: zipf-distributed
        tenants (hot tenants dominate — the dashboard pattern), 80%
        tiny parameterized point-ish queries, 20% medium aggregations."""
        rng = np.random.default_rng(seed)
        out = []
        for _ in range(per_client):
            t = int(rng.zipf(1.5)) % n_tenants
            if rng.random() < 0.8:
                tpl = tiny_templates[int(rng.integers(len(tiny_templates)))]
                out.append((f"tenant-{t}", tpl.format(t=t)))
            else:
                m = medium_templates[int(rng.integers(
                    len(medium_templates)))]
                out.append((f"tenant-{t}", m))
        return out

    admin = Client(srv.uri)

    def set_knobs(on: bool):
        v = "true" if on else "false"
        for name in ("plan_cache_enabled", "result_cache_enabled",
                     "admission_batching_enabled"):
            admin.execute(f"set session {name} = {v}")

    def run_phase(label: str, caches_on: bool) -> dict:
        set_knobs(caches_on)
        lat = [[] for _ in range(n_clients)]
        errors = []

        def worker(ci: int):
            cl = Client(srv.uri)
            for user, sql in workload(1000 + ci):
                cl.user = user
                t0 = time.perf_counter()
                try:
                    cl.execute(sql)
                except Exception as e:  # counted, not fatal per query
                    errors.append(repr(e))
                    continue
                lat[ci].append(time.perf_counter() - t0)

        t0 = time.time()
        threads = [threading.Thread(target=worker, args=(ci,))
                   for ci in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.time() - t0
        all_lat = sorted(x for chunk in lat for x in chunk)
        n = len(all_lat)
        return {
            "label": label, "queries": n, "errors": len(errors),
            "wall_s": round(wall, 2),
            "qps": round(n / wall, 2) if wall > 0 else 0.0,
            "p50_ms": round(all_lat[n // 2] * 1e3, 1) if n else 0.0,
            "p99_ms": round(all_lat[min(n - 1, int(n * 0.99))] * 1e3, 1)
            if n else 0.0,
        }

    off = run_phase("uncached", caches_on=False)
    on = run_phase("cached", caches_on=True)

    # zero-retrace probe: a repeat statement through the warm plan/
    # processor caches must not trace anything (result cache off so the
    # probe actually EXECUTES the pipeline)
    admin.execute("set session result_cache_enabled = false")
    probe_user, probe_sql = workload(1000)[0]
    admin.user = probe_user
    admin.execute(probe_sql)          # re-key under the final session fp
    before = jit_stats.total()
    admin.execute(probe_sql)
    probe_traces = jit_stats.total() - before

    # single-launch witness (round 16): an 8-statement same-shape burst
    # through execute_batch must run each vmappable pipeline stage as
    # ONE vmapped launch — the profiler counts launches independent of
    # the batch depth B, so launches-per-statement is the ratchetable
    # amortization metric (serial execution pays >= 1.0; a 2-stage
    # fully batched pipeline over one scan page pays 2/8 = 0.25)
    # the witness shape is filter/project (scan->fp*->collect): the
    # original (round 16) vmappable pipeline class; aggregating shapes
    # get their OWN witness + ratchet below (round 17)
    from trino_tpu.telemetry import profiler as _prof
    burst_tpl = ("select o_orderkey, o_totalprice from orders "
                 "where o_custkey % 64 = {t}")
    burst = [burst_tpl.format(t=t) for t in range(8)]
    runner.execute_batch(burst, user="tenant-0")  # warm template+traces
    # profiled re-run uses FRESH literals: same shape and padded depth,
    # so it rides the warm template and traces, but misses the result
    # cache — every member occupies a live vmap lane
    burst2 = [burst_tpl.format(t=t) for t in range(8, 16)]
    _prof.reset()
    with _prof.profiling(True):
        runner.execute_batch(burst2, user="tenant-0")
        _snap = _prof.snapshot()
    launches = sum(e["calls"] for e in _snap
                   if e["name"] in ("page_processor",
                                    "page_processor_batched"))
    launch_depth = round(launches / len(burst), 4)

    # aggregating single-launch witness (round 17): a GROUP BY burst
    # rides the masked vmapped agg barrier — per-page partial kernels
    # plus one merge/finalize barrier for the whole batch, so its
    # launch depth ratchets separately (more stages than the fp-only
    # shape, still well under the serial 1.0/statement)
    agg_tpl = ("select o_orderpriority, count(*) c, "
               "sum(o_totalprice) s from orders "
               "where o_custkey % 64 = {t} group by o_orderpriority")
    agg_burst = [agg_tpl.format(t=t) for t in range(8)]
    runner.execute_batch(agg_burst, user="tenant-0")  # warm traces
    agg_burst2 = [agg_tpl.format(t=t) for t in range(8, 16)]
    _prof.reset()
    with _prof.profiling(True):
        runner.execute_batch(agg_burst2, user="tenant-0")
        _asnap = _prof.snapshot()
    agg_launches = sum(
        e["calls"] for e in _asnap
        if e["name"] in ("page_processor", "page_processor_batched",
                         "batched_agg_partial", "batched_agg_merge",
                         "batched_agg_finalize"))
    agg_launch_depth = round(agg_launches / len(agg_burst2), 4)
    agg_vmapped = runner.query_cache.templates.dispositions.get(
        "agg_stage_vmapped", 0)
    batched_launches = runner.query_cache.batched_launches
    counters = runner.query_cache.counters()

    # bounded _QueryState growth: all delivered results must have been
    # popped; nothing may accumulate with sustained submissions
    states_left = len(srv.queries)

    speedup = round(on["qps"] / off["qps"], 2) if off["qps"] else 0.0
    cache = _load_cache()
    base = cache.get(f"qps:{schema}")
    ratio = round(on["qps"] / base, 3) if base else 0.0
    # the RATCHET gates on the speedup (cached/uncached within ONE run
    # — self-normalizing, both phases share the host's load), not on
    # absolute QPS: wall-clock throughput on a shared host swings ~2x
    # between identical runs, which would make an absolute ratchet cry
    # wolf.  Absolute QPS still rides the metric line as vs_baseline.
    speed_base = cache.get(f"qps_speedup:{schema}")
    speed_ratio = round(speedup / speed_base, 3) if speed_base else 0.0
    floor = float(os.environ.get("BENCH_QPS_RATCHET_MIN", "0.6"))
    regressed = bool(speed_base) and speed_ratio < floor
    # launch-depth ratchet is STRICT (launch counts are deterministic
    # for a fixed schema — no host-load noise to forgive): growing
    # launches-per-statement means the vmapped path stopped amortizing
    depth_base = cache.get(f"batch_launch_depth:{schema}")
    depth_regressed = bool(depth_base) and launch_depth > depth_base
    agg_depth_base = cache.get(f"batch_launch_depth_agg:{schema}")
    agg_depth_regressed = bool(agg_depth_base) \
        and agg_launch_depth > agg_depth_base
    # template-eligible shapes ride the plan TEMPLATE (round 16), whose
    # roots deliberately never enter the value-specialized plan cache —
    # the "planning amortized" witness is the SUM of both reuse paths
    plan_reuse = (counters["plan_hits"] + counters["plan_shape_hits"]
                  + counters["template_hits"])
    ok = (on["queries"] == off["queries"] == n_clients * per_client
          and on["errors"] == 0 and off["errors"] == 0
          and plan_reuse > 0
          and probe_traces == 0
          and states_left <= 2 * n_clients
          and speedup >= min_speedup
          and batched_launches > 0
          and launch_depth < 1.0
          and agg_launch_depth < 1.0
          and agg_vmapped > 0
          and not regressed
          and not depth_regressed
          and not agg_depth_regressed)
    out = {
        "ok": ok, "schema": schema, "clients": n_clients,
        "uncached": off, "cached": on, "speedup": speedup,
        "plan_cache": {k: v for k, v in counters.items()
                       if k.startswith("plan")},
        "result_cache": {k: v for k, v in counters.items()
                         if k.startswith("result")},
        "batching": {k: counters[k] for k in
                     ("batches", "batched_queries", "coalesced",
                      "batched_launches", "result_shortcircuits")},
        "templates": {k: v for k, v in counters.items()
                      if k.startswith("template")},
        "batch_launch_depth": launch_depth,
        "batch_launch_depth_agg": agg_launch_depth,
        "agg_stage_vmapped": agg_vmapped,
        "probe_traces": probe_traces,
        "query_states_left": states_left,
        "wall_s": round(time.time() - t_start, 2),
    }
    print(json.dumps({
        "metric": f"qps_{schema}_queries_per_sec", "value": on["qps"],
        "unit": "qps", "vs_baseline": ratio,
        "p50_ms": on["p50_ms"], "p99_ms": on["p99_ms"],
        "clients": n_clients,
    }), flush=True)
    print(json.dumps({
        "metric": f"qps_{schema}_speedup_vs_uncached", "value": speedup,
        "unit": "x", "vs_baseline": speed_ratio,
        "uncached_qps": off["qps"], "uncached_p99_ms": off["p99_ms"],
    }), flush=True)
    print(json.dumps({
        "metric": f"qps_{schema}_batch_launch_depth",
        "value": launch_depth, "unit": "launches_per_statement",
        "vs_baseline": (round(launch_depth / depth_base, 3)
                        if depth_base else 0.0),
        "batched_launches": batched_launches,
    }), flush=True)
    print(json.dumps({
        "metric": f"qps_{schema}_batch_launch_depth_agg",
        "value": agg_launch_depth, "unit": "launches_per_statement",
        "vs_baseline": (round(agg_launch_depth / agg_depth_base, 3)
                        if agg_depth_base else 0.0),
        "agg_stage_vmapped": agg_vmapped,
    }), flush=True)
    if regressed:
        print(json.dumps({
            "metric": f"qps_{schema}_speedup_regressed",
            "value": speed_ratio, "unit": "x_vs_baseline",
            "vs_baseline": speed_ratio,
        }), flush=True)
    print("QPS_RESULT " + json.dumps(out), flush=True)
    srv.stop()
    if not ok:
        raise SystemExit(10)
    return out


# ---------------------------------------------------------------- parent ----

def _guarded_child_cls():
    """Load subproc.py by file path: importing the trino_tpu package would
    run its __init__ (`import jax` + config), and the parent must stay free
    of anything that can stall."""
    import importlib.util

    path = os.path.join(REPO, "trino_tpu", "subproc.py")
    spec = importlib.util.spec_from_file_location("_bench_subproc", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.GuardedChild


def _spawn(platform: str):
    env = dict(os.environ, BENCH_ROLE="measure", BENCH_PLATFORM=platform)
    return _guarded_child_cls()(
        [sys.executable, "-u", os.path.abspath(__file__)],
        env=env, tag=f"bench-{platform}")


def _parse_results(text: str):
    """All RESULT lines, in print order (q1 before q3)."""
    out = []
    for line in text.splitlines():
        if line.startswith("RESULT "):
            try:
                out.append(json.loads(line[len("RESULT "):]))
            except ValueError:
                continue
    return out


def _load_cache():
    try:
        return json.load(open(CACHE_PATH))
    except Exception:
        return {}


def _base_for(cache, res):
    """CPU-baseline rate for a result: 'q3:tiny' keys, with the bare
    'tiny' spelling accepted for q1 (pre-round-4 cache layout)."""
    q = res.get("query", "q1")
    base = cache.get(f"{q}:{res['schema']}")
    if base is None and q == "q1":
        base = cache.get(res["schema"])
    return base


def _emit(state, res, suffix, base, cached_base=False):
    q = res.get("query", "q1")
    if res.get("stages"):
        # per-stage wall-time breakdown + jit-trace counts ride along as
        # a non-headline metric line (printed BEFORE the rate line so
        # the headline stays last on stdout)
        bd = res["stages"]
        total = round(sum(bd["stage_ms"].values()), 1)
        extra = {}
        if bd.get("exchange_stats"):
            extra["exchange_stats"] = bd["exchange_stats"]
        print(json.dumps({
            "metric": f"tpch_{q}_{res['schema']}_stage_wall_ms{suffix}",
            "value": total, "unit": "ms", "vs_baseline": 0.0,
            "stages": bd["stage_ms"], "compiles": bd["compiles"],
            "jit_traces": res.get("jit_traces"), **extra,
        }), flush=True)
    ratio = round(res["rate"] / base, 3) if base else 0.0
    device = res.get("device", "")
    line = json.dumps({
        "metric": f"tpch_{q}_{res['schema']}_rows_per_sec{suffix}",
        "value": round(res["rate"], 1),
        "unit": "rows/s",
        "vs_baseline": ratio,
        # provenance stamp: a CPU-fallback run can never masquerade as
        # a TPU number — the backend that actually ran is in the line,
        # not only in the metric suffix
        "backend": "tpu" if device and "cpu" not in device.lower()
        else "cpu",
        "device_kind": device,
    })
    state["line"] = line
    if q == "q3":
        state["q3_line"] = line
    print(line, flush=True)
    # the ratchet: a CPU rate below its COMMITTED cached baseline is a
    # failing check (round 5's q1 slid to 0.928 with nothing tripping) —
    # an explicit *_regressed line plus a nonzero exit from main().
    # Same-run solo baselines are exempt (ratio there is ~1 by
    # construction); threshold overridable for noisy hosts.
    floor = float(os.environ.get("BENCH_RATCHET_MIN", "1.0"))
    if cached_base and suffix == "_cpu_fallback" and base and ratio < floor:
        state.setdefault("regressed", []).append(json.dumps({
            "metric": f"tpch_{q}_{res['schema']}_rows_per_sec_regressed",
            "value": ratio, "unit": "x_vs_baseline",
            "vs_baseline": ratio,
        }))


def _load_qlint():
    """Load trino_tpu/analysis as a SYNTHETIC package by file path —
    NOT through ``import trino_tpu`` — because the parent package's
    __init__ imports jax, and this parent process must never import
    jax (a down axon tunnel hangs the import forever, before the
    watchdog thread even exists — the round-5 failure the parent/
    child split was built to prevent). The analysis package is
    self-contained stdlib-ast, so its relative imports resolve inside
    the synthetic package without touching trino_tpu/__init__.py."""
    import importlib.util

    pkg_dir = os.path.join(REPO, "trino_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "_bench_qlint", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_bench_qlint"] = mod
    spec.loader.exec_module(mod)
    return mod


def _qlint_preflight():
    """Run the static analyzer BEFORE spawning any bench child: code
    that would retrace per page (or deadlock a worker) burns the whole
    380 s TPU budget producing a garbage number — fail fast with a
    DISTINCT rc=8 instead. Pure stdlib ast, no JAX import, ~3 s.
    BENCH_SKIP_QLINT=1 skips (emergency escape hatch only)."""
    if os.environ.get("BENCH_SKIP_QLINT") == "1":
        return
    qlint = _load_qlint()
    assert "jax" not in sys.modules, \
        "qlint pre-flight must not import jax in the bench parent"
    # all nine passes must be registered (round 14 added
    # cache-coherence + resource-lifecycle, round 15 guarded-by): a
    # refactor that dropped a pass from the registry would silently
    # weaken this gate
    missing = {"trace-purity", "lock-order", "recompile",
               "session-props", "taxonomy", "blocked-protocol",
               "cache-coherence", "resource-lifecycle",
               "guarded-by"} - set(qlint.PASSES)
    assert not missing, f"qlint passes missing from registry: {missing}"

    package = os.path.join(REPO, "trino_tpu")
    findings = qlint.run_passes(qlint.ProjectIndex.from_package(package))
    baseline = qlint.load_baseline(qlint.default_baseline_path(package))
    new, _suppressed, stale = qlint.apply_baseline(findings, baseline)
    if new or stale:
        for f in new:
            sys.stderr.write(f"qlint: {f.render()}\n")
        for key in stale:
            sys.stderr.write(f"qlint: STALE baseline entry {key}\n")
        sys.stderr.write(
            f"bench: qlint pre-flight failed "
            f"({len(new)} finding(s), {len(stale)} stale) — not "
            f"spending the TPU budget on hazardous code\n")
        sys.exit(8)


def main():
    schema = os.environ.get("BENCH_SCHEMA", "tiny")
    _qlint_preflight()
    deadline = float(os.environ.get("BENCH_DEADLINE", "520"))
    tpu_budget = float(os.environ.get("BENCH_TPU_BUDGET", "380"))
    t_start = time.time()
    state = {"line": None, "children": []}

    def watchdog():
        remaining = deadline - (time.time() - t_start)
        if remaining > 0:
            time.sleep(remaining)
        # kill child groups first: an orphaned hung TPU child would keep
        # the chip locked for the next invocation
        for c in state["children"]:
            c.kill_group_only()
        if state["line"] is None:
            print(json.dumps({
                "metric": f"tpch_q1_{schema}_rows_per_sec_timeout",
                "value": 0.0, "unit": "rows/s", "vs_baseline": 0.0,
            }), flush=True)
        sys.stderr.write("bench: watchdog deadline reached; exiting\n")
        sys.stdout.flush()
        os._exit(0)

    threading.Thread(target=watchdog, daemon=True).start()

    cache = _load_cache()

    # Phase 1: CPU fallback child SOLO (~60 s for q1+q3). Its lines go out
    # first so a parseable line exists on stdout early no matter when the
    # driver's unknown outer timeout strikes.
    cpu = _spawn("cpu")
    state["children"] = [cpu]
    cpu_deadline = t_start + max(30.0, min(180.0, deadline - 60))
    while time.time() < cpu_deadline and not cpu.exited():
        time.sleep(0.5)
    cpu_text = cpu.kill()
    cpu_results = _parse_results(cpu_text)
    sys.stderr.write(f"bench: cpu child tail:\n{cpu_text[-800:]}\n")
    solo_base = {}
    for res in cpu_results:
        cbase = _base_for(cache, res)
        _emit(state, res, "_cpu_fallback", cbase,
              cached_base=cbase is not None)
        # uncached query:schema: the phase-1 rate was measured solo, so
        # it is a sound (if unpersisted) baseline for the ratio
        solo_base[res.get("query", "q1")] = res["rate"]

    # Optional trace phase (BENCH_TRACE=1): a guarded child runs the
    # distributed-trace smoke, its stage_overlap metric line re-emits
    # here, and the Perfetto artifact lands next to BENCH_*.json.
    # Before phase 2 so the q3 headline stays the LAST stdout line.
    if os.environ.get("BENCH_TRACE") == "1":
        env = dict(os.environ, BENCH_ROLE="trace")
        tracer = _guarded_child_cls()(
            [sys.executable, "-u", os.path.abspath(__file__)],
            env=env, tag="bench-trace")
        state["children"] = [tracer]
        trace_deadline = min(t_start + deadline - 60, time.time() + 150)
        while time.time() < trace_deadline and not tracer.exited():
            time.sleep(0.5)
        trace_text = tracer.kill()
        for line in trace_text.splitlines():
            if line.startswith('{"metric": "trace_stage_overlap_'
                               'regressed"'):
                # the overlap ratchet tripped: fail the whole bench run
                # like a rows/s regression does
                state.setdefault("regressed", []).append(line)
            elif line.startswith('{"metric": "trace_'):
                print(line, flush=True)
        sys.stderr.write(f"bench: trace child tail:\n"
                         f"{trace_text[-600:]}\n")

    # Phase 2: TPU child SOLO — the per-chip rate must not be measured under
    # host CPU contention from the baseline child. Bounded retry with
    # exponential backoff around backend init: the rc=3 watchdog inside
    # the child fails fast when the axon tunnel hangs `import jax`, and
    # a tunnel that is down NOW is often back in 10-30 s — retrying with
    # backoff while the budget lasts is how a flaky tunnel still yields
    # a real TPU number instead of a silent CPU-only run.
    tpu_deadline = t_start + max(60.0, min(tpu_budget, deadline - 30))
    max_attempts = max(1, int(os.environ.get("BENCH_INIT_RETRIES", "3")))
    backoff = 5.0
    tpu_results = []
    tpu_text = ""
    for attempt in range(max_attempts):
        if time.time() >= tpu_deadline - 30:
            break
        tpu = _spawn("default")
        state["children"] = [tpu]
        while time.time() < tpu_deadline and not tpu.exited():
            time.sleep(0.5)
        crashed_early = tpu.exited()
        rc = tpu.proc.returncode
        tpu_text = tpu.kill()
        # a killed child may still have written RESULTs before hanging
        tpu_results = _parse_results(tpu_text)
        sys.stderr.write(f"bench: tpu child (attempt {attempt + 1}, "
                         f"rc={rc}) tail:\n{tpu_text[-1500:]}\n")
        if tpu_results:
            break
        if not crashed_early and rc != 3:
            break  # a hang was killed at deadline: retrying wastes budget
        # rc=3 (init watchdog) or an early crash (transient chip lock):
        # back off, then respawn while budget remains
        time.sleep(min(backoff, max(0.0, tpu_deadline - time.time())))
        backoff *= 2

    for res in tpu_results:
        q = res.get("query", "q1")
        cbase = _base_for(cache, res)
        base = cbase or solo_base.get(q)
        is_tpu = "cpu" not in res["device"].lower()
        # a CPU-fallback run must not masquerade as a per-chip TPU
        # number; and if the default platform resolved to CPU, don't
        # print a duplicate _cpu_fallback line when one is already out
        if is_tpu:
            _emit(state, res, "_per_chip", base)
        elif q not in solo_base:
            _emit(state, res, "_cpu_fallback", base,
                  cached_base=cbase is not None)
    # any query with no emitted line at all gets an explicit failed
    # line, so a child killed between its q1 and q3 prints cannot leave
    # the q1 line masquerading as the headline (last-line) metric
    emitted = {r.get("query", "q1") for r in cpu_results} | \
        {r.get("query", "q1") for r in tpu_results}
    printed_failed = False
    for q in ("q1", "q3"):
        if q not in emitted:
            printed_failed = True
            line = json.dumps({
                "metric": f"tpch_{q}_{schema}_rows_per_sec_failed",
                "value": 0.0, "unit": "rows/s", "vs_baseline": 0.0,
            })
            if state["line"] is None:
                state["line"] = line
            print(line, flush=True)
    # ratchet verdict: regressed lines print before the headline gets
    # re-asserted, then main exits nonzero so the check FAILS loudly
    regressed = state.get("regressed", [])
    for line in regressed:
        print(line, flush=True)
    # a late q1 failed / regressed line must not displace a real q3
    # headline as the LAST stdout line — re-assert it
    if (printed_failed or regressed) and state.get("q3_line"):
        state["line"] = state["q3_line"]
        print(state["q3_line"], flush=True)
    if regressed:
        sys.stderr.write(f"bench: {len(regressed)} metric(s) regressed "
                         "below the cached baseline\n")
        sys.exit(1)


if __name__ == "__main__":
    if os.environ.get("BENCH_ROLE") == "measure":
        _measure_child()
    elif os.environ.get("BENCH_ROLE") == "chaos":
        _chaos_smoke()
    elif os.environ.get("BENCH_ROLE") == "elastic":
        _elastic_smoke()
    elif os.environ.get("BENCH_ROLE") == "memory":
        _memory_smoke()
    elif os.environ.get("BENCH_ROLE") == "skew":
        _skew_smoke()
    elif os.environ.get("BENCH_ROLE") == "kernels":
        _kernels_smoke()
    elif os.environ.get("BENCH_ROLE") == "trace":
        _trace_smoke()
    elif os.environ.get("BENCH_ROLE") == "qps":
        _qps_smoke()
    elif os.environ.get("BENCH_ROLE") == "hbo":
        _hbo_smoke()
    else:
        main()
