"""Benchmark entry: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures TPC-H q1 (scan data pre-generated; pipeline = host->device upload +
fused filter/project + sort-based group aggregation) in lineitem rows/sec on
the real TPU chip. vs_baseline = TPU rate / single-CPU rate of the IDENTICAL
pipeline (cached per schema in the committed .bench_cpu_cache.json) — the
"vs CPU at equal node count" framing of BASELINE.md. Reference harness analog:
testing/trino-benchmark/.../HandTpchQuery1.java (rows/s via LocalQueryRunner).

Hardening (rounds 1+2 produced no number: rc=1 backend crash, then rc=124
hang *after* a successful probe):
  * the parent process never imports the trino_tpu package or initializes
    a jax backend (subproc.py is loaded by file path, skipping the package
    __init__) — it cannot hang;
  * measurement children run via GuardedChild (own process group,
    stdout->file, group-killed on timeout);
  * phase 1 runs the CPU fallback child SOLO (~25 s) and prints its
    _cpu_fallback line immediately — the driver's outer timeout is unknown,
    so a parseable line must exist on stdout early; phase 2 then runs the
    TPU child SOLO (no host contention) and its _per_chip line supersedes;
    an early TPU crash (transient chip lock) gets one respawn;
  * a watchdog kills the live child group, prints the best-known JSON, and
    exits 0 at BENCH_DEADLINE (default 520 s) no matter what;
  * CPU rates are never persisted to the cache at bench time — the
    committed cache is seeded solo; an uncached schema falls back to the
    phase-1 solo rate for the ratio.

Env: BENCH_SCHEMA (micro|tiny|sf1; default tiny), BENCH_DEADLINE (s),
BENCH_TPU_BUDGET (s). Internal: BENCH_ROLE=measure BENCH_PLATFORM=cpu|default.
"""

import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))
CACHE_PATH = os.path.join(REPO, ".bench_cpu_cache.json")


# ----------------------------------------------------------------- child ----

def _measure_child():
    """BENCH_ROLE=measure: pin platform, run q1, print 'RESULT {json}'."""
    schema = os.environ.get("BENCH_SCHEMA", "tiny")
    platform = os.environ.get("BENCH_PLATFORM", "default")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/trino_tpu_jax_cache")
    t0 = time.time()
    import jax

    if platform == "cpu":
        # env vars are not enough: the axon sitecustomize pins the platform
        # in live config at interpreter startup, so mutate the live config
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/trino_tpu_jax_cache")
    sys.stderr.write(f"child[{platform}]: jax ready {time.time() - t0:.1f}s\n")
    devs = jax.devices()
    sys.stderr.write(f"child[{platform}]: devices {devs} "
                     f"{time.time() - t0:.1f}s\n")

    from trino_tpu.benchmarks import build_q1_driver, scan_q1_pages
    from trino_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(page_rows=1 << 16)
    pages = scan_q1_pages(conn, schema, desired_splits=8)
    total_rows = sum(p.num_rows for p in pages)
    sys.stderr.write(f"child[{platform}]: {total_rows} rows generated "
                     f"{time.time() - t0:.1f}s\n")

    times = []
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    for i in range(repeats):
        driver, sink = build_q1_driver(conn, schema, source_pages=list(pages))
        r0 = time.perf_counter()
        driver.run_to_completion()
        times.append(time.perf_counter() - r0)
        sys.stderr.write(f"child[{platform}]: run {i + 1}/{repeats} "
                         f"{times[-1]:.3f}s\n")
    # first run pays compilation; take the best of the rest
    best = min(times[1:]) if len(times) > 1 else times[0]
    print("RESULT " + json.dumps({
        "schema": schema, "platform": platform,
        "device": str(devs[0]), "rows": total_rows,
        "secs": best, "rate": total_rows / best,
    }), flush=True)


# ---------------------------------------------------------------- parent ----

def _guarded_child_cls():
    """Load subproc.py by file path: importing the trino_tpu package would
    run its __init__ (`import jax` + config), and the parent must stay free
    of anything that can stall."""
    import importlib.util

    path = os.path.join(REPO, "trino_tpu", "subproc.py")
    spec = importlib.util.spec_from_file_location("_bench_subproc", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.GuardedChild


def _spawn(platform: str):
    env = dict(os.environ, BENCH_ROLE="measure", BENCH_PLATFORM=platform)
    return _guarded_child_cls()(
        [sys.executable, "-u", os.path.abspath(__file__)],
        env=env, tag=f"bench-{platform}")


def _parse_result(text: str):
    for line in text.splitlines():
        if line.startswith("RESULT "):
            try:
                return json.loads(line[len("RESULT "):])
            except ValueError:
                continue
    return None


def _load_cache():
    try:
        return json.load(open(CACHE_PATH))
    except Exception:
        return {}


def _emit(state, res, suffix, base):
    line = json.dumps({
        "metric": f"tpch_q1_{res['schema']}_rows_per_sec{suffix}",
        "value": round(res["rate"], 1),
        "unit": "rows/s",
        "vs_baseline": round(res["rate"] / base, 3) if base else 0.0,
    })
    state["line"] = line
    print(line, flush=True)


def main():
    schema = os.environ.get("BENCH_SCHEMA", "tiny")
    deadline = float(os.environ.get("BENCH_DEADLINE", "520"))
    tpu_budget = float(os.environ.get("BENCH_TPU_BUDGET", "380"))
    t_start = time.time()
    state = {"line": None, "children": []}

    def watchdog():
        remaining = deadline - (time.time() - t_start)
        if remaining > 0:
            time.sleep(remaining)
        # kill child groups first: an orphaned hung TPU child would keep
        # the chip locked for the next invocation
        for c in state["children"]:
            c.kill_group_only()
        if state["line"] is None:
            print(json.dumps({
                "metric": f"tpch_q1_{schema}_rows_per_sec_timeout",
                "value": 0.0, "unit": "rows/s", "vs_baseline": 0.0,
            }), flush=True)
        sys.stderr.write("bench: watchdog deadline reached; exiting\n")
        sys.stdout.flush()
        os._exit(0)

    threading.Thread(target=watchdog, daemon=True).start()

    cache = _load_cache()
    base = cache.get(schema)

    # Phase 1: CPU fallback child SOLO (~25 s). Its line goes out first so a
    # parseable line exists on stdout early no matter when the driver's
    # unknown outer timeout strikes.
    cpu = _spawn("cpu")
    state["children"] = [cpu]
    cpu_deadline = t_start + max(30.0, min(120.0, deadline - 60))
    while time.time() < cpu_deadline and not cpu.exited():
        time.sleep(0.5)
    cpu_text = cpu.kill()
    cpu_res = _parse_result(cpu_text)
    sys.stderr.write(f"bench: cpu child tail:\n{cpu_text[-800:]}\n")
    cpu_printed = False
    if cpu_res is not None:
        cpu_printed = True
        _emit(state, cpu_res, "_cpu_fallback", base)
        if base is None:
            # uncached schema: the phase-1 rate was measured solo, so it is
            # a sound (if unpersisted) baseline for the ratio
            base = cpu_res["rate"]

    # Phase 2: TPU child SOLO — the per-chip rate must not be measured under
    # host CPU contention from the baseline child. One respawn on an early
    # crash (transient chip lock, the round-1 mode).
    tpu_deadline = t_start + max(60.0, min(tpu_budget, deadline - 30))
    tpu_res = None
    tpu_text = ""
    for attempt in range(2):
        if time.time() >= tpu_deadline - 30:
            break
        tpu = _spawn("default")
        state["children"] = [tpu]
        while time.time() < tpu_deadline and not tpu.exited():
            time.sleep(0.5)
        crashed_early = tpu.exited()
        tpu_text = tpu.kill()
        # a killed child may still have written RESULT before hanging
        tpu_res = _parse_result(tpu_text)
        sys.stderr.write(f"bench: tpu child (attempt {attempt + 1}) "
                         f"tail:\n{tpu_text[-1500:]}\n")
        if tpu_res is not None or not crashed_early:
            break  # success, or a hang (retrying a hang wastes the budget)
        time.sleep(5)

    if tpu_res is not None:
        is_tpu = "cpu" not in tpu_res["device"].lower()
        # a CPU-fallback run must not masquerade as a per-chip TPU number;
        # and if the default platform resolved to CPU, don't print a second
        # (contention-free is moot — sequential now, but still duplicate)
        # _cpu_fallback line when one is already out
        if is_tpu:
            _emit(state, tpu_res, "_per_chip", base)
        elif not cpu_printed:
            _emit(state, tpu_res, "_cpu_fallback", base)
    elif not cpu_printed and state["line"] is None:
        print(json.dumps({
            "metric": f"tpch_q1_{schema}_rows_per_sec_failed",
            "value": 0.0, "unit": "rows/s", "vs_baseline": 0.0,
        }), flush=True)


if __name__ == "__main__":
    if os.environ.get("BENCH_ROLE") == "measure":
        _measure_child()
    else:
        main()
