"""Benchmark entry: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures TPC-H q1 (scan data pre-generated; pipeline = host->device upload +
fused filter/project + sort-based group aggregation) in lineitem rows/sec on
the current JAX platform (real TPU under axon). vs_baseline = TPU rate /
single-CPU rate of the IDENTICAL pipeline (measured in a subprocess, cached
per schema in .bench_cpu_cache.json) — the "vs CPU at equal node count"
framing of BASELINE.md.

Env: BENCH_SCHEMA (micro|tiny|sf1|...; default tiny), BENCH_FORCE_CPU=1
(internal: baseline subprocess).
"""

import json
import os
import subprocess
import sys
import time

FORCE_CPU = os.environ.get("BENCH_FORCE_CPU") == "1"
if FORCE_CPU:
    import jax

    jax.config.update("jax_platforms", "cpu")

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/trino_tpu_jax_cache")


def ensure_backend() -> str:
    """Probe/repair the backend before measuring (round-1 failure mode:
    axon init crashed/hung and the round got rc=1 with no number).
    Returns "" (default platform ok) or "cpu" (fallback pinned)."""
    from trino_tpu.backend_probe import ensure_backend as _ensure

    return _ensure("bench")


def run_q1(schema: str, repeats: int = 3):
    import jax

    from trino_tpu.benchmarks import (build_q1_driver, q1_expressions,
                                      scan_q1_pages, Q1_COLUMNS)
    from trino_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(page_rows=1 << 16)
    pages = scan_q1_pages(conn, schema, desired_splits=8)
    total_rows = sum(p.num_rows for p in pages)

    times = []
    result = None
    for i in range(repeats):
        driver, sink = build_q1_driver(conn, schema, source_pages=list(pages))
        t0 = time.perf_counter()
        driver.run_to_completion()
        times.append(time.perf_counter() - t0)
        result = sink.pages
    # first run pays compilation; take the best of the rest
    best = min(times[1:]) if len(times) > 1 else times[0]
    return total_rows, best, result


def cpu_baseline(schema: str) -> float:
    cache_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              ".bench_cpu_cache.json")
    cache = {}
    if os.path.exists(cache_path):
        try:
            cache = json.load(open(cache_path))
        except Exception:
            cache = {}
    if schema in cache:
        return cache[schema]
    env = dict(os.environ, BENCH_FORCE_CPU="1")
    out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                         env=env, capture_output=True, text=True,
                         timeout=3600)
    rate = None
    for line in out.stdout.splitlines():
        try:
            j = json.loads(line)
            rate = j["value"]
        except Exception:
            continue
    if rate is None:
        sys.stderr.write("cpu baseline failed:\n" + out.stdout + out.stderr)
        return 0.0
    cache[schema] = rate
    json.dump(cache, open(cache_path, "w"))
    return rate


def main():
    schema = os.environ.get("BENCH_SCHEMA", "tiny")
    platform = "" if FORCE_CPU else ensure_backend()
    rows, secs, _ = run_q1(schema)
    rate = rows / secs
    if FORCE_CPU:
        print(json.dumps({"metric": f"tpch_q1_{schema}_rows_per_sec",
                          "value": rate, "unit": "rows/s",
                          "vs_baseline": 1.0}))
        return
    base = cpu_baseline(schema)
    # a CPU-fallback run must not masquerade as a per-chip TPU number
    suffix = "_cpu_fallback" if platform == "cpu" else "_per_chip"
    print(json.dumps({
        "metric": f"tpch_q1_{schema}_rows_per_sec{suffix}",
        "value": round(rate, 1),
        "unit": "rows/s",
        "vs_baseline": round(rate / base, 3) if base else 0.0,
    }))


if __name__ == "__main__":
    main()
