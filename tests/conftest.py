"""Test harness configuration.

Mirrors the reference's two-runner strategy (SURVEY.md §4): fast in-process
tests on a SIMULATED multi-device mesh — 8 virtual CPU devices via
``xla_force_host_platform_device_count`` — so distributed sharding/collective
paths compile and run without TPU hardware (reference analog:
``testing/trino-testing/.../DistributedQueryRunner.java`` spinning N servers
in one JVM).

Must run before jax initializes, hence environment mutation at import time.
"""

import os
import sys

# assignment, not setdefault: the axon sitecustomize pre-sets
# JAX_PLATFORMS=axon (the real-TPU tunnel); tests run on the virtual mesh
_platform = os.environ.get("TRINO_TPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# the axon sitecustomize imports jax at interpreter startup, so env vars
# alone are too late — force platform + persistent compile cache (repeat
# test runs skip XLA compilation) through the live config
import jax  # noqa: E402

if _platform:
    jax.config.update("jax_platforms", _platform)
jax.config.update("jax_compilation_cache_dir", "/tmp/trino_tpu_jax_cache")
# persist sub-second compiles too: the suite triggers hundreds of small
# XLA programs (one per page shape/kernel combo) and re-compiling them
# every run costs minutes against the tier-1 budget; disk is cheap
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _cap_memory_maps():
    """Every compiled XLA executable pins ~30 memory maps for the life
    of the process; a full tier-1 run accumulates enough programs to
    cross the kernel's default ``vm.max_map_count`` (65530) near the
    90% mark, and the failing ``mmap`` surfaces as a segfault (or hang)
    inside XLA's next compile or compile-cache read.  Dropping the
    in-process executable caches between modules once the count gets
    high keeps the run bounded — the on-disk compilation cache makes
    the reload of still-needed kernels cheap."""
    yield
    try:
        with open("/proc/self/maps") as f:
            n = sum(1 for _ in f)
    except OSError:
        return
    if n > 35_000:
        import gc

        jax.clear_caches()
        gc.collect()


@pytest.fixture(autouse=True)
def _isolate_template_seeds():
    """The round-17 template-seed store is process-global (like the HBO
    stats store); without clearing it between tests, one test's earned
    shapes let a LATER test's fresh runner ride a template on its first
    use — admission-timing assertions then depend on test order."""
    yield
    from trino_tpu.cache import template_seeds

    template_seeds().clear()
