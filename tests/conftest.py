"""Test harness configuration.

Mirrors the reference's two-runner strategy (SURVEY.md §4): fast in-process
tests on a SIMULATED multi-device mesh — 8 virtual CPU devices via
``xla_force_host_platform_device_count`` — so distributed sharding/collective
paths compile and run without TPU hardware (reference analog:
``testing/trino-testing/.../DistributedQueryRunner.java`` spinning N servers
in one JVM).

Must run before jax initializes, hence environment mutation at import time.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
