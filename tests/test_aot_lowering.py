"""AOT TPU-lowering smoke tests (VERDICT weak #2).

Five rounds produced zero TPU executions, so Mosaic/layout failures in
the flagship kernels could hide until a chip appears. ``jax.export``
lowers a jitted program for an EXPLICIT target platform without
initializing that platform's backend — Pallas kernels go through the
real Mosaic lowering and sharded programs through SPMD partitioning —
so tile/layout violations surface right here on the CPU-only CI host.
(The original segment-reduce block spec really did fail this lowering:
a (1, C) block over an (n_chunks, C) array breaks the (8, 128) sublane
tiling rule whenever n_chunks > 1; it only ever ran in interpret mode.)

These assert lowering SUCCEEDS; executing the artifacts still needs
hardware (the bench's job).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax import export
from jax.sharding import Mesh

from trino_tpu import types as T

sds = jax.ShapeDtypeStruct


def _export_tpu(fn, *args):
    return export.export(fn, platforms=["tpu"])(*args)


@pytest.mark.parametrize("kind", ["sum", "min", "max"])
@pytest.mark.parametrize("dtype", [jnp.int32, jnp.float32])
def test_pallas_segment_reduce_lowers_for_tpu(kind, dtype):
    """The compiled (interpret=False) Pallas path must pass Mosaic
    lowering for every kind x dtype it claims to support — including
    the multi-chunk grid (n > _CHUNK) that the old block spec broke."""
    from trino_tpu.ops.pallas_kernels import _CHUNK, _segment_reduce_pallas

    n = 4 * _CHUNK  # multi-chunk: exercises the blocked grid

    def fn(col, gid):
        return _segment_reduce_pallas(col, gid, 200, kind,
                                      interpret=False)

    ex = _export_tpu(jax.jit(fn), sds((n,), dtype), sds((n,), jnp.int32))
    assert "tpu" in ex.platforms


def test_device_exchange_program_lowers_for_tpu():
    """The data all_to_all program (shard_map + collective) against an
    8-device TPU-platform lowering."""
    from trino_tpu.parallel.device_exchange import _exchange_program

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("x",))
    types_ = (T.BIGINT, T.BIGINT)
    # .jit: the profiler wrapper keeps the raw jit product for
    # export (jax.export requires the jit callable itself)
    prog = _exchange_program(mesh, types_, (0,), 8, 8, 32).jit
    cap = 128
    cols = tuple(sds((8, cap), jnp.int64) for _ in types_)
    nulls = tuple(sds((8, cap), jnp.bool_) for _ in types_)
    ex = _export_tpu(prog, cols, nulls, sds((8, cap), jnp.bool_), (),
                     sds((8,), jnp.int32))  # the hot-partition mask
    assert "tpu" in ex.platforms


def test_count_program_lowers_for_tpu():
    """The count-first sizing collective (psum/pmax of histograms)."""
    from trino_tpu.parallel.device_exchange import _count_program

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("x",))
    types_ = (T.BIGINT, T.BIGINT)
    prog = _count_program(mesh, types_, (0,), 8, 8).jit
    cap = 128
    cols = tuple(sds((8, cap), jnp.int64) for _ in types_)
    nulls = tuple(sds((8, cap), jnp.bool_) for _ in types_)
    ex = _export_tpu(prog, cols, nulls, sds((8, cap), jnp.bool_), ())
    assert "tpu" in ex.platforms


def test_matmul_join_probe_lowers_for_tpu():
    """The blocked one-hot matmul probe + the build-table construction
    (ops/matmul_join.py) — the MXU path must pass real TPU lowering
    including the fori_loop'd dynamic-slice matmul grid."""
    from functools import partial as _partial

    from trino_tpu.ops.matmul_join import (_build_code_table,
                                           _matmul_lo_count)

    m, kp = 4096, 1024
    ex = _export_tpu(
        _matmul_lo_count.jit,
        sds((m,), jnp.uint64), sds((m,), jnp.bool_),
        sds((), jnp.uint64), sds((), jnp.uint64),
        sds((kp, 2), jnp.float32))
    assert "tpu" in ex.platforms
    ex = _export_tpu(
        jax.jit(_partial(_build_code_table, kp=kp)),
        sds((8192,), jnp.uint64), sds((), jnp.uint64),
        sds((), jnp.uint64))
    assert "tpu" in ex.platforms


def test_global_hash_agg_program_lowers_for_tpu():
    """The global-hash aggregation SPMD program (replicated-table claim
    loop with pmin-agreed inserts + collective scatter-add reduce)
    against an 8-device TPU-platform lowering."""
    from functools import partial as _partial

    from trino_tpu.ops.global_hash_agg import (global_hash_insert,
                                               global_hash_reduce,
                                               pack_keys)
    from trino_tpu.parallel.exchange import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("x",))
    ts, n = 256, 8

    @_partial(shard_map, mesh=mesh, in_specs=(P("x"),) * 3,
              out_specs=(P("x"),) * 3, check_vma=False)
    def prog(keys, vals, valid):
        k, v, va = keys[0], vals[0], valid[0]
        packed = pack_keys([k], [None], (32,))
        table, slot_of, resolved, unresolved = global_hash_insert(
            packed, va, ts, axis_name="x")
        sums, cnts = global_hash_reduce(
            slot_of, resolved, va, (v, va.astype(jnp.int64)),
            ("sum", "sum"), ts, axis_name="x")
        i = jax.lax.axis_index("x")
        sh = ts // n
        return (jax.lax.dynamic_slice(table, (i * sh,), (sh,))[None],
                jax.lax.dynamic_slice(sums, (i * sh,), (sh,))[None],
                unresolved[None])

    cap = 512
    ex = _export_tpu(jax.jit(prog), sds((8, cap), jnp.int64),
                     sds((8, cap), jnp.int64), sds((8, cap), jnp.bool_))
    assert "tpu" in ex.platforms


def test_q1_device_step_lowers_for_tpu():
    """The flagship fused filter+project+group-aggregate step — the
    program ``__graft_entry__.entry`` compiles on the real chip."""
    from trino_tpu.benchmarks import q1_example_args

    step, args = q1_example_args()
    ex = _export_tpu(jax.jit(step), *jax.eval_shape(lambda: args))
    assert "tpu" in ex.platforms
