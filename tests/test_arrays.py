"""ARRAY type (pooled composites), UNNEST, CHAR(n) padding.

Reference analog: ``spi/type/ArrayType`` + ``operator/unnest/`` +
ArrayFunctions/ArraySubscriptOperator tests. Arrays here are the
string strategy generalized: device lanes hold int32 codes into a host
pool of tuples, so grouping/joins/sorting run on codes/ranks and
element access is a LUT gather.
"""

import pytest

from trino_tpu import types as T
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.parallel.distributed import DistributedQueryRunner
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=2048)},
                            Session(catalog="tpch", schema="micro"))


def q(runner, sql):
    return runner.execute(sql).rows


def test_array_literal_and_functions(runner):
    assert q(runner, "select array[1,2,3]") == [([1, 2, 3],)]
    assert q(runner, "select cardinality(array[1,2,3]), "
                     "array[10,20,30][2], element_at(array[1], 5), "
                     "contains(array['a','b'], 'b'), "
                     "array_join(array['x','y'], '-'), "
                     "array_min(array[3,1,2]), "
                     "array_max(array['a','c'])") == \
        [(3, 20, None, True, "x-y", 1, "c")]


def test_split_and_subscript_on_column(runner):
    rows = q(runner, "select split(n_name, ' ')[1] from nation "
                     "where n_nationkey in (23, 24) order by n_nationkey")
    assert rows == [("UNITED",), ("UNITED",)]
    rows = q(runner, "select split(n_name, ' ') from nation "
                     "where n_nationkey = 23")
    assert rows == [(["UNITED", "KINGDOM"],)]


def test_array_equality_and_grouping(runner):
    assert q(runner, "select array[1,2] = array[1,2], "
                     "array[1,2] = array[1,3]") == [(True, False)]
    rows = q(runner, """
        select split(n_name, ' ')[1] w, count(*) c from nation
        group by 1 order by c desc, w limit 1""")
    assert rows == [("UNITED", 2)]


def test_unnest_standalone(runner):
    assert q(runner, "select * from unnest(array[1,2,3]) t(x)") == \
        [(1,), (2,), (3,)]
    assert q(runner, "select x, o from unnest(array['a','b']) "
                     "with ordinality t(x, o)") == [("a", 1), ("b", 2)]
    # multi-array zip pads the shorter with NULL
    assert q(runner, "select * from unnest(array[1,2], "
                     "array['a','b','c']) t(x, y)") == \
        [(1, "a"), (2, "b"), (None, "c")]


def test_unnest_correlated(runner):
    rows = q(runner, """
        select n_name, w from nation
        cross join unnest(split(n_name, ' ')) t(w)
        where n_nationkey = 23""")
    assert rows == [("UNITED KINGDOM", "UNITED"),
                    ("UNITED KINGDOM", "KINGDOM")]
    rows = q(runner, """
        select w, count(*) c from nation
        cross join unnest(split(n_name, ' ')) t(w)
        group by w order by c desc, w limit 2""")
    assert rows == [("UNITED", 2), ("ALGERIA", 1)]


def test_array_wire_serde():
    from trino_tpu.block import Block, Page
    from trino_tpu.exec.serde import PageDeserializer, PageSerializer

    t = T.array_type(T.BIGINT)
    page = Page([Block.from_pylist(t, [(1, 2), (3,), None])], 3)
    out = PageDeserializer().deserialize(PageSerializer().serialize(page))
    assert out.to_rows() == [([1, 2],), ([3],), (None,)]


def test_char_padding_semantics(runner):
    rows = q(runner, "select cast('ab' as char(5)), "
                     "cast('abcdefgh' as char(3))")
    assert rows == [("ab   ", "abc")]
    # equal-length CHARs compare by padded value: trailing spaces in
    # the source don't matter
    assert q(runner, "select cast('x' as char(3)) = "
                     "cast('x  ' as char(3))") == [(True,)]


def test_array_type_parsing():
    t = T.parse_type("array(bigint)")
    assert t.is_array and t.element == T.BIGINT
    assert T.parse_type("array(varchar)").element.is_string


def test_derived_string_grouping_regression(runner):
    """Grouping on a DERIVED string (aligned pool: one value, many
    codes) must group by value, not raw code."""
    rows = q(runner, """
        select substr(n_name, 1, 1) c, count(*) n from nation
        group by 1 order by n desc, c limit 3""")
    assert rows == [("I", 4), ("A", 2), ("C", 2)]
    rows = q(runner, """
        select upper(r_name) u, count(*) from region
        group by 1 order by u""")
    assert len(rows) == 5 and all(n == 1 for _, n in rows)
    # window partitions share the rank-canonical contract
    rows = q(runner, """
        select distinct substr(n_name, 1, 1) c,
               count(*) over (partition by substr(n_name, 1, 1)) n
        from nation order by n desc, c limit 2""")
    assert rows == [("I", 4), ("A", 2)]


def test_map_type(runner):
    assert q(runner, "select map(array['a','b'], array[1,2])") == \
        [({"a": 1, "b": 2},)]
    assert q(runner, "select map(array['a','b'], array[1,2])['b'], "
                     "map(array['a'], array[1])['z'], "
                     "cardinality(map(array['a','b'], array[1,2]))") == \
        [(2, None, 2)]
    # construction order does not matter: maps normalize to sorted pairs
    assert q(runner, "select map(array['b','a'], array[2,1]) = "
                     "map(array['a','b'], array[1,2])") == [(True,)]
    assert q(runner, "select map_keys(map(array['b','a'], array[2,1])), "
                     "map_values(map(array['b','a'], array[2,1]))") == \
        [(["a", "b"], [1, 2])]
    assert q(runner, "select element_at(map(array[10,20], "
                     "array['x','y']), 20)") == [("y",)]


def test_map_validation_and_ordering(runner):
    import pytest as _pytest

    from trino_tpu.types import TrinoError

    with _pytest.raises(TrinoError, match="same length"):
        q(runner, "select map(array['a','b'], array[1])")
    with _pytest.raises(TrinoError, match="Duplicate map keys"):
        q(runner, "select map(array['a','a'], array[1,2])")
    with _pytest.raises(TrinoError, match="cannot be null"):
        q(runner, "select map(array['a', null], array[1,2])")
    with _pytest.raises(TrinoError, match="not orderable"):
        q(runner, "select map(array['a'], array[1]) < "
                  "map(array['a'], array[2])")
    with _pytest.raises(TrinoError, match="does not match"):
        q(runner, "select map(array['a'], array[1])[123]")


def test_map_wire_serde():
    from trino_tpu.block import Block, Page
    from trino_tpu.exec.serde import PageDeserializer, PageSerializer

    t = T.map_type(T.VARCHAR, T.INTEGER)
    page = Page([Block.from_pylist(t, [{"a": 1, "b": 2}, None])], 2)
    out = PageDeserializer().deserialize(PageSerializer().serialize(page))
    assert out.to_rows() == [({"a": 1, "b": 2},), (None,)]


def test_row_type(runner):
    assert q(runner, "select (1, 'a')") == [((1, "a"),)]
    assert q(runner, "select (1, 'a')[2], (1, 'a')[1]") == [("a", 1)]
    assert q(runner, "select (1, 'a') = (1, 'a'), "
                     "(1, 'a') = (1, 'b')") == [(True, False)]


def test_row_wire_serde():
    from trino_tpu.block import Block, Page
    from trino_tpu.exec.serde import PageDeserializer, PageSerializer

    t = T.row_type([(None, T.BIGINT), (None, T.VARCHAR)])
    page = Page([Block.from_pylist(t, [(1, "a"), None])], 2)
    out = PageDeserializer().deserialize(PageSerializer().serialize(page))
    rows = out.to_rows()
    assert rows[0] == ((1, "a"),) and rows[1] == (None,)


def test_map_null_values_and_case(runner):
    # NULL map VALUES are legal and rank-comparable
    assert q(runner, "select map(array['a','b'], array[1, null]) = "
                     "map(array['a','b'], array[1, 2])") == [(False,)]
    rows = q(runner, """
        select m, count(*) from (
            select case when n_nationkey = 1
                        then map(array['a'], array[1]) end m
            from nation) group by m order by 2""")
    assert rows == [({"a": 1}, 1), (None, 24)]
    import pytest as _pytest

    from trino_tpu.types import TrinoError

    with _pytest.raises(TrinoError, match="does not match"):
        q(runner, "select element_at(map(array['a'], array[1]), 123)")
    with _pytest.raises(TrinoError, match="not orderable"):
        q(runner, "select m from (select map(array['a'], array[1]) m) "
                  "order by m")


def test_array_join_keys_remap_regression(runner):
    """Equi-join on ARRAY keys must remap probe-pool codes into the
    build pool (round-3 advisor: is_pooled, not is_string, gates the
    canonicalize/remap path — raw cross-pool code equality is wrong)."""
    plain = q(runner, """
        select count(*) from
          (select substr(n_name, 1, 1) a from nation) x join
          (select substr(n_name, 1, 1) r from nation
           where n_nationkey >= 10) y
          on x.a = y.r""")
    arr = q(runner, """
        select count(*) from
          (select split(substr(n_name, 1, 1), '|') a from nation) x join
          (select split(substr(n_name, 1, 1), '|') r from nation
           where n_nationkey >= 10) y
          on x.a = y.r""")
    assert arr == plain and plain[0][0] > 0
    # semi-join path (IN over arrays)
    plain = q(runner, """
        select count(*) from nation where substr(n_name, 1, 1) in
          (select substr(n_name, 1, 1) from nation
           where n_nationkey < 3)""")
    arr = q(runner, """
        select count(*) from nation
        where split(substr(n_name, 1, 1), '|') in
          (select split(substr(n_name, 1, 1), '|') from nation
           where n_nationkey < 3)""")
    assert arr == plain and plain[0][0] > 0


def test_array_order_by_value_order(runner):
    """ORDER BY an ARRAY column sorts by VALUE rank, not pool
    insertion order (round-3 advisor: value_u64 must rank pooled
    types)."""
    arr = q(runner, """
        select n_nationkey from nation
        order by split(n_name, ' '), n_nationkey""")
    plain = q(runner, """
        select n_nationkey from nation
        order by n_name, n_nationkey""")
    assert arr == plain


def test_array_min_max_aggregates(runner):
    """min/max over ARRAY args reduce on value ranks, mapping back to
    codes (round-3 advisor: pooled, not just string, args)."""
    lo, hi = q(runner, "select min(n_name), max(n_name) from nation")[0]
    rows = q(runner, "select min(split(n_name, ' ')), "
                     "max(split(n_name, ' ')) from nation")
    assert rows == [(lo.split(' '), hi.split(' '))]


def test_window_min_max_pooled_args(runner):
    """Window min/max over string/array args rank-reduce per frame
    (round-3 advisor: the window kernel reduced raw pool codes)."""
    per_group = dict((r[0], r[1]) for r in q(runner, """
        select n_regionkey, min(n_name) from nation group by 1"""))
    rows = q(runner, """
        select n_regionkey, min(n_name) over (partition by n_regionkey)
        from nation""")
    for g, v in rows:
        assert v == per_group[g]
    arr_group = dict((r[0], r[1]) for r in q(runner, """
        select n_regionkey, max(split(n_name, ' ')) from nation
        group by 1"""))
    rows = q(runner, """
        select n_regionkey,
               max(split(n_name, ' ')) over (partition by n_regionkey)
        from nation""")
    for g, v in rows:
        assert v == arr_group[g]
