"""Single-launch batched execution (round 16).

The contract under test: a same-shape admission burst rides ONE plan
template whose literals are opaque ``ParamRef`` slots, executes every
vmappable pipeline stage as ONE device launch for the whole batch, and
demuxes per-statement results that are BYTE-EQUAL to the serial path —
with per-tenant ACL and the result cache enforced per member exactly as
serial execution would.  The fallback taxonomy must be loud (counted by
reason, never silently wrong), and a repeat burst must perform ZERO new
jit traces with exactly one launch per vmapped stage, profiler-counted
independent of the batch depth B.
"""

import numpy as np
import pytest

from trino_tpu import jit_stats
from trino_tpu import types as T
from trino_tpu.block import Block, Page
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.expr.ir import Literal, ParamRef, param_indices
from trino_tpu.ops.output import OutputBuffer
from trino_tpu.runner import LocalQueryRunner, QueryResult
from trino_tpu.security import (AccessDeniedError, RuleBasedAccessControl,
                                TableRule)
from trino_tpu.sql.analyzer import Session


def _mem_runner(**kwargs):
    return LocalQueryRunner({"memory": MemoryConnector()},
                            Session(catalog="memory", schema="default"),
                            **kwargs)


@pytest.fixture()
def runner():
    r = _mem_runner()
    r.execute("create table t (k bigint, v bigint)")
    r.execute("insert into t values (1, 10), (2, 20), (3, 30), "
              "(4, 40), (5, 50), (6, 60), (7, 70), (8, 80)")
    return r


BURST = ["select v from t where k = %d" % i for i in range(1, 9)]
EXPECT = [[(10 * i,)] for i in range(1, 9)]


# -- IR opacity -----------------------------------------------------------


def test_paramref_is_not_a_literal():
    """The whole template design rests on this: every plan-time
    constant reader is ``isinstance(_, Literal)``-gated, so ParamRef is
    opaque BY CONSTRUCTION, not by auditing each reader."""
    p = ParamRef(T.BIGINT, 0)
    assert not isinstance(p, Literal)
    assert param_indices(p) == {0}
    from trino_tpu.expr.ir import Call
    expr = Call("add", T.BIGINT, (ParamRef(T.BIGINT, 1),
                                  Literal(T.BIGINT, 5)))
    assert param_indices(expr) == {1}


# -- serial template reuse ------------------------------------------------


def test_serial_template_reuse_across_literals(runner):
    """Second-and-later uses of a shape ride the template: same root,
    different literal bindings, correct per-literal rows."""
    r1 = runner.execute("select v from t where k = 1")
    r2 = runner.execute("select v from t where k = 2")
    r3 = runner.execute("select v from t where k = 3")
    assert (r1.rows, r2.rows, r3.rows) == ([(10,)], [(20,)], [(30,)])
    # first use misses (below min_shape_uses), later ones hit
    assert r1.stats.get("plan_template") is None
    assert r2.stats.get("plan_template") == "hit"
    assert r3.stats.get("plan_template") == "hit"
    tc = runner.query_cache.templates
    assert tc.builds == 1 and tc.hits >= 1
    assert not tc.fallbacks


def test_template_disabled_by_session_property(runner):
    runner.execute("set session plan_template_enabled = false")
    for i in (1, 2, 3):
        res = runner.execute("select v from t where k = %d" % i)
        assert res.stats.get("plan_template") is None
    assert runner.query_cache.templates.builds == 0


# -- batched execution: byte-equality matrix ------------------------------


def test_batch_matches_serial_oracle(runner):
    serial = [runner.execute(s).rows for s in BURST]
    fresh = _mem_runner()
    fresh.execute("create table t (k bigint, v bigint)")
    fresh.execute("insert into t values (1, 10), (2, 20), (3, 30), "
                  "(4, 40), (5, 50), (6, 60), (7, 70), (8, 80)")
    out = fresh.execute_batch(BURST)
    assert [o.rows for o in out] == serial == EXPECT
    assert all(o.stats.get("plan_template") == "hit" for o in out)
    assert fresh.query_cache.batched_launches == 8


def test_batch_mixed_literals_and_duplicates(runner):
    """Identical literal vectors coalesce to one lane; results still
    demux to every submitter positionally."""
    sqls = [BURST[0], BURST[3], BURST[0], BURST[5], BURST[3]]
    out = runner.execute_batch(sqls)
    assert [o.rows for o in out] == [EXPECT[0], EXPECT[3], EXPECT[0],
                                     EXPECT[5], EXPECT[3]]


def test_batch_failing_member_demuxes_positionally(runner):
    """A statement that fails analysis fails ONLY its own slot; the
    healthy same-shape members still batch."""
    sqls = [BURST[0], "select nope from t where k = 2", BURST[2]]
    out = runner.execute_batch(sqls)
    assert out[0].rows == EXPECT[0]
    assert isinstance(out[1], Exception)
    assert out[2].rows == EXPECT[2]


def test_batch_mixed_shapes_grouped(runner):
    """Two interleaved shapes each batch within their own group."""
    sqls = [BURST[0], "select k from t where v = 20", BURST[2],
            "select k from t where v = 40", BURST[4],
            "select k from t where v = 60"]
    out = runner.execute_batch(sqls)
    assert [o.rows for o in out] == [EXPECT[0], [(2,)], EXPECT[2],
                                     [(4,)], EXPECT[4], [(6,)]]


def test_batch_mixed_tenants_acl_enforced_per_member():
    """Per-tenant ACL is enforced per STATEMENT: the denied tenant's
    member fails with AccessDenied, everyone else's lanes execute."""
    acl = RuleBasedAccessControl([
        TableRule(user="alice", privileges=["SELECT"]),
    ])
    r = LocalQueryRunner({"memory": MemoryConnector()},
                         Session(catalog="memory", schema="default"),
                         access_control=acl)
    # seed as alice (the only user with write-side privileges absent;
    # memory DDL goes through create/insert checks — use ALLOW_ALL
    # runner to seed, sharing the connector)
    seed = LocalQueryRunner(r.metadata.connectors,
                            Session(catalog="memory", schema="default"))
    seed.execute("create table t (k bigint, v bigint)")
    seed.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    out = r.execute_batch(["select v from t where k = 1",
                           "select v from t where k = 2"], user="alice")
    assert [o.rows for o in out] == [[(10,)], [(20,)]]
    out2 = r.execute_batch(["select v from t where k = 1",
                            "select v from t where k = 2"], user="mallory")
    # execute_batch itself raises for a user denied query execution?
    # RuleBasedAccessControl only gates tables here, so both members
    # fail the per-member table check positionally
    assert all(isinstance(o, AccessDeniedError) for o in out2)


def test_batch_result_cache_hit_short_circuits_lane(runner):
    """A member whose full key hits the result cache is served WITHOUT
    occupying a vmap lane — and stores from batched lanes feed later
    serial hits byte-equally."""
    runner.execute("set session result_cache_enabled = true")
    runner.execute(BURST[0])                      # seed result cache
    before = runner.query_cache.batched_launches
    out = runner.execute_batch([BURST[0], BURST[1], BURST[2]])
    assert [o.rows for o in out] == EXPECT[:3]
    assert out[0].stats.get("result_cache") == "hit"
    assert runner.query_cache.result_shortcircuits == 1
    # only the two cache-missing members occupied lanes (padded to 2)
    assert runner.query_cache.batched_launches - before == 2
    # lane-computed results landed in the result cache for serial reuse
    assert runner.execute(BURST[1]).stats.get("result_cache") == "hit"


def test_batch_zero_traces_and_single_launch_per_stage(runner):
    """THE acceptance witness: a repeat same-shape burst of 8 performs
    ZERO new jit traces and each vmapped stage runs as exactly ONE
    device launch, profiler-counted independent of B."""
    from trino_tpu.telemetry import profiler as prof

    assert [o.rows for o in runner.execute_batch(BURST)] == EXPECT
    prof.reset()
    before = jit_stats.counts()
    with prof.profiling(True):
        out = runner.execute_batch(BURST)
        snap = prof.snapshot()
    after = jit_stats.counts()
    assert [o.rows for o in out] == EXPECT
    assert after == before, "repeat burst must not trace anything new"
    batched = [e for e in snap if e["name"] == "page_processor_batched"]
    assert batched, "burst did not ride the vmapped entry"
    assert all(e["calls"] == 1 for e in batched), \
        [(e["key"], e["calls"]) for e in batched]
    # nothing fell back to per-statement serial launches
    assert not any(e["name"] == "page_processor" and e["calls"] > 0
                   for e in snap)


def test_batch_depth_chunking(runner):
    """Bursts beyond batched_execution_max_depth chunk; every chunk
    demuxes correctly."""
    runner.execute("set session batched_execution_max_depth = 4")
    out = runner.execute_batch(BURST)
    assert [o.rows for o in out] == EXPECT
    depths = {o.stats.get("batched_depth") for o in out}
    assert depths == {4}


def test_batch_depth_padding_power_of_two(runner):
    """B=3 pads to the 4-lane bucket (bounded jit cache size), and the
    padding lane's rows are discarded."""
    out = runner.execute_batch(BURST[:3])
    assert [o.rows for o in out] == EXPECT[:3]
    assert {o.stats.get("batched_depth") for o in out} == {4}


# -- fallback taxonomy ----------------------------------------------------


def test_fallback_string_param(runner):
    runner.execute("create table s (name varchar, v bigint)")
    runner.execute("insert into s values ('a', 1), ('b', 2)")
    sqls = ["select v from s where name = 'a'",
            "select v from s where name = 'b'"]
    out = runner.execute_batch(sqls)
    assert [o.rows for o in out] == [[(1,)], [(2,)]]
    assert runner.query_cache.templates.fallbacks.get("string_param")


def test_fallback_ordinal_param(runner):
    """GROUP BY 1 ordinals are extracted as literals — the silent
    value-dependence hazard the pre-walk guard catches BEFORE any
    planning: templating the ordinal would re-aim the grouping key."""
    sqls = ["select k, count(*) from t where v > %d group by 1" % i
            for i in (5, 25)]
    out = runner.execute_batch(sqls)
    assert sorted(out[0].rows) == [(i, 1) for i in range(1, 9)]
    assert sorted(out[1].rows) == [(i, 1) for i in range(3, 9)]
    assert runner.query_cache.templates.fallbacks.get("ordinal_param")


def test_fallback_value_dependent(runner):
    """A literal the compiled path NEEDS as a python value — the lag()
    window offset shifts by a trace-time constant — fails the trial
    plan and falls back loudly at template build, never silently."""
    sqls = ["select lag(v, %d) over (order by k) from t" % i
            for i in (1, 2)]
    out = runner.execute_batch(sqls)
    assert out[0].rows[:3] == [(None,), (10,), (20,)]
    assert out[1].rows[:3] == [(None,), (None,), (10,)]
    fb = runner.query_cache.templates.fallbacks
    assert fb.get("value_dependent"), fb


def test_fallback_plan_shape_not_vmappable(runner):
    """A stage the masked pipeline genuinely cannot vmap (ORDER BY's
    sort) still answers correctly — through the serial path — and
    counts the round-17 taxonomy reason, not a catch-all."""
    sqls = ["select v from t where k > %d order by v" % i for i in (5, 6)]
    out = runner.execute_batch(sqls)
    assert [o.rows for o in out] == [[(60,), (70,), (80,)],
                                     [(70,), (80,)]]
    fb = runner.query_cache.templates.fallbacks
    assert fb.get("unsupported_stage") == 1, fb
    assert runner.query_cache.batched_launches == 0


def test_global_aggregation_now_vmaps(runner):
    """The round-16 fallback case — count(*) over a filtered scan — is
    a masked vmapped lane as of round 17: no fallback, one batched
    launch per member, byte-equal demux."""
    sqls = ["select count(*) from t where k > %d" % i for i in (1, 2)]
    out = runner.execute_batch(sqls)
    assert [o.rows for o in out] == [[(7,)], [(6,)]]
    assert not runner.query_cache.templates.fallbacks
    assert runner.query_cache.templates.dispositions.get(
        "agg_stage_vmapped") == 1
    assert runner.query_cache.batched_launches == 2


def test_nondeterministic_and_writes_never_batch(runner):
    out = runner.execute_batch(
        ["insert into t values (100, 1000)",
         "insert into t values (100, 1000)"])
    assert all(not isinstance(o, Exception) for o in out)
    # both INSERTs ran (no coalescing, no template)
    assert runner.execute("select count(*) from t where k = 100"
                          ).rows == [(2,)]
    assert runner.query_cache.batched_launches == 0


def test_batched_execution_disabled_property(runner):
    runner.execute("set session batched_execution_enabled = false")
    out = runner.execute_batch(BURST)
    assert [o.rows for o in out] == EXPECT
    assert runner.query_cache.batched_launches == 0


# -- metrics surface ------------------------------------------------------


def test_template_counters_scrapeable(runner):
    runner.execute_batch(BURST)
    c = runner.query_cache.counters()
    for key in ("template_hits", "template_misses", "template_builds",
                "template_fallbacks", "template_entries",
                "batched_launches", "result_shortcircuits"):
        assert key in c, key
    assert c["template_builds"] >= 1
    assert c["batched_launches"] >= 8
    fams = runner.metrics_families()
    names = {f["name"] for f in fams}
    assert "trino_plan_template_total" in names
    assert "trino_plan_template_entries" in names


# -- host hot-partition lanes (carried follow-on) -------------------------


def _page(v, rows=1):
    a = np.full(rows, v, dtype=np.int64)
    return Page([Block(T.BIGINT, a, None, None)], rows)


class TestOutputBufferHotLanes:
    def test_split_scales_capacity_and_full_needs_all_lanes(self):
        buf = OutputBuffer(4, max_pending_pages=2)
        buf.enqueue(1, _page(1))
        buf.enqueue(1, _page(2))
        assert buf.full([1])
        assert buf.split_partition(1, 4)
        assert not buf.full([1]), "extra lanes must add slack"
        for i in range(3, 11):
            buf.enqueue(1, _page(i))
        assert buf.full([1]), "full only when EVERY lane is at bound"

    def test_drain_preserves_rows_across_lanes(self):
        buf = OutputBuffer(2, max_pending_pages=4)
        buf.split_partition(0, 3)
        vals = list(range(10))
        for v in vals:
            buf.enqueue(0, _page(v))
        buf.set_no_more_pages()
        got = []
        while buf.has_page(0):
            p = buf.poll(0)
            got.append(int(np.asarray(p.block(0).data)[0]))
        assert buf.at_end(0)
        assert sorted(got) == vals
        assert buf.poll(0) is None

    def test_barrier_pages_snapshot_sees_all_lanes(self):
        buf = OutputBuffer(2)
        buf.split_partition(1, 2)
        for v in range(5):
            buf.enqueue(1, _page(v))
        assert len(buf.pages(1)) == 5
        assert buf.pages(0) == []

    def test_stats_parity_with_device_exchange(self):
        buf = OutputBuffer(4, max_pending_pages=2)
        buf.split_partition(2, 4)
        buf.enqueue(2, _page(7, rows=3))
        s = buf.stats
        assert s["hot_partitions"] == [2]
        assert s["splits"] == 1 and s["split_ways"] == 4
        assert s["hot_spread"] == {2: 4}
        assert s["partition_rows"][2] == 3

    def test_broadcast_and_merge_never_split(self):
        assert not OutputBuffer(2, broadcast=True).split_partition(0, 4)
        # merge-kind: the producer gate — hash-only callers request
        # splits; a merge operator never calls split_partition
        from trino_tpu.ops.output import PartitionedOutputOperator
        buf = OutputBuffer(2, max_pending_pages=2)
        op = PartitionedOutputOperator([T.BIGINT], [0], buf,
                                       kind="merge",
                                       hot_split_threshold=0.1)
        assert buf._hot_lanes == {}

    def test_hash_producer_splits_hot_partition(self):
        """One dominant key drives >threshold of rows -> its partition
        grows lanes automatically."""
        from trino_tpu.block import DevicePage
        from trino_tpu.ops.output import PartitionedOutputOperator

        buf = OutputBuffer(4, max_pending_pages=8)
        op = PartitionedOutputOperator([T.BIGINT, T.BIGINT], [0], buf,
                                       kind="hash",
                                       hot_split_threshold=0.5)
        keys = np.zeros(64, dtype=np.int64)       # all rows, one key
        vals = np.arange(64, dtype=np.int64)
        page = Page([Block(T.BIGINT, keys, None, None),
                     Block(T.BIGINT, vals, None, None)], 64)
        op.add_input(DevicePage.from_page(page))
        assert len(buf._hot_lanes) == 1
        (hot_p, ways), = buf._hot_lanes.items()
        assert ways == 4
        assert buf.stats["hot_partitions"] == [hot_p]
        # every row still lands in the hot partition's lanes
        total = sum(p.num_rows for p in buf.pages(hot_p))
        assert total == 64

    def test_unbounded_buffer_never_splits(self):
        from trino_tpu.block import DevicePage
        from trino_tpu.ops.output import PartitionedOutputOperator

        buf = OutputBuffer(4)    # barrier mode: no pending bound
        op = PartitionedOutputOperator([T.BIGINT], [0], buf,
                                       kind="hash",
                                       hot_split_threshold=0.5)
        keys = np.zeros(16, dtype=np.int64)
        page = Page([Block(T.BIGINT, keys, None, None)], 16)
        op.add_input(DevicePage.from_page(page))
        assert buf._hot_lanes == {}


# -- optimizer opacity ----------------------------------------------------


def test_optimizer_template_param_slots(runner):
    """The optimized template root reports its surviving ParamRef
    slots; a non-template plan reports none."""
    from trino_tpu.planner.optimizer import template_param_slots

    for i in (1, 2):
        runner.execute("select v from t where k = %d" % i)
    tc = runner.query_cache.templates
    (tmpl,) = [v for v in tc._entries.values()
               if not isinstance(v, str)]
    assert template_param_slots(tmpl.root) == (0,)
    plain = runner.plan_statement(
        runner.query_cache.parse("select v from t where k = 1",
                                 runner.session).stmt, hbo=None)
    assert template_param_slots(plain) == ()
    assert any(name == "PlanTemplate"
               for name, _ in tmpl.root.optimizer_trace)


# -- round 17: masked aggregation & join lanes ----------------------------


def _star_runner(nfact=64, nhot=0, **kwargs):
    """Star shape the batched join targets: a big param-filtered fact
    (probe) against a small param-free dim (build — the cost-based join
    order keeps the smaller side on the build)."""
    r = _mem_runner(**kwargs)
    r.execute("create table f (k bigint, v bigint)")
    r.execute("create table d (k bigint, w bigint)")
    r.execute("insert into f values "
              + ", ".join("(%d, %d)" % (i % 4, i) for i in range(nfact)))
    drows = ["(0, %d)" % i for i in range(nhot)] \
        + ["(%d, %d)" % (k, k * 10) for k in (0, 1, 2, 3)]
    r.execute("insert into d values " + ", ".join(drows))
    return r


def _rows(res):
    return sorted(res.rows, key=repr)


AGG_BURST = ["select k, count(*) c, sum(v) s from f where v > %d group by k"
             % (i * 7) for i in range(8)]


def test_batch_group_by_byte_equal_and_counted():
    serial = _star_runner()
    oracle = [_rows(serial.execute(s)) for s in AGG_BURST]
    r = _star_runner()
    out = r.execute_batch(AGG_BURST)
    assert [_rows(o) for o in out] == oracle
    assert r.query_cache.templates.dispositions.get(
        "agg_stage_vmapped") == 1
    assert not r.query_cache.templates.fallbacks
    assert r.query_cache.batched_launches == 8
    assert r.query_cache.batched_spills == 0


def test_batch_agg_zero_new_traces_on_repeat():
    """Repeat aggregating burst: ZERO new jit traces — the masked agg
    kernels are cached by shape config, never by operator identity."""
    r = _star_runner()
    first = r.execute_batch(AGG_BURST)
    before = jit_stats.counts()
    again = r.execute_batch(AGG_BURST)
    assert jit_stats.counts() == before, \
        "repeat agg burst must not trace anything new"
    assert [_rows(o) for o in again] == [_rows(o) for o in first]


def test_batch_agg_null_group_keys():
    """NULL group keys form their own group in every lane, byte-equal
    to serial (the mask must not conflate invalid rows with NULLs)."""
    serial, r = _star_runner(), _star_runner()
    for q in (serial, r):
        q.execute("insert into f values (null, 3), (null, 100), "
                  "(null, 200)")
    oracle = [_rows(serial.execute(s)) for s in AGG_BURST]
    out = r.execute_batch(AGG_BURST)
    assert [_rows(o) for o in out] == oracle


def test_batch_agg_all_rows_masked_empty_lane():
    """A member whose filter keeps ZERO rows yields an empty GROUP BY
    result from its all-masked lane while sibling lanes aggregate."""
    burst = ["select k, count(*) c from f where v > %d group by k" % x
             for x in (10, 10 ** 6, 20)]
    serial = _star_runner()
    oracle = [_rows(serial.execute(s)) for s in burst]
    assert oracle[1] == []
    r = _star_runner()
    out = r.execute_batch(burst)
    assert [_rows(o) for o in out] == oracle
    assert r.query_cache.templates.dispositions.get(
        "agg_stage_vmapped") == 1


@pytest.mark.parametrize("sql", [
    "select f.v, d.w from f join d on f.k = d.k where f.v > %d",
    "select f.v, d.w from f left join d on f.k = d.k where f.v > %d",
    "select v from f where k in (select k from d) and v > %d",
    "select v from f where k not in (select k from d) and v > %d",
], ids=["inner", "left", "semi", "anti"])
def test_batch_join_matrix_byte_equal(sql):
    burst = [sql % (i * 11) for i in range(8)]
    serial = _star_runner()
    # anti needs probe keys missing from the dim to produce rows
    oracle_extra = "insert into f values (7, 1), (8, 2), (9, 500)"
    serial.execute(oracle_extra)
    oracle = [_rows(serial.execute(s)) for s in burst]
    r = _star_runner()
    r.execute(oracle_extra)
    out = r.execute_batch(burst)
    assert [_rows(o) for o in out] == oracle
    assert r.query_cache.templates.dispositions.get(
        "join_stage_vmapped") == 1
    assert not r.query_cache.templates.fallbacks


def test_batch_join_then_group_by_one_pipeline():
    """Join AND aggregation in the same pipeline both vmap: the probe
    feeds masked expanded pages straight into the masked agg barrier."""
    burst = ["select f.k, count(*) c, sum(d.w) s from f join d "
             "on f.k = d.k where f.v > %d group by f.k" % (i * 17)
             for i in range(8)]
    serial = _star_runner()
    oracle = [_rows(serial.execute(s)) for s in burst]
    r = _star_runner()
    out = r.execute_batch(burst)
    assert [_rows(o) for o in out] == oracle
    disp = r.query_cache.templates.dispositions
    assert disp.get("join_stage_vmapped") == 1
    assert disp.get("agg_stage_vmapped") == 1
    assert not r.query_cache.templates.fallbacks


def test_batch_lane_overflow_falls_back_alone():
    """One member probes a hot build key hard enough to overflow the
    unified expansion capacity: THAT lane alone replays serially
    (counted ``lane_overflow``); sibling lanes keep their vmapped
    results, all byte-equal."""
    burst = ["select count(*) from f join d on f.k = d.k where f.v < %d"
             % x for x in (16, 32, 16, 900)]
    serial = _star_runner(1024, nhot=64)
    oracle = [_rows(serial.execute(s)) for s in burst]
    r = _star_runner(1024, nhot=64)
    r.execute("set session join_max_expand_lanes = 1024")
    out = r.execute_batch(burst)
    assert [_rows(o) for o in out] == oracle
    assert r.query_cache.batched_spills == 1
    assert r.query_cache.templates.fallbacks.get("lane_overflow") == 1
    # the two x=16 members coalesced; of the 3 lanes, 2 stayed vmapped
    assert r.query_cache.batched_launches == 2


def test_batch_agg_failing_member_demuxes_positionally():
    r = _star_runner()
    sqls = [AGG_BURST[0], "select nope from f group by k", AGG_BURST[2]]
    out = r.execute_batch(sqls)
    assert not isinstance(out[0], Exception)
    assert isinstance(out[1], Exception)
    assert not isinstance(out[2], Exception)
    serial = _star_runner()
    assert _rows(out[0]) == _rows(serial.execute(AGG_BURST[0]))
    assert _rows(out[2]) == _rows(serial.execute(AGG_BURST[2]))


def test_batch_agg_denied_member_demuxes_positionally():
    """A member denied by ACL fails ONLY its own slot; the aggregating
    siblings still ride the vmapped lane."""
    acl = RuleBasedAccessControl([
        TableRule(user="alice", table="f|d", privileges=["SELECT"]),
    ])
    seed = _star_runner()
    seed.execute("create table secret (k bigint)")
    seed.execute("insert into secret values (1)")
    r = LocalQueryRunner(seed.metadata.connectors,
                         Session(catalog="memory", schema="default"),
                         access_control=acl)
    out = r.execute_batch(
        [AGG_BURST[0], "select k from secret", AGG_BURST[2]],
        user="alice")
    assert not isinstance(out[0], Exception)
    assert isinstance(out[1], AccessDeniedError)
    assert not isinstance(out[2], Exception)


def test_batched_burst_records_hbo_actuals():
    """Satellite 1: batched lanes feed HBO again — per-lane actuals are
    EXACT mask popcounts (padded lanes excluded), recorded per member
    under the shared statement fingerprint."""
    from trino_tpu.telemetry import stats_store

    stats_store.store().clear()
    try:
        r = _star_runner()
        out = r.execute_batch(AGG_BURST[:4])
        assert all(not isinstance(o, Exception) for o in out)
        c = stats_store.store().counters()
        assert c["records"] == 4, c
        snap = stats_store.store().snapshot()
        names = {e["name"] for e in snap}
        assert "TableScanOperator" in names
        assert "HashAggregationOperator" in names
        assert all(e["rows"] >= 0 for e in snap)
    finally:
        stats_store.store().clear()


def test_disposition_taxonomy_and_legacy_alias():
    """Satellite 2: dispositions say what actually ran; the retired
    ``non_fp_stage`` key stays scrapeable one release as an alias of
    ``unsupported_stage``."""
    r = _star_runner()
    r.execute_batch(AGG_BURST)
    r.execute_batch(["select v from f where k > %d order by v" % i
                     for i in (1, 2)])
    disp = r.query_cache.templates.dispositions
    assert disp.get("agg_stage_vmapped") == 1
    fb = r.query_cache.templates.fallbacks
    assert fb.get("unsupported_stage") == 1
    fams = {f["name"]: f for f in r.metrics_families()}
    tmpl = fams["trino_plan_template_total"]
    by_label = {tuple(sorted(labels.items())): value
                for labels, value in tmpl["samples"]}
    legacy = by_label.get((("outcome", "fallback:non_fp_stage"),))
    assert legacy == 1, by_label
    assert by_label.get((("outcome", "fallback:unsupported_stage"),)) \
        == 1


# -- round 17: distributed template-seed coherence ------------------------


def test_template_seed_roundtrip_and_bounds():
    from trino_tpu.cache import TemplateSeedStore

    src = TemplateSeedStore()
    for i in range(40):
        src.note("fp%d" % i, i + 1)
    src.note_fallback_shape("bad", "value_dependent")
    seed = src.export_seed(max_shapes=8)
    assert len(seed["shapes"]) == 8
    hot = {fp for fp, _, _ in seed["shapes"][:7]}
    assert hot <= {"fp%d" % i for i in range(32, 40)}
    dst = TemplateSeedStore()
    assert dst.import_seed(seed) == 8
    assert dst.uses("fp39") == 40


def test_template_seed_max_merge_and_first_verdict_wins():
    """Use totals max-merge (a worker that observed MORE uses must not
    regress); a locally proven fallback verdict is never overwritten by
    a remote one."""
    from trino_tpu.cache import TemplateSeedStore

    dst = TemplateSeedStore()
    dst.note("s", 10)
    dst.note_fallback_shape("s", "string_param")
    src = TemplateSeedStore()
    src.note("s", 3)
    src.note_fallback_shape("s", "value_dependent")
    src.note("other", 7)
    assert dst.import_seed(src.export_seed()) == 1   # only "other" news
    assert dst.uses("s") == 10
    assert dst.fallback_reason("s") == "string_param"
    assert dst.uses("other") == 7


def test_template_seed_malformed_warns_and_imports_nothing():
    from trino_tpu.cache import TemplateSeedStore

    dst = TemplateSeedStore()
    with pytest.warns(RuntimeWarning, match="template seed"):
        assert dst.import_seed({"shapes": [["fp"]]}) == 0
    assert dst.corrupt_loads == 1
    assert dst.uses("fp") == 0


def test_seeded_runner_rides_template_on_first_statement():
    """THE coherence contract: a fresh (replacement) runner whose seed
    store carries an earned shape builds AND rides the template on its
    very FIRST statement — no local re-earn of min_shape_uses."""
    from trino_tpu.cache import template_seeds
    from trino_tpu.telemetry import stats_store
    from trino_tpu.telemetry.stats_store import statement_fingerprint

    # without this, the HBO statement hint could admit the build on its
    # own and mask a broken seed path
    stats_store.store().clear()
    probe = _mem_runner()
    probe.execute("create table t (k bigint, v bigint)")
    probe.execute("insert into t values (1, 10), (2, 20)")
    pq = probe.query_cache.parse("select v from t where k = 1",
                                 probe.session)
    template_seeds().note(statement_fingerprint(pq.shape), 50)

    r = _mem_runner()          # the "replacement worker"
    r.execute("create table t (k bigint, v bigint)")
    r.execute("insert into t values (1, 10), (2, 20)")
    res = r.execute("select v from t where k = 2")
    assert res.rows == [(20,)]
    assert res.stats.get("plan_template") == "hit"
    assert r.query_cache.templates.builds == 1


def test_seeded_fallback_skips_local_trial():
    """A cluster-proved value-dependent shape is negative-cached from
    the seed WITHOUT paying a local trial plan (builds stays 0)."""
    from trino_tpu.cache import template_seeds
    from trino_tpu.telemetry.stats_store import statement_fingerprint

    probe = _mem_runner()
    probe.execute("create table t (k bigint, v bigint)")
    probe.execute("insert into t values (1, 10), (2, 20)")
    pq = probe.query_cache.parse("select v from t where k = 1",
                                 probe.session)
    fp = statement_fingerprint(pq.shape)
    template_seeds().note(fp, 50)
    template_seeds().note_fallback_shape(fp, "value_dependent")

    r = _mem_runner()
    r.execute("create table t (k bigint, v bigint)")
    r.execute("insert into t values (1, 10), (2, 20)")
    res = r.execute("select v from t where k = 2")
    assert res.rows == [(20,)]
    assert r.query_cache.templates.builds == 0
    assert r.query_cache.templates.fallbacks.get("value_dependent") == 1


def test_template_seed_disabled_by_session_property():
    from trino_tpu.cache import template_seeds
    from trino_tpu.telemetry import stats_store
    from trino_tpu.telemetry.stats_store import statement_fingerprint

    # the HBO statement hint is its own first-use admission path (PR
    # 15); clear the process store so THIS test isolates the seed knob
    stats_store.store().clear()
    probe = _mem_runner()
    probe.execute("create table t (k bigint, v bigint)")
    probe.execute("insert into t values (1, 10)")
    pq = probe.query_cache.parse("select v from t where k = 1",
                                 probe.session)
    template_seeds().note(statement_fingerprint(pq.shape), 50)

    r = _mem_runner()
    r.execute("create table t (k bigint, v bigint)")
    r.execute("insert into t values (1, 10)")
    r.execute("set session plan_template_seed_enabled = false")
    res = r.execute("select v from t where k = 1")
    assert res.rows == [(10,)]
    # first use, seed ignored: below min_shape_uses, no build
    assert res.stats.get("plan_template") is None
    assert r.query_cache.templates.builds == 0


def test_worker_configure_imports_template_seed_over_rpc():
    """The real configure handler: a template_seed payload lands in the
    worker-process seed store and the response reports the count —
    mirroring the HBO seed transport."""
    import threading

    from trino_tpu.cache import template_seeds
    from trino_tpu.parallel.rpc import call
    from trino_tpu.parallel.worker import WorkerServer

    from trino_tpu.cache import TemplateSeedStore
    src = TemplateSeedStore()
    src.note("seeded-shape", 9)
    template_seeds().clear()
    server = WorkerServer(0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        resp = call(("127.0.0.1", server.port), {
            "op": "configure", "catalogs": {}, "properties": {},
            "template_seed": src.export_seed()})
        assert resp["ok"] and resp["template_seeded"] == 1
        # in-process server shares this process's store
        assert template_seeds().uses("seeded-shape") == 9
        # heartbeat path: a DELTA seed rides the ping the same way
        src.note("hotter-shape", 4)
        resp2 = call(("127.0.0.1", server.port), {
            "op": "ping", "template_seed": src.export_seed()})
        assert resp2["ok"] and resp2.get("template_seeded") == 1
        assert template_seeds().uses("hotter-shape") == 4
    finally:
        server.server.shutdown()
        template_seeds().clear()


@pytest.mark.slow
def test_process_runner_ships_template_seed_to_replacement_worker():
    """E2E over real worker subprocesses: after the coordinator earns a
    shape, a worker spawned NOW (the replacement path) receives the
    template seed at configure — and the heartbeat ships deltas to
    stale workers without re-sending an unchanged seed."""
    from trino_tpu.cache import template_seeds
    from trino_tpu.parallel.process_runner import ProcessQueryRunner

    catalogs = {"tpch": {"connector": "tpch", "page_rows": 4096}}
    runner = ProcessQueryRunner(
        catalogs, Session(catalog="tpch", schema="micro"),
        n_workers=2, desired_splits=4)
    new = None
    try:
        # initial workers spawned against an empty seed store
        assert all(w.template_seeded == 0 for w in runner.workers)
        template_seeds().note("earned-shape", 25)
        new = runner._spawn_worker_process(generation=1)
        assert new.template_seeded >= 1
        assert new.template_seed_version == template_seeds().version
        # the ORIGINAL workers are stale: one heartbeat catches them up
        stale = [w for w in runner.workers if w is not new]
        assert any(w.template_seed_version < template_seeds().version
                   for w in stale)
        runner.heartbeat()
        assert all(w.template_seed_version == template_seeds().version
                   for w in runner.workers)
        # steady state: a second heartbeat has no delta to ship
        v = template_seeds().version
        runner.heartbeat()
        assert all(w.template_seed_version == v for w in runner.workers)
    finally:
        if new is not None:
            new.proc.kill()
        runner.close()
        template_seeds().clear()
