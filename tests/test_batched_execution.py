"""Single-launch batched execution (round 16).

The contract under test: a same-shape admission burst rides ONE plan
template whose literals are opaque ``ParamRef`` slots, executes every
vmappable pipeline stage as ONE device launch for the whole batch, and
demuxes per-statement results that are BYTE-EQUAL to the serial path —
with per-tenant ACL and the result cache enforced per member exactly as
serial execution would.  The fallback taxonomy must be loud (counted by
reason, never silently wrong), and a repeat burst must perform ZERO new
jit traces with exactly one launch per vmapped stage, profiler-counted
independent of the batch depth B.
"""

import numpy as np
import pytest

from trino_tpu import jit_stats
from trino_tpu import types as T
from trino_tpu.block import Block, Page
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.expr.ir import Literal, ParamRef, param_indices
from trino_tpu.ops.output import OutputBuffer
from trino_tpu.runner import LocalQueryRunner, QueryResult
from trino_tpu.security import (AccessDeniedError, RuleBasedAccessControl,
                                TableRule)
from trino_tpu.sql.analyzer import Session


def _mem_runner(**kwargs):
    return LocalQueryRunner({"memory": MemoryConnector()},
                            Session(catalog="memory", schema="default"),
                            **kwargs)


@pytest.fixture()
def runner():
    r = _mem_runner()
    r.execute("create table t (k bigint, v bigint)")
    r.execute("insert into t values (1, 10), (2, 20), (3, 30), "
              "(4, 40), (5, 50), (6, 60), (7, 70), (8, 80)")
    return r


BURST = ["select v from t where k = %d" % i for i in range(1, 9)]
EXPECT = [[(10 * i,)] for i in range(1, 9)]


# -- IR opacity -----------------------------------------------------------


def test_paramref_is_not_a_literal():
    """The whole template design rests on this: every plan-time
    constant reader is ``isinstance(_, Literal)``-gated, so ParamRef is
    opaque BY CONSTRUCTION, not by auditing each reader."""
    p = ParamRef(T.BIGINT, 0)
    assert not isinstance(p, Literal)
    assert param_indices(p) == {0}
    from trino_tpu.expr.ir import Call
    expr = Call("add", T.BIGINT, (ParamRef(T.BIGINT, 1),
                                  Literal(T.BIGINT, 5)))
    assert param_indices(expr) == {1}


# -- serial template reuse ------------------------------------------------


def test_serial_template_reuse_across_literals(runner):
    """Second-and-later uses of a shape ride the template: same root,
    different literal bindings, correct per-literal rows."""
    r1 = runner.execute("select v from t where k = 1")
    r2 = runner.execute("select v from t where k = 2")
    r3 = runner.execute("select v from t where k = 3")
    assert (r1.rows, r2.rows, r3.rows) == ([(10,)], [(20,)], [(30,)])
    # first use misses (below min_shape_uses), later ones hit
    assert r1.stats.get("plan_template") is None
    assert r2.stats.get("plan_template") == "hit"
    assert r3.stats.get("plan_template") == "hit"
    tc = runner.query_cache.templates
    assert tc.builds == 1 and tc.hits >= 1
    assert not tc.fallbacks


def test_template_disabled_by_session_property(runner):
    runner.execute("set session plan_template_enabled = false")
    for i in (1, 2, 3):
        res = runner.execute("select v from t where k = %d" % i)
        assert res.stats.get("plan_template") is None
    assert runner.query_cache.templates.builds == 0


# -- batched execution: byte-equality matrix ------------------------------


def test_batch_matches_serial_oracle(runner):
    serial = [runner.execute(s).rows for s in BURST]
    fresh = _mem_runner()
    fresh.execute("create table t (k bigint, v bigint)")
    fresh.execute("insert into t values (1, 10), (2, 20), (3, 30), "
                  "(4, 40), (5, 50), (6, 60), (7, 70), (8, 80)")
    out = fresh.execute_batch(BURST)
    assert [o.rows for o in out] == serial == EXPECT
    assert all(o.stats.get("plan_template") == "hit" for o in out)
    assert fresh.query_cache.batched_launches == 8


def test_batch_mixed_literals_and_duplicates(runner):
    """Identical literal vectors coalesce to one lane; results still
    demux to every submitter positionally."""
    sqls = [BURST[0], BURST[3], BURST[0], BURST[5], BURST[3]]
    out = runner.execute_batch(sqls)
    assert [o.rows for o in out] == [EXPECT[0], EXPECT[3], EXPECT[0],
                                     EXPECT[5], EXPECT[3]]


def test_batch_failing_member_demuxes_positionally(runner):
    """A statement that fails analysis fails ONLY its own slot; the
    healthy same-shape members still batch."""
    sqls = [BURST[0], "select nope from t where k = 2", BURST[2]]
    out = runner.execute_batch(sqls)
    assert out[0].rows == EXPECT[0]
    assert isinstance(out[1], Exception)
    assert out[2].rows == EXPECT[2]


def test_batch_mixed_shapes_grouped(runner):
    """Two interleaved shapes each batch within their own group."""
    sqls = [BURST[0], "select k from t where v = 20", BURST[2],
            "select k from t where v = 40", BURST[4],
            "select k from t where v = 60"]
    out = runner.execute_batch(sqls)
    assert [o.rows for o in out] == [EXPECT[0], [(2,)], EXPECT[2],
                                     [(4,)], EXPECT[4], [(6,)]]


def test_batch_mixed_tenants_acl_enforced_per_member():
    """Per-tenant ACL is enforced per STATEMENT: the denied tenant's
    member fails with AccessDenied, everyone else's lanes execute."""
    acl = RuleBasedAccessControl([
        TableRule(user="alice", privileges=["SELECT"]),
    ])
    r = LocalQueryRunner({"memory": MemoryConnector()},
                         Session(catalog="memory", schema="default"),
                         access_control=acl)
    # seed as alice (the only user with write-side privileges absent;
    # memory DDL goes through create/insert checks — use ALLOW_ALL
    # runner to seed, sharing the connector)
    seed = LocalQueryRunner(r.metadata.connectors,
                            Session(catalog="memory", schema="default"))
    seed.execute("create table t (k bigint, v bigint)")
    seed.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    out = r.execute_batch(["select v from t where k = 1",
                           "select v from t where k = 2"], user="alice")
    assert [o.rows for o in out] == [[(10,)], [(20,)]]
    out2 = r.execute_batch(["select v from t where k = 1",
                            "select v from t where k = 2"], user="mallory")
    # execute_batch itself raises for a user denied query execution?
    # RuleBasedAccessControl only gates tables here, so both members
    # fail the per-member table check positionally
    assert all(isinstance(o, AccessDeniedError) for o in out2)


def test_batch_result_cache_hit_short_circuits_lane(runner):
    """A member whose full key hits the result cache is served WITHOUT
    occupying a vmap lane — and stores from batched lanes feed later
    serial hits byte-equally."""
    runner.execute("set session result_cache_enabled = true")
    runner.execute(BURST[0])                      # seed result cache
    before = runner.query_cache.batched_launches
    out = runner.execute_batch([BURST[0], BURST[1], BURST[2]])
    assert [o.rows for o in out] == EXPECT[:3]
    assert out[0].stats.get("result_cache") == "hit"
    assert runner.query_cache.result_shortcircuits == 1
    # only the two cache-missing members occupied lanes (padded to 2)
    assert runner.query_cache.batched_launches - before == 2
    # lane-computed results landed in the result cache for serial reuse
    assert runner.execute(BURST[1]).stats.get("result_cache") == "hit"


def test_batch_zero_traces_and_single_launch_per_stage(runner):
    """THE acceptance witness: a repeat same-shape burst of 8 performs
    ZERO new jit traces and each vmapped stage runs as exactly ONE
    device launch, profiler-counted independent of B."""
    from trino_tpu.telemetry import profiler as prof

    assert [o.rows for o in runner.execute_batch(BURST)] == EXPECT
    prof.reset()
    before = jit_stats.counts()
    with prof.profiling(True):
        out = runner.execute_batch(BURST)
        snap = prof.snapshot()
    after = jit_stats.counts()
    assert [o.rows for o in out] == EXPECT
    assert after == before, "repeat burst must not trace anything new"
    batched = [e for e in snap if e["name"] == "page_processor_batched"]
    assert batched, "burst did not ride the vmapped entry"
    assert all(e["calls"] == 1 for e in batched), \
        [(e["key"], e["calls"]) for e in batched]
    # nothing fell back to per-statement serial launches
    assert not any(e["name"] == "page_processor" and e["calls"] > 0
                   for e in snap)


def test_batch_depth_chunking(runner):
    """Bursts beyond batched_execution_max_depth chunk; every chunk
    demuxes correctly."""
    runner.execute("set session batched_execution_max_depth = 4")
    out = runner.execute_batch(BURST)
    assert [o.rows for o in out] == EXPECT
    depths = {o.stats.get("batched_depth") for o in out}
    assert depths == {4}


def test_batch_depth_padding_power_of_two(runner):
    """B=3 pads to the 4-lane bucket (bounded jit cache size), and the
    padding lane's rows are discarded."""
    out = runner.execute_batch(BURST[:3])
    assert [o.rows for o in out] == EXPECT[:3]
    assert {o.stats.get("batched_depth") for o in out} == {4}


# -- fallback taxonomy ----------------------------------------------------


def test_fallback_string_param(runner):
    runner.execute("create table s (name varchar, v bigint)")
    runner.execute("insert into s values ('a', 1), ('b', 2)")
    sqls = ["select v from s where name = 'a'",
            "select v from s where name = 'b'"]
    out = runner.execute_batch(sqls)
    assert [o.rows for o in out] == [[(1,)], [(2,)]]
    assert runner.query_cache.templates.fallbacks.get("string_param")


def test_fallback_ordinal_param(runner):
    """GROUP BY 1 ordinals are extracted as literals — the silent
    value-dependence hazard the pre-walk guard catches BEFORE any
    planning: templating the ordinal would re-aim the grouping key."""
    sqls = ["select k, count(*) from t where v > %d group by 1" % i
            for i in (5, 25)]
    out = runner.execute_batch(sqls)
    assert sorted(out[0].rows) == [(i, 1) for i in range(1, 9)]
    assert sorted(out[1].rows) == [(i, 1) for i in range(3, 9)]
    assert runner.query_cache.templates.fallbacks.get("ordinal_param")


def test_fallback_value_dependent(runner):
    """A literal the compiled path NEEDS as a python value — the lag()
    window offset shifts by a trace-time constant — fails the trial
    plan and falls back loudly at template build, never silently."""
    sqls = ["select lag(v, %d) over (order by k) from t" % i
            for i in (1, 2)]
    out = runner.execute_batch(sqls)
    assert out[0].rows[:3] == [(None,), (10,), (20,)]
    assert out[1].rows[:3] == [(None,), (None,), (10,)]
    fb = runner.query_cache.templates.fallbacks
    assert fb.get("value_dependent"), fb


def test_fallback_plan_shape_not_vmappable(runner):
    """A same-shape group whose local plan is richer than
    scan->fp*->collect (aggregation) still answers correctly — through
    the serial path — and counts its reason."""
    sqls = ["select count(*) from t where k > %d" % i for i in (1, 2)]
    out = runner.execute_batch(sqls)
    assert [o.rows for o in out] == [[(7,)], [(6,)]]
    fb = runner.query_cache.templates.fallbacks
    assert sum(fb.values()) > 0, fb


def test_nondeterministic_and_writes_never_batch(runner):
    out = runner.execute_batch(
        ["insert into t values (100, 1000)",
         "insert into t values (100, 1000)"])
    assert all(not isinstance(o, Exception) for o in out)
    # both INSERTs ran (no coalescing, no template)
    assert runner.execute("select count(*) from t where k = 100"
                          ).rows == [(2,)]
    assert runner.query_cache.batched_launches == 0


def test_batched_execution_disabled_property(runner):
    runner.execute("set session batched_execution_enabled = false")
    out = runner.execute_batch(BURST)
    assert [o.rows for o in out] == EXPECT
    assert runner.query_cache.batched_launches == 0


# -- metrics surface ------------------------------------------------------


def test_template_counters_scrapeable(runner):
    runner.execute_batch(BURST)
    c = runner.query_cache.counters()
    for key in ("template_hits", "template_misses", "template_builds",
                "template_fallbacks", "template_entries",
                "batched_launches", "result_shortcircuits"):
        assert key in c, key
    assert c["template_builds"] >= 1
    assert c["batched_launches"] >= 8
    fams = runner.metrics_families()
    names = {f["name"] for f in fams}
    assert "trino_plan_template_total" in names
    assert "trino_plan_template_entries" in names


# -- host hot-partition lanes (carried follow-on) -------------------------


def _page(v, rows=1):
    a = np.full(rows, v, dtype=np.int64)
    return Page([Block(T.BIGINT, a, None, None)], rows)


class TestOutputBufferHotLanes:
    def test_split_scales_capacity_and_full_needs_all_lanes(self):
        buf = OutputBuffer(4, max_pending_pages=2)
        buf.enqueue(1, _page(1))
        buf.enqueue(1, _page(2))
        assert buf.full([1])
        assert buf.split_partition(1, 4)
        assert not buf.full([1]), "extra lanes must add slack"
        for i in range(3, 11):
            buf.enqueue(1, _page(i))
        assert buf.full([1]), "full only when EVERY lane is at bound"

    def test_drain_preserves_rows_across_lanes(self):
        buf = OutputBuffer(2, max_pending_pages=4)
        buf.split_partition(0, 3)
        vals = list(range(10))
        for v in vals:
            buf.enqueue(0, _page(v))
        buf.set_no_more_pages()
        got = []
        while buf.has_page(0):
            p = buf.poll(0)
            got.append(int(np.asarray(p.block(0).data)[0]))
        assert buf.at_end(0)
        assert sorted(got) == vals
        assert buf.poll(0) is None

    def test_barrier_pages_snapshot_sees_all_lanes(self):
        buf = OutputBuffer(2)
        buf.split_partition(1, 2)
        for v in range(5):
            buf.enqueue(1, _page(v))
        assert len(buf.pages(1)) == 5
        assert buf.pages(0) == []

    def test_stats_parity_with_device_exchange(self):
        buf = OutputBuffer(4, max_pending_pages=2)
        buf.split_partition(2, 4)
        buf.enqueue(2, _page(7, rows=3))
        s = buf.stats
        assert s["hot_partitions"] == [2]
        assert s["splits"] == 1 and s["split_ways"] == 4
        assert s["hot_spread"] == {2: 4}
        assert s["partition_rows"][2] == 3

    def test_broadcast_and_merge_never_split(self):
        assert not OutputBuffer(2, broadcast=True).split_partition(0, 4)
        # merge-kind: the producer gate — hash-only callers request
        # splits; a merge operator never calls split_partition
        from trino_tpu.ops.output import PartitionedOutputOperator
        buf = OutputBuffer(2, max_pending_pages=2)
        op = PartitionedOutputOperator([T.BIGINT], [0], buf,
                                       kind="merge",
                                       hot_split_threshold=0.1)
        assert buf._hot_lanes == {}

    def test_hash_producer_splits_hot_partition(self):
        """One dominant key drives >threshold of rows -> its partition
        grows lanes automatically."""
        from trino_tpu.block import DevicePage
        from trino_tpu.ops.output import PartitionedOutputOperator

        buf = OutputBuffer(4, max_pending_pages=8)
        op = PartitionedOutputOperator([T.BIGINT, T.BIGINT], [0], buf,
                                       kind="hash",
                                       hot_split_threshold=0.5)
        keys = np.zeros(64, dtype=np.int64)       # all rows, one key
        vals = np.arange(64, dtype=np.int64)
        page = Page([Block(T.BIGINT, keys, None, None),
                     Block(T.BIGINT, vals, None, None)], 64)
        op.add_input(DevicePage.from_page(page))
        assert len(buf._hot_lanes) == 1
        (hot_p, ways), = buf._hot_lanes.items()
        assert ways == 4
        assert buf.stats["hot_partitions"] == [hot_p]
        # every row still lands in the hot partition's lanes
        total = sum(p.num_rows for p in buf.pages(hot_p))
        assert total == 64

    def test_unbounded_buffer_never_splits(self):
        from trino_tpu.block import DevicePage
        from trino_tpu.ops.output import PartitionedOutputOperator

        buf = OutputBuffer(4)    # barrier mode: no pending bound
        op = PartitionedOutputOperator([T.BIGINT], [0], buf,
                                       kind="hash",
                                       hot_split_threshold=0.5)
        keys = np.zeros(16, dtype=np.int64)
        page = Page([Block(T.BIGINT, keys, None, None)], 16)
        op.add_input(DevicePage.from_page(page))
        assert buf._hot_lanes == {}


# -- optimizer opacity ----------------------------------------------------


def test_optimizer_template_param_slots(runner):
    """The optimized template root reports its surviving ParamRef
    slots; a non-template plan reports none."""
    from trino_tpu.planner.optimizer import template_param_slots

    for i in (1, 2):
        runner.execute("select v from t where k = %d" % i)
    tc = runner.query_cache.templates
    (tmpl,) = [v for v in tc._entries.values()
               if not isinstance(v, str)]
    assert template_param_slots(tmpl.root) == (0,)
    plain = runner.plan_statement(
        runner.query_cache.parse("select v from t where k = 1",
                                 runner.session).stmt, hbo=None)
    assert template_param_slots(plain) == ()
    assert any(name == "PlanTemplate"
               for name, _ in tmpl.root.optimizer_trace)
