"""Bench-harness smoke tests: the perf plumbing cannot silently rot.

The fast test asserts both group-by paths (hash table vs sort oracle)
produce identical q1 results on the micro schema through the REAL bench
pipeline builders. The slow-marked test runs the bench measurement
child itself (BENCH_SCHEMA=micro, CPU) end-to-end and checks the
RESULT line carries the rate, the per-stage breakdown, and jit-trace
counts.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _drain(sink):
    from trino_tpu.block import Page

    if not sink.pages:
        return []
    return Page.concat(sink.pages).to_rows()


def test_q1_hash_and_sort_paths_identical():
    from trino_tpu.benchmarks import build_q1_driver, scan_q1_pages
    from trino_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(page_rows=4096)
    pages = scan_q1_pages(conn, "micro", desired_splits=4)
    rows = {}
    for label, hg in (("hash", True), ("sort", False)):
        driver, sink = build_q1_driver(conn, "micro",
                                       source_pages=list(pages),
                                       hash_grouping=hg)
        driver.run_to_completion()
        rows[label] = sorted(_drain(sink))
    assert rows["hash"] == rows["sort"]
    assert len(rows["hash"]) == 4  # the 4 (returnflag, linestatus) groups


def test_q18_hash_and_sort_paths_identical():
    from trino_tpu.benchmarks import build_q18_driver, scan_q18_pages
    from trino_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(page_rows=4096)
    pages = scan_q18_pages(conn, "micro", desired_splits=4)
    rows = {}
    agg_groups = {}
    for label, hg in (("hash", True), ("sort", False)):
        driver, sink = build_q18_driver(pages, hash_grouping=hg,
                                        collect_stats=True)
        driver.run_to_completion()
        rows[label] = sorted(_drain(sink))
        agg_groups[label] = next(
            st.output_rows for st in driver.stats
            if st.name.startswith("HashAggregation"))
    # the HAVING may filter micro down to nothing — the large-group
    # aggregation itself is the point: both paths must produce the same
    # (large) group count and the same final rows
    assert rows["hash"] == rows["sort"]
    assert agg_groups["hash"] == agg_groups["sort"] > 1000


@pytest.mark.slow
def test_bench_measure_child_micro_cpu():
    env = dict(os.environ, BENCH_ROLE="measure", BENCH_PLATFORM="cpu",
               BENCH_SCHEMA="micro", BENCH_QUERIES="q1,q18",
               BENCH_REPEATS="2")
    env.pop("BENCH_DEADLINE", None)
    proc = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    results = [json.loads(line[len("RESULT "):])
               for line in proc.stdout.splitlines()
               if line.startswith("RESULT ")]
    assert [r["query"] for r in results] == ["q1", "q18"]
    for r in results:
        assert r["rate"] > 0
        assert r["stages"]["stage_ms"]["agg"] >= 0
        assert set(r["stages"]["stage_ms"]) >= {
            "scan", "filter_project", "agg", "join", "exchange"}
        assert r["jit_traces"].get("hash_group_ids", 0) > 0
