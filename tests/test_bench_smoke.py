"""Bench-harness smoke tests: the perf plumbing cannot silently rot.

The fast test asserts both group-by paths (hash table vs sort oracle)
produce identical q1 results on the micro schema through the REAL bench
pipeline builders. The slow-marked test runs the bench measurement
child itself (BENCH_SCHEMA=micro, CPU) end-to-end and checks the
RESULT line carries the rate, the per-stage breakdown, and jit-trace
counts.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _drain(sink):
    from trino_tpu.block import Page

    if not sink.pages:
        return []
    return Page.concat(sink.pages).to_rows()


def test_q1_hash_and_sort_paths_identical():
    from trino_tpu.benchmarks import build_q1_driver, scan_q1_pages
    from trino_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(page_rows=4096)
    pages = scan_q1_pages(conn, "micro", desired_splits=4)
    rows = {}
    for label, hg in (("hash", True), ("sort", False)):
        driver, sink = build_q1_driver(conn, "micro",
                                       source_pages=list(pages),
                                       hash_grouping=hg)
        driver.run_to_completion()
        rows[label] = sorted(_drain(sink))
    assert rows["hash"] == rows["sort"]
    assert len(rows["hash"]) == 4  # the 4 (returnflag, linestatus) groups


def test_q18_hash_and_sort_paths_identical():
    from trino_tpu.benchmarks import build_q18_driver, scan_q18_pages
    from trino_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(page_rows=4096)
    pages = scan_q18_pages(conn, "micro", desired_splits=4)
    rows = {}
    agg_groups = {}
    for label, hg in (("hash", True), ("sort", False)):
        driver, sink = build_q18_driver(pages, hash_grouping=hg,
                                        collect_stats=True)
        driver.run_to_completion()
        rows[label] = sorted(_drain(sink))
        agg_groups[label] = next(
            st.output_rows for st in driver.stats
            if st.name.startswith("HashAggregation"))
    # the HAVING may filter micro down to nothing — the large-group
    # aggregation itself is the point: both paths must produce the same
    # (large) group count and the same final rows
    assert rows["hash"] == rows["sort"]
    assert agg_groups["hash"] == agg_groups["sort"] > 1000


def _load_bench():
    """Import bench.py by path (it is an entry script, not a package
    module; importing it runs no measurement)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_ratchet_flags_regression(capsys):
    """A CPU rate below its COMMITTED cached baseline must produce an
    explicit *_regressed line in state (round 5's q1 0.928 sailed
    through silently); same-run solo baselines are exempt."""
    bench = _load_bench()
    res = {"query": "q1", "schema": "tiny", "rate": 900.0}
    state = {}
    bench._emit(state, res, "_cpu_fallback", 1000.0, cached_base=True)
    out = capsys.readouterr().out
    assert '"vs_baseline": 0.9' in out
    regressed = state.get("regressed", [])
    assert len(regressed) == 1
    line = json.loads(regressed[0])
    assert line["metric"] == "tpch_q1_tiny_rows_per_sec_regressed"
    assert line["value"] == 0.9

    # at/above baseline: no regression flag
    state2 = {}
    bench._emit(state2, res, "_cpu_fallback", 900.0, cached_base=True)
    assert not state2.get("regressed")
    # same-run solo baseline: exempt however low the ratio
    state3 = {}
    bench._emit(state3, res, "_cpu_fallback", 10_000.0, cached_base=False)
    assert not state3.get("regressed")
    # per-chip TPU lines have no TPU baseline to ratchet against
    state4 = {}
    bench._emit(state4, res, "_per_chip", 10_000.0, cached_base=True)
    assert not state4.get("regressed")


def test_bench_hbo_qerror_ratchet():
    """The HBO estimate-quality ratchet: quantiles above their
    committed baseline x the tolerance regress (Q-error is
    lower-better, so the bound is an UPPER one); no baseline = no
    ratchet; the committed cache must actually carry the baselines."""
    bench = _load_bench()
    cache = {"hbo_qerror_p50": 2.0, "hbo_qerror_p90": 10.0}
    # at baseline: ratio 1.0, clean
    ratios, regressed = bench._qerror_ratchet(2.0, 10.0, cache)
    assert ratios == {"hbo_qerror_p50": 1.0, "hbo_qerror_p90": 1.0}
    assert regressed == []
    # inside the tolerance: clean
    _, regressed = bench._qerror_ratchet(2.4, 10.0, cache)
    assert regressed == []
    # beyond it: the regressed quantile is named
    ratios, regressed = bench._qerror_ratchet(2.0, 20.0, cache)
    assert regressed == ["hbo_qerror_p90"]
    assert ratios["hbo_qerror_p90"] == 2.0
    # BETTER estimates (lower qerror) never regress
    _, regressed = bench._qerror_ratchet(1.0, 1.0, cache)
    assert regressed == []
    # no committed baseline: ratio 0.0, never regressed
    ratios, regressed = bench._qerror_ratchet(99.0, 99.0, {})
    assert ratios == {"hbo_qerror_p50": 0.0, "hbo_qerror_p90": 0.0}
    assert regressed == []
    # the REAL committed cache carries both baselines (the ratchet is
    # armed, not latent)
    committed = json.load(open(os.path.join(REPO,
                                            ".bench_cpu_cache.json")))
    assert committed.get("hbo_qerror_p50", 0) > 0
    assert committed.get("hbo_qerror_p90", 0) > 0


def test_bench_child_init_watchdog_fails_fast():
    """A measurement child whose backend init never completes must exit
    within seconds (distinct rc=3), not hang its whole 380 s budget —
    the round-5 failure mode (VERDICT directive 1a). A hanging axon
    tunnel cannot be faked portably, so the hang is simulated with a
    watchdog timeout shorter than any possible `import jax`."""
    import time

    env = dict(os.environ, BENCH_ROLE="measure", BENCH_PLATFORM="default",
               BENCH_SCHEMA="micro", BENCH_INIT_TIMEOUT="0.2",
               JAX_PLATFORMS="cpu")
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=90)
    took = time.time() - t0
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-500:])
    assert "failing fast" in proc.stderr
    assert took < 60


@pytest.mark.slow
def test_bench_chaos_smoke_child():
    """The bench harness's chaos role (BENCH_ROLE=chaos): a seeded
    kill-worker fault under retry_policy=TASK must recover to the exact
    fault-free answer and report its recovery counters — run as the real
    child process so the fault-injection code paths cannot rot outside
    the test suite."""
    env = dict(os.environ, BENCH_ROLE="chaos", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [line for line in proc.stdout.splitlines()
             if line.startswith("CHAOS_RESULT ")]
    assert len(lines) == 1, proc.stdout[-2000:]
    out = json.loads(lines[0][len("CHAOS_RESULT "):])
    assert out["ok"] is True
    assert out["recovery"]["task_retries"] >= 1
    assert out["workers_alive"] == [True, True]


@pytest.mark.slow
def test_bench_skew_smoke_child():
    """The bench harness's skew role (BENCH_ROLE=skew): a zipf-keyed
    device exchange with hot-partition splitting must byte-match the
    unsplit oracle while spreading the hot partition over >= 2
    receiver lanes with zero retries, and scaled-writer CTAS must
    byte-match the unscaled plan while rebalancing — run as the real
    child process so the skew code paths cannot rot outside the test
    suite."""
    env = dict(os.environ, BENCH_ROLE="skew", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [line for line in proc.stdout.splitlines()
             if line.startswith("SKEW_RESULT ")]
    assert len(lines) == 1, proc.stdout[-2000:]
    out = json.loads(lines[0][len("SKEW_RESULT "):])
    assert out["ok"] is True
    assert out["splits"] >= 1
    assert max(out["hot_spread"].values()) >= 2
    assert out["a2a_retries"] == 0
    assert out["lane_skew_split"] < out["lane_skew_unsplit"]
    assert out["rebalances"] >= 1
    assert out["rows_per_s"] > 0


@pytest.mark.slow
def test_bench_elastic_smoke_child():
    """The bench harness's elastic-cluster role (BENCH_ROLE=elastic):
    a queue-depth burst against a max_concurrency=2 resource group
    must make the autoscaler grow the membership 2 -> 4 mid-burst, the
    grown cluster must place tasks on the joiners, and idle must drain
    back down to the floor with zero lost rows and zero query retries
    — run as the real child process so the membership/autoscaler paths
    cannot rot outside the test suite."""
    env = dict(os.environ, BENCH_ROLE="elastic", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=560)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [line for line in proc.stdout.splitlines()
             if line.startswith("ELASTIC_RESULT ")]
    assert len(lines) == 1, proc.stdout[-2000:]
    out = json.loads(lines[0][len("ELASTIC_RESULT "):])
    assert out["ok"] is True
    assert out["peak_workers"] >= 4
    assert out["final_workers"] == 2
    assert out["scaled_width_tasks"] is True
    directions = [d["direction"] for d in out["decisions"]]
    assert "up" in directions and directions.count("down") >= 2
    assert out["failures"] == []


@pytest.mark.slow
def test_bench_kernels_smoke_child():
    """The bench harness's kernel-strategy role (BENCH_ROLE=kernels):
    the matmul join must byte-match the sorted-index oracle across the
    NDV sweep, the three SQL-level join strategies must agree, the
    global-hash aggregation must match the exchange shape and the host
    oracle, and the crossover NDVs must be reported — run as the real
    child process so the kernel-strategy paths cannot rot outside the
    test suite."""
    env = dict(os.environ, BENCH_ROLE="kernels", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=580)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [line for line in proc.stdout.splitlines()
             if line.startswith("KERNELS_RESULT ")]
    assert len(lines) == 1, proc.stdout[-2000:]
    out = json.loads(lines[0][len("KERNELS_RESULT "):])
    assert out["ok"] is True
    assert out["join_sql_three_strategies_equal"] is True
    assert len(out["join_sweep"]) == 3
    assert all(r["matmul_rows_per_s"] > 0 for r in out["join_sweep"])
    assert len(out["agg_sweep"]) == 3
    assert "join_crossover_ndv" in out and "agg_crossover_ndv" in out


@pytest.mark.slow
def test_bench_qps_smoke_child():
    """The bench harness's multi-tenant throughput role (BENCH_ROLE=
    qps): 8 concurrent HTTP protocol clients over a zipf tenant mix
    must report p50/p99 + queries/sec for a cache-disabled and a
    cache-enabled phase, with plan-cache hits, ZERO retraces on the
    repeat probe, bounded _QueryState growth, and >= 1.5x QPS from the
    caches — run as the real child process so the whole admission-to-
    execution path cannot rot outside the test suite."""
    env = dict(os.environ, BENCH_ROLE="qps", JAX_PLATFORMS="cpu",
               BENCH_QPS_SCHEMA="micro", BENCH_QPS_QUERIES="12",
               BENCH_QPS_TENANTS="6", BENCH_QPS_RATCHET_MIN="0.4")
    proc = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=560)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [line for line in proc.stdout.splitlines()
             if line.startswith("QPS_RESULT ")]
    assert len(lines) == 1, proc.stdout[-2000:]
    out = json.loads(lines[0][len("QPS_RESULT "):])
    assert out["ok"] is True
    assert out["clients"] == 8
    assert out["cached"]["queries"] == out["uncached"]["queries"] == 96
    assert out["cached"]["p99_ms"] > 0 and out["cached"]["qps"] > 0
    assert out["speedup"] >= 1.5
    assert out["plan_cache"]["plan_hits"] > 0
    assert out["probe_traces"] == 0
    assert out["query_states_left"] <= 16
    assert out["batching"]["batches"] >= 1


@pytest.mark.slow
def test_bench_measure_child_micro_cpu():
    env = dict(os.environ, BENCH_ROLE="measure", BENCH_PLATFORM="cpu",
               BENCH_SCHEMA="micro", BENCH_QUERIES="q1,q18",
               BENCH_REPEATS="2")
    env.pop("BENCH_DEADLINE", None)
    proc = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    results = [json.loads(line[len("RESULT "):])
               for line in proc.stdout.splitlines()
               if line.startswith("RESULT ")]
    assert [r["query"] for r in results] == ["q1", "q18"]
    for r in results:
        assert r["rate"] > 0
        assert r["stages"]["stage_ms"]["agg"] >= 0
        assert set(r["stages"]["stage_ms"]) >= {
            "scan", "filter_project", "agg", "join", "exchange"}
        assert r["jit_traces"].get("hash_group_ids", 0) > 0
