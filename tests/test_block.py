import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Block, Dictionary, Page, padded_size


def test_padded_size_buckets():
    assert padded_size(0) == 16
    assert padded_size(16) == 16
    assert padded_size(17) == 32
    assert padded_size(1000) == 1024


def test_dictionary_roundtrip():
    d = Dictionary()
    codes = d.encode(["apple", "banana", "apple", None])
    assert codes.tolist() == [0, 1, 0, 0]
    assert d.decode(np.array([1, 0])) == ["banana", "apple"]
    assert d.lookup("cherry") == -1
    assert d.code("cherry") == 2


def test_dictionary_sort_rank():
    d = Dictionary(["pear", "apple", "mango"])
    rank = d.sort_rank()
    # apple < mango < pear
    assert rank.tolist() == [2, 0, 1]


def test_block_pylist_roundtrip():
    b = Block.from_pylist(T.BIGINT, [1, None, 3])
    assert b.to_pylist() == [1, None, 3]
    assert b.may_have_nulls

    s = Block.from_pylist(T.VARCHAR, ["x", "y", None, "x"])
    assert s.to_pylist() == ["x", "y", None, "x"]

    d = Block.from_pylist(T.decimal_type(10, 2), ["1.50", None])
    from decimal import Decimal
    assert d.to_pylist() == [Decimal("1.50"), None]


def test_block_region_take_filter():
    b = Block.from_pylist(T.INTEGER, [10, 20, 30, 40, 50])
    assert b.region(1, 3).to_pylist() == [20, 30, 40]
    assert b.take([4, 0]).to_pylist() == [50, 10]
    assert b.filter([True, False, True, False, False]).to_pylist() == [10, 30]


def test_page_ops():
    p = Page.from_pylists(
        [T.BIGINT, T.VARCHAR],
        [[1, 2, 3], ["a", "b", "a"]],
    )
    assert p.num_rows == 3 and p.channel_count == 2
    assert p.to_rows() == [(1, "a"), (2, "b"), (3, "a")]
    assert p.filter([False, True, True]).to_rows() == [(2, "b"), (3, "a")]
    assert p.select_channels([1]).to_rows() == [("a",), ("b",), ("a",)]


def test_page_concat_unifies_dictionaries():
    p1 = Page.from_pylists([T.VARCHAR], [["a", "b"]])
    p2 = Page.from_pylists([T.VARCHAR], [["b", "c"]])
    out = Page.concat([p1, p2])
    assert out.num_rows == 4
    assert out.block(0).to_pylist() == ["a", "b", "b", "c"]
    assert out.block(0).dictionary is p1.block(0).dictionary


def test_page_concat_with_nulls():
    p1 = Page.from_pylists([T.BIGINT], [[1, None]])
    p2 = Page.from_pylists([T.BIGINT], [[3]])
    out = Page.concat([p1, p2])
    assert out.block(0).to_pylist() == [1, None, 3]
