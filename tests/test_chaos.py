"""Seeded chaos matrix over the self-healing multi-process runtime.

Reference analog: ``testing/BaseFailureRecoveryTest.java`` — every fault
shape the deterministic ``FaultSchedule`` can inject (worker kill, RPC
drop mid-frame, straggler delay, spool truncation, fail-after-publish,
injected user error) is driven against TPC-H q1/q3 style queries under
the retry policies that can recover from it, asserting:

- results equal the fault-free run on the SAME cluster (and the local
  oracle) — recovery must never change answers;
- ``task_launches`` match the expected attempt shape (no silent
  double-launch, no producer re-runs under retry-from-spool);
- USER errors fail fast with ZERO retry attempts;
- dead workers get REPLACED (spawn + register + replica re-sync) and
  the replacement serves subsequent queries.

All cases run 2 workers on the micro schema to stay far under the ~10 s
per-case tier-1 budget rule.
"""

import threading
import time

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.events import EventListener
from trino_tpu.parallel.fault import FaultSchedule
from trino_tpu.parallel.process_runner import ProcessQueryRunner
from trino_tpu.resources.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session
from trino_tpu.types import TrinoError

CATALOGS = {"tpch": {"connector": "tpch", "page_rows": 4096},
            "memory": {"connector": "memory"}}
Q1 = ("select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
      "from lineitem group by l_returnflag, l_linestatus")
Q3 = TPCH_QUERIES[3]


class _Recorder(EventListener):
    def __init__(self):
        self.replaced = []
        self.retries = []

    def worker_replaced(self, event):
        self.replaced.append(event)

    def task_retry(self, event):
        self.retries.append(event)


RECORDER = _Recorder()


def _mk_session(**props):
    s = Session(catalog="tpch", schema="micro")
    s.properties.update({"retry_initial_backoff": 0.02,
                         "retry_max_backoff": 0.2, **props})
    return s


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=4096)},
                            Session(catalog="tpch", schema="micro"))


@pytest.fixture(scope="module")
def task_cluster():
    """retry_policy=TASK over the spooled barrier shape — the full
    fault-tolerant stack: retry-from-spool, speculation, replacement."""
    # speculation off by default in this module: a cold replacement
    # worker's first-task warmup (seconds) would otherwise let a
    # legitimate speculative win rescue a faulted task BEFORE the
    # task-retry path each test means to pin down; the dedicated
    # straggler test re-enables it
    s = _mk_session(streaming_execution=False, retry_policy="TASK",
                    speculative_execution_enabled=False,
                    speculation_min_seconds=0.3)
    with ProcessQueryRunner(CATALOGS, s, n_workers=2, desired_splits=4,
                            broadcast_threshold=300.0,
                            heartbeat_interval=0.25,
                            event_listeners=[RECORDER]) as c:
        c.fault_schedule = FaultSchedule(seed=42)
        yield c


@pytest.fixture(scope="module")
def barrier_cluster():
    """retry_policy=QUERY, streaming off: barrier stages whose results
    are pulled over get_results — the transient-RPC-retry seam."""
    s = _mk_session(streaming_execution=False, retry_policy="QUERY")
    with ProcessQueryRunner(CATALOGS, s, n_workers=2, desired_splits=4,
                            broadcast_threshold=300.0,
                            heartbeat_interval=0.25) as c:
        c.fault_schedule = FaultSchedule(seed=42)
        yield c


@pytest.fixture(scope="module")
def stream_cluster():
    """retry_policy=QUERY, streaming on (the default shape): outputs
    are not durable, every fault recovers via full-query retry."""
    s = _mk_session(retry_policy="QUERY")
    with ProcessQueryRunner(CATALOGS, s, n_workers=2, desired_splits=4,
                            broadcast_threshold=300.0,
                            heartbeat_interval=0.25) as c:
        c.fault_schedule = FaultSchedule(seed=42)
        yield c


def _await_capacity(c, timeout=90):
    """Wait for self-healing to restore every worker slot."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(c.heal()):
            return
        time.sleep(0.1)
    raise AssertionError(f"cluster never healed: {c.heartbeat()}")


def _next_qid(c):
    return f"q{c._task_seq + 1}a0"


def _launches_since(c, mark):
    return c.task_launches[mark:]


# ----------------------------------------------------------- TASK policy ----


def test_task_clean_baselines(local, task_cluster):
    """Fault-free anchors (also warms the per-cluster compile caches so
    later straggler medians are tight)."""
    c = task_cluster
    c._q1_clean = sorted(c.execute(Q1).rows)
    c._q3_clean = c.execute(Q3).rows
    assert c._q1_clean == sorted(local.execute(Q1).rows)
    assert c._q3_clean == local.execute(Q3).rows


def test_kill_worker_mid_query_task_policy(task_cluster):
    """THE acceptance scenario: a seeded FaultSchedule kills a worker
    mid-query under TASK policy — correct results, completed producer
    stages NOT re-run (task_launches), all recovery inside attempt 0,
    and the replacement worker serves the next query."""
    c = task_cluster
    _await_capacity(c)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f1", "kill-worker")
    mark = len(c.task_launches)
    res = c.execute(Q1)
    assert sorted(res.rows) == c._q1_clean
    launches = _launches_since(c, mark)
    assert all("a0." in t for t in launches), launches
    f0 = [t for t in launches if f"{qid}.f0." in t]
    f1 = [t for t in launches if f"{qid}.f1." in t]
    assert len(f0) == 2, f"producer stage re-ran: {f0}"
    assert len(f1) == 3, f"expected exactly one retried task: {f1}"
    rec = res.stats["recovery"]
    assert rec["task_retries"] == 1
    assert rec["retries_by_type"].get("EXTERNAL") == 1
    assert rec["query_retries"] == 0
    # self-healing: the killed slot comes back and serves queries
    _await_capacity(c)
    assert sorted(c.execute(Q1).rows) == c._q1_clean


def test_kill_worker_q3_join_task_policy(task_cluster):
    """Same fault against the join+TopN pipeline (more fragments, merge
    output): recovery stays inside attempt 0."""
    c = task_cluster
    _await_capacity(c)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f1", "kill-worker")
    mark = len(c.task_launches)
    res = c.execute(Q3)
    assert res.rows == c._q3_clean
    launches = _launches_since(c, mark)
    assert all("a0." in t for t in launches), launches
    _await_capacity(c)


def test_fail_after_spool_publish_first_publish_wins(task_cluster):
    """A task that fails AFTER publishing its spool output retries; the
    duplicate publish is discarded (first-publish-wins hard link) and
    results stay exact."""
    c = task_cluster
    _await_capacity(c)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f0", "fail-after-publish")
    mark = len(c.task_launches)
    res = c.execute(Q1)
    assert sorted(res.rows) == c._q1_clean
    launches = _launches_since(c, mark)
    assert all("a0." in t for t in launches), launches
    assert any(".r1" in t for t in launches
               if f"{qid}.f0." in t), launches
    assert res.stats["recovery"]["retries_by_type"].get("INTERNAL") == 1


def test_truncate_spool_frame_query_retry(task_cluster):
    """A torn spool file must fail loudly (never partial rows); a task
    retry re-reads the same bytes, so recovery comes from the QUERY
    retry rebuilding the exchange under a fresh attempt id."""
    c = task_cluster
    _await_capacity(c)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f0", "truncate-spool")
    mark = len(c.task_launches)
    res = c.execute(Q1)
    assert sorted(res.rows) == c._q1_clean
    launches = _launches_since(c, mark)
    assert any("a1." in t for t in launches), launches
    assert res.stats["recovery"]["query_retries"] >= 1


def test_straggler_speculative_redispatch(task_cluster):
    """A task delayed far past its sibling's median is re-dispatched on
    another worker; the speculative attempt wins and the query never
    waits out the straggler's full delay."""
    c = task_cluster
    _await_capacity(c)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f0", "delay", delay_s=4.0)
    mark = len(c.task_launches)
    c.session.properties["speculative_execution_enabled"] = True
    try:
        res = c.execute(Q1)
    finally:
        c.session.properties["speculative_execution_enabled"] = False
    assert sorted(res.rows) == c._q1_clean
    launches = _launches_since(c, mark)
    assert any(t.endswith(".spec") for t in launches), launches
    rec = res.stats["recovery"]
    assert rec["speculative_launched"] >= 1
    assert rec["speculative_wins"] >= 1
    assert rec["query_retries"] == 0
    assert all("a0." in t for t in launches), launches


def test_user_error_is_never_retried(task_cluster):
    """A USER-typed failure (deterministic) fails the query fast: zero
    task retries, zero query retries, and the TrinoError names the real
    remote failure including its traceback."""
    c = task_cluster
    _await_capacity(c)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f0", "user-error")
    mark = len(c.task_launches)
    before = (c.recovery_total.task_retries,
              c.recovery_total.query_retries)
    with pytest.raises(TrinoError) as ei:
        c.execute(Q1)
    assert ei.value.code == "DIVISION_BY_ZERO"
    assert "injected user error" in str(ei.value)
    assert "remote traceback" in str(ei.value)
    launches = _launches_since(c, mark)
    assert not any(".r1" in t or ".spec" in t or "a1." in t
                   for t in launches), launches
    assert (c.recovery_total.task_retries,
            c.recovery_total.query_retries) == before


def test_query_deadline_enforced_as_user_error(task_cluster):
    """query_max_run_time caps the query across all RPCs and raises
    EXCEEDED_TIME_LIMIT — classified USER, so no retry burns the
    remaining budget on a doomed query."""
    c = task_cluster
    _await_capacity(c)
    mark = len(c.task_launches)
    c.session.properties["query_max_run_time"] = 0.001
    try:
        with pytest.raises(TrinoError) as ei:
            c.execute(Q1)
    finally:
        del c.session.properties["query_max_run_time"]
    assert ei.value.code == "EXCEEDED_TIME_LIMIT"
    launches = _launches_since(c, mark)
    assert not any("a1." in t for t in launches), launches


def test_worker_replacement_resyncs_replicated_tables(task_cluster):
    """Replacement is a full re-register: the new process receives the
    replicated memory-catalog tables, so distributed scans of local
    replicas stay correct after the swap."""
    c = task_cluster
    _await_capacity(c)
    c.execute("create table memory.default.chaos_t as "
              "select n_nationkey k, n_name from tpch.micro.nation")
    victim = c.workers[0]
    victim.proc.kill()
    victim.proc.wait(timeout=10)
    _await_capacity(c)
    assert c.workers[0].proc.pid != victim.proc.pid
    res = c.execute("select count(*) from memory.default.chaos_t")
    assert res.rows == [(25,)]
    c.execute("drop table memory.default.chaos_t")


def test_explain_analyze_surfaces_recovery(task_cluster):
    """EXPLAIN ANALYZE on the process runner renders the recovery
    counters (attempts, retries by type, backoff) for a faulted run."""
    c = task_cluster
    _await_capacity(c)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f1", "error")
    res = c.execute("explain analyze " + Q1)
    text = "\n".join(r[0] for r in res.rows)
    assert "Recovery:" in text, text
    assert "task retries" in text
    assert "INTERNAL=1" in text


def test_chaos_events_recorded(task_cluster):
    """The event listener SPI observed the module's self-healing:
    replacements and typed retries fanned out to listeners."""
    assert any(e.new_pid != e.old_pid for e in RECORDER.replaced)
    assert any(e.error_type == "EXTERNAL" for e in RECORDER.retries)
    assert any(e.speculative for e in RECORDER.retries)


# ---------------------------------------------------------- QUERY policy ----


def test_rpc_drop_mid_frame_recovers_in_place(local, barrier_cluster):
    """A connection torn mid-frame during a result pull is retried at
    the transport layer (each get_results response is an independent
    snapshot): NO task relaunch, NO query retry — zero extra launches
    vs the fault-free run."""
    c = barrier_cluster
    mark0 = len(c.task_launches)
    clean = sorted(c.execute(Q1).rows)
    assert clean == sorted(local.execute(Q1).rows)
    clean_count = len(c.task_launches) - mark0
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f1", "drop-connection")
    mark = len(c.task_launches)
    res = c.execute(Q1)
    assert sorted(res.rows) == clean
    launches = _launches_since(c, mark)
    # identical attempt shape to the fault-free run: no silent
    # double-launch anywhere
    assert len(launches) == clean_count, (launches, clean_count)
    assert not any(".r1" in t or "a1." in t for t in launches), launches
    assert res.stats["recovery"]["retries_by_type"].get(
        "EXTERNAL", 0) >= 1
    assert res.stats["recovery"]["query_retries"] == 0


def test_kill_worker_streaming_query_retry(local, stream_cluster):
    """Streaming outputs are not durable: a killed worker loses them,
    the query retries wholesale on the healed cluster, answers stay
    exact."""
    c = stream_cluster
    clean = sorted(c.execute(Q1).rows)
    assert clean == sorted(local.execute(Q1).rows)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f0", "kill-worker")
    mark = len(c.task_launches)
    res = c.execute(Q1)
    assert sorted(res.rows) == clean
    launches = _launches_since(c, mark)
    assert any("a1." in t for t in launches), launches
    assert res.stats["recovery"]["query_retries"] >= 1
    _await_capacity(c)


def test_rpc_drop_streaming_replays_in_place(stream_cluster):
    """A mid-frame drop on the streaming pull RECOVERS IN PLACE: the
    producer retains unacked frames (_RetainedStream), so the channel
    reconnects and replays them byte-identically from its cursor — zero
    full-query restarts for a single dropped connection, identical
    attempt shape to the fault-free run."""
    c = stream_cluster
    _await_capacity(c)
    mark0 = len(c.task_launches)
    clean = sorted(c.execute(Q1).rows)
    clean_count = len(c.task_launches) - mark0
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f0", "drop-connection")
    mark = len(c.task_launches)
    res = c.execute(Q1)
    assert sorted(res.rows) == clean
    assert res.stats["recovery"]["query_retries"] == 0
    launches = _launches_since(c, mark)
    assert len(launches) == clean_count, (launches, clean_count)
    assert not any("a1." in t for t in launches), launches


def test_rpc_drop_streaming_repeated_drops_still_replay(stream_cluster):
    """Several torn connections across the query's streaming pulls
    (one drop per producer task, on both fragments) all replay in
    place — drops on independent streams never accumulate toward any
    shared budget or escalate to a query retry."""
    c = stream_cluster
    _await_capacity(c)
    clean = sorted(c.execute(Q3).rows)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f0", "drop-connection", times=2)
    c.fault_schedule.add(f"{qid}.f1", "drop-connection")
    res = c.execute(Q3)
    assert sorted(res.rows) == clean
    assert res.stats["recovery"]["query_retries"] == 0


def test_user_error_fails_fast_streaming(stream_cluster):
    """The taxonomy propagates transitively through streaming pulls:
    a USER error in a mid-plan task surfaces as the original error with
    zero query retries."""
    c = stream_cluster
    _await_capacity(c)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f0", "user-error")
    mark = len(c.task_launches)
    with pytest.raises(TrinoError) as ei:
        c.execute(Q1)
    assert ei.value.code == "DIVISION_BY_ZERO"
    assert "injected user error" in str(ei.value)
    launches = _launches_since(c, mark)
    assert not any("a1." in t for t in launches), launches


# ---------------------------------------------------- memory governance ----


def test_memory_escalation_retry(barrier_cluster):
    """THE memory-governance acceptance path: an attempt that dies with
    INSUFFICIENT_RESOURCES (per-query cap far below the working set)
    re-admits with a GROWN budget — max(retry_initial_memory, 2x the
    observed peak the worker piggybacked on its failure response) — and
    a halved task width, instead of replaying the identical doomed
    plan."""
    c = barrier_cluster
    _await_capacity(c)
    clean = sorted(c.execute(Q1).rows)
    saved = dict(c.session.properties)
    c.session.properties.update({"query_max_memory_bytes": 60_000,
                                 "retry_initial_memory": 1 << 30})
    mark = len(c.task_launches)
    try:
        res = c.execute(Q1)
    finally:
        c.session.properties.clear()
        c.session.properties.update(saved)
    assert sorted(res.rows) == clean
    rec = res.stats["recovery"]
    assert rec["memory_escalations"] >= 1
    assert rec["retries_by_type"].get("INSUFFICIENT_RESOURCES", 0) >= 1
    launches = _launches_since(c, mark)
    # width reduction: the escalated attempt (a1) runs its partitioned
    # fragments at half width -> no .t1 tasks
    a1 = [t for t in launches if "a1." in t]
    assert a1, launches
    assert not any(".t1" in t for t in a1), a1
    # the configured session must come back untouched (overrides are
    # per-attempt state, not global mutation)
    assert c.session.properties == saved


def test_low_memory_killer_kills_policy_victim(barrier_cluster):
    """Cluster-overcommit: with a blocked node attributing the largest
    reservation to the in-flight query, the governance tick kills
    exactly the policy-chosen victim (EXCEEDED_CLUSTER_MEMORY); the
    victim then SUCCEEDS on retry while a concurrent query finishes
    unharmed."""
    from trino_tpu.events import EventListener

    class KillRecorder(EventListener):
        def __init__(self):
            self.kills = []

        def memory_kill(self, event):
            self.kills.append(event)

    c = barrier_cluster
    _await_capacity(c)
    rec = KillRecorder()
    c.event_manager.add(rec)
    clean = sorted(c.execute(Q1).rows)
    victim_qid = _next_qid(c)
    # slow the victim's scan tasks so the kill window is open
    c.fault_schedule.add(f"{victim_qid}.f1", "delay", times=2,
                         delay_s=1.5)
    results = {}

    def run_victim():
        results["victim"] = sorted(c.execute(Q1).rows)

    th = threading.Thread(target=run_victim, daemon=True)
    th.start()
    time.sleep(0.4)  # victim tasks are now sleeping in their delay
    # a blocked node reports the victim attempt as its largest holder
    # (synthetic worker id: real heartbeats never overwrite it)
    c.cluster_memory.update(99, {
        "max_bytes": 1000, "reserved_bytes": 1000, "blocked_events": 1,
        "queries": {victim_qid: {"reserved": 900, "peak": 900},
                    "tiny_q": {"reserved": 100, "peak": 100}}})
    assert c.run_memory_governance() == victim_qid
    # a concurrent query sails through while the victim is dying
    assert sorted(c.execute(Q1).rows) == clean
    th.join(timeout=60)
    assert not th.is_alive()
    c.cluster_memory.forget_worker(99)
    assert results["victim"] == clean
    assert [e.query_id for e in rec.kills] == [victim_qid]
    assert rec.kills[0].policy == "total-reservation-on-blocked-nodes"


def test_barrier_driver_observes_abort_at_page_boundaries():
    """The kill path's lever INSIDE a worker: a barrier (non-streaming)
    task polls its abort flag at every page-move quantum, so an
    abort_task broadcast (low-memory kill, superseded attempt) stops
    the driver mid-execution — not after it drained its pipeline."""
    from trino_tpu.parallel.fault import RemoteTaskError
    from trino_tpu.parallel.remote_exchange import run_barrier_driver

    class Driver:
        def __init__(self, finish_at=None, abort=None, abort_at=None):
            self.quanta = 0
            self._finish_at = finish_at
            self._abort = abort
            self._abort_at = abort_at

        def process(self):
            self.quanta += 1
            if self._abort_at == self.quanta:
                self._abort.set()
            return self._finish_at == self.quanta

    # pre-set abort: not a single page moves
    d = Driver()
    with pytest.raises(RemoteTaskError):
        run_barrier_driver(d, _set_event())
    assert d.quanta == 0
    # abort lands mid-run: observed at the NEXT page boundary
    ev = threading.Event()
    d = Driver(abort=ev, abort_at=7)
    with pytest.raises(RemoteTaskError):
        run_barrier_driver(d, ev)
    assert d.quanta == 7
    # flag never set: the driver runs to completion untouched
    d = Driver(finish_at=3)
    run_barrier_driver(d, threading.Event())
    assert d.quanta == 3
    # a driver that can NEVER finish still terminates (stuck-pipeline
    # bound), it does not spin the worker thread forever
    with pytest.raises(RemoteTaskError):
        run_barrier_driver(Driver(), threading.Event(), max_quanta=100)


def _set_event():
    ev = threading.Event()
    ev.set()
    return ev


def test_heartbeat_piggybacks_pool_snapshots(barrier_cluster):
    """Stats parity: what the ClusterMemoryManager aggregated from the
    heartbeat must equal what the workers report when asked directly."""
    from trino_tpu.parallel.rpc import call

    c = barrier_cluster
    _await_capacity(c)
    c.execute(Q1)
    c.heartbeat()
    stats = c.cluster_memory.cluster_stats()
    direct = []
    for w in c.workers:
        resp = call(w.addr, {"op": "ping"}, timeout=10)
        assert resp.get("memory") is not None
        direct.append(resp["memory"])
    assert stats["workers"] == len(c.workers)
    assert stats["total_max_bytes"] == sum(m["max_bytes"]
                                           for m in direct)
    # per-query peaks flowed through: the finished query left its peak
    # in some worker's released-peaks section
    peaks = [q["peak"] for m in direct
             for q in m.get("queries", {}).values()]
    assert any(p > 0 for p in peaks)
    # EXPLAIN ANALYZE surfaces the cluster view
    res = c.execute("explain analyze " + Q1)
    text = "\n".join(r[0] for r in res.rows)
    assert "Cluster memory:" in text


# ------------------------------------------------- hybrid join chaos ----


def _spill_records():
    """Hybrid-join spill records currently in the coordinator's HBO
    store (worker demotions ride task responses into it — the witness
    that a fault actually demoted build partitions, not just fired)."""
    from trino_tpu.telemetry import stats_store

    st = stats_store.store()
    with st._lock:
        return [h.spill for s in st._stmts.values()
                for h in s["nodes"].values() if h.spill is not None]


def test_revoke_memory_mid_build_hybrid_join(barrier_cluster):
    """A seeded revoke-memory fault forces a full pool revocation
    early in the join stage (mid-BUILD): the builder enters
    partitioned mode and demotes partitions in place — the query
    completes byte-equal with ZERO retries of any kind."""
    c = barrier_cluster
    _await_capacity(c)
    clean = c.execute(Q3).rows
    before = len(_spill_records())
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.", "revoke-memory", times=16,
                         countdown=2)
    res = c.execute(Q3)
    assert res.rows == clean
    rec = res.stats["recovery"]
    assert rec["query_retries"] == 0, rec
    assert rec["task_retries"] == 0, rec
    assert len(_spill_records()) > before, \
        "no partition demotion reached the coordinator's history store"


def test_revoke_memory_mid_probe_hybrid_join(barrier_cluster):
    """Same fault armed DEEP into the task (mid-PROBE / downstream):
    cold probe rows park beside their build partition and replay in
    the deferred per-partition passes — still byte-equal, still zero
    retries."""
    c = barrier_cluster
    _await_capacity(c)
    clean = c.execute(Q3).rows
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.", "revoke-memory", times=16,
                         countdown=24)
    res = c.execute(Q3)
    assert res.rows == clean
    rec = res.stats["recovery"]
    assert rec["query_retries"] == 0, rec
    assert rec["task_retries"] == 0, rec


def test_kill_worker_during_partitioned_spill_join(task_cluster):
    """kill-worker lands while the join stage is running partitioned
    (a revoke-memory fault demoted build partitions first): TASK
    policy recovers inside attempt 0 and the answer stays byte-equal
    to the fault-free oracle."""
    c = task_cluster
    _await_capacity(c)
    clean = getattr(c, "_q3_clean", None) or c.execute(Q3).rows
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.", "revoke-memory", times=16,
                         countdown=2)
    c.fault_schedule.add(f"{qid}.f1", "kill-worker")
    mark = len(c.task_launches)
    res = c.execute(Q3)
    assert res.rows == clean
    launches = _launches_since(c, mark)
    assert all("a0." in t for t in launches), launches
    rec = res.stats["recovery"]
    assert rec["query_retries"] == 0
    _await_capacity(c)


# ------------------------------- elastic cluster + partial-stage retry ----


@pytest.fixture(scope="module")
def elastic_cluster():
    """partial_stage_retry over the default streaming shape: producers
    retain their serialized frames (durable streams), tee pages into
    the external spool backend, and consumers resolve lost producers
    through the coordinator's resolve_task op — the elastic-cluster
    fault model where task output outlives its worker."""
    s = _mk_session(retry_policy="QUERY", partial_stage_retry=True)
    with ProcessQueryRunner(CATALOGS, s, n_workers=2, desired_splits=4,
                            broadcast_threshold=300.0,
                            heartbeat_interval=0.25) as c:
        c.fault_schedule = FaultSchedule(seed=42)
        yield c


def test_partial_retry_restarts_only_lost_tasks(local, elastic_cluster):
    """THE acceptance scenario: a producer-task worker dies mid-stream
    during a multi-stage streaming query. ONLY the lost tasks restart
    (same wire ids, ``.r1`` markers), consumers resume from their ack
    cursors, results stay byte-equal, and the query-retry counter stays
    at ZERO — no wholesale re-execution."""
    c = elastic_cluster
    clean = sorted(c.execute(Q1).rows)
    assert clean == sorted(local.execute(Q1).rows)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f1", "kill-worker")
    mark = len(c.task_launches)
    res = c.execute(Q1)
    assert sorted(res.rows) == clean
    rec = res.stats["recovery"]
    assert rec["query_retries"] == 0, rec
    launches = _launches_since(c, mark)
    assert not any("a1." in t for t in launches), launches
    assert any(".r1" in t for t in launches), launches
    _await_capacity(c)
    assert sorted(c.execute(Q1).rows) == clean


def test_partial_retry_join_pipeline(elastic_cluster):
    """Same fault against the join+TopN pipeline (4 fragments, merge
    output): the resolve cascade repoints merge channels too, still
    zero query retries, still byte-equal."""
    c = elastic_cluster
    _await_capacity(c)
    clean = c.execute(Q3).rows
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f1", "kill-worker")
    res = c.execute(Q3)
    assert res.rows == clean
    assert res.stats["recovery"]["query_retries"] == 0
    _await_capacity(c)


def test_scale_down_mid_query_streaming(elastic_cluster):
    """retire_worker(drain=True) while a streaming query runs: the
    slot drains (finishes its tasks) before the process dies, the
    in-flight query loses nothing, and the shrunk cluster keeps
    answering correctly."""
    c = elastic_cluster
    _await_capacity(c)
    clean = sorted(c.execute(Q1).rows)
    assert c.add_workers(1, reason="test-grow") == 1
    results = {}

    def run_q():
        results["r"] = c.execute(Q1)

    th = threading.Thread(target=run_q, daemon=True)
    th.start()
    time.sleep(0.05)
    assert c.retire_worker(len(c.workers) - 1, drain=True, timeout=60)
    th.join(timeout=60)
    assert not th.is_alive()
    assert sorted(results["r"].rows) == clean
    assert results["r"].stats["recovery"]["query_retries"] == 0
    assert len(c.workers) == 2
    assert sorted(c.execute(Q1).rows) == clean


def test_scale_down_mid_query_barrier(elastic_cluster):
    """Drain-based retire under the barrier shape: stage results on the
    draining worker are pulled before it exits — loss-free, zero
    retries of any kind."""
    c = elastic_cluster
    _await_capacity(c)
    saved = dict(c.session.properties)
    c.session.properties["streaming_execution"] = False
    try:
        clean = sorted(c.execute(Q1).rows)
        assert c.add_workers(1, reason="test-grow") == 1
        results = {}

        def run_q():
            results["r"] = c.execute(Q1)

        th = threading.Thread(target=run_q, daemon=True)
        th.start()
        time.sleep(0.05)
        assert c.retire_worker(len(c.workers) - 1, drain=True,
                               timeout=60)
        th.join(timeout=60)
        assert not th.is_alive()
    finally:
        c.session.properties.clear()
        c.session.properties.update(saved)
    assert sorted(results["r"].rows) == clean
    # a stage launch may race the retire onto the dying slot; the
    # lost-worker seam absorbs it as a task retry — never a query retry
    assert results["r"].stats["recovery"]["query_retries"] == 0
    assert len(c.workers) == 2


def test_membership_churn_races_heal(elastic_cluster):
    """A worker dies the moment the membership is also growing: the
    heal loop replaces the dead slot while add_workers registers a new
    one — no lost slots, no double-registration, queries stay exact,
    and the ledger recorded every transition."""
    c = elastic_cluster
    _await_capacity(c)
    clean = sorted(c.execute(Q1).rows)
    joined_before, retired_before = c.cluster.counts()
    victim = c.workers[0]
    victim.proc.kill()
    assert c.add_workers(1, reason="churn") == 1
    _await_capacity(c)
    assert sorted(c.execute(Q1).rows) == clean
    assert c.retire_worker(len(c.workers) - 1, drain=True, timeout=60)
    assert len(c.workers) == 2
    joined, retired = c.cluster.counts()
    assert joined >= joined_before + 2   # churn join + heal replacement
    assert retired >= retired_before + 2  # killed slot + drained retire
    active = [n for n in c.cluster.snapshot() if n.state == "active"]
    assert len(active) == len(c.workers)


def test_kill_after_publish_served_from_spool(task_cluster):
    """A worker dies right AFTER durably publishing a task's output:
    the output outlives the process — the coordinator adopts the
    published spool bytes instead of relaunching the task (zero
    retries), and the dead slot heals in the background."""
    c = task_cluster
    _await_capacity(c)
    clean = getattr(c, "_q1_clean", None) or sorted(c.execute(Q1).rows)
    pids = sorted(w.proc.pid for w in c.workers)
    qid = _next_qid(c)
    c.fault_schedule.add(f"{qid}.f1", "kill-after-publish")
    mark = len(c.task_launches)
    res = c.execute(Q1)
    assert sorted(res.rows) == clean
    launches = _launches_since(c, mark)
    assert not any(".r1" in t for t in launches
                   if f"{qid}.f1." in t), launches
    rec = res.stats["recovery"]
    assert rec["task_retries"] == 0, rec
    assert rec["query_retries"] == 0, rec
    _await_capacity(c)
    # the fault really killed a process: one slot healed to a new pid
    assert sorted(w.proc.pid for w in c.workers) != pids


def test_stream_spool_corruption_is_loud_and_typed():
    """A corrupted committed spool object fails the reader with the
    typed SpoolCorruption — short reads and checksum mismatches never
    surface as silently-partial rows."""
    import os

    from trino_tpu import types as T
    from trino_tpu.block import Page
    from trino_tpu.parallel.spool import SpoolCorruption
    from trino_tpu.parallel.spool_backend import (
        LocalFileSpoolBackend, SpooledTaskWriter, committed_attempt,
        open_committed_partition, partition_key)

    be = LocalFileSpoolBackend()
    try:
        w = SpooledTaskWriter(be, "qx", 0, 0, 0, 1)
        w.add(0, Page.from_pylists([T.BIGINT, T.VARCHAR],
                                   [[1, 2], ["a", "b"]]))
        assert w.commit()
        assert committed_attempt(be, "qx", 0, 0) == 0
        path = os.path.join(be.base_dir,
                            partition_key("qx", 0, 0, 0, 0))
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 3)
        with pytest.raises(SpoolCorruption):
            open_committed_partition(be, "qx", 0, 0, 0).pages()
    finally:
        be.remove_all()


def test_sizing_seed_ships_to_joining_worker(elastic_cluster):
    """Exchange-sizing knowledge crosses the membership boundary: a
    joining worker is configured with the coordinator's merged sizing
    history and acknowledges how many entries it imported."""
    from trino_tpu.parallel.device_exchange import SIZING_HISTORY

    c = elastic_cluster
    _await_capacity(c)
    SIZING_HISTORY.import_seed(
        [[[["bigint"], "chaos-synthetic", 2, 4], 321.0, 3, None]])
    assert c.add_workers(1, reason="seed-test") == 1
    try:
        assert c.workers[-1].sizing_seeded >= 1
    finally:
        assert c.retire_worker(len(c.workers) - 1, drain=True,
                               timeout=60)
    assert len(c.workers) == 2


def test_system_runtime_nodes_reflects_ledger(elastic_cluster):
    """system.runtime.nodes is the SQL view of the membership ledger:
    one ACTIVE row per live slot, RETIRED rows for everything the
    module churned through, generations monotonic."""
    c = elastic_cluster
    _await_capacity(c)
    rows = c.execute(
        "select node_id, address, state, pid, generation "
        "from system.runtime.nodes").rows
    active = [r for r in rows if r[2] == "ACTIVE"]
    assert len(active) == len(c.workers)
    live_pids = {w.proc.pid for w in c.workers}
    assert {r[3] for r in active} == live_pids
    assert any(r[2] == "RETIRED" for r in rows)
    gens = [r[4] for r in rows]
    assert gens == sorted(gens)
    # elastic metrics families are registered alongside
    fams = {f["name"] for f in c.metrics_families()}
    assert {"trino_cluster_size", "trino_nodes_total",
            "trino_autoscaler_target_workers"} <= fams
