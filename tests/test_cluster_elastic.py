"""Elastic membership primitives: ledger, placement, autoscaler policy.

Pure-python units (no worker processes): the ClusterLedger's generation
monotonicity, topology-aware placement determinism (including exact
degeneration to the historical round-robin when topology carries no
signal), and the autoscaler's hysteresis/cooldown/bounds behavior.
Process-level elasticity (add/retire mid-query, chaos) lives in
test_chaos.py and the BENCH_ROLE=elastic smoke.
"""

from trino_tpu.parallel.autoscaler import Autoscaler
from trino_tpu.parallel.cluster import (NODE_ACTIVE, NODE_DRAINING,
                                        NODE_RETIRED, ClusterLedger,
                                        place_task)


class _W:
    def __init__(self, port):
        self.addr = ("127.0.0.1", port)


# -- ledger ------------------------------------------------------------


def test_ledger_generation_monotonic_over_churn():
    led = ClusterLedger()
    n1 = led.record_join(("127.0.0.1", 1), pid=11, reason="initial")
    n2 = led.record_join(("127.0.0.1", 2), pid=12, reason="initial")
    assert (n1.generation, n2.generation) == (1, 2)
    assert led.generation == 2
    led.mark_draining(n1.node_id)
    assert led.snapshot()[0].state == NODE_DRAINING
    assert led.record_retire(n1.node_id, "scale-down") is not None
    assert led.generation == 3
    # double-retire is a no-op, generation does not advance
    assert led.record_retire(n1.node_id) is None
    assert led.generation == 3
    n3 = led.record_join(("127.0.0.1", 3), pid=13, reason="heal")
    assert n3.generation == 4
    states = [n.state for n in led.snapshot()]
    assert states == [NODE_RETIRED, NODE_ACTIVE, NODE_ACTIVE]
    assert led.counts() == (3, 1)


# -- placement ---------------------------------------------------------


def test_place_task_degenerates_to_round_robin_without_topology():
    ws = [_W(1), _W(2), _W(3)]
    for t in range(7):
        assert place_task(t, 0, ws) is ws[t % 3]
        # no upstream signal at all (leaf scan)
        assert place_task(t, 0, ws, upstream_addrs=[]) is ws[t % 3]
        # upstream lives elsewhere entirely: still round-robin
        assert place_task(t, 0, ws,
                          upstream_addrs=[("10.0.0.9", 5)]) is ws[t % 3]


def test_place_task_prefers_upstream_holder():
    ws = [_W(1), _W(2), _W(3)]
    up = [("127.0.0.1", 2), ("127.0.0.1", 2), ("127.0.0.1", 3)]
    # worker 2 holds two of three producer tasks: every task index
    # prefers it (deterministically)
    for t in range(5):
        assert place_task(t, 0, ws, upstream_addrs=up) is ws[1]


def test_place_task_breaks_score_ties_round_robin():
    ws = [_W(1), _W(2), _W(3)]
    up = [("127.0.0.1", 1), ("127.0.0.1", 3)]
    # workers 1 and 3 tie: rotate between them by task index
    assert place_task(0, 0, ws, upstream_addrs=up) is ws[0]
    assert place_task(1, 0, ws, upstream_addrs=up) is ws[2]
    assert place_task(2, 0, ws, upstream_addrs=up) is ws[0]


def test_place_task_retry_rotates_full_candidate_list():
    ws = [_W(1), _W(2), _W(3)]
    up = [("127.0.0.1", 2)]
    # retry ignores stale affinity: the preferred node just failed
    assert place_task(0, 1, ws, upstream_addrs=up) is ws[1]
    assert place_task(0, 2, ws, upstream_addrs=up) is ws[2]


# -- autoscaler --------------------------------------------------------


def _mk(clock):
    return Autoscaler(clock=lambda: clock[0])


def test_autoscaler_hysteresis_and_doubling():
    clock = [0.0]
    a = _mk(clock)
    kw = dict(min_workers=2, max_workers=8, cooldown_s=10.0,
              up_queue_depth=1, down_idle_ticks=4)
    # one pressure tick is not enough (hysteresis)
    assert a.tick(size=2, queued=3, running=2, **kw) is None
    d = a.tick(size=2, queued=3, running=2, **kw)
    assert d == {"direction": "up", "from": 2, "to": 4,
                 "reason": "queued=3"}
    # cooldown: sustained pressure cannot fire again yet
    assert a.tick(size=4, queued=3, running=4, **kw) is None
    assert a.tick(size=4, queued=3, running=4, **kw) is None
    clock[0] = 11.0
    d = a.tick(size=4, queued=3, running=4, **kw)
    assert d["to"] == 8  # doubles, capped at max
    clock[0] = 22.0
    a.tick(size=8, queued=9, running=8, **kw)
    assert a.tick(size=8, queued=9, running=8, **kw) is None  # at max
    assert a.scale_ups == 2


def test_autoscaler_idle_scale_down_one_at_a_time():
    clock = [0.0]
    a = _mk(clock)
    kw = dict(min_workers=2, max_workers=8, cooldown_s=5.0,
              up_queue_depth=1, down_idle_ticks=3)
    for _ in range(2):
        assert a.tick(size=4, queued=0, running=0, **kw) is None
    d = a.tick(size=4, queued=0, running=0, **kw)
    assert d == {"direction": "down", "from": 4, "to": 3,
                 "reason": "idle 3 ticks"}
    # a busy (but unpressured) tick resets the idle streak
    clock[0] = 10.0
    a.tick(size=3, queued=0, running=0, **kw)
    a.tick(size=3, queued=0, running=1, **kw)  # reset
    a.tick(size=3, queued=0, running=0, **kw)
    a.tick(size=3, queued=0, running=0, **kw)
    assert a.tick(size=3, queued=0, running=0, **kw)["to"] == 2
    # never below min
    clock[0] = 20.0
    for _ in range(10):
        assert a.tick(size=2, queued=0, running=0, **kw) is None
    assert a.scale_downs == 2


def test_autoscaler_below_min_restores_immediately():
    a = _mk([0.0])
    d = a.tick(size=1, queued=0, running=0, min_workers=2,
               max_workers=8, cooldown_s=100.0, up_queue_depth=1,
               down_idle_ticks=4)
    assert d == {"direction": "up", "from": 1, "to": 2,
                 "reason": "below min_workers"}


def test_autoscaler_blocked_nodes_count_as_pressure():
    clock = [0.0]
    a = _mk(clock)
    kw = dict(min_workers=1, max_workers=4, cooldown_s=0.0,
              up_queue_depth=5, down_idle_ticks=4)
    a.tick(size=2, queued=0, running=1, blocked_nodes=1, **kw)
    d = a.tick(size=2, queued=0, running=1, blocked_nodes=1, **kw)
    assert d["direction"] == "up" and "blocked_nodes" in d["reason"]


def test_autoscaler_deterministic_replay():
    ticks = [dict(size=2, queued=q, running=r)
             for q, r in [(0, 0), (2, 1), (3, 2), (0, 1), (0, 0),
                          (0, 0), (0, 0), (0, 0)]]
    kw = dict(min_workers=1, max_workers=8, cooldown_s=0.0,
              up_queue_depth=1, down_idle_ticks=2)

    def run():
        clock = [0.0]
        a = _mk(clock)
        out = []
        for t in ticks:
            clock[0] += 1.0
            out.append(a.tick(**t, **kw))
        return out

    assert run() == run()
