"""Compile-count stability: same-shape pages must not retrace.

Silent retracing (a jit cache key that varies page-to-page) is the
classic JAX perf bug — the engine would recompile per page and slide to
interpreter speed. Every hot-path kernel bumps a named counter in
``trino_tpu.jit_stats`` at TRACE time only, so after a warmup page the
total must stay flat across same-shape pages. The driver attributes
per-operator deltas into OperatorStats, surfacing them through EXPLAIN
ANALYZE and the bench output.
"""

import numpy as np
import pytest

from trino_tpu import jit_stats
from trino_tpu import types as T
from trino_tpu.block import DevicePage, Page
from trino_tpu.ops.aggregation import AggCall, HashAggregationOperator, \
    resolve_agg_type


def _page(rng, n, nkeys=16):
    types = [T.BIGINT, T.BIGINT, T.REAL]
    cols = [[int(v) for v in rng.integers(0, nkeys, size=n)],
            [int(v) for v in rng.integers(-100, 100, size=n)],
            [float(np.float32(v)) for v in rng.normal(size=n)]]
    return types, DevicePage.from_page(Page.from_pylists(types, cols))


AGGS = [AggCall("count_star", None, None, T.BIGINT),
        AggCall("sum", 1, T.BIGINT, resolve_agg_type("sum", T.BIGINT)),
        AggCall("max", 2, T.REAL, T.REAL)]


@pytest.mark.parametrize("hash_grouping", [True, False])
def test_agg_same_shape_pages_do_not_retrace(hash_grouping):
    rng = np.random.default_rng(1)
    types, warm = _page(rng, 1000)
    op = HashAggregationOperator(types, [0], AGGS, "single",
                                 hash_grouping=hash_grouping)
    op.add_input(warm)  # warmup page pays all traces
    before = jit_stats.total()
    for _ in range(4):
        _, page = _page(rng, 1000)
        op.add_input(page)
    assert jit_stats.total() == before, (
        "same-shape pages retraced the aggregation path: "
        f"{jit_stats.counts()}")
    op.finish()
    assert op.get_output() is not None


def test_partial_passthrough_does_not_retrace():
    """The adaptive pass-through layout conversion is sort/jit-free; it
    must add zero traces once tripped."""
    rng = np.random.default_rng(2)
    types, warm = _page(rng, 1024, nkeys=10**9)
    op = HashAggregationOperator(types, [0], AGGS, "partial",
                                 adaptive_partial=True,
                                 adaptive_min_rows=64, adaptive_ratio=0.5)
    op.add_input(warm)
    assert op.passthrough
    before = jit_stats.total()
    for _ in range(3):
        _, page = _page(rng, 1024, nkeys=10**9)
        op.add_input(page)
    assert jit_stats.total() == before, jit_stats.counts()


def test_driver_attributes_compile_counts_and_explain_reports_them():
    """End-to-end: per-operator compile counts flow into Driver stats
    and the EXPLAIN ANALYZE rendering."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runner import LocalQueryRunner

    runner = LocalQueryRunner({"tpch": TpchConnector(page_rows=2048)})
    runner.session.catalog = "tpch"
    runner.session.schema = "micro"
    res = runner.execute(
        "EXPLAIN ANALYZE SELECT l_returnflag, count(*), sum(l_quantity) "
        "FROM lineitem GROUP BY l_returnflag")
    text = "\n".join(r[0] for r in res.rows)
    assert "compiles" in text
    # the aggregation operator line carries its compile count
    agg_lines = [ln for ln in text.splitlines()
                 if "HashAggregationOperator" in ln]
    assert agg_lines and all("compiles" in ln for ln in agg_lines)


def test_query_repeat_keeps_kernel_traces_flat():
    """Running the same query shape again must not re-trace the
    module-level grouping kernels (the jit caches are keyed on shapes +
    static config, not operator instances)."""
    rng = np.random.default_rng(3)
    types, warm = _page(rng, 512)

    def run_once():
        op = HashAggregationOperator(types, [0], AGGS, "single")
        for _ in range(2):
            _, page = _page(rng, 512)
            op.add_input(page)
        op.finish()
        return op.get_output()

    run_once()  # warmup
    grouping = ("hash_group_ids", "hash_segment_reduce",
                "sort_group_reduce", "segment_reduce_pallas")
    before = {k: v for k, v in jit_stats.counts().items() if k in grouping}
    run_once()
    after = {k: v for k, v in jit_stats.counts().items() if k in grouping}
    assert after == before, (before, after)
