"""Memory/blackhole connectors + write path (CTAS/INSERT/DELETE/DDL).

Reference analog: plugin/trino-memory and plugin/trino-blackhole test
suites + AbstractTestQueries write tests.
"""

import pytest

from trino_tpu.connectors.blackhole import BlackHoleConnector
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture()
def runner():
    return LocalQueryRunner(
        {"memory": MemoryConnector(),
         "blackhole": BlackHoleConnector(rows_per_page=10,
                                         pages_per_split=2, split_count=2),
         "tpch": TpchConnector(page_rows=4096)},
        Session(catalog="memory", schema="default"))


def test_create_insert_select(runner):
    runner.execute("create table t (a bigint, b varchar)")
    r = runner.execute("insert into t values (1, 'x'), (2, 'y')")
    assert r.rows == [(2,)]
    r = runner.execute("select * from t order by a")
    assert r.rows == [(1, "x"), (2, "y")]
    # positional + named column insert
    runner.execute("insert into t (b, a) values ('z', 3)")
    r = runner.execute("select * from t order by a")
    assert r.rows == [(1, "x"), (2, "y"), (3, "z")]


def test_ctas_from_tpch(runner):
    r = runner.execute("create table n as select n_name, n_regionkey "
                       "from tpch.micro.nation")
    assert r.rows == [(25,)]
    r = runner.execute("select count(*), max(n_regionkey) from n")
    assert r.rows == [(25, 4)]
    # group by on re-read string column
    r = runner.execute("select n_regionkey, count(*) from n "
                       "group by n_regionkey order by n_regionkey")
    assert r.rows == [(0, 5), (1, 5), (2, 5), (3, 5), (4, 5)]


def test_insert_missing_columns_get_null(runner):
    runner.execute("create table u (a bigint, b double, c varchar)")
    runner.execute("insert into u (a) values (7)")
    assert runner.execute("select * from u").rows == [(7, None, None)]


def test_delete(runner):
    runner.execute("create table d as select n_nationkey, n_regionkey "
                   "from tpch.micro.nation")
    r = runner.execute("delete from d where n_regionkey = 0")
    assert r.rows == [(5,)]
    assert runner.execute("select count(*) from d").rows == [(20,)]
    r = runner.execute("delete from d")
    assert r.rows == [(20,)]
    assert runner.execute("select count(*) from d").rows == [(0,)]


def test_drop_table(runner):
    runner.execute("create table g (x bigint)")
    assert ("g",) in runner.execute("show tables").rows
    runner.execute("drop table g")
    assert ("g",) not in runner.execute("show tables").rows
    # if exists
    runner.execute("drop table if exists g")
    with pytest.raises(Exception):
        runner.execute("drop table g")


def test_create_if_not_exists(runner):
    runner.execute("create table e (x bigint)")
    runner.execute("create table if not exists e (x bigint)")
    with pytest.raises(Exception):
        runner.execute("create table e (x bigint)")


def test_blackhole_read_write(runner):
    runner.execute("create table blackhole.default.bh "
                   "as select n_nationkey from tpch.micro.nation")
    # reads produce synthetic rows: 2 splits x 2 pages x 10 rows
    r = runner.execute("select count(*) from blackhole.default.bh")
    assert r.rows == [(40,)]
    r = runner.execute("insert into blackhole.default.bh values (1), (2)")
    assert r.rows == [(2,)]


def test_memory_distributed_read():
    from trino_tpu.parallel.distributed import DistributedQueryRunner

    mem = MemoryConnector()
    tpch = TpchConnector(page_rows=1024)
    local = LocalQueryRunner({"memory": mem, "tpch": tpch},
                             Session(catalog="memory", schema="default"))
    local.execute("create table li as select l_orderkey, l_quantity "
                  "from tpch.micro.lineitem")
    dist = DistributedQueryRunner({"memory": mem, "tpch": tpch},
                                  Session(catalog="memory",
                                          schema="default"), n_workers=3)
    want = local.execute("select count(*), sum(l_quantity) from li").rows
    got = dist.execute("select count(*), sum(l_quantity) from li").rows
    assert got == want
