"""Device-collective exchange inside DistributedQueryRunner.

The flagship TPU-native path (SURVEY.md §2.8): hash stage boundaries run
as one all_to_all over the mesh. These tests assert the collective
ACTUALLY runs (not silently falling back to the host path) and that
results are identical either way.
"""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.parallel import distributed as dist_mod
from trino_tpu.parallel.device_exchange import DeviceExchange
from trino_tpu.parallel.distributed import DistributedQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(page_rows=2048)


def _runner(conn, device: bool, n_workers: int = 3):
    s = Session(catalog="tpch", schema="micro")
    s.properties["device_exchange"] = device
    return DistributedQueryRunner({"tpch": conn}, s, n_workers=n_workers,
                                  desired_splits=8,
                                  broadcast_threshold=300.0)


def _key(row):
    return tuple(("\0" if v is None else str(v)) for v in row)


QUERIES = [
    # group-by: partial agg -> hash exchange -> final agg
    "select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
    "from lineitem group by l_returnflag, l_linestatus",
    # string group keys: pool unification + value-stable routing
    "select l_shipmode, count(*) from lineitem group by l_shipmode",
    # partitioned join: both sides hash-exchange on orderkey
    "select o_orderpriority, count(*) from orders, lineitem "
    "where o_orderkey = l_orderkey and l_quantity < 10 "
    "group by o_orderpriority",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_device_vs_host_exchange_identical(conn, sql):
    dev = _runner(conn, True)
    host = _runner(conn, False)
    drows = sorted(dev.execute(sql).rows, key=_key)
    hrows = sorted(host.execute(sql).rows, key=_key)
    assert drows == hrows


def test_collective_actually_runs(conn, monkeypatch):
    """Guard against silent host fallback: the a2a path must execute for
    a plain group-by."""
    ran = []
    orig = DeviceExchange._collect

    def spying_collect(self):
        out = orig(self)
        ran.append(self.collective_ran)
        return out

    monkeypatch.setattr(DeviceExchange, "_collect", spying_collect)
    r = _runner(conn, True)
    res = r.execute("select l_returnflag, count(*) from lineitem "
                    "group by l_returnflag")
    assert len(res.rows) == 3
    assert any(ran), "device exchange fell back to host path"


def test_device_exchange_disabled_uses_host(conn):
    r = _runner(conn, False)
    frag = None
    for f in r.create_fragments(
            "select l_returnflag, count(*) from lineitem "
            "group by l_returnflag"):
        if f.output_kind == "hash":
            frag = f
    assert frag is not None
    assert r._device_exchange_for(frag, r.n_workers) is None


def test_device_exchange_chosen_for_hash(conn):
    r = _runner(conn, True)
    frag = None
    for f in r.create_fragments(
            "select l_returnflag, count(*) from lineitem "
            "group by l_returnflag"):
        if f.output_kind == "hash":
            frag = f
    assert isinstance(r._device_exchange_for(frag, r.n_workers),
                      DeviceExchange)
    # task-count mismatch -> host fallback
    assert r._device_exchange_for(frag, r.n_workers + 1) is None


@pytest.mark.parametrize("n_devices", [1, 2])
@pytest.mark.parametrize("sql", QUERIES)
def test_fewer_devices_than_partitions(conn, monkeypatch, sql, n_devices):
    """Single-chip degeneracy: p partitions on d < p devices (partition
    p lives on device p % d, ids carried through the collective). The
    flagship path must EXECUTE — not fall back — and match the host
    path. Ref: operator/output/PartitionedOutputOperator.java (which has
    no such coupling because its buffers are host-side)."""
    import jax

    real = jax.devices()
    monkeypatch.setattr(jax, "devices",
                        lambda *a, **k: real[:n_devices])
    ran = []
    orig = DeviceExchange._collect

    def spying_collect(self):
        assert self.d == min(n_devices, self.n)
        out = orig(self)
        ran.append(self.collective_ran)
        return out

    monkeypatch.setattr(DeviceExchange, "_collect", spying_collect)
    dev = _runner(conn, True)
    drows = sorted(dev.execute(sql).rows, key=_key)
    monkeypatch.undo()
    host = _runner(conn, False)
    hrows = sorted(host.execute(sql).rows, key=_key)
    assert drows == hrows
    assert any(ran), "device exchange fell back to host path"
