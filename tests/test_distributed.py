"""DistributedQueryRunner vs LocalQueryRunner equivalence.

Reference analog: the AbstractTestQueries suites run against
DistributedQueryRunner (N servers, real exchanges) asserting the same
results as single-node execution.
"""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.parallel.distributed import DistributedQueryRunner
from trino_tpu.resources.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(page_rows=4096)


@pytest.fixture(scope="module")
def local(conn):
    return LocalQueryRunner({"tpch": conn},
                            Session(catalog="tpch", schema="micro"))


@pytest.fixture(scope="module")
def dist(conn):
    return DistributedQueryRunner({"tpch": conn},
                                  Session(catalog="tpch", schema="micro"),
                                  n_workers=3, desired_splits=8,
                                  broadcast_threshold=300.0)


def _norm(row):
    # f64 aggregate addend order differs between the mesh partitioning
    # and local execution: compare floats at 9 significant digits
    return tuple("\0" if v is None
                 else f"{v:.9g}" if isinstance(v, float) else str(v)
                 for v in row)


def _key(row):
    return _norm(row)


def check(local, dist, sql, ordered=None):
    lres = local.execute(sql)
    dres = dist.execute(sql)
    if ordered is None:
        ordered = "order by" in sql.lower()
    lrows = [_norm(r) for r in lres.rows]
    drows = [_norm(r) for r in dres.rows]
    if not ordered:
        lrows = sorted(lrows)
        drows = sorted(drows)
    assert drows == lrows, \
        f"distributed != local for {sql[:80]}...\n" \
        f"dist={drows[:5]}\nlocal={lrows[:5]}"


def test_scan_filter(local, dist):
    check(local, dist, "select n_name from nation where n_regionkey = 2")


def test_global_agg(local, dist):
    check(local, dist,
          "select count(*), sum(l_quantity), min(l_shipdate), "
          "avg(l_discount) from lineitem")


def test_group_by(local, dist):
    check(local, dist,
          "select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
          "from lineitem group by l_returnflag, l_linestatus "
          "order by l_returnflag, l_linestatus")


def test_string_group_keys(local, dist):
    check(local, dist,
          "select l_shipmode, count(*) from lineitem "
          "group by l_shipmode order by l_shipmode")


def test_full_outer_join(local, dist):
    # FULL forces co-partitioned distribution; unmatched rows from both
    # sides must appear exactly once across workers
    check(local, dist, """
        select o_custkey, c_custkey
        from (select o_custkey from orders where o_custkey < 100) o
        full outer join customer c
        on o_custkey = c_custkey""")


def test_distributed_explain_analyze(dist):
    res = dist.execute("""explain analyze
        select n_regionkey, count(*) c from nation
        group by n_regionkey order by c desc""")
    text = "\n".join(r[0] for r in res.rows)
    assert "Stage" in text and "task 0:" in text
    assert "TableScanOperator" in text
    tree = res.stats["query_stats"]
    assert tree["stages"], tree
    stage_ids = {s["stage_id"] for s in tree["stages"]}
    assert len(stage_ids) >= 2  # source stage + final stage at least
    for s in tree["stages"]:
        assert s["tasks"], s
        for t in s["tasks"]:
            assert isinstance(t["wall_ms"], float)
            assert t["operators"]


def test_broadcast_join(local, dist):
    check(local, dist,
          "select n_name, count(*) c from customer, nation "
          "where c_nationkey = n_nationkey group by n_name order by c, "
          "n_name")


def test_partitioned_join(local, dist):
    # orders x lineitem is above the (tiny) broadcast threshold ->
    # both sides hash-exchange on orderkey
    check(local, dist,
          "select o_orderpriority, count(*) from orders, lineitem "
          "where o_orderkey = l_orderkey and l_quantity < 10 "
          "group by o_orderpriority order by o_orderpriority")


def test_distinct_distributed(local, dist):
    check(local, dist,
          "select distinct c_nationkey from customer order by c_nationkey")


def test_topn_and_limit(local, dist):
    check(local, dist,
          "select c_custkey, c_acctbal from customer "
          "order by c_acctbal desc, c_custkey limit 7")
    lres = local.execute("select count(*) from (select * from lineitem "
                         "limit 100) t")
    dres = dist.execute("select count(*) from (select * from lineitem "
                        "limit 100) t")
    assert lres.rows == dres.rows == [(100,)]


def test_semi_join_distributed(local, dist):
    check(local, dist, """
        select count(*) from orders where o_custkey in
        (select c_custkey from customer where c_acctbal > 0)""")


# tier-1 keeps a representative distributed smoke (q1 aggregation, q3
# join+agg+TopN); the full 22-query sweep runs in the slow tier — each
# distributed query costs 5-25s on the virtual mesh and the tier-1
# budget cannot hold all of them alongside the rest of the suite
TPCH_DIST_TIER1 = (1, 3)


@pytest.mark.parametrize("qid", [
    pytest.param(q, marks=() if q in TPCH_DIST_TIER1
                 else (pytest.mark.slow,))
    for q in sorted(TPCH_QUERIES)])
def test_tpch_distributed(qid, local, dist):
    """All 22 TPC-H queries through the distributed runner (round-4
    verdict: the assertions must cover the same breadth the execution
    paths do)."""
    if qid in (2, 15, 17, 20):
        # ties under LIMIT (q2) / tied top-supplier revenue (q15) /
        # correlated-avg ties (q17/q20) can legitimately pick different
        # rows: compare row counts AND the multiset of first-column
        # values (tie-insensitive, catches value corruption)
        lres = local.execute(TPCH_QUERIES[qid])
        dres = dist.execute(TPCH_QUERIES[qid])
        assert len(lres.rows) == len(dres.rows)
        lfirst = sorted(_norm((r[0],)) for r in lres.rows)
        dfirst = sorted(_norm((r[0],)) for r in dres.rows)
        assert lfirst == dfirst
        return
    check(local, dist, TPCH_QUERIES[qid])


@pytest.fixture(scope="module")
def tpcds_pair():
    from trino_tpu.connectors.tpcds import TpcdsConnector

    conn = TpcdsConnector(page_rows=4096)
    local = LocalQueryRunner({"tpcds": conn},
                             Session(catalog="tpcds", schema="micro"))
    s = Session(catalog="tpcds", schema="micro")
    # host-path exchanges: q64/q72's dozen join boundaries would each
    # compile a fresh XLA collective (minutes of compile for no extra
    # coverage — the collective path is exercised by TPC-H + the dryrun)
    s.properties["device_exchange"] = False
    dist = DistributedQueryRunner({"tpcds": conn}, s,
                                  n_workers=3, desired_splits=8,
                                  broadcast_threshold=300.0)
    return local, dist


@pytest.mark.parametrize("qid", [
    pytest.param(q, marks=(pytest.mark.slow,))
    for q in (3, 7, 19, 42, 55, 64, 72)])
def test_tpcds_distributed(qid, tpcds_pair):
    """TPC-DS through the distributed runner — the round-4 verdict
    flagged TPC-DS as local-only."""
    from trino_tpu.resources.tpcds_queries import TPCDS_QUERIES

    local, dist = tpcds_pair
    check(local, dist, TPCDS_QUERIES[qid])


def test_cold_connector_string_groups():
    """Fresh connector: dictionary pools grow concurrently across scan
    tasks (regression: unsynchronized Dictionary.code)."""
    cold = TpchConnector(page_rows=512)
    d = DistributedQueryRunner({"tpch": cold},
                               Session(catalog="tpch", schema="micro"),
                               n_workers=4, desired_splits=8)
    res = d.execute("select l_shipmode, count(*) from lineitem "
                    "group by l_shipmode order by l_shipmode")
    l = LocalQueryRunner({"tpch": TpchConnector(page_rows=512)},
                         Session(catalog="tpch", schema="micro"))
    want = l.execute("select l_shipmode, count(*) from lineitem "
                     "group by l_shipmode order by l_shipmode")
    assert res.rows == want.rows
