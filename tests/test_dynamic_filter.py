"""Dynamic filtering: build-side domains prune probe-side scans.

Reference analog: TestDynamicFiltering — a selective build side makes
the probe scan emit measurably fewer rows, without changing results.
"""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.dynamic_filter import DynamicFilter, resolve_scan_column
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session

SEMI_SQL = ("select count(*) from lineitem where l_orderkey in "
            "(select o_orderkey from orders where "
            "o_orderpriority = '1-URGENT' and o_totalprice > 150000)")

JOIN_SQL = ("select count(*), sum(l_quantity) from orders o, lineitem l "
            "where o.o_orderkey = l.l_orderkey "
            "and o.o_orderdate >= date '1995-01-01' "
            "and o.o_orderdate < date '1995-02-01'")


def run(sql, enabled=True):
    session = Session(catalog="tpch", schema="micro")
    session.properties["enable_dynamic_filtering"] = enabled
    r = LocalQueryRunner({"tpch": TpchConnector(page_rows=2048)}, session,
                         desired_splits=4)
    return r.execute(sql)


@pytest.mark.parametrize("sql", [SEMI_SQL, JOIN_SQL])
def test_results_unchanged_and_rows_pruned(sql):
    off = run(sql, enabled=False)
    on = run(sql, enabled=True)
    assert on.rows == off.rows
    assert "dynamic_filters" not in (off.stats or {})
    dfs = on.stats["dynamic_filters"]
    assert dfs, "no dynamic filter registered"
    total_pruned = sum(d["pruned_rows"] for d in dfs)
    total_scanned = sum(d["scanned_rows"] for d in dfs)
    assert all(d["ready"] for d in dfs)
    # the build sides are selective: most probe rows must be pruned
    assert total_pruned > 0.5 * total_scanned > 0


def test_left_join_not_filtered():
    """LEFT probes keep unmatched rows — no dynamic filter may apply."""
    sql = ("select count(*) from orders o left join lineitem l "
           "on o.o_orderkey = l.l_orderkey and l.l_quantity > 49")
    res = run(sql, enabled=True)
    assert "dynamic_filters" not in (res.stats or {})
    assert res.rows == run(sql, enabled=False).rows


def test_empty_build_prunes_everything():
    sql = ("select count(*) from lineitem where l_orderkey in "
           "(select o_orderkey from orders where o_totalprice < 0)")
    res = run(sql, enabled=True)
    assert res.rows == [(0,)]
    dfs = res.stats["dynamic_filters"]
    assert dfs and dfs[0]["build_rows"] == 0
    assert dfs[0]["pruned_rows"] == dfs[0]["scanned_rows"] > 0


def test_resolve_through_projection():
    """The scan walk follows renaming projections but stops at computed
    expressions."""
    from trino_tpu.planner.logical_planner import LogicalPlanner, Metadata
    from trino_tpu.planner.optimizer import optimize
    from trino_tpu.planner.plan import TableScanNode
    from trino_tpu.sql.parser import parse_statement

    meta = Metadata({"tpch": TpchConnector()})
    session = Session(catalog="tpch", schema="micro")
    planner = LogicalPlanner(meta, session)
    root = planner.plan(parse_statement(
        "select l_orderkey k from lineitem where l_quantity > 10"))
    root = optimize(root, meta, planner.allocator)
    sym = root.outputs[0]
    hit = resolve_scan_column(root.source, sym.name)
    assert hit is not None
    scan, pos = hit
    assert isinstance(scan, TableScanNode)
    assert scan.assignments[pos][0].type == sym.type


def test_filter_domain_semantics():
    import jax.numpy as jnp
    import numpy as np

    df = DynamicFilter("t")
    df.collect(jnp.asarray(np.array([5, 7, 9, 0], dtype=np.int64)),
               jnp.asarray(np.array([False, False, False, True])),
               jnp.asarray(np.array([True, True, True, True])))
    col = jnp.asarray(np.array([4, 5, 6, 7, 9, 10], dtype=np.int64))
    nulls = jnp.zeros(6, dtype=bool)
    valid = jnp.ones(6, dtype=bool)
    keep = np.asarray(df.apply(col, nulls, valid))
    assert keep.tolist() == [False, True, False, True, True, False]
    assert df.pruned_rows == 3
    assert df.scanned_rows == 6


def test_dynamic_filter_to_domain():
    """The build-side key domain interops with the TupleDomain model
    (round-4: dynamic filters re-expressed on predicate.Domain)."""
    import jax.numpy as jnp
    import numpy as np

    from trino_tpu.exec.dynamic_filter import DynamicFilter

    df = DynamicFilter("t")
    col = jnp.asarray(np.array([5, 9, 5, 12], dtype=np.int64))
    nulls = jnp.zeros(4, dtype=bool)
    valid = jnp.ones(4, dtype=bool)
    df.collect(col, nulls, valid)
    dom = df.to_domain()
    assert dom.includes(5) and dom.includes(9) and dom.includes(12)
    assert not dom.includes(7) and not dom.includes(None)

    empty = DynamicFilter("e")
    empty.collect(col, jnp.ones(4, dtype=bool), valid)  # all null keys
    assert empty.to_domain().is_none
