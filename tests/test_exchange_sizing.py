"""Skew-adaptive device-exchange sizing (parallel/device_exchange.py).

The overflow protocol used to be a 2x cost cliff: lane overflow re-ran
the WHOLE all_to_all with doubled per_dest, so a skewed key distribution
paid the full shuffle twice or more. These tests pin the count-first
protocol: a 90%-of-rows-in-one-partition exchange completes with ZERO
doubling retries and exactly one data collective (exact mode), the
per-shape history pre-sizes repeat shapes without re-counting OR
recompiling (asserted via jit_stats), legacy mode still shows the cliff
(the knob works), and the skew stats surface identically on the device
and host paths through EXPLAIN ANALYZE.
"""

import jax
import numpy as np
import pytest

from trino_tpu import jit_stats
from trino_tpu import types as T
from trino_tpu.block import DevicePage, Page
from trino_tpu.parallel.device_exchange import (DeviceExchange,
                                                SIZING_HISTORY)

SIZING_KERNELS = ("device_exchange_program", "device_exchange_count")


@pytest.fixture(autouse=True)
def fresh_history():
    SIZING_HISTORY.reset()
    yield
    SIZING_HISTORY.reset()


def _skewed_exchange(sizing: str, n: int = 4, d: int = None,
                     rows_per_task: int = 1000, hot_frac: float = 0.9,
                     seed: int = 0) -> DeviceExchange:
    """Build + drain a DeviceExchange where ~hot_frac of all rows carry
    ONE key (=> one hot partition). Returns the collected exchange."""
    devs = jax.devices()
    d = n if d is None else d
    ex = DeviceExchange(n, devs[:d], sizing=sizing)
    ex.configure([T.BIGINT, T.BIGINT], [0])
    rng = np.random.default_rng(seed)
    for t in range(n):
        keys = np.where(rng.random(rows_per_task) < hot_frac, 7,
                        rng.integers(0, 10_000, rows_per_task))
        vals = rng.integers(0, 100, rows_per_task)
        p = Page.from_pylists([T.BIGINT, T.BIGINT],
                              [keys.tolist(), vals.tolist()])
        ex.add_page(t, DevicePage.from_page(p))
    ex.set_no_more_pages()
    # drain every partition (first pages() call triggers the collective)
    total = sum(pg.count() for part in range(n) for pg in ex.pages(part))
    assert total == n * rows_per_task
    return ex


def test_exact_sizing_zero_retries_single_data_collective():
    before = DeviceExchange.total_collectives
    ex = _skewed_exchange("exact")
    assert ex.collective_ran
    assert ex.a2a_retries == 0
    assert ex.data_collectives == 1
    assert ex.count_collectives == 1
    assert DeviceExchange.total_collectives - before == 1
    s = ex.stats
    assert s["sizing_used"] == "exact"
    # 90% of 4000 rows in one of 4 partitions: skew ratio near 4 * 0.9
    assert s["skew_ratio"] > 2.5
    assert max(s["partition_rows"]) > 0.85 * s["rows"]
    assert s["per_dest"] >= s["observed_max_pair_rows"]
    assert s["bytes_moved"] > 0


def test_history_presizes_repeat_without_count_or_recompile():
    ex1 = _skewed_exchange("history", seed=1)
    assert ex1.count_collectives == 1  # unconfident: counted
    assert ex1.a2a_retries == 0
    traces_before = jit_stats.total_for(*SIZING_KERNELS)
    ex2 = _skewed_exchange("history", seed=1)
    # presized from history: no count pass, no doubling, and the data
    # program came straight from the lru_cache (zero new traces)
    assert ex2.count_collectives == 0
    assert ex2.a2a_retries == 0
    assert ex2.data_collectives == 1
    assert ex2.stats["sizing_used"] == "history"
    assert ex2.stats["per_dest"] == ex1.stats["per_dest"]
    assert jit_stats.total_for(*SIZING_KERNELS) == traces_before, (
        "history-presized repeat shape recompiled an exchange kernel")


def test_legacy_mode_pays_the_doubling_cliff():
    ex = _skewed_exchange("legacy")
    assert ex.count_collectives == 0
    assert ex.a2a_retries >= 1  # the 2x cliff the count pass removes
    assert ex.data_collectives == ex.a2a_retries + 1
    assert ex.stats["sizing"] == "legacy"


def test_stale_history_recovers_via_backstop_and_relearns():
    """An undersized history presize must not wedge the exchange: the
    doubling backstop completes it, and the observation re-teaches the
    history so the NEXT run presizes correctly."""
    # teach the history a tiny load for this exchange shape
    ex_small = _skewed_exchange("history", rows_per_task=40,
                                hot_frac=0.0, seed=2)
    assert ex_small.a2a_retries == 0
    # same shape signature (types/keys/n/d), much bigger skewed load
    ex_big = _skewed_exchange("history", rows_per_task=4000, seed=3)
    assert ex_big.count_collectives == 0  # presized (stale)
    assert ex_big.a2a_retries >= 1        # backstop fired
    ex_next = _skewed_exchange("history", rows_per_task=4000, seed=3)
    assert ex_next.a2a_retries == 0       # history re-learned


@pytest.mark.parametrize("d", [1, 2])
def test_skew_with_fewer_devices_than_partitions(d):
    """The d<p carried-partition path under 90% skew: partitions split
    device slabs by carried id, sizing stays exact (zero retries), and
    every row lands in its hash partition."""
    import jax.numpy as jnp

    from trino_tpu.parallel.exchange import hash_partition_ids

    n = 4
    ex = _skewed_exchange("exact", n=n, d=d, rows_per_task=500)
    assert ex.d == d and ex.n == n
    assert ex.a2a_retries == 0
    assert ex.data_collectives == 1
    assert sum(ex.stats["partition_rows"]) == ex.stats["rows"]
    # routing correctness: rows of partition p hash to p
    for part in range(n):
        for pg in ex.pages(part):
            keys = np.asarray(pg.cols[0])[np.asarray(pg.valid)]
            if len(keys) == 0:
                continue
            got = np.asarray(hash_partition_ids(
                [jnp.asarray(keys).astype(jnp.int64).view(jnp.uint64)],
                n))
            assert (got == part).all()


def test_host_buffer_stats_parity():
    """The host path exposes the SAME stats surface (keys) the device
    path records, so EXPLAIN ANALYZE renders both identically."""
    from trino_tpu.ops.output import OutputBuffer

    buf = OutputBuffer(4)
    for p, rows in ((0, 90), (1, 5), (2, 5)):
        page = Page.from_pylists([T.BIGINT], [list(range(rows))])
        buf.enqueue(p, page)
    s = buf.stats
    assert s["kind"] == "host"
    assert s["rows"] == 100
    assert s["partition_rows"] == [90, 5, 5, 0]
    assert s["skew_ratio"] == 3.6
    ex = _skewed_exchange("exact", seed=4)
    assert set(s) <= set(ex.stats) | {"source_fragment"}


def test_explain_analyze_shows_exchange_skew_lines():
    """Acceptance surface: EXPLAIN ANALYZE shows per-exchange skew
    ratio, per_dest chosen, and retry count on the device path."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.parallel.distributed import DistributedQueryRunner
    from trino_tpu.sql.analyzer import Session

    s = Session(catalog="tpch", schema="micro")
    s.properties["device_exchange"] = True
    s.properties["device_exchange_sizing"] = "exact"
    r = DistributedQueryRunner({"tpch": TpchConnector(page_rows=2048)}, s,
                               n_workers=3, desired_splits=8)
    res = r.execute(
        "EXPLAIN ANALYZE SELECT l_returnflag, count(*), sum(l_quantity) "
        "FROM lineitem GROUP BY l_returnflag")
    text = "\n".join(row[0] for row in res.rows)
    device_lines = [ln for ln in text.splitlines()
                    if "exchange [device]" in ln]
    assert device_lines, text
    for ln in device_lines:
        assert "skew" in ln and "per_dest=" in ln and "retries=" in ln
        assert "sizing=exact" in ln
    # host-side boundaries of the same query render the same shape
    assert any("exchange [host]" in ln for ln in text.splitlines())


def test_sizing_session_property_validates_and_normalizes():
    from trino_tpu.session_properties import set_property
    from trino_tpu.types import TrinoError

    props = {}
    set_property(props, "device_exchange_sizing", "EXACT")
    assert props["device_exchange_sizing"] == "exact"
    with pytest.raises(TrinoError):
        set_property(props, "device_exchange_sizing", "sometimes")
