"""Skew-adaptive device-exchange sizing (parallel/device_exchange.py).

The overflow protocol used to be a 2x cost cliff: lane overflow re-ran
the WHOLE all_to_all with doubled per_dest, so a skewed key distribution
paid the full shuffle twice or more. These tests pin the count-first
protocol: a 90%-of-rows-in-one-partition exchange completes with ZERO
doubling retries and exactly one data collective (exact mode), the
per-shape history pre-sizes repeat shapes without re-counting OR
recompiling (asserted via jit_stats), legacy mode still shows the cliff
(the knob works), and the skew stats surface identically on the device
and host paths through EXPLAIN ANALYZE.
"""

import jax
import numpy as np
import pytest

from trino_tpu import jit_stats
from trino_tpu import types as T
from trino_tpu.block import DevicePage, Page
from trino_tpu.parallel.device_exchange import (DeviceExchange,
                                                SIZING_HISTORY)

SIZING_KERNELS = ("device_exchange_program", "device_exchange_count")


@pytest.fixture(autouse=True)
def fresh_history():
    SIZING_HISTORY.reset()
    yield
    SIZING_HISTORY.reset()


def _skewed_exchange(sizing: str, n: int = 4, d: int = None,
                     rows_per_task: int = 1000, hot_frac: float = 0.9,
                     seed: int = 0,
                     threshold: float = 0.5) -> DeviceExchange:
    """Build + drain a DeviceExchange where ~hot_frac of all rows carry
    ONE key (=> one hot partition). Returns the collected exchange."""
    devs = jax.devices()
    d = n if d is None else d
    ex = DeviceExchange(n, devs[:d], sizing=sizing,
                        hot_split_threshold=threshold)
    ex.configure([T.BIGINT, T.BIGINT], [0])
    rng = np.random.default_rng(seed)
    for t in range(n):
        keys = np.where(rng.random(rows_per_task) < hot_frac, 7,
                        rng.integers(0, 10_000, rows_per_task))
        vals = rng.integers(0, 100, rows_per_task)
        p = Page.from_pylists([T.BIGINT, T.BIGINT],
                              [keys.tolist(), vals.tolist()])
        ex.add_page(t, DevicePage.from_page(p))
    ex.set_no_more_pages()
    # drain every partition (first pages() call triggers the collective)
    total = sum(pg.count() for part in range(n) for pg in ex.pages(part))
    assert total == n * rows_per_task
    return ex


def _partition_rows(ex: DeviceExchange, n: int):
    """Sorted (key, value) multiset per partition — the byte-equality
    surface: splitting may reorder rows across receiver slabs but must
    deliver the identical multiset to each consumer partition."""
    out = []
    for part in range(n):
        rows = []
        for pg in ex.pages(part):
            v = np.asarray(pg.valid)
            rows.extend(zip(np.asarray(pg.cols[0])[v].tolist(),
                            np.asarray(pg.cols[1])[v].tolist()))
        out.append(sorted(rows))
    return out


def test_exact_sizing_zero_retries_single_data_collective():
    before = DeviceExchange.total_collectives
    ex = _skewed_exchange("exact")
    assert ex.collective_ran
    assert ex.a2a_retries == 0
    assert ex.data_collectives == 1
    assert ex.count_collectives == 1
    assert DeviceExchange.total_collectives - before == 1
    s = ex.stats
    assert s["sizing_used"] == "exact"
    # 90% of 4000 rows in one of 4 partitions: skew ratio near 4 * 0.9
    assert s["skew_ratio"] > 2.5
    assert max(s["partition_rows"]) > 0.85 * s["rows"]
    assert s["per_dest"] >= s["observed_max_pair_rows"]
    assert s["bytes_moved"] > 0


def test_history_presizes_repeat_without_count_or_recompile():
    ex1 = _skewed_exchange("history", seed=1)
    assert ex1.count_collectives == 1  # unconfident: counted
    assert ex1.a2a_retries == 0
    traces_before = jit_stats.total_for(*SIZING_KERNELS)
    ex2 = _skewed_exchange("history", seed=1)
    # presized from history: no count pass, no doubling, and the data
    # program came straight from the lru_cache (zero new traces)
    assert ex2.count_collectives == 0
    assert ex2.a2a_retries == 0
    assert ex2.data_collectives == 1
    assert ex2.stats["sizing_used"] == "history"
    assert ex2.stats["per_dest"] == ex1.stats["per_dest"]
    assert jit_stats.total_for(*SIZING_KERNELS) == traces_before, (
        "history-presized repeat shape recompiled an exchange kernel")


def test_legacy_mode_pays_the_doubling_cliff():
    ex = _skewed_exchange("legacy")
    assert ex.count_collectives == 0
    assert ex.a2a_retries >= 1  # the 2x cliff the count pass removes
    assert ex.data_collectives == ex.a2a_retries + 1
    assert ex.stats["sizing"] == "legacy"


def test_stale_history_recovers_via_backstop_and_relearns():
    """An undersized history presize must not wedge the exchange: the
    doubling backstop completes it, and the observation re-teaches the
    history so the NEXT run presizes correctly."""
    # teach the history a tiny load for this exchange shape
    ex_small = _skewed_exchange("history", rows_per_task=40,
                                hot_frac=0.0, seed=2)
    assert ex_small.a2a_retries == 0
    # same shape signature (types/keys/n/d), much bigger skewed load
    ex_big = _skewed_exchange("history", rows_per_task=4000, seed=3)
    assert ex_big.count_collectives == 0  # presized (stale)
    assert ex_big.a2a_retries >= 1        # backstop fired
    ex_next = _skewed_exchange("history", rows_per_task=4000, seed=3)
    assert ex_next.a2a_retries == 0       # history re-learned


@pytest.mark.parametrize("d", [1, 2])
def test_skew_with_fewer_devices_than_partitions(d):
    """The d<p carried-partition path under 90% skew: partitions split
    device slabs by carried id, sizing stays exact (zero retries), and
    every row lands in its hash partition."""
    import jax.numpy as jnp

    from trino_tpu.parallel.exchange import hash_partition_ids

    n = 4
    ex = _skewed_exchange("exact", n=n, d=d, rows_per_task=500)
    assert ex.d == d and ex.n == n
    assert ex.a2a_retries == 0
    assert ex.data_collectives == 1
    assert sum(ex.stats["partition_rows"]) == ex.stats["rows"]
    # routing correctness: rows of partition p hash to p
    for part in range(n):
        for pg in ex.pages(part):
            keys = np.asarray(pg.cols[0])[np.asarray(pg.valid)]
            if len(keys) == 0:
                continue
            got = np.asarray(hash_partition_ids(
                [jnp.asarray(keys).astype(jnp.int64).view(jnp.uint64)],
                n))
            assert (got == part).all()


def test_host_buffer_stats_parity():
    """The host path exposes the SAME stats surface (keys) the device
    path records, so EXPLAIN ANALYZE renders both identically."""
    from trino_tpu.ops.output import OutputBuffer

    buf = OutputBuffer(4)
    for p, rows in ((0, 90), (1, 5), (2, 5)):
        page = Page.from_pylists([T.BIGINT], [list(range(rows))])
        buf.enqueue(p, page)
    s = buf.stats
    assert s["kind"] == "host"
    assert s["rows"] == 100
    assert s["partition_rows"] == [90, 5, 5, 0]
    assert s["skew_ratio"] == 3.6
    ex = _skewed_exchange("exact", seed=4)
    assert set(s) <= set(ex.stats) | {"source_fragment"}


def test_explain_analyze_shows_exchange_skew_lines():
    """Acceptance surface: EXPLAIN ANALYZE shows per-exchange skew
    ratio, per_dest chosen, and retry count on the device path."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.parallel.distributed import DistributedQueryRunner
    from trino_tpu.sql.analyzer import Session

    s = Session(catalog="tpch", schema="micro")
    s.properties["device_exchange"] = True
    s.properties["device_exchange_sizing"] = "exact"
    r = DistributedQueryRunner({"tpch": TpchConnector(page_rows=2048)}, s,
                               n_workers=3, desired_splits=8)
    res = r.execute(
        "EXPLAIN ANALYZE SELECT l_returnflag, count(*), sum(l_quantity) "
        "FROM lineitem GROUP BY l_returnflag")
    text = "\n".join(row[0] for row in res.rows)
    device_lines = [ln for ln in text.splitlines()
                    if "exchange [device]" in ln]
    assert device_lines, text
    for ln in device_lines:
        assert "skew" in ln and "per_dest=" in ln and "retries=" in ln
        assert "sizing=exact" in ln
    # host-side boundaries of the same query render the same shape
    assert any("exchange [host]" in ln for ln in text.splitlines())


def test_sizing_session_property_validates_and_normalizes():
    from trino_tpu.session_properties import set_property
    from trino_tpu.types import TrinoError

    props = {}
    set_property(props, "device_exchange_sizing", "EXACT")
    assert props["device_exchange_sizing"] == "exact"
    with pytest.raises(TrinoError):
        set_property(props, "device_exchange_sizing", "sometimes")
    set_property(props, "hot_partition_split_threshold", 0.8)
    assert props["hot_partition_split_threshold"] == 0.8
    with pytest.raises(TrinoError):
        set_property(props, "hot_partition_split_threshold", 1.5)
    set_property(props, "scale_writers_enabled", "true")
    assert props["scale_writers_enabled"] is True
    with pytest.raises(TrinoError):
        set_property(props, "rebalance_min_collectives", 0)


# ------------------------------------------ hot-partition splitting ----


def test_hot_split_byte_equal_and_spreads_receivers():
    """The acceptance witness: a 95%-hot-key exchange with splitting
    delivers the IDENTICAL per-partition row multisets as the unsplit
    path, but the hot partition's rows arrive over >= 2 receiver lanes
    and the max receiver-lane load (lane skew) collapses — with zero
    overflow retries and one data collective."""
    ex_split = _skewed_exchange("exact", hot_frac=0.95, seed=10,
                                threshold=0.5)
    SIZING_HISTORY.reset()
    ex_plain = _skewed_exchange("exact", hot_frac=0.95, seed=10,
                                threshold=1.0)
    n = 4
    assert _partition_rows(ex_split, n) == _partition_rows(ex_plain, n)
    s, p = ex_split.stats, ex_plain.stats
    assert s["splits"] == 1 and len(s["hot_partitions"]) == 1
    assert s["split_ways"] == ex_split.d
    hot = s["hot_partitions"][0]
    assert s["hot_spread"][hot] >= 2
    assert p["splits"] == 0 and p["hot_spread"] == {}
    # receiver-lane loads flatten; the DATA's partition skew stays put
    assert s["lane_skew_ratio"] < 1.5 < p["lane_skew_ratio"]
    assert s["skew_ratio"] == p["skew_ratio"] > 2.5
    # the split collective is also SMALLER: lanes sized to the spread
    # load, not the hot partition's full per-sender load
    assert s["per_dest"] < p["per_dest"]
    assert ex_split.a2a_retries == 0
    assert ex_split.data_collectives == 1
    assert ex_split.count_collectives == 1


def test_hot_split_engages_above_threshold_not_below():
    ex = _skewed_exchange("exact", hot_frac=0.95, seed=11,
                          threshold=0.97)
    assert ex.stats["splits"] == 0  # 95% < 97%: below threshold
    SIZING_HISTORY.reset()
    ex = _skewed_exchange("exact", hot_frac=0.95, seed=11,
                          threshold=0.5)
    assert ex.stats["splits"] == 1  # above: engaged
    SIZING_HISTORY.reset()
    # uniform keys: no partition crosses any sane threshold
    ex = _skewed_exchange("exact", hot_frac=0.0, seed=11, threshold=0.5)
    assert ex.stats["splits"] == 0


def test_hot_split_repeat_hits_program_cache():
    """History-presized repeats of a SPLIT exchange shape re-use the
    compiled program: the hot set rides as a traced mask (not a cache
    key), the hot decision comes from the history's remembered
    partition fractions, and jit-trace counters stay flat."""
    ex1 = _skewed_exchange("history", hot_frac=0.95, seed=12)
    assert ex1.stats["splits"] == 1
    assert ex1.count_collectives == 1  # unconfident: counted
    traces_before = jit_stats.total_for(*SIZING_KERNELS)
    ex2 = _skewed_exchange("history", hot_frac=0.95, seed=12)
    assert ex2.count_collectives == 0  # presized: no count pass
    assert ex2.a2a_retries == 0
    assert ex2.stats["splits"] == 1   # hot set remembered by shape
    assert ex2.stats["hot_partitions"] == ex1.stats["hot_partitions"]
    assert ex2.stats["per_dest"] == ex1.stats["per_dest"]
    assert jit_stats.total_for(*SIZING_KERNELS) == traces_before, (
        "split repeat shape recompiled an exchange kernel")
    assert _partition_rows(ex1, 4) == _partition_rows(ex2, 4)


def test_hot_split_with_fewer_devices_than_partitions():
    """d < n plus splitting: hot sub-buckets and carried-partition
    slab-splitting compose — every row still reaches the consumer of
    its ORIGINAL hash partition, exactly once."""
    import jax.numpy as jnp

    from trino_tpu.parallel.exchange import hash_partition_ids

    n, d = 4, 2
    ex = _skewed_exchange("exact", n=n, d=d, rows_per_task=500,
                          hot_frac=0.95, seed=13)
    assert ex.stats["splits"] == 1
    hot = ex.stats["hot_partitions"][0]
    assert ex.stats["hot_spread"][hot] == d
    assert ex.a2a_retries == 0
    for part in range(n):
        for pg in ex.pages(part):
            keys = np.asarray(pg.cols[0])[np.asarray(pg.valid)]
            if len(keys) == 0:
                continue
            got = np.asarray(hash_partition_ids(
                [jnp.asarray(keys).astype(jnp.int64).view(jnp.uint64)],
                n))
            assert (got == part).all()


# ------------------------------------------ scaled-writer rebalancer ----


def _feed(reb, hist, times):
    trail = []
    for _ in range(times):
        reb.observe(hist)
        trail.append(reb.assignment())
    return trail


def test_rebalancer_deterministic_under_fixed_seed():
    from trino_tpu.parallel.rebalancer import UniformPartitionRebalancer

    hist = [9000, 50, 40, 60, 30, 45, 55, 35]
    t1 = _feed(UniformPartitionRebalancer(8, 4, seed=42), hist, 6)
    t2 = _feed(UniformPartitionRebalancer(8, 4, seed=42), hist, 6)
    assert t1 == t2  # the FULL assignment history reproduces


def test_rebalancer_scales_hot_partition_and_does_not_flap():
    from trino_tpu.parallel.rebalancer import UniformPartitionRebalancer

    reb = UniformPartitionRebalancer(8, 4, min_collectives=2)
    hist = [9000, 50, 40, 60, 30, 45, 55, 35]
    trail = [reb.assignment()] + _feed(reb, hist, 10)
    # the hot logical partition ends up SCALED over >= 2 writer lanes
    assert len(trail[-1][0]) >= 2
    assert reb.stats()["scaled_partitions"] >= 1
    # stability: under a stationary distribution the assignment
    # converges and then stops changing (no flapping)
    assert trail[-1] == trail[-2] == trail[-3]
    changes = sum(1 for a, b in zip(trail, trail[1:]) if a != b)
    assert 1 <= changes <= 4
    # a balanced distribution never triggers a rebalance at all
    calm = UniformPartitionRebalancer(8, 4, min_collectives=2)
    assert _feed(calm, [100] * 8, 6)[-1] == calm.assignment()
    assert calm.rebalances == 0


def test_rebalancer_unscales_cooled_partition():
    """The reverse transition: a hot partition that SCALED over extra
    writer lanes releases them again once its load cools (same
    hysteresis window), and the flap guard holds — a stationary
    distribution, hot or cooled, converges and stays put."""
    from trino_tpu.parallel.rebalancer import UniformPartitionRebalancer

    reb = UniformPartitionRebalancer(8, 4, min_collectives=2)
    hot = [9000, 50, 40, 60, 30, 45, 55, 35]
    _feed(reb, hot, 10)
    scaled_lanes = len(reb.lanes_for(0))
    assert scaled_lanes >= 2
    stable_hot = reb.assignment()
    # keep feeding the SAME hot distribution: no un-scale (no flap)
    _feed(reb, hot, 6)
    assert reb.assignment() == stable_hot
    # the partition cools to the pack: lanes come back, one per
    # hysteresis window, down to a single lane
    cool = [50, 50, 40, 60, 30, 45, 55, 35]
    trail = _feed(reb, cool, 24)
    assert len(reb.lanes_for(0)) == 1
    # converged again: the cooled layout stops changing
    assert trail[-1] == trail[-2] == trail[-3]
    # determinism: an identical history reproduces the transitions
    reb2 = UniformPartitionRebalancer(8, 4, min_collectives=2)
    _feed(reb2, hot, 16)
    _feed(reb2, cool, 24)
    assert reb2.assignment() == reb.assignment()


def test_rebalancer_hysteresis_respects_min_collectives():
    from trino_tpu.parallel.rebalancer import UniformPartitionRebalancer

    reb = UniformPartitionRebalancer(8, 4, min_collectives=4)
    hist = [9000, 50, 40, 60, 30, 45, 55, 35]
    trail = _feed(reb, hist, 12)
    changes = [i for i, (a, b) in enumerate(zip(trail, trail[1:]))
               if a != b]
    # consecutive assignment changes are >= min_collectives apart
    assert all(b - a >= 4 for a, b in zip(changes, changes[1:]))


def test_partitioned_join_splits_hot_probe_and_matches_broadcast():
    """Acceptance, end to end: a PARTITIONED join whose probe side is
    90% one key ships RAW rows through the device exchange — the hot
    partition splits (EXPLAIN ANALYZE shows the splits=..x.. surface),
    zero overflow retries, and the result matches the broadcast plan
    (no exchange of probe rows at all — the unsplit oracle)."""
    from trino_tpu import types as TT
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.parallel.distributed import DistributedQueryRunner
    from trino_tpu.sql.analyzer import Session

    rng = np.random.default_rng(31)
    keys = np.where(rng.random(6000) < 0.9, 7,
                    rng.integers(0, 300, 6000))
    conn = MemoryConnector()

    def runner(**props):
        s = Session(catalog="mem", schema="default")
        s.properties.update(props)
        return DistributedQueryRunner({"mem": conn}, s, n_workers=4,
                                      desired_splits=4)

    r = runner(join_distribution_type="PARTITIONED",
               device_exchange_sizing="exact")
    r.execute("create table z (k bigint, v bigint)")
    h = conn.metadata().get_table_handle("default", "z")
    cols = conn.metadata().get_columns(h)
    sink = conn.page_sink(h, cols)
    sink.append_page(Page.from_pylists(
        [TT.BIGINT, TT.BIGINT], [keys.tolist(), keys.tolist()]))
    sink.finish()
    r.execute("create table dim (k bigint, name bigint)")
    sink2 = conn.page_sink(
        conn.metadata().get_table_handle("default", "dim"),
        conn.metadata().get_columns(h))
    sink2.append_page(Page.from_pylists(
        [TT.BIGINT, TT.BIGINT],
        [list(range(300)) + [7], list(range(301))]))
    sink2.finish()
    sql = "select count(*) from z, dim where z.k = dim.k"
    res = r.execute("EXPLAIN ANALYZE " + sql)
    text = "\n".join(row[0] for row in res.rows)
    device_lines = [ln for ln in text.splitlines()
                    if "exchange [device]" in ln]
    assert any("splits=" in ln for ln in device_lines), text
    assert all("retries=0" in ln for ln in device_lines)
    got = r.execute(sql).rows
    want = runner(join_distribution_type="BROADCAST").execute(sql).rows
    assert got == want


def test_scaled_writer_ctas_correct_and_rebalances():
    """End-to-end: CTAS over a 90%-hot key with scale_writers_enabled
    routes rows through the rebalancing hash boundary — written rows
    identical to the unscaled plan, rebalancer engaged."""
    from trino_tpu import types as TT
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.parallel.distributed import DistributedQueryRunner
    from trino_tpu.parallel.rebalancer import UniformPartitionRebalancer
    from trino_tpu.sql.analyzer import Session

    rng = np.random.default_rng(21)
    keys = np.where(rng.random(8000) < 0.9, 7,
                    rng.integers(0, 500, 8000))
    vals = rng.integers(0, 100, 8000)

    def run(scale):
        SIZING_HISTORY.reset()
        s = Session(catalog="mem", schema="default")
        s.properties["scale_writers_enabled"] = scale
        r = DistributedQueryRunner({"mem": MemoryConnector()}, s,
                                   n_workers=4, desired_splits=4)
        r.execute("create table z (k bigint, v bigint)")
        conn = r.metadata.connectors["mem"]
        h = conn.metadata().get_table_handle("default", "z")
        sink = conn.page_sink(h, conn.metadata().get_columns(h))
        sink.append_page(Page.from_pylists(
            [TT.BIGINT, TT.BIGINT], [keys.tolist(), vals.tolist()]))
        sink.finish()
        written = r.execute("create table out as select k, v from z")
        rows = sorted(r.execute("select k, v from out").rows)
        return written.rows, rows

    before = UniformPartitionRebalancer.total_rebalances
    count_off, rows_off = run(False)
    count_on, rows_on = run(True)
    assert count_on == count_off == [(8000,)]
    assert rows_on == rows_off
    assert UniformPartitionRebalancer.total_rebalances > before
