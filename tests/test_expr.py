from decimal import Decimal

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.block import DevicePage, Page
from trino_tpu.expr import Call, InputRef, Literal, PageProcessor
from trino_tpu.expr.functions import days_from_civil_host


def run(input_types, columns, projections, filter_expr=None):
    page = Page.from_pylists(input_types, columns)
    proc = PageProcessor(input_types, projections, filter_expr)
    out = proc.process(DevicePage.from_page(page))
    return out.to_page()


def c(ch, t):
    return InputRef(t, ch)


def lit(v, t):
    return Literal(t, v)


def call(name, t, *args):
    return Call(t, name, tuple(args))


def test_arithmetic_bigint():
    out = run([T.BIGINT, T.BIGINT], [[1, 2, None], [10, 20, 30]],
              [call("add", T.BIGINT, c(0, T.BIGINT), c(1, T.BIGINT)),
               call("multiply", T.BIGINT, c(0, T.BIGINT), lit(3, T.BIGINT))])
    assert out.to_rows() == [(11, 3), (22, 6), (None, None)]


def test_decimal_arithmetic_matches_reference_rules():
    d12_2 = T.decimal_type(12, 2)
    # l_extendedprice * (1 - l_discount) — the q1/q6 revenue expression
    one = lit(1, T.BIGINT)
    disc = c(1, d12_2)
    price = c(0, d12_2)
    sub = call("subtract", T.decimal_type(13, 2), one, disc)
    mul = call("multiply", T.decimal_type(18, 4), price, sub)
    out = run([d12_2, d12_2], [["100.00", "10.00"], ["0.05", "0.10"]], [mul])
    assert out.block(0).to_pylist() == [Decimal("95.0000"), Decimal("9.0000")]


def test_decimal_divide_rounding():
    d4_2 = T.decimal_type(4, 2)
    expr = call("divide", T.decimal_type(8, 2), c(0, d4_2), c(1, d4_2))
    out = run([d4_2, d4_2], [["1.00", "-1.00"], ["3.00", "3.00"]], [expr])
    # 1/3 = 0.33 (round half up), -1/3 = -0.33 (away from zero)
    assert out.block(0).to_pylist() == [Decimal("0.33"), Decimal("-0.33")]


def test_filter_and_three_valued_logic():
    b = T.BOOLEAN
    x = c(0, T.BIGINT)
    f = call("$and", b,
             call("gt", b, x, lit(1, T.BIGINT)),
             call("lt", b, x, lit(5, T.BIGINT)))
    out = run([T.BIGINT], [[0, 2, None, 4, 7]], [x], f)
    assert out.block(0).to_pylist() == [2, 4]


def test_or_null_semantics():
    b = T.BOOLEAN
    x = c(0, T.BIGINT)
    # (x > 10) OR (x < 100) is TRUE even when one side is NULL? No —
    # NULL input makes both sides NULL; OR of (NULL, NULL) is NULL => drop.
    f = call("$or", b,
             call("gt", b, x, lit(10, T.BIGINT)),
             call("lt", b, x, lit(3, T.BIGINT)))
    out = run([T.BIGINT], [[1, 5, None, 50]], [x], f)
    assert out.block(0).to_pylist() == [1, 50]


def test_case_expression():
    x = c(0, T.BIGINT)
    expr = call("$case", T.BIGINT,
                call("lt", T.BOOLEAN, x, lit(0, T.BIGINT)), lit(-1, T.BIGINT),
                call("eq", T.BOOLEAN, x, lit(0, T.BIGINT)), lit(0, T.BIGINT),
                lit(1, T.BIGINT))
    out = run([T.BIGINT], [[-5, 0, 9, None]], [expr])
    assert out.block(0).to_pylist() == [-1, 0, 1, 1]  # NULL: no cond fires -> default


def test_coalesce_and_is_null():
    x = c(0, T.BIGINT)
    out = run([T.BIGINT], [[1, None]],
              [call("$coalesce", T.BIGINT, x, lit(42, T.BIGINT)),
               call("$is_null", T.BOOLEAN, x)])
    assert out.to_rows() == [(1, False), (42, True)]


def test_string_comparison_and_like():
    v = T.VARCHAR
    s = c(0, v)
    out = run([v], [["AIR", "MAIL", "SHIP", "AIR REG", None]],
              [call("eq", T.BOOLEAN, s, lit("AIR", v)),
               call("$like", T.BOOLEAN, s, lit("%AI%", v)),
               call("lt", T.BOOLEAN, s, lit("MAIL", v))])
    rows = out.to_rows()
    assert rows[0] == (True, True, True)     # AIR
    assert rows[1] == (False, True, False)   # MAIL
    assert rows[2] == (False, False, False)  # SHIP
    assert rows[3] == (False, True, True)    # AIR REG
    assert rows[4] == (None, None, None)


def test_string_functions_via_dictionary():
    v = T.VARCHAR
    s = c(0, v)
    sub = call("substr", v, s, lit(1, T.BIGINT), lit(2, T.BIGINT))
    out = run([v], [["PROMO BURNISHED", "STANDARD", None]],
              [call("length", T.BIGINT, s),
               sub,
               call("eq", T.BOOLEAN, sub, lit("PR", v))])
    assert out.to_rows() == [(15, "PR", True), (8, "ST", False),
                             (None, None, None)]


def test_in_lists():
    v = T.VARCHAR
    out = run([v, T.BIGINT], [["a", "b", "c"], [1, 2, 3]],
              [call("$in", T.BOOLEAN, c(0, v), lit("a", v), lit("c", v)),
               call("$in", T.BOOLEAN, c(1, T.BIGINT),
                    lit(1, T.BIGINT), lit(3, T.BIGINT))])
    assert out.to_rows() == [(True, True), (False, False), (True, True)]


def test_date_extract_and_interval():
    d = days_from_civil_host
    dates = [d(1994, 1, 1), d(1995, 12, 31), d(1996, 2, 29)]
    x = c(0, T.DATE)
    out = run([T.DATE], [dates],
              [call("$extract_year", T.BIGINT, x),
               call("$extract_month", T.BIGINT, x),
               call("$extract_day", T.BIGINT, x),
               call("add", T.DATE, x,
                    lit(3, T.INTERVAL_YEAR_MONTH))])  # + 3 months
    rows = out.to_rows()
    assert [r[0] for r in rows] == [1994, 1995, 1996]
    assert [r[1] for r in rows] == [1, 12, 2]
    assert [r[2] for r in rows] == [1, 31, 29]
    assert rows[0][3] == d(1994, 4, 1)
    assert rows[1][3] == d(1996, 3, 31)
    assert rows[2][3] == d(1996, 5, 29)


def test_between_dates():
    d = days_from_civil_host
    x = c(0, T.DATE)
    f = call("$between", T.BOOLEAN, x,
             lit(d(1994, 1, 1), T.DATE), lit(d(1994, 12, 31), T.DATE))
    out = run([T.DATE], [[d(1993, 6, 1), d(1994, 6, 1), d(1995, 6, 1)]],
              [x], f)
    assert out.block(0).to_pylist() == [d(1994, 6, 1)]


def test_cast_decimal_double():
    d12_2 = T.decimal_type(12, 2)
    x = c(0, d12_2)
    out = run([d12_2], [["12.50"]],
              [Call(T.DOUBLE, "$cast", (x,)),
               Call(T.BIGINT, "$cast", (x,))])
    assert out.to_rows() == [(12.5, 12)]


def test_cast_varchar_to_date():
    v = T.VARCHAR
    out = run([v], [["1998-09-02", None]],
              [Call(T.DATE, "$cast", (c(0, v),))])
    assert out.block(0).to_pylist() == [
        days_from_civil_host(1998, 9, 2), None]
