"""Function-registry breadth: math/bitwise/string/date scalars, new
aggregates, string-valued CASE/COALESCE/NULLIF, string min/max.

Reference analog: operator/scalar/* + operator/aggregation/* unit
suites (MathFunctions, BitwiseFunctions, DateTimeFunctions, ...).
"""

import math
from decimal import Decimal

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.parallel.distributed import DistributedQueryRunner
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=2048)},
                            Session(catalog="tpch", schema="micro"))


def one(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0]


def test_math_scalars(runner):
    p, lg, s1, s2, cb = one(runner, "select power(2, 10), log(2, 8.0), "
                                    "sign(-7), sign(0), cbrt(27.0)")
    assert (p, lg, s1, s2) == (1024.0, 3.0, -1, 0)
    assert abs(cb - 3.0) < 1e-9
    (at2,) = one(runner, "select atan2(1.0, 1.0)")
    assert abs(at2 - math.pi / 4) < 1e-9


def test_constants_and_predicates(runner):
    row = one(runner, "select round(pi(), 6), round(e(), 6), "
                      "is_nan(nan()), is_infinite(infinity()), "
                      "is_finite(1.0)")
    assert row == (3.141593, 2.718282, True, True, True)


def test_truncate(runner):
    assert one(runner, "select truncate(3.99), truncate(-2.75), "
                       "truncate(5.5e0)") == \
        (Decimal("3.00"), Decimal("-2.00"), 5.0)


def test_bitwise(runner):
    assert one(runner, "select bitwise_and(12, 10), bitwise_or(12, 10), "
                       "bitwise_xor(12, 10), bitwise_not(0), "
                       "bitwise_left_shift(1, 4), "
                       "bitwise_right_shift(16, 2)") == \
        (8, 14, 6, -1, 16, 4)


def test_string_scalars(runner):
    assert one(runner, "select codepoint('A'), "
                       "split_part('a,b,c', ',', 2), "
                       "split_part('a,b', ',', 9), "
                       "translate('abcd', 'ab', 'x')") == \
        (65, "b", None, "xcd")


def test_date_trunc(runner):
    d1, d2, d3, d4 = one(runner, """
        select date_trunc('month', date '2020-07-15'),
               date_trunc('quarter', date '2020-08-15'),
               date_trunc('year', date '2020-08-15'),
               date_trunc('week', date '2026-07-30')""")
    import datetime
    epoch = datetime.date(1970, 1, 1)
    assert epoch + datetime.timedelta(days=d1) == datetime.date(2020, 7, 1)
    assert epoch + datetime.timedelta(days=d2) == datetime.date(2020, 7, 1)
    assert epoch + datetime.timedelta(days=d3) == datetime.date(2020, 1, 1)
    # 2026-07-30 is a Thursday; ISO week starts Monday 2026-07-27
    assert epoch + datetime.timedelta(days=d4) == datetime.date(2026, 7, 27)
    (h,) = one(runner, "select date_trunc('hour', "
                       "timestamp '2020-01-01 10:45:33')")
    assert h == 1577872800000000  # 2020-01-01T10:00:00 micros


def test_date_diff_and_parts(runner):
    assert one(runner, """
        select date_diff('day', date '2020-01-01', date '2020-03-01'),
               date_diff('hour', timestamp '2020-01-01 00:00:00',
                         timestamp '2020-01-02 12:00:00'),
               day_of_week(date '2026-07-30'),
               day_of_year(date '2020-02-01'),
               week(date '2021-01-07')""") == (60, 36, 4, 32, 1)


def test_unixtime_roundtrip(runner):
    ts, back = one(runner, "select to_unixtime(timestamp "
                           "'1970-01-02 00:00:00'), "
                           "from_unixtime(86400)")
    assert ts == 86400.0
    assert back.timestamp() == 86400.0


def test_last_day_of_month(runner):
    (d,) = one(runner,
               "select last_day_of_month(date '2020-02-10')")
    import datetime
    assert datetime.date(1970, 1, 1) + datetime.timedelta(days=d) == \
        datetime.date(2020, 2, 29)


def test_new_aggregates(runner):
    assert one(runner, "select bool_and(n_regionkey < 5), "
                       "bool_or(n_regionkey = 4), "
                       "every(n_regionkey >= 0) from nation") == \
        (True, True, True)
    assert one(runner, "select count_if(n_regionkey = 0) from nation") \
        == (5,)
    assert one(runner, "select approx_distinct(n_regionkey) from nation") \
        == (5,)
    gm = one(runner, "select geometric_mean(n_nationkey + 1) "
                     "from nation")[0]
    want = math.exp(sum(math.log(i + 1) for i in range(25)) / 25)
    assert abs(gm - want) < 1e-6
    arb, av = one(runner, "select arbitrary(n_name), any_value(n_name) "
                          "from nation where n_regionkey = 2")
    assert arb == "CHINA" and av == "CHINA"


def test_string_min_max(runner):
    assert one(runner, "select min(n_name), max(n_name) from nation") == \
        ("ALGERIA", "VIETNAM")
    rows = runner.execute(
        "select n_regionkey, min(n_name) from nation "
        "group by 1 order by 1").rows
    assert rows[0] == (0, "ALGERIA") and rows[2] == (2, "CHINA")


def test_string_min_max_distributed():
    conn = TpchConnector(page_rows=2048)
    d = DistributedQueryRunner({"tpch": conn},
                               Session(catalog="tpch", schema="micro"),
                               n_workers=3, desired_splits=8,
                               broadcast_threshold=300.0)
    rows = d.execute("select n_regionkey, min(n_name), max(n_name) "
                     "from nation group by 1 order by 1").rows
    assert rows[0] == (0, "ALGERIA", "MOZAMBIQUE")
    assert rows[4] == (4, "EGYPT", "SAUDI ARABIA")


def test_string_case_coalesce_nullif(runner):
    rows = runner.execute("""
        select case when n_regionkey = 0 then n_name else 'other' end
        from nation order by n_nationkey limit 3""").rows
    assert rows == [("ALGERIA",), ("other",), ("other",)]
    assert one(runner, "select coalesce(cast(null as varchar), 'x')") \
        == ("x",)
    assert one(runner, "select nullif('a', 'a'), nullif('a', 'b')") == \
        (None, "a")
    # nested select + group over the merged pool
    rows = runner.execute("""
        select x, count(*) from (
            select coalesce(nullif(n_name, 'ALGERIA'), 'SUB') x
            from nation) group by x order by x limit 2""").rows
    assert rows == [("ARGENTINA", 1), ("BRAZIL", 1)]


def test_string_case_over_join(runner):
    rows = runner.execute("""
        select r_name, coalesce(x.nm, 'NONE')
        from region left join (
            select n_regionkey rk, min(n_name) nm from nation
            where n_nationkey < 3 group by n_regionkey) x
        on r_regionkey = rk order by r_regionkey""").rows
    assert rows == [("AFRICA", "ALGERIA"), ("AMERICA", "ARGENTINA"),
                    ("ASIA", "NONE"), ("EUROPE", "NONE"),
                    ("MIDDLE EAST", "NONE")]


def test_mixed_distinct_aggregates(runner):
    # reference plans MarkDistinct; here the decomposable-reaggregation
    # rewrite (inner group by (k, x) carrying non-distinct partials)
    assert one(runner, "select count(distinct n_regionkey), count(*) "
                       "from nation") == (5, 25)
    rows = runner.execute("""
        select n_regionkey, count(distinct n_name), sum(n_nationkey),
               max(n_name)
        from nation group by 1 order by 1 limit 2""").rows
    assert rows == [(0, 5, 50, "MOZAMBIQUE"),
                    (1, 5, 47, "UNITED STATES")]
    c, s, n = one(runner, "select count(distinct o_custkey), "
                          "sum(o_totalprice), count(*) from orders")
    assert n == 1500 and c <= n and s > 0


def test_delete_via_plan_quoted_identifiers():
    from trino_tpu.connectors.memory import MemoryConnector

    r = LocalQueryRunner({"mem": MemoryConnector()},
                         Session(catalog="mem", schema="default"))
    r.execute('create table "weird col" (x bigint, "select" varchar)')
    r.execute("insert into \"weird col\" values "
              "(1, 'a'), (2, 'b'), (3, null)")
    # NULL predicate rows are KEPT (not deleted), per SQL semantics
    assert r.execute(
        'delete from "weird col" where "select" = \'a\'').rows == [(1,)]
    assert r.execute('select count(*) from "weird col"').rows == [(2,)]
    assert r.execute('delete from "weird col"').rows == [(2,)]


def test_extract_time_of_day_fields(runner):
    assert one(runner, "select extract(hour from timestamp "
                       "'2020-06-01 13:45:30.250'), "
                       "minute(timestamp '2020-06-01 13:45:30.250'), "
                       "second(timestamp '2020-06-01 13:45:30.250'), "
                       "millisecond(timestamp "
                       "'2020-06-01 13:45:30.250')") == (13, 45, 30, 250)
    # tz values read the wall clock in their zone; DATE fields are 0
    assert one(runner, "select extract(hour from timestamp "
                       "'2020-06-01 23:10:00 +02:30'), "
                       "extract(minute from timestamp "
                       "'2020-06-01 23:10:00 +02:30'), "
                       "hour(date '2020-06-01')") == (23, 10, 0)
