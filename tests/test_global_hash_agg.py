"""Global-hash device aggregation (ops/global_hash_agg.py): the
replicated-table kernel against host oracles, its overflow contract,
the key packing, and the kernel sizing history.

The mesh-level byte-equality against the exchange+merge-final shape
(and the 'auto' cost-rule pick) lives in test_mesh_query.py; here the
kernel itself is pinned down on one device and on the 8-virtual-device
mesh with every reduce kind.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh, PartitionSpec as P

from trino_tpu.ops.global_hash_agg import (EMPTY, global_hash_insert,
                                           global_hash_reduce, pack_keys,
                                           unpack_keys)
from trino_tpu.parallel.exchange import shard_map


def test_pack_unpack_roundtrip_with_nulls():
    k1 = jnp.asarray([0, 5, 1 << 20, 3, 7], dtype=jnp.int64)
    n1 = jnp.asarray([False, False, False, True, False])
    k2 = jnp.asarray([9, 0, 2, 4, 1 << 30], dtype=jnp.int64)
    packed = pack_keys([k1, k2], [n1, None], (32, 32))
    assert int(jnp.sum(packed == EMPTY)) == 0
    (v1, u1), (v2, u2) = unpack_keys(packed, (32, 32))
    got1 = np.asarray(v1)
    assert np.array_equal(np.asarray(u1), np.asarray(n1))
    assert np.array_equal(got1[~np.asarray(n1)],
                          np.asarray(k1)[~np.asarray(n1)])
    assert not np.asarray(u2).any()
    assert np.array_equal(np.asarray(v2), np.asarray(k2))
    # distinct tuples pack to distinct u64s
    assert len(set(np.asarray(packed).tolist())) == 5


def _host_groupby(keys, vals, valid):
    out = {}
    for k, v, va in zip(keys, vals, valid):
        if va:
            s, c, mn, mx = out.get(int(k), (0, 0, 1 << 62, -(1 << 62)))
            out[int(k)] = (s + int(v), c + 1, min(mn, int(v)),
                           max(mx, int(v)))
    return out


def test_single_device_kernel_matches_host_oracle():
    rng = np.random.default_rng(2)
    n, ndv, ts = 4096, 300, 1024
    keys = rng.integers(0, ndv, n)
    vals = rng.integers(-500, 500, n).astype(np.int64)
    valid = rng.random(n) > 0.1
    packed = pack_keys([jnp.asarray(keys)], [None], (32,))
    table, slot_of, resolved, unresolved = global_hash_insert(
        packed, jnp.asarray(valid), ts)
    assert int(unresolved) == 0
    v = jnp.asarray(vals)
    va = jnp.asarray(valid)
    info = jnp.iinfo(jnp.int64)
    sums, cnts, mns, mxs = global_hash_reduce(
        slot_of, resolved, va,
        (jnp.where(va, v, 0), va.astype(jnp.int64),
         jnp.where(va, v, info.max), jnp.where(va, v, info.min)),
        ("sum", "sum", "min", "max"), ts)
    t = np.asarray(table)
    occ = t != np.uint64(EMPTY)
    got = {}
    for slot in np.nonzero(occ)[0]:
        key = int((t[slot] & np.uint64(0xFFFFFFFF)) - 1)
        got[key] = (int(np.asarray(sums)[slot]),
                    int(np.asarray(cnts)[slot]),
                    int(np.asarray(mns)[slot]),
                    int(np.asarray(mxs)[slot]))
    assert got == _host_groupby(keys, vals, valid)


def test_mesh_kernel_matches_host_oracle_all_kinds():
    rng = np.random.default_rng(7)
    n_dev, rows, ndv, ts = 8, 1024, 150, 512
    keys = rng.integers(0, ndv, (n_dev, rows))
    vals = rng.integers(-100, 900, (n_dev, rows)).astype(np.int64)
    valid = rng.random((n_dev, rows)) > 0.05
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("x",))
    info = jnp.iinfo(jnp.int64)

    @partial(shard_map, mesh=mesh, in_specs=(P("x"),) * 3,
             out_specs=(P("x"),) * 5, check_vma=False)
    def prog(k, v, va):
        k, v, va = k[0], v[0], va[0]
        packed = pack_keys([k], [None], (32,))
        table, slot_of, resolved, unresolved = global_hash_insert(
            packed, va, ts, axis_name="x")
        sums, cnts, mns, mxs = global_hash_reduce(
            slot_of, resolved, va,
            (jnp.where(va, v, 0), va.astype(jnp.int64),
             jnp.where(va, v, info.max), jnp.where(va, v, info.min)),
            ("sum", "sum", "min", "max"), ts, axis_name="x")
        i = jax.lax.axis_index("x")
        sh = ts // 8
        sl = lambda a: jax.lax.dynamic_slice(a, (i * sh,), (sh,))  # noqa: E731
        return (sl(table)[None], sl(sums)[None], sl(cnts)[None],
                sl(mns)[None], sl(mxs)[None])

    t, s, c, mn, mx = prog(jnp.asarray(keys), jnp.asarray(vals),
                           jnp.asarray(valid))
    t = np.asarray(t).reshape(-1)
    s, c, mn, mx = (np.asarray(a).reshape(-1) for a in (s, c, mn, mx))
    occ = t != np.uint64(EMPTY)
    got = {}
    for slot in np.nonzero(occ)[0]:
        key = int((t[slot] & np.uint64(0xFFFFFFFF)) - 1)
        got[key] = (int(s[slot]), int(c[slot]), int(mn[slot]),
                    int(mx[slot]))
    want = _host_groupby(keys.reshape(-1), vals.reshape(-1),
                         valid.reshape(-1))
    assert got == want
    # the replicated table resolved every live row identically
    assert len(got) <= ndv


def test_reduce_handles_float32_min_max_states():
    """REAL aggregates carry float32 min/max states — the sentinel
    selection must branch on floating-ness, not float64 equality
    (jnp.iinfo on f32 raises at trace time)."""
    rng = np.random.default_rng(11)
    n, ts = 512, 64
    keys = rng.integers(0, 20, n)
    vals = rng.standard_normal(n).astype(np.float32)
    packed = pack_keys([jnp.asarray(keys)], [None], (32,))
    valid = jnp.ones(n, dtype=bool)
    table, slot_of, resolved, unresolved = global_hash_insert(
        packed, valid, ts)
    assert int(unresolved) == 0
    v = jnp.asarray(vals)
    mns, mxs = global_hash_reduce(
        slot_of, resolved, valid, (v, v), ("min", "max"), ts)
    t = np.asarray(table)
    for slot in np.nonzero(t != np.uint64(EMPTY))[0]:
        key = int((t[slot] & np.uint64(0xFFFFFFFF)) - 1)
        sel = vals[keys == key]
        assert np.asarray(mns)[slot] == sel.min()
        assert np.asarray(mxs)[slot] == sel.max()


def test_probe_budget_overflow_is_reported_not_wrong():
    """More distinct keys than the table can hold: the kernel must
    REPORT unresolved rows (the caller's fallback trigger), and every
    row it did resolve must still aggregate correctly."""
    rng = np.random.default_rng(5)
    n, ts = 512, 16  # 512 distinct keys into 16 slots
    keys = np.arange(n)
    packed = pack_keys([jnp.asarray(keys)], [None], (32,))
    valid = jnp.ones(n, dtype=bool)
    table, slot_of, resolved, unresolved = global_hash_insert(
        packed, valid, ts)
    assert int(unresolved) > 0
    assert int(unresolved) == n - int(jnp.sum(resolved))
    sums, = global_hash_reduce(
        slot_of, resolved, valid, (jnp.asarray(keys, jnp.int64),),
        ("sum",), ts)
    t = np.asarray(table)
    for slot in np.nonzero(t != np.uint64(EMPTY))[0]:
        key = int((t[slot] & np.uint64(0xFFFFFFFF)) - 1)
        # resolved rows of this key all carry value == key
        r = np.asarray(resolved) & (keys == key)
        assert int(np.asarray(sums)[slot]) == int(keys[r].sum())


def test_kernel_sizing_history_stabilizes_capacity():
    from trino_tpu.ops.kernel_sizing import ShapeSizingHistory

    h = ShapeSizingHistory()
    key = ("test", "shape")
    assert h.suggest(key, 1000) == 1024
    # fast-up: a larger need grows immediately
    assert h.suggest(key, 5000) == 8192
    # slow-down: a shrunken need keeps the remembered bucket (EWMA)
    assert h.suggest(key, 900) >= 2048
    # the need is a floor even on a cold key
    assert h.suggest(("other",), 17) == 32
    # repeated small needs eventually decay the remembered level
    for _ in range(12):
        got = h.suggest(key, 900)
    assert got == 1024


@pytest.mark.parametrize("override,expect", [
    ("AUTOMATIC", "global-hash"),
    ("EXCHANGE", "exchange"),
    ("GLOBAL_HASH", "global-hash"),
])
def test_agg_strategy_cost_rule_and_override(override, expect):
    from trino_tpu.planner.optimizer import choose_agg_strategy

    strat, detail = choose_agg_strategy(10, 4, override=override)
    assert strat == expect
    assert detail
    # AUTOMATIC flips past the table cap
    strat, detail = choose_agg_strategy(1 << 20, 4)
    assert strat == "exchange"


def test_agg_strategy_annotation_in_explain():
    """The planner annotates grouped aggregations with the cost-model
    pick + estimate, honoring the session override both ways."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.sql.analyzer import Session

    sql = ("select l_returnflag, count(*) from lineitem "
           "group by l_returnflag")

    def runner(**props):
        s = Session(catalog="tpch", schema="micro")
        s.properties.update(props)
        return LocalQueryRunner(
            {"tpch": TpchConnector(page_rows=4096)}, s)

    plan = runner().explain(sql)
    # l_returnflag ndv=3: deep inside the global-hash win region
    assert "strategy=global-hash" in plan
    assert "groups" in plan
    assert "strategy=global-hash" not in runner(
        aggregation_strategy="EXCHANGE").explain(sql)
    # past the cap the rule flips to exchange (override forces it back)
    high = ("select l_orderkey, count(*) from lineitem "
            "group by l_orderkey")
    assert "strategy=global-hash" not in runner(
        global_hash_agg_max_table=16).explain(high)
    assert "strategy=global-hash" in runner(
        aggregation_strategy="GLOBAL_HASH").explain(high)
