"""Grouped top-N ranking + order-preserving merge exchange.

Reference analog: ``operator/GroupedTopNBuilder.java`` /
``TopNRankingOperator.java`` (per-group truncation under row_number/
rank) and ``operator/MergeOperator.java`` + LocalMergeSourceOperator
(distributed ORDER BY gathers pre-sorted runs and merges — no full
re-sort).
"""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.parallel.distributed import DistributedQueryRunner
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session

RANKING_SQL = (
    "select * from (select c_nationkey, c_name, c_acctbal, "
    "row_number() over (partition by c_nationkey "
    "order by c_acctbal desc, c_custkey) rn from customer) "
    "where rn <= 2 order by c_nationkey, rn")


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(page_rows=2048)


@pytest.fixture(scope="module")
def local(conn):
    return LocalQueryRunner({"tpch": conn},
                            Session(catalog="tpch", schema="micro"))


@pytest.fixture(scope="module")
def dist(conn):
    return DistributedQueryRunner({"tpch": conn},
                                  Session(catalog="tpch",
                                          schema="micro"),
                                  n_workers=3, desired_splits=8,
                                  broadcast_threshold=300.0)


def test_ranking_query_plans_to_grouped_topn(local):
    """The round-3/4 carried 'done' criterion: a bounded ranking window
    PLANS to TopNRanking (EXPLAIN assert) instead of materializing
    whole window partitions."""
    plan = local.explain(RANKING_SQL)
    assert "TopNRanking" in plan
    assert "FilterOverWindowToTopNRanking" in plan
    # the window node itself is gone
    assert "- Window" not in plan


def test_grouped_topn_distributed_partial_final(dist):
    """Distributed plan: partial truncation BEFORE the hash exchange
    (at most groups*N rows cross the wire), final re-rank after."""
    plan = dist.explain(RANKING_SQL)
    assert "TopNRanking [partial]" in plan
    assert "TopNRanking [final]" in plan
    before, after = plan.split("Fragment 1")[0], \
        plan.split("Fragment 1")[1]
    assert "TopNRanking [partial]" in before


def test_grouped_topn_rows_match_window(local, dist):
    lrows = local.execute(RANKING_SQL).rows
    drows = dist.execute(RANKING_SQL).rows
    assert lrows == drows
    assert len(lrows) == 50  # 25 nations x top-2
    # cross-check against the unrewritten window semantics: every
    # nation's rows are its 2 largest balances
    full = local.execute(
        "select c_nationkey, c_acctbal from customer").rows
    by_nation = {}
    for k, bal in full:
        by_nation.setdefault(k, []).append(bal)
    for k, _name, bal, rn in lrows:
        top2 = sorted(by_nation[k], reverse=True)[:2]
        assert bal == top2[rn - 1], (k, rn, bal, top2)


def test_rank_ties_kept(local):
    rows = local.execute(
        "select * from (select l_linestatus, l_quantity, "
        "rank() over (partition by l_linestatus "
        "order by l_quantity) rk from lineitem) where rk <= 3").rows
    # quantity is integral: rank 1..3 covers all ties at those ranks
    assert rows
    for _st, q, rk in rows:
        assert rk <= 3
    # every linestatus keeps ALL minimal-quantity ties
    import collections

    per = collections.Counter(st for st, _q, _r in rows)
    assert all(v >= 3 for v in per.values())


def test_merge_exchange_plan_and_order(local, dist):
    """Distributed ORDER BY: per-task sorts + a 'merge' gather, and NO
    Sort node above the exchange (the round-3/4 carried criterion:
    merge-preserving distributed sort, not gather-then-resort)."""
    sql = ("select l_orderkey, l_extendedprice from lineitem "
           "where l_quantity < 15 "
           "order by l_extendedprice desc, l_orderkey")
    plan = dist.explain(sql)
    head, tail = plan.split("Fragment 1")
    assert "-> merge" in head and "- Sort" in head
    assert "- Sort" not in tail.split("Optimizer")[0]
    lrows = local.execute(sql).rows
    drows = dist.execute(sql).rows
    assert drows == lrows


def test_merge_exchange_strings_and_nulls(local, dist):
    sql = ("select c_mktsegment, c_name from customer "
           "order by c_mktsegment, c_name desc limit 40")
    assert local.execute(sql).rows == dist.execute(sql).rows


def test_grouped_topn_cross_process():
    """The multi-process runtime takes the same plan shape."""
    from trino_tpu.parallel.process_runner import ProcessQueryRunner

    with ProcessQueryRunner(
            {"tpch": {"connector": "tpch", "page_rows": 2048}},
            Session(catalog="tpch", schema="micro"),
            n_workers=2, desired_splits=4) as c:
        rows = c.execute(RANKING_SQL).rows
        assert len(rows) == 50
        sql = ("select o_orderkey, o_totalprice from orders "
               "order by o_totalprice desc limit 20")
        lr = LocalQueryRunner(
            {"tpch": TpchConnector(page_rows=2048)},
            Session(catalog="tpch", schema="micro"))
        assert c.execute(sql).rows == lr.execute(sql).rows
