"""Hash-based vs sort-based grouping cross-checks.

The vectorized open-addressing table (ops/hashtable.py) is the default
grouping path; the sort path is retained as the correctness oracle.
These tests drive both paths over adversarial key distributions —
all-null keys, a single group, near-capacity cardinality (forcing
linear-probe chains at load factor 0.5), multi-key pages, int64 and
float32 state columns — and require identical results.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from trino_tpu import types as T
from trino_tpu.block import DevicePage, Dictionary, Page
from trino_tpu.ops.aggregation import AggCall, HashAggregationOperator, \
    resolve_agg_type
from trino_tpu.ops.hashtable import hash_group_ids, hashable_key_types
from trino_tpu.ops.sortkeys import group_operands


# ---------------------------------------------------------------- primitive


def _reference_gids(keys_cols, nulls_cols, n):
    """First-occurrence dense group ids over tuples of (is_null, value)."""
    seen = {}
    out = []
    for i in range(n):
        k = tuple((bool(nc[i]), None if nc[i] else int(kc[i]))
                  for kc, nc in zip(keys_cols, nulls_cols))
        out.append(seen.setdefault(k, len(seen)))
    return out, len(seen)


@pytest.mark.parametrize("nvals,n,cap", [
    (4, 13, 16),          # few groups
    (1, 13, 16),          # single group
    (10**9, 61, 64),      # near-capacity: all keys distinct
    (50, 1000, 1024),
    (10**9, 1021, 1024),  # near-capacity at a real page size
])
def test_hash_gids_match_reference(nvals, n, cap):
    rng = np.random.default_rng(n * 31 + nvals % 97)
    keys = rng.integers(-nvals, nvals, size=cap).astype(np.int64)
    nulls = rng.random(cap) < 0.15
    valid = np.zeros(cap, dtype=bool)
    valid[:n] = True
    ops = group_operands(jnp.asarray(keys), jnp.asarray(nulls), T.BIGINT)
    gid, group_rows, ngroups, overflow = hash_group_ids(
        tuple(ops), jnp.asarray(valid))
    gid, group_rows = np.asarray(gid), np.asarray(group_rows)
    assert not bool(overflow)
    ref, nref = _reference_gids([keys], [nulls], n)
    assert int(ngroups) == nref
    assert gid[:n].tolist() == ref
    assert (gid[n:] == cap).all()
    for g in range(nref):
        r = group_rows[g]
        assert gid[r] == g and (gid[:r] != g).all(), \
            "group_rows must point at the FIRST row of each group"


def test_hash_gids_multi_key_and_all_null():
    cap = 64
    n = 50
    rng = np.random.default_rng(7)
    k1 = rng.integers(0, 5, size=cap).astype(np.int64)
    k2 = rng.integers(0, 4, size=cap).astype(np.int64)
    n1 = np.zeros(cap, dtype=bool)
    n2 = np.ones(cap, dtype=bool)     # second key entirely NULL
    valid = np.arange(cap) < n
    ops = group_operands(jnp.asarray(k1), jnp.asarray(n1), T.BIGINT) \
        + group_operands(jnp.asarray(k2), jnp.asarray(n2), T.BIGINT)
    gid, _rows, ngroups, overflow = hash_group_ids(
        tuple(ops), jnp.asarray(valid))
    assert not bool(overflow)
    ref, nref = _reference_gids([k1, k2], [n1, n2], n)
    assert int(ngroups) == nref  # all-null key contributes one dimension
    assert np.asarray(gid)[:n].tolist() == ref


def test_probe_budget_overflow_is_flagged():
    """With a 1-round budget, near-capacity distinct keys must collide
    and exact mode must report overflow instead of wrong gids; the
    non-exact (partial) mode resolves by singleton groups instead."""
    cap = 256
    keys = np.arange(cap, dtype=np.int64) * 7919
    valid = np.ones(cap, dtype=bool)
    ops = group_operands(jnp.asarray(keys), None, T.BIGINT)
    _gid, _rows, _ng, overflow = hash_group_ids(
        tuple(ops), jnp.asarray(valid), rounds=1, exact=True)
    assert bool(overflow)
    gid, _rows, ngroups, overflow = hash_group_ids(
        tuple(ops), jnp.asarray(valid), rounds=1, exact=False)
    assert not bool(overflow)
    # every row got SOME group; duplicates allowed, coverage is dense
    gid = np.asarray(gid)
    ng = int(ngroups)
    assert ng >= cap // 2 and (gid < ng).all()


def test_hashable_key_types_gate():
    assert hashable_key_types([T.BIGINT, T.varchar_type(10), T.DATE])
    assert not hashable_key_types([T.BIGINT, T.DOUBLE])
    assert not hashable_key_types([T.REAL])
    assert hashable_key_types([])


# ---------------------------------------------------------- operator oracle


def _sorted_rows(rows):
    return sorted(rows, key=lambda r: tuple(
        (v is None, 0 if v is None else v) for v in r))


def _run_single(input_types, columns, group_channels, aggs,
                hash_grouping, page_rows=None):
    """Run a single-step aggregation over the columns split into pages."""
    n = len(columns[0])
    page_rows = page_rows or n
    # one pool per string column, shared across pages (the engine's
    # dictionary-stability contract)
    dicts = [Dictionary() if t.is_pooled else None for t in input_types]
    op = HashAggregationOperator(input_types, group_channels, aggs,
                                 "single", hash_grouping=hash_grouping)
    for lo in range(0, n, page_rows):
        chunk = [c[lo:lo + page_rows] for c in columns]
        page = Page.from_pylists(input_types, chunk, dicts)
        op.add_input(DevicePage.from_page(page))
    op.finish()
    pages = []
    while not op.is_finished():
        p = op.get_output()
        if p is not None:
            pages.append(p.to_page())
    return _sorted_rows(Page.concat(pages).to_rows())


def _assert_rows_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert len(ra) == len(rb)
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and va is not None and vb is not None:
                assert vb == pytest.approx(va, rel=1e-9), (ra, rb)
            else:
                assert va == vb, (ra, rb)


AGG_SUITE = [
    AggCall("count_star", None, None, T.BIGINT),
    AggCall("sum", 1, T.BIGINT, resolve_agg_type("sum", T.BIGINT)),
    AggCall("sum", 2, T.REAL, resolve_agg_type("sum", T.REAL)),
    AggCall("min", 1, T.BIGINT, T.BIGINT),
    AggCall("max", 2, T.REAL, T.REAL),
    AggCall("count", 2, T.REAL, T.BIGINT),
]
AGG_TYPES = [T.BIGINT, T.BIGINT, T.REAL]


def _payload(rng, nkeys):
    s1 = [int(v) if rng.random() > 0.1 else None
          for v in rng.integers(-1000, 1000, size=nkeys)]
    s2 = [float(np.float32(v)) if rng.random() > 0.1 else None
          for v in rng.normal(size=nkeys)]
    return s1, s2


@pytest.mark.parametrize("case", [
    "all_null", "single_group", "near_capacity", "mixed"])
def test_hash_vs_sort_single_key(case):
    rng = np.random.default_rng(hash(case) % 2**32)
    n = 700
    if case == "all_null":
        keys = [None] * n
    elif case == "single_group":
        keys = [42] * n
    elif case == "near_capacity":
        keys = [int(v) for v in np.arange(n) * 1_000_003]
    else:
        keys = [int(v) if rng.random() > 0.2 else None
                for v in rng.integers(0, 40, size=n)]
    s1, s2 = _payload(rng, n)
    cols = [keys, s1, s2]
    for page_rows in (n, 128):
        got = _run_single(AGG_TYPES, cols, [0], AGG_SUITE, True, page_rows)
        want = _run_single(AGG_TYPES, cols, [0], AGG_SUITE, False,
                           page_rows)
        _assert_rows_equal(got, want)


def test_hash_vs_sort_multi_key_with_strings():
    rng = np.random.default_rng(11)
    n = 500
    vt = T.varchar_type(8)
    types = [T.BIGINT, vt, T.BIGINT, T.REAL]
    k1 = [int(v) if rng.random() > 0.15 else None
          for v in rng.integers(0, 9, size=n)]
    k2 = [rng.choice(["aa", "bb", "cc", "dd"]) if rng.random() > 0.15
          else None for _ in range(n)]
    s1, s2 = _payload(rng, n)
    aggs = [
        AggCall("count_star", None, None, T.BIGINT),
        AggCall("sum", 2, T.BIGINT, resolve_agg_type("sum", T.BIGINT)),
        AggCall("min", 3, T.REAL, T.REAL),
        AggCall("max", 1, vt, vt),   # string min/max rides rank LUTs
    ]
    cols = [k1, k2, s1, s2]
    got = _run_single(types, cols, [0, 1], aggs, True, 128)
    want = _run_single(types, cols, [0, 1], aggs, False, 128)
    _assert_rows_equal(got, want)


def test_float_keys_fall_back_to_sort():
    """DOUBLE grouping keys are not hashable (no f64<->u64 bitcast on
    TPU): the operator must silently take the sort path and still be
    correct."""
    n = 200
    rng = np.random.default_rng(3)
    types = [T.DOUBLE, T.BIGINT]
    keys = [float(v) for v in rng.integers(0, 10, size=n)]
    s1 = [int(v) for v in rng.integers(0, 100, size=n)]
    aggs = [AggCall("sum", 1, T.BIGINT,
                    resolve_agg_type("sum", T.BIGINT))]
    op = HashAggregationOperator(types, [0], aggs, "single",
                                 hash_grouping=True)
    page = Page.from_pylists(types, [keys, s1])
    op.add_input(DevicePage.from_page(page))
    op.finish()
    out = op.get_output().to_page()
    assert op.path_counts["hash"] == 0 and op.path_counts["sort"] > 0
    assert out.num_rows == 10


def test_overflow_falls_back_to_sort_oracle(monkeypatch):
    """Exact-mode probe-budget overflow must transparently re-group via
    the sort path with identical results."""
    from functools import partial

    from trino_tpu.ops import aggregation as agg_mod
    from trino_tpu.ops import hashtable

    monkeypatch.setattr(
        agg_mod, "hash_group_ids",
        partial(hashtable.hash_group_ids, rounds=1))
    rng = np.random.default_rng(5)
    n = 900
    keys = [int(v) for v in np.arange(n) * 7919]  # all distinct
    s1, s2 = _payload(rng, n)
    cols = [keys, s1, s2]
    got = _run_single(AGG_TYPES, cols, [0], AGG_SUITE, True, 256)
    monkeypatch.undo()
    want = _run_single(AGG_TYPES, cols, [0], AGG_SUITE, False, 256)
    _assert_rows_equal(got, want)


# ----------------------------------------------------- partial/final chain


def _run_partial_final(input_types, columns, group_channels, aggs,
                       page_rows, adaptive=False, adaptive_min_rows=10**9,
                       adaptive_ratio=0.9):
    n = len(columns[0])
    partial = HashAggregationOperator(
        input_types, group_channels, aggs, "partial",
        adaptive_partial=adaptive, adaptive_min_rows=adaptive_min_rows,
        adaptive_ratio=adaptive_ratio)
    final_aggs = [AggCall(a.function, None, a.arg_type, a.output_type)
                  for a in aggs]
    inter_types = partial._intermediate_types()
    final = HashAggregationOperator(
        inter_types, list(range(len(group_channels))), final_aggs, "final")
    dicts = [Dictionary() if t.is_pooled else None for t in input_types]
    for lo in range(0, n, page_rows):
        chunk = [c[lo:lo + page_rows] for c in columns]
        page = Page.from_pylists(input_types, chunk, dicts)
        partial.add_input(DevicePage.from_page(page))
        while True:
            out = partial.get_output()
            if out is None:
                break
            final.add_input(out)
    partial.finish()
    while not partial.is_finished():
        out = partial.get_output()
        if out is not None:
            final.add_input(out)
    final.finish()
    pages = []
    while not final.is_finished():
        p = final.get_output()
        if p is not None:
            pages.append(p.to_page())
    return partial, _sorted_rows(Page.concat(pages).to_rows())


def test_partial_final_hash_matches_single_sort():
    rng = np.random.default_rng(17)
    n = 1000
    keys = [int(v) if rng.random() > 0.2 else None
            for v in rng.integers(0, 37, size=n)]
    s1, s2 = _payload(rng, n)
    cols = [keys, s1, s2]
    _, got = _run_partial_final(AGG_TYPES, cols, [0], AGG_SUITE, 256)
    want = _run_single(AGG_TYPES, cols, [0], AGG_SUITE, False)
    _assert_rows_equal(got, want)


def test_adaptive_partial_switches_to_passthrough():
    """High-cardinality keys: the partial step must observe the
    non-reducing ratio, switch to pass-through, and final results must
    be unchanged."""
    rng = np.random.default_rng(23)
    n = 1200
    keys = [int(v) for v in rng.permutation(n * 50)[:n]]  # all distinct
    s1, s2 = _payload(rng, n)
    cols = [keys, s1, s2]
    partial, got = _run_partial_final(
        AGG_TYPES, cols, [0], AGG_SUITE, 256,
        adaptive=True, adaptive_min_rows=256, adaptive_ratio=0.5)
    assert partial.passthrough, "adaptive partial agg must have tripped"
    assert partial.path_counts["passthrough"] > 0
    want = _run_single(AGG_TYPES, cols, [0], AGG_SUITE, False)
    _assert_rows_equal(got, want)


def test_adaptive_partial_stays_on_for_reducing_input():
    rng = np.random.default_rng(29)
    n = 1200
    keys = [int(v) for v in rng.integers(0, 4, size=n)]  # 4 groups
    s1, s2 = _payload(rng, n)
    cols = [keys, s1, s2]
    partial, got = _run_partial_final(
        AGG_TYPES, cols, [0], AGG_SUITE, 256,
        adaptive=True, adaptive_min_rows=256, adaptive_ratio=0.5)
    assert not partial.passthrough
    assert partial.path_counts["passthrough"] == 0
    want = _run_single(AGG_TYPES, cols, [0], AGG_SUITE, False)
    _assert_rows_equal(got, want)


def test_adaptive_per_key_range_splits_skewed_stream():
    """'Partial Partial Aggregates': a skewed stream — one hot key
    carrying ~40% of rows plus an all-distinct tail — must flip only
    its COLD key ranges to pass-through (range-split mode), keep
    aggregating the hot range, and still produce the oracle's final
    rows."""
    rng = np.random.default_rng(31)
    n = 1536
    hot = rng.random(n) < 0.4
    uniq = rng.permutation(n * 50)[:n] + 100
    keys = [7 if h else int(u) for h, u in zip(hot, uniq)]
    s1, s2 = _payload(rng, n)
    cols = [keys, s1, s2]
    partial, got = _run_partial_final(
        AGG_TYPES, cols, [0], AGG_SUITE, 256,
        adaptive=True, adaptive_min_rows=512, adaptive_ratio=0.6)
    # mixed verdicts: the stream SPLIT instead of flipping wholesale
    assert partial._pass_buckets is not None
    assert not partial.passthrough
    assert partial.path_counts["range_split"] > 0
    m = partial.metrics()
    assert m["adaptive"].startswith("range-split")
    assert m["grouping_paths"]["range_split"] > 0
    want = _run_single(AGG_TYPES, cols, [0], AGG_SUITE, False)
    _assert_rows_equal(got, want)


def test_adaptive_single_bucket_keeps_legacy_whole_stream_decision():
    """adaptive_key_buckets=1 is the PR 1 behavior: one global
    verdict, never a range split."""
    rng = np.random.default_rng(37)
    n = 1200
    keys = [int(v) for v in rng.permutation(n * 50)[:n]]
    s1, s2 = _payload(rng, n)
    partial_ = HashAggregationOperator(
        AGG_TYPES, [0], AGG_SUITE, "partial", adaptive_partial=True,
        adaptive_min_rows=256, adaptive_ratio=0.5,
        adaptive_key_buckets=1)
    for lo in range(0, n, 256):
        chunk = [c[lo:lo + 256] for c in [keys, s1, s2]]
        partial_.add_input(DevicePage.from_page(
            Page.from_pylists(AGG_TYPES, chunk)))
        while partial_.get_output() is not None:
            pass
    assert partial_.passthrough
    assert partial_._pass_buckets is None
