"""History-based statistics (round 13): estimate-vs-actual attribution
that closes the loop into the cost model.

The heart is the acceptance loop: a repeated query whose CONNECTOR
estimate is wrong must demonstrably flip its join strategy on the
second run via recorded history (EXPLAIN shows source=hbo), with
results byte-equal to the first run — and ``hbo_enabled=false`` must
restore exactly the pre-HBO engine (no store writes, plan-cache key
unchanged, zero extra jit traces).  Around it: fingerprint canonics
(literals out, children out), EWMA merge math, sidecar persistence +
corrupt-sidecar loudness, data_version invalidation both ways,
adaptive-verdict seeding, the progress fallback, and every
observability surface (plan_stats SQL, trino_hbo_* metrics, slow-query
worst-misestimate, EXPLAIN ANALYZE Q-error)."""

import json
import warnings

import pytest

from trino_tpu import jit_stats
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnStatistics, TableStatistics
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session
from trino_tpu.sql.parser import parse_statement
from trino_tpu.telemetry import stats_store
from trino_tpu.telemetry.stats_store import (
    DEFAULT_EWMA_ALPHA, HboContext, NodeHistory, RuntimeStatsStore,
    merge_actuals, plan_node_fp, q_error, statement_fingerprint)


@pytest.fixture(autouse=True)
def _fresh_store():
    """Every test starts from an empty process-wide store (the global
    accumulates across the whole pytest process otherwise)."""
    stats_store.store().clear()
    yield
    stats_store.store().clear()


def _mem_runner(connector=None, **session_props):
    s = Session(catalog="memory", schema="default")
    s.properties.update(session_props)
    return LocalQueryRunner({"memory": connector or MemoryConnector()},
                            s)


# ---------------------------------------------------------------------------
# the lying connector: truthful data, wrong statistics


class _LyingMetadata:
    def __init__(self, inner, lies):
        self._inner = inner
        self._lies = lies

    def get_statistics(self, table):
        return self._lies.get((table.schema, table.table)) \
            or self._inner.get_statistics(table)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class LyingMemoryConnector(MemoryConnector):
    """Real memory-connector data under fabricated statistics — the
    stale-metastore scenario HBO exists to survive."""

    def __init__(self, lies):
        super().__init__()
        self.lies = lies

    def metadata(self):
        return _LyingMetadata(super().metadata(), self.lies)


def _join_runner(**session_props):
    """fact(4 rows) join dim(3 rows), with stats claiming both are in
    the hundreds of millions: the matmul probe is cost-model-ineligible
    until history corrects the build-side cardinality."""
    lies = {
        ("default", "dim"): TableStatistics(
            row_count=50_000_000.0,
            columns={"k": ColumnStatistics(distinct_count=10.0,
                                           min_value=0, max_value=99),
                     "name": ColumnStatistics(distinct_count=10.0)}),
        ("default", "fact"): TableStatistics(row_count=500_000_000.0),
    }
    r = _mem_runner(LyingMemoryConnector(lies), **session_props)
    r.execute("create table fact (fk bigint, amt bigint)")
    r.execute("create table dim (k bigint, name bigint)")
    r.execute("insert into fact values (1, 10), (2, 20), (3, 30), "
              "(1, 40)")
    r.execute("insert into dim values (1, 100), (2, 200), (3, 300)")
    return r


JOIN_SQL = ("select f.fk, d.name, f.amt from fact f "
            "join dim d on f.fk = d.k order by f.amt")


# ---------------------------------------------------------------------------
# fingerprints


def test_statement_fingerprint_parameterizes_literals():
    from trino_tpu.cache import normalize_statement

    a = normalize_statement(parse_statement(
        "select v from t where k = 5"))[0]
    b = normalize_statement(parse_statement(
        "select v from t where k = 9"))[0]
    c = normalize_statement(parse_statement(
        "select v from t where k < 9"))[0]
    assert statement_fingerprint(a) == statement_fingerprint(b)
    assert statement_fingerprint(a) != statement_fingerprint(c)


def test_plan_node_fp_canonicalizes_literals_and_children():
    r = _mem_runner()
    r.execute("create table t (k bigint, v bigint)")
    r.execute("insert into t values (1, 10)")
    roots = [r.create_plan(f"select v from t where k = {lit}")
             for lit in (5, 9)]

    def by_type(root):
        out = {}

        def walk(n):
            out.setdefault(type(n).__name__, []).append(plan_node_fp(n))
            for s in n.sources:
                walk(s)

        walk(root)
        return out

    a, b = by_type(roots[0]), by_type(roots[1])
    # same shape, different literal vectors -> identical fingerprints
    # node for node (k=5's history must steer k=9's plan)
    assert a == b
    # strategy stamping must not move the fingerprint (a flip must not
    # orphan the history that caused it)
    join_root = _join_runner().create_plan(JOIN_SQL)

    def find_join(n):
        from trino_tpu.planner.plan import JoinNode

        if isinstance(n, JoinNode):
            return n
        for s in n.sources:
            got = find_join(s)
            if got is not None:
                return got

    jn = find_join(join_root)
    before = plan_node_fp(jn)
    jn.strategy, jn.strategy_detail = "matmul", "whatever"
    assert plan_node_fp(jn) == before


def test_agg_step_canonicalization_single_shares_final():
    """Exchange planning splits single -> partial+final AFTER the
    optimizer ran: the single-step node the cost rules consult must
    share its fingerprint with the final node the executed operator
    records under, while partial keeps its own stream."""
    from trino_tpu.planner.plan import AggregationNode, ValuesNode
    from trino_tpu.planner.symbols import Symbol
    from trino_tpu import types as T

    src = ValuesNode([Symbol("k", T.BIGINT)], [])
    single = AggregationNode(src, [Symbol("k", T.BIGINT)], [], "single")
    final = AggregationNode(src, [Symbol("k", T.BIGINT)], [], "final")
    partial = AggregationNode(src, [Symbol("k", T.BIGINT)], [],
                              "partial")
    assert plan_node_fp(single) == plan_node_fp(final)
    assert plan_node_fp(single) != plan_node_fp(partial)


# ---------------------------------------------------------------------------
# store mechanics


def test_ewma_update_math():
    st = RuntimeStatsStore()
    a = DEFAULT_EWMA_ALPHA
    st.record_query("s1", "snap", [{"fp": "n1", "name": "Scan",
                                    "rows": 100.0}])
    h = st.lookup("s1", "n1", "snap")
    assert h.rows == 100.0 and h.runs == 1   # first run seeds exactly
    st.record_query("s1", "snap", [{"fp": "n1", "name": "Scan",
                                    "rows": 200.0}])
    h = st.lookup("s1", "n1", "snap")
    assert h.rows == pytest.approx((1 - a) * 100.0 + a * 200.0)
    assert h.runs == 2


def test_material_only_on_decision_nodes():
    st = RuntimeStatsStore()
    # non-decision node with a terrible estimate: not material
    assert st.record_query("s1", "snap", [
        {"fp": "n1", "name": "Filter", "rows": 1000.0,
         "est_rows": 1.0}]) is False
    # decision node (join input) with the same misestimate: material
    assert st.record_query("s2", "snap", [
        {"fp": "n2", "name": "Scan", "rows": 1000.0, "est_rows": 1.0,
         "decision": True}]) is True
    # converged history: recording the same value again is not material
    assert st.record_query("s2", "snap", [
        {"fp": "n2", "name": "Scan", "rows": 1000.0,
         "est_rows": 1000.0, "decision": True}]) is False


def test_data_version_invalidation_both_ways():
    st = RuntimeStatsStore()
    st.record_query("s1", "snapA", [{"fp": "n1", "name": "Scan",
                                     "rows": 10.0}])
    assert st.lookup("s1", "n1", "snapA").rows == 10.0
    # a moved snapshot drops the statement's history loudly
    assert st.lookup("s1", "n1", "snapB") is None
    assert st.invalidations == 1
    assert st.lookup("s1", "n1", "snapA") is None  # gone for good
    # re-recording under the new snapshot serves again...
    st.record_query("s1", "snapB", [{"fp": "n1", "name": "Scan",
                                     "rows": 20.0}])
    assert st.lookup("s1", "n1", "snapB").rows == 20.0
    # ...and recording under a THIRD snapshot discards the merge base
    # instead of blending across versions
    st.record_query("s1", "snapC", [{"fp": "n1", "name": "Scan",
                                     "rows": 99.0}])
    h = st.lookup("s1", "n1", "snapC")
    assert h.rows == 99.0 and h.runs == 1


def test_persistence_roundtrip(tmp_path):
    path = str(tmp_path / "hbo.json")
    st = RuntimeStatsStore()
    st.record_query("s1", "snapA",
                    [{"fp": "n1", "name": "Scan", "rows": 42.0,
                      "peak_bytes": 1024.0,
                      "adaptive": {"verdict": "passthrough"}}],
                    scan_rows=42.0, peak_bytes=2048.0)
    st.save(path)
    fresh = RuntimeStatsStore()
    assert fresh.load(path) is True
    h = fresh.lookup("s1", "n1", "snapA")
    assert h.rows == 42.0 and h.runs == 1
    assert h.adaptive == {"verdict": "passthrough"}
    hint = fresh.statement_hint("s1", "snapA")
    assert hint["scan_rows"] == 42.0 and hint["peak_bytes"] == 2048.0


def test_corrupt_sidecar_is_loud(tmp_path):
    path = tmp_path / "hbo.json"
    path.write_text("{this is not json")
    st = RuntimeStatsStore()
    with pytest.warns(RuntimeWarning, match="corrupt"):
        assert st.load(str(path)) is False
    assert st.corrupt_loads == 1
    assert st.counters()["statements"] == 0
    # structurally-valid JSON with the wrong schema is just as corrupt
    path.write_text(json.dumps({"something": "else"}))
    with pytest.warns(RuntimeWarning, match="corrupt"):
        assert st.load(str(path)) is False
    assert st.corrupt_loads == 2


def test_merge_actuals_sums_shards():
    merged = merge_actuals([
        [{"fp": "a", "name": "Scan", "rows": 10.0, "bytes": 0.0,
          "wall_ms": 1.0, "flops": 0.0, "peak_bytes": 0.0}],
        [{"fp": "a", "name": "Scan", "rows": 5.0, "bytes": 0.0,
          "wall_ms": 2.0, "flops": 0.0, "peak_bytes": 0.0,
          "adaptive": {"verdict": "aggregate"}}],
    ])
    assert len(merged) == 1
    assert merged[0]["rows"] == 15.0
    assert merged[0]["wall_ms"] == 3.0
    assert merged[0]["adaptive"] == {"verdict": "aggregate"}


def test_q_error():
    assert q_error(10, 1000) == 100.0
    assert q_error(1000, 10) == 100.0
    assert q_error(0, 0) == 1.0   # floored at one row


# ---------------------------------------------------------------------------
# the acceptance loop: misestimated join flips strategy on re-run


def test_misestimated_join_flips_to_matmul_on_rerun():
    r = _join_runner()
    ex1 = r.explain(JOIN_SQL)
    assert "strategy=matmul" not in ex1      # connector lie: ineligible
    res1 = r.execute(JOIN_SQL)
    assert res1.stats["hbo"]["material"] is True
    assert r.query_cache.plans.hbo_invalidations >= 1
    ex2 = r.explain(JOIN_SQL)
    # the loop closed: recorded build-side cardinality beat the lie
    assert "strategy=matmul" in ex2
    assert "source=hbo" in ex2
    res2 = r.execute(JOIN_SQL)
    assert res2.rows == res1.rows            # byte-equal flip
    # converged: the third run re-uses the re-planned cached plan
    res3 = r.execute(JOIN_SQL)
    assert res3.rows == res1.rows
    assert res3.stats.get("plan_cache") == "hit"


def test_hbo_disabled_restores_pre_hbo_behavior():
    r = _join_runner(hbo_enabled=False)
    store = stats_store.store()
    res1 = r.execute(JOIN_SQL)
    assert "hbo" not in (res1.stats or {})
    assert store.counters()["records"] == 0      # no store writes
    assert store.counters()["misses"] == 0       # not even consulted
    before = jit_stats.total()
    res2 = r.execute(JOIN_SQL)
    assert res2.rows == res1.rows
    # the plan-cache hit path is untouched: zero jit traces, no
    # hbo invalidation ever fired
    assert res2.stats.get("plan_cache") == "hit"
    assert jit_stats.total() == before
    assert r.query_cache.plans.hbo_invalidations == 0
    # and no strategy flip: the lie stands uncorrected
    assert "strategy=matmul" not in r.explain(JOIN_SQL)


def test_literal_sibling_shares_history():
    """A recorded run must steer every literal vector of the shape:
    ``amt >= 0``'s history plans ``amt >= 15`` too (the WHERE literal
    is parameterized out of the statement shape AND canonicalized out
    of the node fingerprints, pushed-down domain bounds included)."""
    r = _join_runner()
    tpl = ("select f.fk, d.name, f.amt from fact f "
           "join dim d on f.fk = d.k where f.amt >= {} order by f.amt")
    r.execute(tpl.format(0))
    ex = r.explain(tpl.format(15))
    assert "source=hbo" in ex and "strategy=matmul" in ex


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE + slow-query surfaces


def test_explain_analyze_renders_qerror_and_worst():
    r = _join_runner()
    r.execute(JOIN_SQL)
    out = "\n".join(row[0] for row in r.execute(
        "explain analyze " + JOIN_SQL).rows)
    assert "q=" in out
    assert "est " in out
    assert "Worst misestimate:" in out


def test_slow_query_log_carries_worst_misestimate():
    from trino_tpu.events import EventListener

    events = []

    class Listener(EventListener):
        def query_completed(self, e):
            events.append(e)

    r = _join_runner(slow_query_log_threshold=1e-9)
    r.event_manager.listeners.append(Listener())
    r.execute(JOIN_SQL)
    slow = [e for e in events
            if (e.stats or {}).get("slow_query")]
    assert slow, "no slow-query record fired"
    worst = slow[-1].stats["slow_query"]["worst_misestimate"]
    assert worst is not None
    assert worst["qerror"] >= 2.0
    assert worst["name"]
    # and system.runtime.queries renders it in the slow column
    rows = r.execute("select slow from system.runtime.queries "
                     "where slow is not null").rows
    assert any("misest=" in row[0] for row in rows)


# ---------------------------------------------------------------------------
# progress fallback, admission hint


class _StatlessMemory(MemoryConnector):
    """A connector that reports NO statistics at all (the progress
    fraction would stay 0 forever without the HBO fallback)."""

    def metadata(self):
        inner = super().metadata()

        class M:
            def get_statistics(self, table, _inner=inner):
                return TableStatistics()

            def __getattr__(self, name, _inner=inner):
                return getattr(_inner, name)

        return M()


def test_progress_falls_back_to_hbo_actuals():
    from trino_tpu.telemetry.progress import QueryProgress

    r = _mem_runner(_StatlessMemory())
    r.execute("create table t (k bigint)")
    r.execute("insert into t values (1), (2), (3)")
    sql = "select count(*) c from t"
    p1 = QueryProgress("q1")
    r.execute(sql, progress=p1)
    assert p1.total_rows == 0               # connector knows nothing
    assert p1.estimate_source == "connector"
    assert p1.fraction() == 1.0             # terminal anyway
    p2 = QueryProgress("q2")
    r.execute(sql, progress=p2)
    assert p2.total_rows == 3               # history filled the gap
    assert p2.estimate_source == "hbo"
    assert p2.to_dict()["estimate_source"] == "hbo"


def test_admission_hint_lowers_memory_charge():
    from trino_tpu.resource_groups import (ResourceGroupManager,
                                           ResourceGroupSpec)

    groups = ResourceGroupManager([ResourceGroupSpec("all")])
    r = _mem_runner()
    r.resource_groups = groups
    r.execute("create table t (k bigint)")
    r.execute("insert into t values (1), (2)")
    sql = "select sum(k) s from t"
    r.execute(sql)
    hinted = r._hbo_admission_bytes(sql)
    assert hinted is not None
    assert hinted >= 64 << 20               # floored
    assert hinted < 8 << 30                 # way under the default cap
    # second execution rides the hinted admission without error
    assert r.execute(sql).rows == [(3,)]


# ---------------------------------------------------------------------------
# adaptive partial aggregation seeding


def test_adaptive_seed_applies_and_reports_source():
    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.ops.aggregation import HashAggregationOperator

    op = HashAggregationOperator(
        [T.BIGINT, T.BIGINT], [0], [], step="partial",
        adaptive_seed={"verdict": "passthrough"})
    assert op.passthrough and op._adaptive_decided
    assert "seeded by hbo" in op.metrics()["adaptive"]
    mask = [1, 0] * 8
    op2 = HashAggregationOperator(
        [T.BIGINT, T.BIGINT], [0], [], step="partial",
        adaptive_key_buckets=16,
        adaptive_seed={"verdict": "range-split", "pass_buckets": mask})
    assert op2._adaptive_decided and not op2.passthrough
    assert list(np.asarray(op2._pass_buckets).astype(int)) == mask
    assert op2.metrics()["adaptive_verdict"]["pass_buckets"] == mask
    # a re-tuned bucket knob must NOT misapply a stale mask
    op3 = HashAggregationOperator(
        [T.BIGINT, T.BIGINT], [0], [], step="partial",
        adaptive_key_buckets=8,
        adaptive_seed={"verdict": "range-split", "pass_buckets": mask})
    assert not op3._adaptive_decided
    op4 = HashAggregationOperator(
        [T.BIGINT, T.BIGINT], [0], [], step="partial",
        adaptive_seed={"verdict": "aggregate"})
    assert op4._adaptive_decided and not op4.passthrough
    assert op4.metrics()["adaptive_verdict"] == {"verdict": "aggregate"}


def test_adaptive_verdict_recorded_and_seeded_e2e():
    """A partial agg over mostly-unique keys decides pass-through;
    the verdict lands in history and the next run's operator starts
    decided (seeded by hbo), with identical results."""
    from trino_tpu.parallel.distributed import DistributedQueryRunner

    conn = MemoryConnector()
    s = Session(catalog="memory", schema="default")
    s.properties["adaptive_partial_aggregation_min_rows"] = 64
    r = DistributedQueryRunner({"memory": conn}, s, n_workers=2,
                               desired_splits=2)
    LocalQueryRunner({"memory": conn}, s).execute(
        "create table u (k bigint, v bigint)")
    LocalQueryRunner({"memory": conn}, s).execute(
        "insert into u values " + ", ".join(
            f"({i}, {i % 7})" for i in range(512)))
    sql = "select k, sum(v) s from u group by k order by k limit 5"
    res1 = r.execute(sql)
    # the partial-agg verdict was recorded under the statement shape
    snap = [e for e in stats_store.store().snapshot()
            if e.get("adaptive")]
    assert snap, "no adaptive verdict recorded"
    assert snap[0]["adaptive"]["verdict"] in ("passthrough",
                                              "range-split")
    res2 = r.execute(sql)
    assert res2.rows == res1.rows


# ---------------------------------------------------------------------------
# observability surfaces


def test_plan_stats_sql_catalog():
    r = _mem_runner()
    r.execute("create table t (k bigint)")
    r.execute("insert into t values (1), (2)")
    r.execute("select count(*) c from t")
    rows = r.execute(
        "select statement, node, name, runs, rows "
        "from system.runtime.plan_stats").rows
    assert rows
    names = {row[2] for row in rows}
    assert "TableScanOperator" in names
    assert all(row[3] >= 1 for row in rows)


def test_hbo_metric_families_and_prometheus_roundtrip():
    from trino_tpu.telemetry.metrics import (parse_prometheus,
                                             render_prometheus)

    r = _mem_runner()
    r.execute("create table t (k bigint)")
    r.execute("insert into t values (1)")
    r.execute("select count(*) c from t")
    fams = {f["name"]: f for f in r.metrics_families()}
    assert "trino_hbo_store_entries" in fams
    assert "trino_hbo_lookups_total" in fams
    assert "trino_hbo_qerror" in fams
    assert fams["trino_hbo_qerror"]["type"] == "histogram"
    text = render_prometheus(r.metrics_families())
    parsed = parse_prometheus(text)
    assert "trino_hbo_qerror_count" in parsed
    assert "trino_hbo_records_total" in parsed
    # misestimate histogram actually observed something
    assert sum(parsed["trino_hbo_qerror_count"].values()) >= 1


def test_qerror_quantiles_for_bench():
    st = RuntimeStatsStore()
    st.record_query("s1", "snap", [
        {"fp": f"n{i}", "name": "Scan", "rows": 10.0,
         "est_rows": 10.0 * (2 ** i)} for i in range(4)])
    assert st.qerror_quantile(0.5) is not None
    assert st.qerror_quantile(0.9) >= st.qerror_quantile(0.5)
    assert RuntimeStatsStore().qerror_quantile(0.5) is None


def test_store_bounded_lru():
    st = RuntimeStatsStore(max_statements=4)
    for i in range(10):
        st.record_query(f"s{i}", "snap", [{"fp": "n", "name": "X",
                                           "rows": 1.0}])
    assert st.counters()["statements"] == 4
    assert st.lookup("s9", "n", "snap") is not None
    assert st.lookup("s0", "n", "snap") is None


# ---------------------------------------------------------------------------
# distributed + sidecar e2e


def test_distributed_runner_records_and_reuses_history(tmp_path):
    from trino_tpu.parallel.distributed import DistributedQueryRunner

    conn = MemoryConnector()
    s = Session(catalog="memory", schema="default")
    local = LocalQueryRunner({"memory": conn}, s)
    local.execute("create table t (k bigint, v bigint)")
    local.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    r = DistributedQueryRunner({"memory": conn}, s, n_workers=2,
                               desired_splits=2)
    sql = "select k, sum(v) s from t group by k order by k"
    res1 = r.execute(sql)
    assert res1.stats.get("hbo", {}).get("recorded", 0) > 0
    assert stats_store.store().counters()["records"] == 1
    res2 = r.execute(sql)
    assert res2.rows == res1.rows
    # EXPLAIN ANALYZE renders per-node q-errors from the same store
    out = "\n".join(row[0] for row in r.execute(
        "explain analyze " + sql).rows)
    assert "q=" in out


def test_process_runner_worker_actuals_piggyback():
    """The multi-process path: worker tasks tag operators, their
    actuals ride the task responses back, and the coordinator's store
    records the merged query — no extra RPC, byte-equal repeats."""
    from trino_tpu.parallel.process_runner import ProcessQueryRunner

    catalogs = {"tpch": {"connector": "tpch", "page_rows": 4096}}
    runner = ProcessQueryRunner(
        catalogs, Session(catalog="tpch", schema="micro"),
        n_workers=2, desired_splits=4)
    try:
        sql = ("select o_orderstatus, count(*) c from orders "
               "group by o_orderstatus order by o_orderstatus")
        res1 = runner.execute(sql)
        assert res1.stats.get("hbo", {}).get("recorded", 0) > 0
        c = stats_store.store().counters()
        assert c["records"] == 1
        # scan actuals arrived from WORKER processes (the coordinator
        # only runs the output stage, which has no table scans)
        snap = stats_store.store().snapshot()
        assert any(e["name"] == "TableScanOperator" and e["rows"] > 0
                   for e in snap), snap
        res2 = runner.execute(sql)
        assert res2.rows == res1.rows
    finally:
        runner.close()


def test_seed_export_import_roundtrip_and_bounds():
    """export_seed ships the MOST RECENT statements, bounded;
    import_seed folds them into an empty (worker) store losslessly."""
    src = RuntimeStatsStore()
    for i in range(40):
        src.record_query(f"s{i}", "snap",
                         [{"fp": "n", "name": "Scan", "rows": float(i)}])
    seed = src.export_seed(max_statements=8)
    assert len(seed["statements"]) == 8
    assert {s["fp"] for s in seed["statements"]} == \
        {f"s{i}" for i in range(32, 40)}   # recency, not insertion
    dst = RuntimeStatsStore()
    assert dst.import_seed(seed) == 8
    assert dst.counters()["statements"] == 8
    h = dst.lookup("s39", "n", "snap")
    assert h is not None and h.rows == 39.0


def test_seed_existing_statements_win():
    """A worker that already observed fresher actuals must not regress
    to the coordinator's shipped EWMA."""
    dst = RuntimeStatsStore()
    dst.record_query("s", "snap",
                     [{"fp": "n", "name": "Scan", "rows": 100.0}])
    src = RuntimeStatsStore()
    src.record_query("s", "snap",
                     [{"fp": "n", "name": "Scan", "rows": 5.0}])
    src.record_query("other", "snap",
                     [{"fp": "n", "name": "Scan", "rows": 7.0}])
    # the return value counts what was ACTUALLY imported: "s" already
    # exists (kept), only "other" lands
    assert dst.import_seed(src.export_seed()) == 1
    assert dst.lookup("s", "n", "snap").rows == 100.0   # kept
    assert dst.lookup("other", "n", "snap").rows == 7.0  # gained


def test_seed_malformed_warns_and_imports_nothing():
    dst = RuntimeStatsStore()
    with pytest.warns(RuntimeWarning, match="hbo seed"):
        ok = dst.import_seed({"statements": [{"fp": "x"}]})
    assert not ok
    assert dst.counters()["statements"] == 0
    assert dst.counters()["corrupt_loads"] == 1


def test_worker_configure_imports_seed_over_rpc():
    """The real configure handler: an hbo_seed payload lands in the
    worker-local store and the response reports the seeded count."""
    import threading

    from trino_tpu.parallel.rpc import call
    from trino_tpu.parallel.worker import WorkerServer

    src = RuntimeStatsStore()
    src.record_query("seeded-stmt", "snap",
                     [{"fp": "n", "name": "Scan", "rows": 3.0}])
    stats_store.store().clear()
    server = WorkerServer(0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        resp = call(("127.0.0.1", server.port), {
            "op": "configure", "catalogs": {},
            "properties": {}, "hbo_seed": src.export_seed()})
        assert resp["ok"] and resp["hbo_seeded"] == 1
        # in-process server shares this process's store: the seed is
        # visible right here
        assert stats_store.store().lookup("seeded-stmt", "n", "snap") \
            is not None
    finally:
        server.server.shutdown()
        stats_store.store().clear()


def test_process_runner_ships_seed_and_binding_to_workers():
    """E2E over real worker subprocesses: after the coordinator learns
    a statement's actuals, a newly spawned (replacement-shaped) worker
    receives the bounded history seed at configure — workers no longer
    plan from nothing."""
    from trino_tpu.parallel.process_runner import ProcessQueryRunner

    catalogs = {"tpch": {"connector": "tpch", "page_rows": 4096}}
    runner = ProcessQueryRunner(
        catalogs, Session(catalog="tpch", schema="micro"),
        n_workers=2, desired_splits=4)
    new = None
    try:
        # the initial workers spawned against an empty store
        assert all(w.hbo_seeded == 0 for w in runner.workers)
        sql = ("select o_orderstatus, count(*) c from orders "
               "group by o_orderstatus order by o_orderstatus")
        res1 = runner.execute(sql)
        assert res1.stats.get("hbo", {}).get("recorded", 0) > 0
        # a worker spawned NOW (the replacement path) gets the learned
        # history piggybacked on its configure
        new = runner._spawn_worker_process(generation=1)
        assert new.hbo_seeded >= 1
        # and the run_task binding carries the statement key workers
        # need to look that history up
        from trino_tpu.parallel.process_runner import _QueryCtx
        ctx = _QueryCtx(runner.session, "qtest")
        from trino_tpu.telemetry.stats_store import HboContext
        ctx.hbo = HboContext("fp", "snap", stats_store.store())
        assert runner._hbo_binding(ctx) == {"stmt_fp": "fp",
                                            "snap": "snap"}
        ctx.hbo = None
        assert runner._hbo_binding(ctx) is None
        res2 = runner.execute(sql)
        assert res2.rows == res1.rows
    finally:
        if new is not None:
            new.proc.kill()
        runner.close()


def test_sidecar_survives_process_restart_simulation(tmp_path):
    path = str(tmp_path / "hbo.json")
    r = _join_runner(hbo_store_path=path)
    res1 = r.execute(JOIN_SQL)
    # "restart": clear the process store, build a fresh runner over the
    # same catalog state; the sidecar restores the learned history
    stats_store.store().clear()
    r2 = _join_runner(hbo_store_path=path)
    with warnings.catch_warnings():
        warnings.simplefilter("error")      # a corrupt load would raise
        ex = r2.explain(JOIN_SQL)
    assert "strategy=matmul" in ex and "source=hbo" in ex
    assert r2.execute(JOIN_SQL).rows == res1.rows

# ---------------------------------------------------------------------------
# plan exploration: history drives join ORDER and exchange DISTRIBUTION


def _star_runner(**session_props):
    """fact(12) joining dim1(50) and dim2(2) where the connector's lies
    INVERT the dimension sizes: estimates say join dim1 first, recorded
    actuals say join dim2 first."""
    lies = {
        ("default", "f"): TableStatistics(row_count=500_000.0),
        ("default", "d1"): TableStatistics(row_count=2.0),
        ("default", "d2"): TableStatistics(row_count=50_000.0),
    }
    r = _mem_runner(LyingMemoryConnector(lies), **session_props)
    r.execute("create table f (k bigint, j bigint, v bigint)")
    r.execute("create table d1 (k bigint, a bigint)")
    r.execute("create table d2 (j bigint, b bigint)")
    r.execute("insert into f values " + ", ".join(
        f"({i % 3 + 1}, {i % 2 + 1}, {i * 10})" for i in range(12)))
    r.execute("insert into d1 values " + ", ".join(
        f"({i + 1}, {i * 100})" for i in range(50)))
    r.execute("insert into d2 values (1, 7), (2, 8)")
    return r


STAR_SQL = ("select f.k, f.j, f.v, d1.a, d2.b from f "
            "join d1 on f.k = d1.k join d2 on f.j = d2.j "
            "order by f.v")


def _reorder_detail(explain_text: str) -> str:
    for line in explain_text.splitlines():
        if "ReorderJoins" in line and "[" in line:
            return line
    return ""


def _scan_order(explain_text: str, *tables: str):
    pos = {t: explain_text.find(f"memory.default.{t}") for t in tables}
    assert all(p >= 0 for p in pos.values()), explain_text
    return sorted(tables, key=lambda t: pos[t])


def test_hbo_reorders_join_order_on_rerun():
    r = _star_runner()
    ex1 = r.explain(STAR_SQL)
    # estimates alone (d1 claims 2 rows): d1 joins first, no history tag
    assert "(hbo reordered)" not in ex1
    assert _scan_order(ex1, "d1", "d2") == ["d1", "d2"]
    res1 = r.execute(STAR_SQL)
    assert res1.stats["hbo"]["material"] is True
    ex2 = r.explain(STAR_SQL)
    # recorded cardinalities re-priced the DP: relations tagged [hbo],
    # and the chosen order CHANGED versus estimates alone — the
    # actually-2-row d2 now joins first
    d2 = _reorder_detail(ex2)
    assert "[hbo]" in d2
    assert "(hbo reordered)" in d2
    assert _scan_order(ex2, "f", "d1", "d2") != \
        _scan_order(ex1, "f", "d1", "d2")
    assert stats_store.store().plan_flips.get("join_order", 0) >= 1
    res2 = r.execute(STAR_SQL)
    assert res2.rows == res1.rows            # byte-equal flip
    sorted_by_oracle = sorted(res1.rows, key=lambda t: t[2])
    assert res1.rows == sorted_by_oracle


def test_reorder_gate_keeps_connector_order():
    r = _star_runner(hbo_reorder_joins_enabled=False)
    r.execute(STAR_SQL)
    ex = r.explain(STAR_SQL)
    assert "(hbo reordered)" not in ex
    assert _scan_order(ex, "d1", "d2") == ["d1", "d2"]
    assert stats_store.store().plan_flips.get("join_order", 0) == 0


def test_shared_calculator_memoizes_region_estimates(monkeypatch):
    """One optimize() run prices every (group, version) region ONCE:
    the per-run shared calculator + RuleContext region memo must make
    strictly fewer estimator calls than fresh per-application
    calculators (the pre-round-20 behavior) on a q3-shaped plan."""
    from trino_tpu.planner.memo import RuleContext
    from trino_tpu.planner.stats import StatsCalculator

    conn = MemoryConnector()
    seed = _mem_runner(conn)
    seed.execute("create table f (k bigint, j bigint, v bigint)")
    seed.execute("create table d1 (k bigint, a bigint)")
    seed.execute("create table d2 (j bigint, b bigint)")
    seed.execute("insert into f values (1, 1, 10), (2, 2, 20)")
    seed.execute("insert into d1 values (1, 100), (2, 200)")
    seed.execute("insert into d2 values (1, 7), (2, 8)")
    sql = ("select f.k, f.j, f.v, d1.a, d2.b from f "
           "join d1 on f.k = d1.k join d2 on f.j = d2.j")

    calls = {"n": 0}
    orig_stats = StatsCalculator.stats

    def counting(self, node):
        calls["n"] += 1
        return orig_stats(self, node)

    monkeypatch.setattr(StatsCalculator, "stats", counting)
    _mem_runner(conn).explain(sql)
    shared = calls["n"]

    # pre-shared-calculator behavior: no cross-rule region memo and a
    # fresh calculator per shared_stats() consult
    monkeypatch.setattr(RuleContext, "_region_key",
                        lambda self, leaf: None)

    def fresh(self):
        return StatsCalculator(self.metadata, history=self.hbo)

    monkeypatch.setattr(RuleContext, "shared_stats", fresh)
    calls["n"] = 0
    _mem_runner(conn).explain(sql)
    assert shared < calls["n"], \
        f"shared calculator made {shared} estimator calls, " \
        f"per-application calculators made {calls['n']}"


def _dist_pair(**session_props):
    """Distributed runner over a lying build side: the connector claims
    2 build rows (broadcast territory under threshold=50); the table
    actually has 200 (partitioned territory)."""
    from trino_tpu.parallel.distributed import DistributedQueryRunner

    lies = {
        ("default", "probe"): TableStatistics(row_count=100_000.0),
        ("default", "build"): TableStatistics(row_count=2.0),
    }
    conn = LyingMemoryConnector(lies)
    s = Session(catalog="memory", schema="default")
    # keep join ORDER pinned to connector estimates so the witness
    # isolates the distribution decision
    s.properties["hbo_reorder_joins_enabled"] = False
    s.properties.update(session_props)
    local = LocalQueryRunner({"memory": conn}, s)
    local.execute("create table probe (k bigint, v bigint)")
    local.execute("create table build (k bigint, w bigint)")
    local.execute("insert into probe values " + ", ".join(
        f"({i % 200 + 1}, {i})" for i in range(40)))
    local.execute("insert into build values " + ", ".join(
        f"({i + 1}, {i * 3})" for i in range(200)))
    r = DistributedQueryRunner({"memory": conn}, s, n_workers=2,
                               desired_splits=2, broadcast_threshold=50)
    return r


DIST_SQL = ("select probe.k, probe.v, build.w from probe "
            "join build on probe.k = build.k order by probe.v")


def test_distribution_flips_to_partitioned_on_rerun():
    r = _dist_pair()
    ex1 = r.explain(DIST_SQL)
    assert "distribution=broadcast [source=connector]" in ex1
    res1 = r.execute(DIST_SQL)
    # the 2-vs-200 build misestimate sits on a DISTRIBUTION decision
    # node: material, so the cached fragment plan is invalidated
    assert res1.stats["hbo"]["material"] is True
    assert r.plan_cache.hbo_invalidations >= 1
    ex2 = r.explain(DIST_SQL)
    assert "distribution=partitioned [source=hbo]" in ex2
    assert stats_store.store().plan_flips.get("distribution", 0) >= 1
    res2 = r.execute(DIST_SQL)
    assert res2.rows == res1.rows            # byte-equal flip
    # converged: the third run reuses the re-planned cached fragments
    res3 = r.execute(DIST_SQL)
    assert res3.rows == res1.rows
    assert res3.stats.get("plan_cache") == "hit"


def test_distribution_gate_keeps_connector_choice():
    r = _dist_pair(hbo_distribution_enabled=False)
    r.execute(DIST_SQL)
    ex = r.explain(DIST_SQL)
    # est~ annotations stay history-fed (a different, ungated surface);
    # the DISTRIBUTION decision itself must ignore the observed rows
    assert "distribution=broadcast [source=connector]" in ex
    assert "distribution=partitioned" not in ex
    assert "distribution=broadcast [source=hbo]" not in ex
    assert stats_store.store().plan_flips.get("distribution", 0) == 0


def test_spill_hint_refuses_broadcast():
    """A build that spilled on a prior run must not be replicated even
    when its observed cardinality is comfortably under the broadcast
    threshold."""
    from trino_tpu.parallel.distributed import DistributedQueryRunner

    conn = MemoryConnector()
    s = Session(catalog="memory", schema="default")
    s.properties["hbo_reorder_joins_enabled"] = False
    local = LocalQueryRunner({"memory": conn}, s)
    local.execute("create table probe (k bigint, v bigint)")
    local.execute("create table build (k bigint, w bigint)")
    local.execute("insert into probe values (1, 10), (2, 20), (3, 30)")
    local.execute("insert into build values (1, 7), (2, 8), (3, 9)")
    r = DistributedQueryRunner({"memory": conn}, s, n_workers=2,
                               desired_splits=2, broadcast_threshold=50)
    sql = ("select probe.k, probe.v, build.w from probe "
           "join build on probe.k = build.k order by probe.v")
    res1 = r.execute(sql)
    # 3 observed build rows < 50: still broadcast
    assert "distribution=broadcast" in r.explain(sql)
    # inject a spill record onto every recorded node of the statement
    # (the hybrid-join runtime does this for the build it spilled)
    store = stats_store.store()
    for stmt_fp, st in list(store._stmts.items()):
        store.record_query(stmt_fp, st["snap"], [
            {"fp": fp, "name": h.name, "rows": h.rows,
             "spill": {"fanout": 4, "fraction": 0.5}}
            for fp, h in st["nodes"].items()])
    ex = r.explain(sql)
    assert "distribution=partitioned [source=hbo]" in ex
    assert r.execute(sql).rows == res1.rows


def test_plan_flips_metric_family():
    store = stats_store.store()
    store.record_query("s", "snap", [{"fp": "n", "name": "X",
                                      "rows": 1.0}])
    store.note_plan_flip("join_order")
    store.note_plan_flip("distribution")
    store.note_plan_flip("distribution")
    fams = {f["name"]: f for f in store.families()}
    fam = fams["trino_hbo_plan_flips"]
    assert fam["type"] == "counter"
    by_kind = {tuple(sorted(l.items())): v for l, v in fam["samples"]}
    assert by_kind[(("kind", "join_order"),)] == 1
    assert by_kind[(("kind", "distribution"),)] == 2
    assert store.counters()["plan_flips"] == 3
