import numpy as np

from trino_tpu import types as T
from trino_tpu.block import DevicePage, Page
from trino_tpu.ops.join import (HashBuilderOperator, JoinBridge,
                                LookupJoinOperator)
from trino_tpu.ops.sort import OrderByOperator, TopNOperator
from trino_tpu.ops.sortkeys import SortKey


def dev(types_, cols):
    return DevicePage.from_page(Page.from_pylists(types_, cols))


def build_probe(build_types, build_cols, build_keys, probe_types,
                probe_cols, probe_keys, join_type="inner"):
    bridge = JoinBridge()
    builder = HashBuilderOperator(build_types, build_keys, bridge)
    builder.add_input(dev(build_types, build_cols))
    builder.finish()
    builder.get_output()
    probe = LookupJoinOperator(probe_types, probe_keys, bridge, join_type)
    probe.add_input(dev(probe_types, probe_cols))
    probe.finish()
    out = probe.get_output()
    return out.to_page() if out is not None else None


def test_inner_join_single_key():
    out = build_probe(
        [T.BIGINT, T.VARCHAR], [[1, 2, 2, 4], ["a", "b", "c", "d"]], [0],
        [T.BIGINT, T.BIGINT], [[2, 1, 5, 2], [10, 20, 30, 40]], [0])
    rows = sorted(out.to_rows())
    # probe rows with key 2 match two build rows each; key 5 drops
    assert rows == sorted([
        (2, 10, 2, "b"), (2, 10, 2, "c"), (1, 20, 1, "a"),
        (2, 40, 2, "b"), (2, 40, 2, "c")])


def test_left_join_emits_unmatched_with_nulls():
    out = build_probe(
        [T.BIGINT, T.VARCHAR], [[1], ["a"]], [0],
        [T.BIGINT], [[1, 3]], [0], join_type="left")
    rows = sorted(out.to_rows(), key=lambda r: r[0])
    assert rows == [(1, 1, "a"), (3, None, None)]


def test_join_null_keys_never_match():
    out = build_probe(
        [T.BIGINT], [[1, None]], [0],
        [T.BIGINT], [[1, None]], [0])
    assert out.to_rows() == [(1, 1)]


def test_semi_and_anti_join():
    semi = build_probe([T.BIGINT], [[2, 4]], [0],
                       [T.BIGINT], [[1, 2, 3, 4]], [0], join_type="semi")
    assert sorted(r[0] for r in semi.to_rows()) == [2, 4]
    anti = build_probe([T.BIGINT], [[2, 4]], [0],
                       [T.BIGINT], [[1, 2, 3, 4]], [0], join_type="anti")
    assert sorted(r[0] for r in anti.to_rows()) == [1, 3]


def test_two_key_join():
    out = build_probe(
        [T.BIGINT, T.BIGINT, T.VARCHAR],
        [[1, 1, 2], [10, 20, 10], ["x", "y", "z"]], [0, 1],
        [T.BIGINT, T.BIGINT], [[1, 2, 1], [20, 10, 99]], [0, 1])
    rows = sorted(out.to_rows())
    assert rows == sorted([(1, 20, 1, 20, "y"), (2, 10, 2, 10, "z")])


def test_order_by_multi_key_with_nulls():
    op = OrderByOperator([T.BIGINT, T.DOUBLE],
                         [SortKey(0, ascending=True),
                          SortKey(1, ascending=False)])
    op.add_input(dev([T.BIGINT, T.DOUBLE],
                     [[3, 1, None, 1], [1.5, 2.5, 9.9, 0.5]]))
    op.finish()
    out = op.get_output().to_page()
    # asc nulls last on key0; desc on key1
    assert out.to_rows() == [(1, 2.5), (1, 0.5), (3, 1.5), (None, 9.9)]


def test_order_by_strings_uses_rank():
    op = OrderByOperator([T.VARCHAR], [SortKey(0)])
    op.add_input(dev([T.VARCHAR], [[ "pear", "apple", "mango"]]))
    op.finish()
    out = op.get_output().to_page()
    assert out.block(0).to_pylist() == ["apple", "mango", "pear"]


def test_topn_streaming():
    op = TopNOperator([T.BIGINT], [SortKey(0, ascending=False)], 3)
    op.add_input(dev([T.BIGINT], [[5, 1, 9]]))
    op.add_input(dev([T.BIGINT], [[7, 2, 8, 3]]))
    op.finish()
    out = op.get_output().to_page()
    assert out.block(0).to_pylist() == [9, 8, 7]
