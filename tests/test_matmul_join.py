"""MXU matmul join strategy (ops/matmul_join.py) vs the sorted-index
oracle, and the cost-model plumbing that selects it.

The matmul operator IS a LookupJoinOperator with the probe's candidate
lookup swapped for a blocked one-hot matmul, so every join type must
produce identical rows over adversarial distributions — dense and
sparse NDV, nulls, skew, dictionary-coded strings — and every
infeasible build must fall back to the inherited sorted-index probe
with the reason in metrics, still row-identical.
"""

import numpy as np
import pytest

from trino_tpu import jit_stats
from trino_tpu import types as T
from trino_tpu.block import DevicePage, Page
from trino_tpu.ops.join import (HashBuilderOperator, JoinBridge,
                                LookupJoinOperator)
from trino_tpu.ops.matmul_join import MatmulJoinOperator


def _run_join(op_cls, join_type, types_, build_cols, probe_cols,
              key_channels=(0,), page_rows=512, **kw):
    from trino_tpu.block import Dictionary

    bridge = JoinBridge()
    build = HashBuilderOperator(types_, list(key_channels), bridge)
    n_b = len(build_cols[0])
    # one pool per side, shared across its pages (the exchange-unified
    # contract); build and probe pools still DIFFER, so the remap seam
    # is exercised
    bdicts = [Dictionary() if t.is_pooled else None for t in types_]
    pdicts = [Dictionary() if t.is_pooled else None for t in types_]
    for lo in range(0, n_b, page_rows):
        build.add_input(DevicePage.from_page(Page.from_pylists(
            types_, [c[lo:lo + page_rows] for c in build_cols],
            bdicts)))
    build.finish()
    build.get_output()
    probe = op_cls(types_, list(key_channels), bridge, join_type, **kw)
    rows = []
    n_p = len(probe_cols[0])
    for lo in range(0, n_p, page_rows):
        probe.add_input(DevicePage.from_page(Page.from_pylists(
            types_, [c[lo:lo + page_rows] for c in probe_cols],
            pdicts)))
        while (p := probe.get_output()) is not None:
            rows.extend(p.to_page().to_rows())
    probe.finish()
    while not probe.is_finished():
        p = probe.get_output()
        if p is not None:
            rows.extend(p.to_page().to_rows())
    return sorted(rows, key=repr), probe


def _int_cols(rng, n, ndv, null_frac=0.0, skew=False):
    if skew:
        keys = (rng.zipf(1.8, n) % max(ndv, 1)).astype(int)
    else:
        keys = rng.integers(0, max(ndv, 1), n)
    k = [int(v) if rng.random() >= null_frac else None for v in keys]
    payload = [int(v) for v in rng.integers(0, 1000, n)]
    return [k, payload]


@pytest.mark.parametrize("join_type,ndv,null_frac,skew", [
    # every join type on the adversarial middle (skew + nulls) runs
    # tier-1; the dense/sparse NDV extremes ride the slow mark (the
    # BENCH_ROLE=kernels child sweeps them too) — tier-1 budget
    ("inner", 150, 0.1, True),
    ("semi", 150, 0.1, True),
    ("anti", 150, 0.1, True),
    ("left", 150, 0.1, True),
    pytest.param("inner", 4, 0.0, False, marks=pytest.mark.slow),
    pytest.param("semi", 4, 0.0, False, marks=pytest.mark.slow),
    pytest.param("inner", 900, 0.05, False, marks=pytest.mark.slow),
    pytest.param("semi", 900, 0.05, False, marks=pytest.mark.slow),
])
def test_matmul_matches_sorted_index_oracle(join_type, ndv, null_frac,
                                            skew):
    rng = np.random.default_rng(ndv * 7 + len(join_type))
    types_ = [T.BIGINT, T.BIGINT]
    build_cols = _int_cols(rng, 768, ndv, null_frac)
    probe_cols = _int_cols(rng, 1024, int(ndv * 1.5) + 4, null_frac,
                           skew)
    want, _ = _run_join(LookupJoinOperator, join_type, types_,
                        build_cols, probe_cols)
    got, op = _run_join(MatmulJoinOperator, join_type, types_,
                        build_cols, probe_cols)
    assert op._fallback_reason is None, op._fallback_reason
    assert op.metrics()["strategy"] == "matmul"
    assert got == want


def test_matmul_string_keys_match_oracle():
    """Dictionary-coded keys: the probe remaps its pool into the
    build's (the inherited seam), and the codes ARE the dense domain —
    per-page pools differ on purpose."""
    rng = np.random.default_rng(5)
    types_ = [T.VARCHAR, T.BIGINT]
    vocab = [f"k{i:03d}" for i in range(60)]
    bk = [vocab[i] if rng.random() > 0.05 else None
          for i in rng.integers(0, 40, 900)]
    pk = [vocab[i] if rng.random() > 0.05 else None
          for i in rng.integers(0, 60, 1100)]
    bv = [int(v) for v in rng.integers(0, 100, 900)]
    pv = [int(v) for v in rng.integers(0, 100, 1100)]
    for jt in ("inner", "semi"):
        want, _ = _run_join(LookupJoinOperator, jt, types_, [bk, bv],
                            [pk, pv])
        got, op = _run_join(MatmulJoinOperator, jt, types_, [bk, bv],
                            [pk, pv])
        assert op._fallback_reason is None, op._fallback_reason
        assert got == want


@pytest.mark.parametrize("case,build_cols_fn,kw", [
    ("negative keys (u64 wrap)",
     lambda rng: _int_cols(rng, 400, 50), {}),
    ("range past max_key_range",
     lambda rng: [[0, 10_000_000], [1, 2]], {}),
    ("multi-key build", None, {}),
])
def test_infeasible_builds_fall_back_row_identical(case, build_cols_fn,
                                                   kw):
    rng = np.random.default_rng(9)
    types_ = [T.BIGINT, T.BIGINT]
    if case == "multi-key build":
        build_cols = _int_cols(rng, 300, 20)
        probe_cols = _int_cols(rng, 400, 25)
        keys = (0, 1)
    elif case.startswith("negative"):
        build_cols = build_cols_fn(rng)
        build_cols[0] = [None if v is None else v - 25
                         for v in build_cols[0]]
        probe_cols = _int_cols(rng, 500, 60)
        probe_cols[0] = [None if v is None else v - 30
                         for v in probe_cols[0]]
        keys = (0,)
    else:
        build_cols = build_cols_fn(rng)
        probe_cols = [[0, 5, 10_000_000], [7, 8, 9]]
        keys = (0,)
    want, _ = _run_join(LookupJoinOperator, "inner", types_,
                        build_cols, probe_cols, key_channels=keys)
    got, op = _run_join(MatmulJoinOperator, "inner", types_,
                        build_cols, probe_cols, key_channels=keys, **kw)
    assert op._fallback_reason is not None
    assert op.metrics()["strategy"] == "matmul->sorted-index"
    assert got == want


def test_matmul_probe_same_shape_pages_do_not_retrace():
    """Repeat probe pages of one shape must reuse the compiled one-hot
    matmul (the KERNEL_SIZING pow2 bucket keys the table width)."""
    rng = np.random.default_rng(3)
    types_ = [T.BIGINT, T.BIGINT]
    bridge = JoinBridge()
    build = HashBuilderOperator(types_, [0], bridge)
    build.add_input(DevicePage.from_page(Page.from_pylists(
        types_, _int_cols(rng, 512, 100))))
    build.finish()
    build.get_output()
    op = MatmulJoinOperator(types_, [0], bridge, "inner")
    for i in range(4):
        op.add_input(DevicePage.from_page(Page.from_pylists(
            types_, _int_cols(rng, 512, 120))))
        while op.get_output() is not None:
            pass
        if i == 0:
            before = jit_stats.total_for("matmul_join_probe",
                                         "matmul_join_build_table")
    assert jit_stats.total_for("matmul_join_probe",
                               "matmul_join_build_table") == before


# --------------------------------------------------------- cost model


def _tpch_runner(**props):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.sql.analyzer import Session

    s = Session(catalog="tpch", schema="micro")
    s.properties.update(props)
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=4096)}, s)


JOIN_SQL = ("select c.c_custkey, o.o_orderkey from customer c "
            "join orders o on c.c_custkey = o.o_custkey")


def test_cost_rule_selects_matmul_only_in_win_region():
    """AUTOMATIC picks matmul exactly when the stats-estimated key
    range fits matmul_join_max_key_range: micro custkey (range 150)
    flips, the same join under a shrunken cap does not, and a
    wide-key join (o_orderkey range ~6000) never does."""
    r = _tpch_runner()
    plan = r.explain(JOIN_SQL)
    assert "strategy=matmul" in plan
    assert "key range 150" in plan
    # the estimate that picked it also reaches EXPLAIN's provenance
    assert "MatmulJoinStrategy" in plan

    narrow = _tpch_runner(matmul_join_max_key_range=64)
    assert "strategy=matmul" not in narrow.explain(JOIN_SQL)

    wide = ("select o.o_orderkey, l.l_quantity from orders o "
            "join lineitem l on o.o_orderkey = l.l_orderkey")
    assert "strategy=matmul" not in r.explain(wide)


def test_join_strategy_override_respected_both_ways():
    forced_off = _tpch_runner(join_strategy="SORTED_INDEX")
    assert "strategy=matmul" not in forced_off.explain(JOIN_SQL)
    wide = ("select o.o_orderkey, l.l_quantity from orders o "
            "join lineitem l on o.o_orderkey = l.l_orderkey")
    forced_on = _tpch_runner(join_strategy="MATMUL")
    plan = forced_on.explain(wide)
    assert "strategy=matmul" in plan and "forced by join_strategy" in plan
    # forcing matmul on an infeasible join still answers correctly:
    # the operator falls back per build (reason in EXPLAIN ANALYZE)
    want = sorted(_tpch_runner().execute(wide).rows)
    assert sorted(forced_on.execute(wide).rows) == want
    res = forced_on.execute("explain analyze " + wide)
    txt = "\n".join(x[0] for x in res.rows)
    assert "matmul->sorted-index" in txt


def test_matmul_join_end_to_end_sql_matches_sorted():
    """The full engine path: AUTOMATIC (matmul on micro) and forced
    SORTED_INDEX return identical rows, and EXPLAIN ANALYZE shows the
    strategy + estimate on the operator line."""
    auto = _tpch_runner()
    sorted_ = _tpch_runner(join_strategy="SORTED_INDEX")
    assert sorted(auto.execute(JOIN_SQL).rows) \
        == sorted(sorted_.execute(JOIN_SQL).rows)
    res = auto.execute("explain analyze " + JOIN_SQL)
    txt = "\n".join(x[0] for x in res.rows)
    assert "MatmulJoinOperator" in txt
    assert "strategy matmul" in txt
    assert "key range 150" in txt
