"""Memo-based iterative optimizer: rules, exploration, join ordering.

Reference analog: the IterativeOptimizer/Memo tests
(``sql/planner/iterative/``) and ``TestReorderJoins`` — rule fixpoint
per group, pattern matching through the lookup, cost-based join-order
exploration with provenance in EXPLAIN.
"""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.resources.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=2048)},
                            Session(catalog="tpch", schema="micro"))


def test_q9_join_order_explored(runner):
    """The round-3/4 carried criterion: q9's six-relation region gets a
    cost-based order — no CrossJoin survives, the selective %green%
    filter sits under the join against part, and EXPLAIN names the
    rule."""
    plan = runner.explain(TPCH_QUERIES[9])
    assert "CrossJoin" not in plan
    assert "ReorderJoins" in plan
    # the selective filter was sunk into its relation (below some join)
    like_line = [l for l in plan.splitlines() if "like" in l][0]
    scan_part = [l for l in plan.splitlines()
                 if "TableScan tpch.micro.part " in l][0]
    join_lines = [l for l in plan.splitlines() if "Join inner" in l]
    assert join_lines, plan
    depth = len(like_line) - len(like_line.lstrip())
    join_depth = min(len(l) - len(l.lstrip()) for l in join_lines)
    assert depth > join_depth, "filter not pushed below the join region"
    assert len(scan_part) - len(scan_part.lstrip()) > depth


def test_q9_rows_unchanged_by_reorder(runner):
    rows = runner.execute(TPCH_QUERIES[9]).rows
    assert len(rows) == 54
    assert rows == sorted(rows, key=lambda r: (r[0], -r[1]))


def test_provenance_in_explain(runner):
    plan = runner.explain(
        "select n_name from nation where n_regionkey = 2 "
        "order by n_name limit 3")
    assert "Optimizer rules applied:" in plan
    assert "PushFilterIntoTableScan" in plan


def test_limit_over_sort_becomes_topn(runner):
    plan = runner.explain(
        "select o_custkey from orders order by o_totalprice limit 5")
    assert "TopN" in plan
    assert "LimitOverSortToTopN" in plan or "Limit" not in plan


def test_filter_pushes_through_aggregation(runner):
    """HAVING-style key conjuncts sink below the aggregation."""
    plan = runner.explain(
        "select * from (select l_returnflag f, count(*) c from lineitem "
        "group by l_returnflag) where f = 'A'")
    lines = plan.splitlines()
    agg = [i for i, l in enumerate(lines) if "Aggregation" in l][0]
    constrained_scan = [i for i, l in enumerate(lines)
                        if "constraint{l_returnflag" in l]
    assert constrained_scan and constrained_scan[0] > agg, plan
    rows = runner.execute(
        "select * from (select l_returnflag f, count(*) c from lineitem "
        "group by l_returnflag) where f = 'A'").rows
    assert rows == [("A", 1590)]


def test_exploration_terminates_and_is_idempotent(runner):
    """Re-optimizing an already-optimal plan must not diverge (the
    ReorderJoins termination argument: the DP is deterministic with
    optimal substructure)."""
    p1 = runner.explain(TPCH_QUERIES[3])
    p2 = runner.explain(TPCH_QUERIES[3])
    assert p1 == p2


def test_merge_limits_rule():
    from trino_tpu.planner.memo import (IterativeOptimizer, Lookup,
                                        Memo, RuleContext)
    from trino_tpu.planner.plan import LimitNode, ValuesNode
    from trino_tpu.planner.rules import MergeLimits
    from trino_tpu.planner.symbols import Symbol
    from trino_tpu import types as T

    v = ValuesNode([Symbol("x", T.BIGINT)], [])
    plan = LimitNode(LimitNode(v, 10, 0), 3, 0)
    memo = Memo()
    gid = memo.insert(plan)
    ctx = RuleContext(Lookup(memo), None, None, None)
    out = MergeLimits().apply(memo.node(gid), ctx)
    assert isinstance(out, LimitNode) and out.count == 3
    assert not isinstance(ctx.lookup.resolve(out.source), LimitNode)


def test_join_region_through_views(runner):
    """Regions flatten through group references left by other rules
    (filters/projections between joins)."""
    sql = ("select c.c_name, sum(l.l_quantity) q from customer c, "
           "orders o, lineitem l where c.c_custkey = o.o_custkey and "
           "o.o_orderkey = l.l_orderkey and c.c_mktsegment = 'BUILDING' "
           "group by c.c_name order by q desc limit 5")
    plan = runner.explain(sql)
    assert "CrossJoin" not in plan
    assert "ReorderJoins" in plan
    rows = runner.execute(sql).rows
    assert len(rows) == 5
