"""Memory accounting + host spill.

Reference analog: TestMemoryPools / TestMemoryRevokingScheduler — a query
under an artificially low memory cap completes when spill is enabled
(revoking operators park state in host RAM) and fails with
EXCEEDED_LOCAL_MEMORY_LIMIT when it is not.
"""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.memory import (MemoryExceededError, QueryMemoryPool,
                                   device_page_bytes)
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session
from trino_tpu.types import TrinoError

# an aggregation + join + sort query with real state to account
# (q18 shape: big build side, big agg)
SQL = ("select l_orderkey, sum(l_quantity) qty from lineitem "
       "group by l_orderkey order by qty desc, l_orderkey limit 10")

JOIN_SQL = ("select o_orderpriority, count(*) from orders o, lineitem l "
            "where o.o_orderkey = l.l_orderkey and l_quantity > 30 "
            "group by o_orderpriority order by o_orderpriority")


def make_runner(**props):
    session = Session(catalog="tpch", schema="micro")
    session.properties.update(props)
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=1024)},
                            session, desired_splits=8)


@pytest.fixture(scope="module")
def baseline_rows():
    return {SQL: make_runner().execute(SQL).rows,
            JOIN_SQL: make_runner().execute(JOIN_SQL).rows}


def test_accounting_records_peak():
    res = make_runner().execute(SQL)
    mem = res.stats["memory"]
    assert mem["peak_bytes"] > 0
    assert mem["spill_events"] == 0
    assert mem["reserved_bytes"] == 0  # everything released at finish


def test_low_cap_without_spill_fails():
    r = make_runner(query_max_memory_bytes=120_000, spill_enabled=False)
    with pytest.raises(TrinoError) as exc:
        r.execute(SQL)
    assert exc.value.code == "EXCEEDED_LOCAL_MEMORY_LIMIT"


def test_low_cap_with_spill_completes(baseline_rows):
    r = make_runner(query_max_memory_bytes=600_000, spill_enabled=True)
    res = r.execute(SQL)
    assert res.rows == baseline_rows[SQL]
    mem = res.stats["memory"]
    assert mem["spill_events"] > 0
    assert mem["spilled_bytes"] > 0


def test_join_spill_matches_baseline(baseline_rows):
    r = make_runner(query_max_memory_bytes=150_000, spill_enabled=True)
    res = r.execute(JOIN_SQL)
    assert res.rows == baseline_rows[JOIN_SQL]
    assert res.stats["memory"]["spill_events"] > 0


SORT_SQL = "select * from lineitem order by l_extendedprice"


def test_host_sort_under_low_cap_matches_device_sort():
    """A cap too small for the whole-input device sort falls back to the
    host-merge path (page-at-a-time download + lexsort + chunked
    re-upload) and must produce the same ordering."""
    want = make_runner().execute(SORT_SQL)
    r = make_runner(query_max_memory_bytes=1_000_000, spill_enabled=True)
    res = r.execute(SORT_SQL)
    assert res.stats["memory"]["spill_events"] > 0
    # ties on l_extendedprice make exact row order plan-dependent;
    # compare the multiset and the sort-key ordering
    assert sorted(res.rows) == sorted(want.rows)
    prices = [row[5] for row in res.rows]  # l_extendedprice
    assert prices == sorted(prices)


def test_pool_revokes_largest_first():
    pool = QueryMemoryPool(1000, spill_enabled=True)
    order = []
    a = pool.create_context("a")
    b = pool.create_context("b")
    a.set_revoke_callback(lambda: order.append("a") or 600)
    b.set_revoke_callback(lambda: order.append("b") or 300)
    a.reserve(600)
    b.reserve(300)
    c = pool.create_context("c")
    c.reserve(500)  # must revoke a (largest) to fit
    assert order == ["a"]
    assert pool.reserved == 300 + 500
    assert pool.spill_events == 1


def test_pool_raises_when_spill_disabled():
    pool = QueryMemoryPool(100, spill_enabled=False)
    ctx = pool.create_context("x")
    ctx.reserve(90)
    with pytest.raises(MemoryExceededError):
        ctx.reserve(20)


def test_device_page_bytes():
    import jax.numpy as jnp

    from trino_tpu import types as T
    from trino_tpu.block import DevicePage

    page = DevicePage([T.BIGINT], [jnp.zeros(16, dtype=jnp.int64)],
                      [jnp.zeros(16, dtype=bool)],
                      jnp.ones(16, dtype=bool), [None])
    # 16*8 data + 16 nulls + 16 valid
    assert device_page_bytes(page) == 16 * 8 + 16 + 16
