"""Cluster memory governance: pool hierarchy, disk spill tier, killer
policies, memory-aware retry sizing, resource-group memory limits.

Reference analogs: TestMemoryPools (node pool + per-query reservations),
TestFileSingleStreamSpiller (checksummed spill files),
TestTotalReservationOnBlockedNodesLowMemoryKiller (victim determinism),
TestPartitionMemoryEstimator (peak-driven retry budgets) and the
resource-group memory-limit tests.

Everything here is in-process (no worker spawns — the process-level
integration rides tests/test_chaos.py's module cluster).
"""

import os
import threading

import numpy as np
import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.memory import (DiskSpilledPage, NodeMemoryExceededError,
                                   NodeMemoryPool, QueryMemoryPool,
                                   SpilledPage, spill_pages)
from trino_tpu.exec.serde import (parse_spill_frame, read_spill_file,
                                  spill_frame, write_spill_file)
from trino_tpu.parallel.cluster_memory import (ClusterMemoryManager,
                                               MemoryEstimator,
                                               QueryKilledError, killer_for)
from trino_tpu.parallel.fault import (INSUFFICIENT_RESOURCES,
                                      DecayingFailureStats,
                                      classify_error_code)
from trino_tpu.resource_groups import (ResourceGroupManager,
                                       ResourceGroupSpec)
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session
from trino_tpu.types import TrinoError

AGG_SQL = ("select l_orderkey, sum(l_quantity) qty from lineitem "
           "group by l_orderkey order by qty desc, l_orderkey limit 10")
JOIN_SQL = ("select o_orderpriority, count(*) from orders o, lineitem l "
            "where o.o_orderkey = l.l_orderkey and l_quantity > 30 "
            "group by o_orderpriority order by o_orderpriority")
SORT_SQL = "select * from lineitem order by l_extendedprice, l_orderkey"


def make_runner(**props):
    session = Session(catalog="tpch", schema="micro")
    session.properties.update(props)
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=1024)},
                            session, desired_splits=8)


@pytest.fixture(scope="module")
def baselines():
    r = make_runner()
    return {sql: r.execute(sql).rows
            for sql in (AGG_SQL, JOIN_SQL, SORT_SQL)}


# ------------------------------------------------- disk spill oracle ----


@pytest.mark.parametrize("sql,cap", [(AGG_SQL, 600_000),
                                     (JOIN_SQL, 150_000),
                                     (SORT_SQL, 1_000_000)])
def test_disk_spill_oracle(sql, cap, baselines):
    """agg / join / sort forced through the DISK tier
    (spill_host_memory_bytes=0 demotes every parked page) must return
    byte-equal rows to the unconstrained run — the acceptance bar for
    the spill subsystem."""
    r = make_runner(query_max_memory_bytes=cap, spill_enabled=True,
                    spill_to_disk_enabled=True, spill_host_memory_bytes=0)
    res = r.execute(sql)
    mem = res.stats["memory"]
    assert mem["spill_events"] > 0
    assert mem["disk_spill_events"] > 0, mem
    assert mem["disk_spilled_bytes"] > 0
    if sql is SORT_SQL:
        # ties make exact order plan-dependent: compare multiset + keys
        assert sorted(res.rows) == sorted(baselines[sql])
    else:
        assert res.rows == baselines[sql]


def test_disk_spill_files_reaped_after_query():
    r = make_runner(query_max_memory_bytes=600_000, spill_enabled=True,
                    spill_to_disk_enabled=True, spill_host_memory_bytes=0)
    res = r.execute(AGG_SQL)
    assert res.stats["memory"]["disk_spill_events"] > 0
    root = os.path.join("/tmp/trino_tpu_spill", str(os.getpid()))
    leftovers = []
    if os.path.isdir(root):
        for d in os.listdir(root):
            leftovers.extend(os.listdir(os.path.join(root, d)))
    assert leftovers == []


def test_host_tier_preferred_until_ledger_full(baselines):
    """With a roomy host budget the disk tier must stay cold — the
    tiers are ordered, not parallel."""
    r = make_runner(query_max_memory_bytes=600_000, spill_enabled=True,
                    spill_to_disk_enabled=True,
                    spill_host_memory_bytes=1 << 30)
    res = r.execute(AGG_SQL)
    mem = res.stats["memory"]
    assert mem["spill_events"] > 0
    assert mem["disk_spill_events"] == 0
    assert res.rows == baselines[AGG_SQL]


# ------------------------------------------------- spill frame serde ----


def _arrays():
    cols = [np.arange(64, dtype=np.int64),
            np.linspace(0, 1, 64).astype(np.float64)]
    nulls = [np.zeros(64, dtype=bool), (np.arange(64) % 7 == 0)]
    valid = np.arange(64) < 50
    return cols, nulls, valid


def test_spill_frame_roundtrip(tmp_path):
    cols, nulls, valid = _arrays()
    c2, n2, v2 = parse_spill_frame(spill_frame(cols, nulls, valid))
    for a, b in zip(cols + nulls + [valid], c2 + n2 + [v2]):
        assert a.dtype == b.dtype
        assert np.array_equal(a, b)
    path = str(tmp_path / "s.bin")
    write_spill_file(path, cols, nulls, valid)
    assert not os.path.exists(path + ".tmp")  # atomic: no temp residue
    c3, n3, v3 = read_spill_file(path)
    assert np.array_equal(c3[0], cols[0]) and np.array_equal(v3, valid)


def test_spill_file_streaming_read_write(tmp_path):
    """The streaming spill paths (chunked compressobj write + bounded
    incremental read) interoperate both ways with the one-shot frame
    forms, and a corrupted/torn FILE fails loudly on the streaming
    read — CRC verifies before any array is handed back."""
    from trino_tpu.exec.serde import _SPILL_CHUNK

    cols = [np.arange(_SPILL_CHUNK // 4 + 7, dtype=np.int64)]  # > chunk
    nulls = [np.zeros(len(cols[0]), dtype=bool)]
    valid = np.arange(len(cols[0])) < 50
    path = str(tmp_path / "big.bin")
    write_spill_file(path, cols, nulls, valid)
    # streaming write -> one-shot parse (format unchanged on disk)
    c1, n1, v1 = parse_spill_frame(open(path, "rb").read())
    assert np.array_equal(c1[0], cols[0])
    # streaming read
    c2, n2, v2 = read_spill_file(path)
    assert np.array_equal(c2[0], cols[0])
    assert np.array_equal(v2, valid)
    assert c2[0].flags.writeable
    # one-shot write -> streaming read
    with open(path, "wb") as f:
        f.write(spill_frame(cols, nulls, valid))
    c3, _, _ = read_spill_file(path)
    assert np.array_equal(c3[0], cols[0])
    # corruption: flipped body byte, then a torn tail
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(blob)
    with pytest.raises(TrinoError):
        read_spill_file(path)
    with open(path, "wb") as f:
        f.write(spill_frame(cols, nulls, valid)[: len(blob) // 2])
    with pytest.raises(TrinoError):
        read_spill_file(path)


def test_spill_frame_detects_corruption(tmp_path):
    cols, nulls, valid = _arrays()
    frame = bytearray(spill_frame(cols, nulls, valid))
    frame[20] ^= 0xFF  # flip a body byte: CRC must catch it
    with pytest.raises(TrinoError):
        parse_spill_frame(bytes(frame))
    with pytest.raises(TrinoError):
        parse_spill_frame(frame[: len(frame) // 2])  # torn frame


def test_disk_spilled_page_roundtrip():
    import jax.numpy as jnp

    from trino_tpu import types as T
    from trino_tpu.block import DevicePage

    page = DevicePage([T.BIGINT], [jnp.arange(32, dtype=jnp.int64)],
                      [jnp.zeros(32, dtype=bool)],
                      jnp.arange(32) < 20, [None])
    pool = QueryMemoryPool(1 << 20, spill_enabled=True,
                           spill_to_disk=True, host_spill_limit=0)
    pages = [page]
    freed = spill_pages(pages, pool)
    assert freed > 0
    assert isinstance(pages[0], DiskSpilledPage)
    assert os.path.exists(pages[0].path)
    back = pages[0].to_device()
    assert np.array_equal(np.asarray(back.cols[0])[:20], np.arange(20))
    assert int(np.asarray(back.valid).sum()) == 20
    pool.close()


def _device_page(rows: int):
    import jax.numpy as jnp

    from trino_tpu import types as T
    from trino_tpu.block import DevicePage

    return DevicePage([T.BIGINT], [jnp.arange(rows, dtype=jnp.int64)],
                      [jnp.zeros(rows, dtype=bool)],
                      jnp.ones(rows, dtype=bool), [None])


def test_ledger_demotes_across_operator_lists():
    """Cross-operator-list demotion (PR 4 follow-on): when the spilling
    operator's own list cannot bring the node ledger under its limit,
    the LARGEST parked pages of OTHER tracked lists demote — the last
    spiller is rarely the biggest holder."""
    node = NodeMemoryPool(1 << 30, host_spill_limit=1 << 30)
    a = node.create_query_pool("qa", 1 << 30, spill_enabled=True,
                               spill_to_disk=True)
    b = node.create_query_pool("qb", 1 << 30, spill_enabled=True,
                               spill_to_disk=True)
    ca = a.create_context("a-agg")
    cb = b.create_context("b-join")
    # operator A parks BIG pages while the ledger has headroom
    a_pages = [_device_page(4096), _device_page(4096)]
    with ca.lock:
        spill_pages(a_pages, a, ca.lock)
    assert all(isinstance(p, SpilledPage) and
               not isinstance(p, DiskSpilledPage) for p in a_pages)
    # tighten the (shared, node-wide) limit, then operator B spills a
    # SMALL page: its own list can't cover the overage
    node.host_ledger.limit_bytes = 1024
    b_pages = [_device_page(32)]
    with cb.lock:
        spill_pages(b_pages, b, cb.lock)
    assert any(isinstance(p, DiskSpilledPage) for p in a_pages), \
        "demotion never reached the other operator's list"
    assert node.host_ledger.cross_list_demotions >= 1
    # A's disk pages reload transparently and carry A's spill files
    back = next(p for p in a_pages if isinstance(p, DiskSpilledPage))
    assert os.path.exists(back.path)
    assert int(np.asarray(back.to_device().valid).sum()) == 4096
    # closing A drops its lists from the ledger's candidates
    node.release_query("qa")
    assert not any(t[2] is a for t in node.host_ledger._tracked)
    node.release_query("qb")


def test_ledger_cross_list_skips_busy_foreign_locks():
    """A foreign operator actively holding its context lock is skipped
    (never blocked on): cooperative demotion must not deadlock two
    concurrently-spilling operators."""
    node = NodeMemoryPool(1 << 30, host_spill_limit=1 << 30)
    a = node.create_query_pool("qa", 1 << 30, spill_enabled=True,
                               spill_to_disk=True)
    b = node.create_query_pool("qb", 1 << 30, spill_enabled=True,
                               spill_to_disk=True)
    ca = a.create_context("a-op")
    cb = b.create_context("b-op")
    a_pages = [_device_page(4096)]
    with ca.lock:
        spill_pages(a_pages, a, ca.lock)
    node.host_ledger.limit_bytes = 64

    held = threading.Event()
    release = threading.Event()

    def hold_a():
        with ca.lock:
            held.set()
            release.wait(5)

    t = threading.Thread(target=hold_a)
    t.start()
    held.wait(5)
    b_pages = [_device_page(32)]
    with cb.lock:
        spill_pages(b_pages, b, cb.lock)  # must return, not deadlock
    assert not isinstance(a_pages[0], DiskSpilledPage)  # skipped
    release.set()
    t.join()
    node.release_query("qa")
    node.release_query("qb")


def test_default_node_memory_bytes_falls_back_on_cpu():
    from trino_tpu.exec.memory import default_node_memory_bytes

    # the CPU backend reports no memory stats -> documented fallback
    assert default_node_memory_bytes(fallback=123) in (123,) or \
        default_node_memory_bytes(fallback=123) > 1 << 28


# ------------------------------------------- node pool (cross-query) ----


def test_node_pool_cross_query_revoke_largest_first():
    node = NodeMemoryPool(1000)
    a = node.create_query_pool("qa", 1000, spill_enabled=True)
    b = node.create_query_pool("qb", 1000, spill_enabled=True)
    order = []
    ca = a.create_context("a-op")
    cb = b.create_context("b-op")
    ca.set_revoke_callback(lambda: order.append("qa") or 600)
    cb.set_revoke_callback(lambda: order.append("qb") or 300)
    ca.reserve(600)
    cb.reserve(300)
    assert node.reserved == 900
    # qc needs 500: node over budget -> revoke qa (largest) only
    c = node.create_query_pool("qc", 1000, spill_enabled=True)
    cc = c.create_context("c-op")
    cc.reserve(500)
    assert order == ["qa"]
    assert node.reserved == 300 + 500
    assert node.cross_query_revokes == 1


def test_node_pool_blocked_raises_insufficient_resources():
    node = NodeMemoryPool(1000)
    a = node.create_query_pool("qa", 1000, spill_enabled=False)
    a.create_context("x").reserve(900)
    b = node.create_query_pool("qb", 1000, spill_enabled=False)
    with pytest.raises(NodeMemoryExceededError) as exc:
        b.create_context("y").reserve(500)
    assert classify_error_code(exc.value.code) == INSUFFICIENT_RESOURCES
    assert node.blocked_events == 1
    assert node.snapshot()["blocked_events"] == 1
    # the failed reservation must not leak into either pool
    assert b.reserved == 0
    assert node.reserved == 900


def test_node_pool_snapshot_tracks_per_query_and_release():
    node = NodeMemoryPool(1 << 20)
    a = node.create_query_pool("qa", 1 << 20)
    a.create_context("x").reserve(1234)
    snap = node.snapshot()
    assert snap["queries"]["qa"]["reserved"] == 1234
    node.release_query("qa")
    assert node.reserved == 0
    # released peaks survive for the retry estimator
    assert node.snapshot()["queries"]["qa"]["peak"] == 1234


# ------------------------------------------------- killer policies ------


def _snap(worker_id, blocked, queries, max_bytes=1000):
    return {"max_bytes": max_bytes,
            "reserved_bytes": sum(q["reserved"] for q in queries.values()),
            "blocked_events": 1 if blocked else 0,
            "queries": queries}


def test_killer_blocked_nodes_policy_is_deterministic():
    mgr = ClusterMemoryManager("total-reservation-on-blocked-nodes")
    # node 0 blocked: qa holds 70 there; node 1 healthy: qb holds 900
    mgr.update(0, _snap(0, True, {"qa": {"reserved": 70, "peak": 70},
                                  "qb": {"reserved": 30, "peak": 30}}))
    mgr.update(1, _snap(1, False, {"qb": {"reserved": 900, "peak": 900}}))
    # blocked-nodes policy ignores qb's off-node bulk: qa dies
    assert mgr.maybe_kill() == "qa"
    with pytest.raises(QueryKilledError) as exc:
        mgr.check_killed("qa")
    assert exc.value.code == "EXCEEDED_CLUSTER_MEMORY"
    assert classify_error_code(exc.value.code) == INSUFFICIENT_RESOURCES
    # the flag was consumed: the retry attempt runs clean
    mgr.check_killed("qa")


def test_killer_total_reservation_policy():
    mgr = ClusterMemoryManager("total-reservation")
    mgr.update(0, _snap(0, True, {"qa": {"reserved": 70, "peak": 0},
                                  "qb": {"reserved": 30, "peak": 0}}))
    mgr.update(1, _snap(1, False, {"qb": {"reserved": 900, "peak": 0}}))
    assert mgr.maybe_kill() == "qb"  # cluster-wide largest


def test_killer_tie_breaks_lexicographically():
    mgr = ClusterMemoryManager("total-reservation-on-blocked-nodes")
    mgr.update(0, _snap(0, True, {"qz": {"reserved": 50, "peak": 0},
                                  "qa": {"reserved": 50, "peak": 0}}))
    assert mgr.maybe_kill() == "qa"


def test_killer_none_policy_and_no_blocked_nodes():
    mgr = ClusterMemoryManager("none")
    mgr.update(0, _snap(0, True, {"qa": {"reserved": 50, "peak": 0}}))
    assert mgr.maybe_kill() is None
    mgr2 = ClusterMemoryManager("total-reservation-on-blocked-nodes")
    mgr2.update(0, _snap(0, False, {"qa": {"reserved": 50, "peak": 0}}))
    assert mgr2.maybe_kill() is None
    with pytest.raises(TrinoError):
        killer_for("bogus")


def test_killer_fires_once_per_victim():
    """Worker snapshots keep naming a dying victim for a few
    heartbeats, and the victim popping its flag must not re-register:
    one pressure episode = one kill, one event."""
    mgr = ClusterMemoryManager("total-reservation-on-blocked-nodes")
    snap = _snap(0, True, {"qa": {"reserved": 70, "peak": 70}})
    mgr.update(0, snap)
    assert mgr.maybe_kill() == "qa"
    with pytest.raises(QueryKilledError):
        mgr.check_killed("qa")           # flag consumed
    mgr.update(0, snap)                  # stale heartbeat, still blocked
    assert mgr.maybe_kill() is None      # no duplicate kill
    assert mgr.kill_count == 1


def test_blocked_delta_survives_interleaved_heartbeats():
    """A heartbeat that stores a blocked delta without a governance
    tick must not lose the signal when the next (unblocked) heartbeat
    arrives: deltas accumulate until a kill consumes them."""
    mgr = ClusterMemoryManager("total-reservation-on-blocked-nodes")
    mgr.update(0, _snap(0, True, {"qa": {"reserved": 70, "peak": 0}}))
    # next ping: worker's delta already consumed -> blocked_events 0
    mgr.update(0, _snap(0, False, {"qa": {"reserved": 70, "peak": 0}}))
    assert mgr.maybe_kill() == "qa"


def test_blocked_signal_not_latched_past_a_no_victim_tick():
    """A pressure episode that resolves before governance runs (the
    blocking query failed and released) must not leave the node marked
    blocked: the tick that found no victim consumes the signal, so a
    later innocent query is not killed."""
    mgr = ClusterMemoryManager("total-reservation-on-blocked-nodes")
    mgr.update(0, _snap(0, True, {}))     # blocked, nothing killable
    assert mgr.maybe_kill() is None
    # innocent newcomer, no new blocked events
    mgr.update(0, _snap(0, False, {"qb": {"reserved": 50, "peak": 0}}))
    assert mgr.maybe_kill() is None
    assert mgr.kill_count == 0


def test_query_max_total_memory_cap_kills():
    mgr = ClusterMemoryManager("none", query_max_total_bytes=100)
    mgr.update(0, _snap(0, False, {"qa": {"reserved": 80, "peak": 0}}))
    mgr.update(1, _snap(1, False, {"qa": {"reserved": 60, "peak": 0}}))
    assert mgr.maybe_kill() == "qa"  # 140 > 100 across nodes
    stats = mgr.cluster_stats()
    assert stats["kills"] == 1 and stats["workers"] == 2


# --------------------------------------------- estimator + escalation ---


def test_memory_estimator_grows_from_observed_peak():
    est = MemoryEstimator()
    est.record_peak("q7a0", 500_000)
    est.record_peak("q7a0", 400_000)      # lower later peak: keep max
    assert est.peak_for("q7a0") == 500_000
    # 2x observed peak wins over the failed budget when peak is larger
    assert est.next_budget("q7a0", 120_000, 0) == 1_000_000
    # floor wins when both are tiny
    assert est.next_budget("q7a0", 120_000, 8 << 20) == 8 << 20
    # no observation: grow from the failed budget itself
    assert est.next_budget("q9a1", 300_000, 0) == 600_000


# --------------------------------------------- decaying failure stats ---


def test_decaying_failure_stats_halve_per_half_life():
    s = DecayingFailureStats(half_life_s=60.0)
    s.record(now=0.0)
    assert s.score(now=0.0) == pytest.approx(1.0)
    assert s.score(now=60.0) == pytest.approx(0.5, rel=1e-3)
    s.record(now=60.0)
    assert s.score(now=60.0) == pytest.approx(1.5, rel=1e-3)
    assert s.score(now=180.0) == pytest.approx(1.5 / 4, rel=1e-3)
    assert s.total == 2


def test_prefer_healthy_placement():
    from trino_tpu.parallel.process_runner import prefer_healthy

    class W:
        def __init__(self):
            self.failure_stats = DecayingFailureStats()

    good, bad = W(), W()
    bad.failure_stats.record()
    assert prefer_healthy([bad, good]) == [good]
    # nobody healthy: fall back to everyone rather than starve
    good.failure_stats.record()
    assert prefer_healthy([bad, good]) == [bad, good]


# --------------------------------------------- resource group limits ----


def test_resource_group_hard_memory_limit_blocks_admission():
    mgr = ResourceGroupManager([ResourceGroupSpec(
        "g", max_concurrency=10, hard_memory_limit_bytes=1000)])
    g = mgr.select("alice")
    g.acquire(memory_bytes=700)
    admitted = threading.Event()

    def second():
        g.acquire(timeout=5, memory_bytes=700)  # 1400 > 1000: waits
        admitted.set()

    t = threading.Thread(target=second, daemon=True)
    t.start()
    assert not admitted.wait(0.2)
    g.release(memory_bytes=700)    # frees headroom -> second admits
    assert admitted.wait(5)
    g.release(memory_bytes=700)


def test_resource_group_soft_memory_limit_stops_new_admissions():
    mgr = ResourceGroupManager([ResourceGroupSpec(
        "g", max_concurrency=10, soft_memory_limit_bytes=500)])
    g = mgr.select("alice")
    g.acquire(memory_bytes=600)    # first query may overshoot the soft cap
    admitted = threading.Event()

    def second():
        g.acquire(timeout=5, memory_bytes=10)
        admitted.set()

    threading.Thread(target=second, daemon=True).start()
    assert not admitted.wait(0.2)  # soft-exceeded: no NEW admissions
    g.release(memory_bytes=600)
    assert admitted.wait(5)
    g.release(memory_bytes=10)


def test_resource_group_rejects_unsatisfiable_budget():
    """A budget above the hard limit can never fit: reject loudly
    instead of queueing forever."""
    mgr = ResourceGroupManager([ResourceGroupSpec(
        "g", hard_memory_limit_bytes=1000)])
    g = mgr.select("alice")
    with pytest.raises(TrinoError) as exc:
        g.acquire(timeout=1, memory_bytes=2000)
    assert exc.value.code == "QUERY_REJECTED"
    assert g.running == 0 and g.memory_reserved == 0


def test_resource_group_memory_limits_from_config():
    mgr = ResourceGroupManager.from_config({"groups": [
        {"name": "g", "soft_memory_limit_bytes": 123,
         "hard_memory_limit_bytes": 456}]})
    spec = mgr.select("anyone").spec
    assert spec.soft_memory_limit_bytes == 123
    assert spec.hard_memory_limit_bytes == 456


# --------------------------------------------- surfaces ----------------


def test_session_properties_registered():
    from trino_tpu import session_properties as SP

    for name in ("query_max_total_memory", "spill_to_disk_enabled",
                 "memory_killer_policy", "retry_initial_memory",
                 "node_max_memory_bytes", "spill_host_memory_bytes",
                 "scan_coalesce_enabled"):
        assert name in SP.REGISTRY, name
    props = {}
    SP.set_property(props, "memory_killer_policy", "TOTAL-RESERVATION")
    assert props["memory_killer_policy"] == "total-reservation"
    with pytest.raises(TrinoError):
        SP.set_property(props, "memory_killer_policy", "nuke-everything")


def test_protocol_stats_carry_recovery_and_cluster_memory():
    from trino_tpu.runner import QueryResult
    from trino_tpu.server.protocol import ProtocolServer
    from trino_tpu import types as T

    class Stub:
        def execute(self, sql):
            return QueryResult(["x"], [T.BIGINT], [(1,)], stats={
                "memory": {"peak_bytes": 7},
                "recovery": {"task_attempts": 3},
                "cluster_memory": {"workers": 2, "kills": 1},
            })

    srv = ProtocolServer(Stub()).start()
    try:
        import json
        import urllib.request

        doc = json.loads(urllib.request.urlopen(urllib.request.Request(
            f"{srv.uri}/v1/statement", data=b"select 1",
            method="POST")).read())
        for _ in range(100):
            if "data" in doc or "error" in doc:
                break
            doc = json.loads(
                urllib.request.urlopen(doc["nextUri"]).read())
        assert doc["stats"]["recovery"]["task_attempts"] == 3
        assert doc["stats"]["clusterMemory"]["kills"] == 1
        assert doc["stats"]["memory"]["peak_bytes"] == 7
    finally:
        srv.stop()


def test_scan_coalesce_upload_batches():
    """Split-fragmented small pages coalesce to the connector page size
    before upload: one device batch instead of eight."""
    from trino_tpu.ops.operator import TableScanOperator

    conn = TpchConnector(page_rows=512)
    meta = conn.metadata()
    table = meta.get_table_handle("micro", "lineitem")
    cols = meta.get_columns(table)
    counts, totals = {}, {}
    for coalesce in (None, 1 << 16):
        scan = TableScanOperator(conn, cols, coalesce_rows=coalesce)
        for s in conn.split_manager().get_splits(table, 8):
            scan.add_split(s)
        scan.no_more_splits()
        pages = []
        while True:
            p = scan.get_output()
            if p is None and scan.is_finished():
                break
            if p is not None:
                pages.append(p)
        counts[coalesce] = len(pages)
        totals[coalesce] = sum(int(np.asarray(p.valid).sum())
                               for p in pages)
    assert totals[None] == totals[1 << 16]  # never changes row counts
    assert counts[None] > 1
    assert counts[1 << 16] == 1


def test_local_explain_analyze_shows_disk_spill():
    r = make_runner(query_max_memory_bytes=600_000, spill_enabled=True,
                    spill_to_disk_enabled=True, spill_host_memory_bytes=0)
    res = r.execute("explain analyze " + AGG_SQL)
    text = "\n".join(row[0] for row in res.rows)
    assert "disk" in text and "spills" in text


# ------------------------------------------------- hybrid hash join ----

#: no aggregation above the join: the hybrid acceptance bar is about
#: the JOIN surviving a pool far smaller than its build, not about
#: the agg's own spill behaviour (and ORDER BY pins row order — cold
#: partitions emit after the resident stream)
HYBRID_SQL = ("select o_orderkey, o_orderpriority, l_quantity "
              "from orders o, lineitem l "
              "where o.o_orderkey = l.l_orderkey and l_quantity > 45 "
              "order by o_orderkey, l_quantity limit 50")


@pytest.fixture(scope="module")
def hybrid_baseline():
    return make_runner(hbo_enabled=False).execute(HYBRID_SQL).rows


def test_hybrid_join_over_pool_completes_without_retry(hybrid_baseline):
    """The tentpole acceptance bar: a join whose build + probe
    transients exceed the pool several times over completes in ONE
    attempt — partition demotions (partition_spills > 0) instead of a
    MemoryExceededError/retry — and returns byte-equal rows."""
    r = make_runner(query_max_memory_bytes=60_000, spill_enabled=True,
                    spill_to_disk_enabled=True, spill_host_memory_bytes=0,
                    hbo_enabled=False)
    res = r.execute(HYBRID_SQL)
    mem = res.stats["memory"]
    assert mem["partition_spills"] > 0, mem
    assert mem["partition_spilled_bytes"] > 0
    assert mem["peak_bytes"] <= 60_000
    assert res.rows == hybrid_baseline


def test_hybrid_disabled_property_restores_wholesale_spill(
        hybrid_baseline):
    """hybrid_join_enabled=false falls back to the wholesale park-
    everything path: still byte-equal, zero partition demotions."""
    r = make_runner(query_max_memory_bytes=150_000, spill_enabled=True,
                    spill_to_disk_enabled=True, spill_host_memory_bytes=0,
                    hbo_enabled=False, hybrid_join_enabled=False)
    res = r.execute(HYBRID_SQL)
    assert res.stats["memory"]["partition_spills"] == 0
    assert res.rows == hybrid_baseline


def test_hybrid_second_run_sizes_fanout_from_hbo(hybrid_baseline):
    """First constrained run records its spill record into the HBO
    store; the SECOND run's builder sizes fan-out from it
    (source=hbo) before any revocation pressure."""
    from trino_tpu.ops import join as J

    states = []
    orig = J.HashBuilderOperator._init_partitions

    def spy(self):
        orig(self)
        states.append(self._hstate)

    J.HashBuilderOperator._init_partitions = spy
    try:
        r = make_runner(query_max_memory_bytes=60_000,
                        spill_enabled=True, spill_to_disk_enabled=True,
                        spill_host_memory_bytes=0)
        res1 = r.execute(HYBRID_SQL)
        assert res1.rows == hybrid_baseline
        assert states and states[-1].source == "local"
        first_fanout = states[-1].fanout
        res2 = r.execute(HYBRID_SQL)
        assert res2.rows == hybrid_baseline
        assert states[-1].source == "hbo", \
            "second run did not consume the HBO spill record"
        assert states[-1].fanout >= first_fanout
    finally:
        J.HashBuilderOperator._init_partitions = orig


def _skewed_join_page(fanout: int, heavy_pid_rows: int,
                      light_pid_rows: int):
    """One bigint key page whose rows are HEAVILY skewed onto a single
    partition of ``fanout`` (returns the page and the heavy pid)."""
    import jax.numpy as jnp

    from trino_tpu import types as T
    from trino_tpu.block import DevicePage
    from trino_tpu.ops import join as J

    hs = J.HybridJoinState(fanout)
    keys = np.arange(16384, dtype=np.int64)
    pids = hs.partition_ids([keys], [np.zeros(keys.size, bool)],
                            [T.BIGINT], [None])
    heavy = int(np.bincount(pids, minlength=fanout).argmax())
    picked = [keys[pids == heavy][:heavy_pid_rows]]
    for pid in range(fanout):
        if pid != heavy:
            picked.append(keys[pids == pid][:light_pid_rows])
    col = np.concatenate(picked)
    page = DevicePage([T.BIGINT], [jnp.asarray(col)],
                      [jnp.zeros(col.size, dtype=bool)],
                      jnp.ones(col.size, dtype=bool), [None])
    return page, heavy


def test_hybrid_mid_build_revocation_demotes_largest_in_place():
    """Satellite unit: one revocation demotes exactly the LARGEST
    resident partition — the rest of the build stays on device (in
    place), the pool counts one partition spill."""
    from trino_tpu import types as T
    from trino_tpu.block import DevicePage
    from trino_tpu.ops import join as J

    pool = QueryMemoryPool(1 << 20, spill_enabled=True)
    ctx = pool.create_context("build")
    bridge = J.JoinBridge()
    op = J.HashBuilderOperator(
        [T.BIGINT], [0], bridge, memory_context=ctx,
        hybrid={"fanout": 4, "max_depth": 3, "hint": None})
    page, heavy = _skewed_join_page(4, 1024, 16)
    op.add_input(page)
    with ctx.lock:
        freed = op._revoke()
    hs = bridge.hybrid
    assert freed > 0
    assert hs.demotions == 1
    assert set(hs.spilled_build) == {heavy}, \
        "demotion did not pick the largest resident partition"
    assert hs.resident == frozenset(range(4)) - {heavy}
    assert any(isinstance(p, DevicePage) for p in op._pages), \
        "revocation spilled the whole build instead of one partition"
    assert pool.stats()["partition_spills"] == 1
    assert hs.spill_fraction() > 0.5  # the heavy partition dominated
    ctx.close()
    pool.close()


def test_hybrid_recursive_repartition_depth_bound():
    """Satellite unit: an oversized cold partition repartitions with a
    depth-salted hash while depth < max_depth; AT the bound it must
    reserve-or-raise instead of recursing forever."""
    import jax.numpy as jnp

    from trino_tpu import types as T
    from trino_tpu.exec.memory import MemoryExceededError
    from trino_tpu.ops import join as J

    types_ = [T.BIGINT]
    cols = [jnp.arange(64, dtype=jnp.int64)]
    nulls = [jnp.zeros(64, dtype=bool)]
    b = J._assemble_build_side(types_, [0], cols, nulls,
                               jnp.ones(64, dtype=bool), 64, [None])
    bridge = J.JoinBridge()
    bridge.set_build(b)
    op = J.LookupJoinOperator(types_, [0], bridge, "inner")
    op._ready = []
    pool = QueryMemoryPool(64, spill_enabled=True)  # nothing fits
    ctx = pool.create_context("build")
    hs = J.HybridJoinState(4, max_depth=2)
    hs.ctx = ctx
    keys = np.arange(4096, dtype=np.int64)
    sp = J._host_spilled(types_, [keys], [np.zeros(keys.size, bool)],
                         keys.size, [None])
    spp = J._host_spilled(types_, [keys[:128]],
                          [np.zeros(128, bool)], 128, [None])
    # below the bound: splits into depth-1 children at the queue FRONT
    op._deferred = [{"depth": 0, "build": [sp], "probe": [spp]}]
    op._advance_deferred(hs)
    assert hs.repartitions == 1
    assert op._deferred and all(e["depth"] == 1 for e in op._deferred)
    child_rows = sum(int(np.asarray(p.valid).sum())
                     for e in op._deferred for p in e["build"])
    assert child_rows == keys.size  # no rows lost across the split
    # the depth-salted hash actually redistributed the partition
    assert len(op._deferred) > 1
    # AT the bound: no further recursion — the reserve failure surfaces
    op._deferred = [{"depth": 2, "build": [sp], "probe": [spp]}]
    with pytest.raises(MemoryExceededError):
        op._advance_deferred(hs)
    assert hs.repartitions == 1  # did not split past max_depth
    assert hs.max_depth_seen == 1
    ctx.close()
    pool.close()


def test_hybrid_spill_record_hbo_roundtrip():
    """Satellite unit: the spill record survives NodeHistory serde and
    EWMA merges verbatim (it is replaced, never averaged)."""
    from trino_tpu.telemetry.stats_store import NodeHistory

    rec = {"fanout": 16, "source": "local", "fraction": 0.25,
           "partitions_spilled": 3, "demotions": 3, "repartitions": 0,
           "max_depth": 0}
    h = NodeHistory("fp0", "JoinNode")
    h.merge({"rows": 100.0, "spill": rec}, alpha=0.3)
    assert h.spill == rec
    # a later run WITHOUT spill keeps the last observed record
    h.merge({"rows": 120.0}, alpha=0.3)
    assert h.spill == rec
    # a later run with a new record replaces it outright
    rec2 = dict(rec, fanout=32, fraction=0.5)
    h.merge({"rows": 90.0, "spill": rec2}, alpha=0.3)
    assert h.spill == rec2
    back = NodeHistory.from_dict(h.to_dict())
    assert back.spill == rec2 and back.runs == 3
