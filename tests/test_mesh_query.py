"""The complete-distributed-query mesh program (parallel/mesh_query.py)
on the 8-virtual-CPU-device mesh — the same program the driver dry-run
executes (``__graft_entry__.dryrun_multichip``).

Reference analog gate: DistributedQueryRunner-style distributed-vs-local
equivalence (``testing/trino-testing/.../DistributedQueryRunner.java``).
"""

import jax
import pytest

from trino_tpu.parallel.mesh_query import run_q1_mesh, run_q1_mesh_demo


@pytest.mark.parametrize("n", [2, 8])
def test_mesh_q1_matches_local(n):
    devices = jax.devices("cpu")[:n]
    assert len(devices) == n
    run_q1_mesh_demo(devices, schema="micro")


def test_mesh_q1_overflow_retry():
    """per_dest=1 forces exchange overflow; the protocol doubles capacity
    and re-runs instead of aborting."""
    devices = jax.devices("cpu")[:4]
    rows, retries, _conn, _pages = run_q1_mesh(devices, schema="micro",
                                               per_dest=1)
    assert retries >= 1
    assert len(rows) == 4  # q1 has 4 (returnflag, linestatus) groups
