"""The complete-distributed-query mesh program (parallel/mesh_query.py)
on the 8-virtual-CPU-device mesh — the same program the driver dry-run
executes (``__graft_entry__.dryrun_multichip``).

Reference analog gate: DistributedQueryRunner-style distributed-vs-local
equivalence (``testing/trino-testing/.../DistributedQueryRunner.java``).
"""

import jax
import pytest

from trino_tpu.parallel.mesh_query import run_q1_mesh, run_q1_mesh_demo


@pytest.mark.parametrize("n", [2, 8])
def test_mesh_q1_matches_local(n):
    devices = jax.devices("cpu")[:n]
    assert len(devices) == n
    run_q1_mesh_demo(devices, schema="micro")


def test_mesh_q1_overflow_retry():
    """per_dest=1 forces exchange overflow; the protocol doubles capacity
    and re-runs instead of aborting. With the split program the retry
    re-runs ONLY the exchange+final, never the scan/partial-agg."""
    devices = jax.devices("cpu")[:4]
    from trino_tpu import jit_stats

    s1_before = jit_stats.total_for("mesh_q1_stage1")
    rows, retries, _conn, _pages = run_q1_mesh(devices, schema="micro",
                                               per_dest=1)
    assert retries >= 1
    assert len(rows) == 4  # q1 has 4 (returnflag, linestatus) groups
    # stage 1 traced at most once; the doubling only re-built the
    # exchange+final program (the old fused protocol re-paid stage 1
    # per retry — the 2x cliff)
    assert jit_stats.total_for("mesh_q1_stage1") - s1_before <= 1


def test_mesh_q1_repeat_run_does_not_retrace():
    """Repeat runs reuse the memoized stage1/exchange+final programs
    (and their jit caches) — a fresh build per call would re-trace and
    re-lower both SPMD programs every invocation."""
    from trino_tpu import jit_stats

    devices = jax.devices("cpu")[:4]
    run_q1_mesh(devices, schema="micro")  # warm
    before = jit_stats.total_for("mesh_q1_stage1",
                                 "mesh_q1_exchange_final")
    run_q1_mesh(devices, schema="micro")
    assert jit_stats.total_for("mesh_q1_stage1",
                               "mesh_q1_exchange_final") == before


def test_mesh_q1_count_first_sizing_zero_retries():
    """Count-first sizing on the exchange shape (pinned: q1's low NDV
    would otherwise pick global-hash): stage 1's histogram collective
    picks per_dest exactly, so the data all_to_all runs ONCE with zero
    doubling retries, and the skew stats come back filled."""
    devices = jax.devices("cpu")[:4]
    stats = {}
    rows, retries, _conn, _pages = run_q1_mesh(devices, schema="micro",
                                               stats_out=stats,
                                               agg_strategy="exchange")
    assert retries == 0
    assert len(rows) == 4
    assert stats["sizing"] == "exact"
    assert stats["agg_strategy"] == "exchange"
    assert stats["data_collectives"] == 1
    assert stats["per_dest"] >= stats["observed_max_pair_rows"]
    assert len(stats["partition_rows"]) == 4
    assert sum(stats["partition_rows"]) == stats["rows"] > 0
    assert stats["skew_ratio"] >= 1.0


def test_mesh_q1_auto_picks_global_hash_and_matches_exchange():
    """q1's 4 groups sit deep in the global-hash win region: 'auto'
    must pick the replicated-table shape (stage-1 observed groups
    through the choose_agg_strategy cost rule), produce the exact
    rows of the pinned exchange shape, and report the estimate that
    picked it."""
    devices = jax.devices("cpu")[:4]
    stats = {}
    rows, retries, _conn, _pages = run_q1_mesh(devices, schema="micro",
                                               stats_out=stats)
    assert retries == 0
    assert stats["agg_strategy"] == "global-hash"
    assert "groups" in stats["strategy_detail"]
    assert stats["table_slots"] >= 2 * 4
    want, _r, _c, _p = run_q1_mesh(devices, schema="micro",
                                   agg_strategy="exchange")

    def key(r):
        return (r[0], r[1])

    got_s, want_s = sorted(rows, key=key), sorted(want, key=key)
    assert len(got_s) == len(want_s) == 4
    for g, w in zip(got_s, want_s):
        for a, b in zip(g, w):
            if isinstance(a, float):
                assert abs(a - b) <= 1e-9 * max(1.0, abs(b)), (g, w)
            else:
                assert a == b, (g, w)
    # repeat run: the memoized program + the kernel-sizing history's
    # table bucket must hold — a fresh table size per run would
    # re-trace the whole SPMD program every invocation
    from trino_tpu import jit_stats

    before = jit_stats.total_for("mesh_q1_global_hash",
                                 "global_hash_insert",
                                 "global_hash_reduce")
    run_q1_mesh(devices, schema="micro", agg_strategy="global_hash")
    assert jit_stats.total_for("mesh_q1_global_hash",
                               "global_hash_insert",
                               "global_hash_reduce") == before
