"""Pallas segment-reduce kernel vs the lax path (interpret mode on CPU).

The kernel is the TPU-native replacement for the group-by scatter
(SURVEY.md §7; ref operator/MultiChannelGroupByHash.java:199-294). These
tests force interpret mode and cross-check every (kind, dtype) pair and
the engine-level aggregation path against jax.ops.segment_*.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trino_tpu.ops import pallas_kernels as pk


@pytest.fixture(autouse=True)
def force_interpret(monkeypatch):
    monkeypatch.setenv("TRINO_TPU_PALLAS", "interpret")


def _sorted_gids(rng, n, approx_groups, n_invalid=0):
    """Non-decreasing gids with steps of <=1 (a cumsum of boundaries),
    then n_invalid trailing rows jumped to the dump segment — exactly
    the shape ops/aggregation._group_reduce produces."""
    b = (rng.random(n) < (approx_groups / max(n, 1))).astype(np.int32)
    b[0] = 1
    gid = np.cumsum(b) - 1
    if n_invalid:
        gid[-n_invalid:] = n  # dump segment (num_segments = n + 1)
    return jnp.asarray(gid, dtype=jnp.int32)


KINDS = ["sum", "min", "max"]
DTYPES = ["int32", "float32"]


@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("n", [7, 512, 1000, 4096])
def test_matches_lax(kind, dtype, n):
    rng = np.random.default_rng(42 + n)
    gid = _sorted_gids(rng, n, approx_groups=max(2, n // 7),
                       n_invalid=min(n // 5, 100))
    if dtype == "int32":
        col = jnp.asarray(
            rng.integers(-2**30, 2**30, n), dtype=jnp.int32)
    else:
        col = jnp.asarray(rng.normal(size=n) * 1e3, dtype=jnp.float32)
    got = pk.segment_reduce(col, gid, num_segments=n + 1, kind=kind)
    fn = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
          "max": jax.ops.segment_max}[kind]
    want = fn(col, gid, num_segments=n + 1)
    # drop the dump segment (trailing; kernel leaves identity there by
    # design when the jump exits the chunk window) and compare
    got, want = np.asarray(got)[:n], np.asarray(want)[:n]
    live = int(gid[-(min(n // 5, 100) + 1)]) + 1 if n > 5 else n
    if dtype == "float32":
        np.testing.assert_allclose(got[:live], want[:live], rtol=1e-5)
    else:
        np.testing.assert_array_equal(got[:live], want[:live])


def test_int32_sum_exact_at_large_magnitude():
    """The hi/lo split must keep int32 sums EXACT where a naive f32
    accumulation would round."""
    n = 2048
    rng = np.random.default_rng(7)
    col = jnp.asarray(rng.integers(2**24, 2**30, n), dtype=jnp.int32)
    gid = jnp.asarray(np.minimum(np.arange(n) // 700, 5), dtype=jnp.int32)
    got = np.asarray(pk.segment_reduce(col, gid, 8, "sum"))
    want = np.asarray(jax.ops.segment_sum(col, gid, num_segments=8))
    np.testing.assert_array_equal(got, want)


def test_single_group_and_empty_tail():
    col = jnp.ones(300, dtype=jnp.int32)
    gid = jnp.zeros(300, dtype=jnp.int32)
    got = np.asarray(pk.segment_reduce(col, gid, 4, "sum"))
    assert got[0] == 300 and (got[1:] == 0).all()


def test_dispatch_falls_back_for_unsupported_dtype():
    col = jnp.ones(64, dtype=jnp.int64)
    gid = jnp.zeros(64, dtype=jnp.int32)
    got = np.asarray(pk.segment_reduce(col, gid, 2, "sum"))
    assert got[0] == 64


def test_engine_groupby_through_kernel():
    """End-to-end: a GROUP BY query whose state columns are f32/i32
    routes through the Pallas kernel and matches the lax-path answer."""
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.sql.analyzer import Session

    sql = ("select l_returnflag, count(*), sum(l_quantity) "
           "from lineitem group by l_returnflag")

    def run():
        r = LocalQueryRunner(
            {"tpch": TpchConnector(page_rows=512)},
            Session(catalog="tpch", schema="micro"))
        return sorted(r.execute(sql).rows)

    # kernel_calls increments at trace time; bust the jit cache so the
    # assertion is order-independent across the test session
    from trino_tpu.ops.aggregation import _group_reduce
    _group_reduce.clear_cache()
    before = pk.kernel_calls
    with_kernel = run()
    assert pk.kernel_calls > before, \
        "GROUP BY did not route through the Pallas kernel"
    import os
    os.environ["TRINO_TPU_PALLAS"] = "0"
    try:
        without = run()
    finally:
        os.environ["TRINO_TPU_PALLAS"] = "interpret"
    assert with_kernel == without
