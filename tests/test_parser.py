import pytest

from trino_tpu.resources.tpch_queries import TPCH_QUERIES
from trino_tpu.sql import ast
from trino_tpu.sql.parser import ParseError, parse_expression, parse_statement


@pytest.mark.parametrize("qid", sorted(TPCH_QUERIES))
def test_parses_all_tpch_queries(qid):
    stmt = parse_statement(TPCH_QUERIES[qid])
    assert isinstance(stmt, ast.QueryStatement)


def test_simple_select_shape():
    s = parse_statement("select a, b + 1 as c from t where a > 5 "
                        "group by a, b order by c desc limit 10")
    q = s.query
    spec = q.body
    assert isinstance(spec, ast.QuerySpecification)
    assert len(spec.select_items) == 2
    assert spec.select_items[1].alias == "c"
    assert isinstance(spec.where, ast.ComparisonExpression)
    assert len(spec.group_by.expressions) == 2
    assert q.order_by[0].ascending is False
    assert q.limit == 10


def test_expression_precedence():
    e = parse_expression("1 + 2 * 3")
    assert isinstance(e, ast.ArithmeticBinary) and e.op == "+"
    assert isinstance(e.right, ast.ArithmeticBinary) and e.right.op == "*"

    e = parse_expression("a or b and c")
    assert isinstance(e, ast.LogicalBinary) and e.op == "OR"
    assert isinstance(e.right, ast.LogicalBinary) and e.right.op == "AND"

    e = parse_expression("not a = b")
    assert isinstance(e, ast.NotExpression)
    assert isinstance(e.value, ast.ComparisonExpression)


def test_predicates():
    e = parse_expression("x between 1 and 10")
    assert isinstance(e, ast.BetweenPredicate)
    e = parse_expression("x not in (1, 2)")
    assert isinstance(e, ast.NotExpression)
    assert isinstance(e.value, ast.InPredicate)
    e = parse_expression("name like 'a%' escape '\\'")
    assert isinstance(e, ast.LikePredicate)
    e = parse_expression("x is not null")
    assert isinstance(e, ast.IsNotNullPredicate)


def test_date_interval_literals():
    e = parse_expression("date '1998-12-01' - interval '90' day")
    assert isinstance(e, ast.ArithmeticBinary)
    assert isinstance(e.left, ast.GenericLiteral)
    assert isinstance(e.right, ast.IntervalLiteral)
    assert e.right.unit == "day"


def test_case_forms():
    e = parse_expression(
        "case when a > 0 then 'pos' when a < 0 then 'neg' else 'zero' end")
    assert isinstance(e, ast.SearchedCase)
    assert len(e.when_clauses) == 2
    e = parse_expression("case x when 1 then 'one' else 'other' end")
    assert isinstance(e, ast.SimpleCase)


def test_subqueries():
    s = parse_statement(
        "select * from t where exists (select 1 from u where u.a = t.a)")
    w = s.query.body.where
    assert isinstance(w, ast.ExistsPredicate)
    e = parse_expression("x = (select max(y) from t)")
    assert isinstance(e.right, ast.ScalarSubquery)
    e = parse_expression("x > all (select y from t)")
    assert isinstance(e, ast.QuantifiedComparison)


def test_joins():
    s = parse_statement(
        "select * from a left outer join b on a.x = b.x "
        "join c using (y) cross join d")
    rel = s.query.body.from_
    assert isinstance(rel, ast.Join) and rel.join_type == "CROSS"
    assert rel.left.join_type == "INNER"
    assert rel.left.using_columns == ("y",)
    assert rel.left.left.join_type == "LEFT"


def test_with_and_setops():
    s = parse_statement(
        "with r as (select a from t) "
        "select * from r union all select * from r "
        "intersect select * from r")
    q = s.query
    assert len(q.with_queries) == 1
    assert isinstance(q.body, ast.SetOperation)
    assert q.body.op == "UNION" and not q.body.distinct


def test_window_function():
    e = parse_expression(
        "rank() over (partition by a order by b desc "
        "rows between unbounded preceding and current row)")
    assert isinstance(e, ast.FunctionCall)
    assert e.window is not None
    assert e.window.frame[0] == "rows"


def test_statements():
    assert isinstance(parse_statement("show tables"), ast.ShowTables)
    assert isinstance(parse_statement("show catalogs"), ast.ShowCatalogs)
    assert isinstance(parse_statement("explain select 1"), ast.Explain)
    s = parse_statement("create table x as select 1 as a")
    assert isinstance(s, ast.CreateTableAsSelect)
    s = parse_statement("insert into t (a, b) select 1, 2")
    assert isinstance(s, ast.Insert) and s.columns == ("a", "b")


def test_parse_errors():
    with pytest.raises(ParseError):
        parse_statement("select from where")
    with pytest.raises(ParseError):
        parse_statement("select 1 extra_garbage ,")
    with pytest.raises(ParseError):
        parse_expression("1 +")
