"""Milestone A gate: TPC-H q1 as a hand-built physical pipeline on tpch.tiny
(reference analog: testing/trino-benchmark HandTpchQuery1), cross-checked
against an independent numpy computation of the same generated data."""

from decimal import Decimal

import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Page
from trino_tpu.connectors.tpch import TpchConnector, _SCHEMAS
from trino_tpu.exec.driver import Driver
from trino_tpu.expr import Call, InputRef, Literal, PageProcessor
from trino_tpu.expr.functions import days_from_civil_host
from trino_tpu.ops.aggregation import AggCall, HashAggregationOperator, resolve_agg_type
from trino_tpu.ops.operator import (FilterProjectOperator,
                                    OutputCollectorOperator,
                                    TableScanOperator)

D = T.decimal_type(12, 2)


def build_q1_driver(conn, schema="micro"):
    meta = conn.metadata()
    table = meta.get_table_handle(schema, "lineitem")
    cols = {c.name: c for c in meta.get_columns(table)}
    scan_cols = [cols[n] for n in
                 ["l_returnflag", "l_linestatus", "l_quantity",
                  "l_extendedprice", "l_discount", "l_tax", "l_shipdate"]]
    scan = TableScanOperator(conn, scan_cols)

    # channels in scan order
    rf, ls, qty, price, disc, tax, ship = [
        InputRef(c.type, i) for i, c in enumerate(scan_cols)]
    cutoff = days_from_civil_host(1998, 12, 1) - 90
    filt = Call(T.BOOLEAN, "le", (ship, Literal(T.DATE, cutoff)))
    one = Literal(T.BIGINT, 1)
    disc_price_t = T.decimal_type(18, 4)
    disc_price = Call(disc_price_t, "multiply",
                      (price, Call(T.decimal_type(13, 2), "subtract", (one, disc))))
    charge_t = T.decimal_type(18, 6)
    charge = Call(charge_t, "multiply",
                  (disc_price, Call(T.decimal_type(13, 2), "add", (one, tax))))
    proc = PageProcessor([c.type for c in scan_cols],
                         [rf, ls, qty, price, disc, tax, disc_price, charge],
                         filt)
    fp = FilterProjectOperator(proc)

    aggs = []
    for fn, ch, t in [("sum", 2, D), ("sum", 3, D), ("sum", 6, disc_price_t),
                      ("sum", 7, charge_t), ("avg", 2, D), ("avg", 3, D),
                      ("avg", 4, D), ("count_star", None, None)]:
        aggs.append(AggCall(fn, ch, t, resolve_agg_type(fn, t)))
    agg = HashAggregationOperator(proc.output_types, [0, 1], aggs)

    sink = OutputCollectorOperator()
    driver = Driver([scan, fp, agg, sink])
    splits = conn.split_manager().get_splits(table, 4)
    for s in splits:
        driver.add_split(s)
    driver.no_more_splits()
    return driver, sink


def reference_q1(conn, schema="micro"):
    """Independent numpy computation over the same generated pages."""
    meta = conn.metadata()
    table = meta.get_table_handle(schema, "lineitem")
    cols = {c.name: c for c in meta.get_columns(table)}
    names = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
             "l_discount", "l_tax", "l_shipdate"]
    splits = conn.split_manager().get_splits(table, 4)
    pages = []
    for s in splits:
        src = conn.page_source(s, [cols[n] for n in names])
        while True:
            p = src.get_next_page()
            if p is None:
                break
            pages.append(p)
    page = Page.concat(pages)
    rf = np.asarray(page.block(0).data)
    ls = np.asarray(page.block(1).data)
    d_rf, d_ls = page.block(0).dictionary, page.block(1).dictionary
    qty = np.asarray(page.block(2).data).astype(object)
    price = np.asarray(page.block(3).data).astype(object)
    disc = np.asarray(page.block(4).data).astype(object)
    tax = np.asarray(page.block(5).data).astype(object)
    ship = np.asarray(page.block(6).data)
    cutoff = days_from_civil_host(1998, 12, 1) - 90
    keep = ship <= cutoff
    out = {}
    for i in np.nonzero(keep)[0]:
        key = (d_rf.values[rf[i]], d_ls.values[ls[i]])
        g = out.setdefault(key, [0, 0, 0, 0, 0])
        g[0] += qty[i]
        g[1] += price[i]
        disc_price = price[i] * (100 - disc[i])          # scale 4
        g[2] += disc_price
        g[3] += disc_price * (100 + tax[i])              # scale 6
        g[4] += 1
    return out


def test_q1_tiny_end_to_end():
    conn = TpchConnector(page_rows=8192)
    driver, sink = build_q1_driver(conn)
    driver.run_to_completion()
    result = Page.concat(sink.pages)
    expected = reference_q1(conn)

    assert result.num_rows == len(expected) == 4  # 4 (flag,status) groups
    names_rows = result.to_rows()
    for row in names_rows:
        key = (row[0], row[1])
        exp = expected[key]
        sum_qty, sum_price, sum_disc_price, sum_charge = row[2], row[3], row[4], row[5]
        avg_qty, avg_price, avg_disc, cnt = row[6], row[7], row[8], row[9]
        assert sum_qty == Decimal(exp[0]).scaleb(-2), key
        assert sum_price == Decimal(exp[1]).scaleb(-2), key
        assert sum_disc_price == Decimal(exp[2]).scaleb(-4), key
        assert sum_charge == Decimal(exp[3]).scaleb(-6), key
        assert cnt == exp[4]
        # avg: exact decimal division round-half-up
        assert avg_qty == (Decimal(exp[0]).scaleb(-2) / exp[4]).quantize(
            Decimal("0.01"), rounding="ROUND_HALF_UP"), key
        assert avg_price == (Decimal(exp[1]).scaleb(-2) / exp[4]).quantize(
            Decimal("0.01"), rounding="ROUND_HALF_UP"), key


def test_tpch_generator_determinism():
    conn = TpchConnector()
    t = conn.table("orders")
    a = t.generate(0.01, 100, 200, ["o_orderkey", "o_totalprice",
                                    "o_orderstatus"])
    b = t.generate(0.01, 150, 160, ["o_orderkey", "o_totalprice",
                                    "o_orderstatus"])
    # same rows regardless of the requested range
    assert a.region(50, 10).to_rows() == b.to_rows()


def test_tpch_partsupp_lineitem_join_keys():
    """Every (l_partkey, l_suppkey) pair must exist in partsupp."""
    conn = TpchConnector()
    li = conn.table("lineitem").generate(0.01, 0, 500,
                                         ["l_partkey", "l_suppkey"])
    ps = conn.table("partsupp").generate(
        0.01, 0, conn.table("partsupp").row_count(0.01),
        ["ps_partkey", "ps_suppkey"])
    pairs = set(zip(ps.block(0).to_pylist(), ps.block(1).to_pylist()))
    for pk, sk in zip(li.block(0).to_pylist(), li.block(1).to_pylist()):
        assert (pk, sk) in pairs
