"""TupleDomain algebra + connector pushdown negotiation.

Reference analog: ``spi/predicate/TestTupleDomain.java`` /
``TestDomain.java`` / ``TestSortedRangeSet.java`` and
``TestPushPredicateIntoTableScan.java``.
"""

import numpy as np
import pytest

from trino_tpu import session_properties as SP
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.connectors.tpcds import TpcdsConnector
from trino_tpu.predicate import (Domain, Range, TupleDomain, ValueSet,
                                 domain_mask)
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session

# ------------------------------------------------------------ algebra ----


def test_range_basics():
    r = Range(1, True, 5, False)        # [1, 5)
    assert r.includes(1) and r.includes(4) and not r.includes(5)
    assert not r.includes(0)
    with pytest.raises(ValueError):
        Range(5, True, 1, True)
    with pytest.raises(ValueError):
        Range(3, False, 3, True)        # (3,3] is empty
    assert Range.single(3).includes(3)


def test_value_set_union_intersect_complement():
    a = ValueSet.of_ranges(Range(0, True, 10, True))
    b = ValueSet.of_ranges(Range(5, True, 20, True))
    u = a.union(b)
    assert u.ranges == (Range(0, True, 20, True),)
    i = a.intersect(b)
    assert i.ranges == (Range(5, True, 10, True),)
    c = a.complement()
    assert len(c.ranges) == 2
    assert c.includes(-1) and c.includes(11)
    assert not c.includes(0) and not c.includes(10)
    # complement round-trips
    assert c.complement().ranges == a.ranges
    # disjoint stay disjoint; touching-at-excluded stay separate
    d = ValueSet.of_ranges(Range(0, True, 1, False),
                           Range(1, False, 2, True))
    assert len(d.ranges) == 2
    # touching-at-included merge
    e = ValueSet.of_ranges(Range(0, True, 1, True),
                           Range(1, False, 2, True))
    assert e.ranges == (Range(0, True, 2, True),)


def test_value_set_discrete():
    v = ValueSet.of(3, 1, 2, 2)
    assert [r.low for r in v.ranges] == [1, 2, 3]
    assert v.includes(2) and not v.includes(4)
    assert ValueSet.all_().intersect(v) == v
    assert v.union(ValueSet.none()) == v
    assert ValueSet.none().is_none


def test_domain_null_handling():
    d = Domain.single(5)
    assert not d.includes(None) and d.includes(5)
    n = Domain.only_null()
    assert n.includes(None) and not n.includes(5)
    u = d.union(n)
    assert u.includes(None) and u.includes(5)
    assert d.complement().includes(None)
    assert Domain.not_null().intersect(Domain.all_()) == Domain.not_null()
    assert d.intersect(Domain.single(6)).is_none


def test_tuple_domain():
    td1 = TupleDomain.of({"a": Domain.single(1),
                          "b": Domain.not_null()})
    td2 = TupleDomain.of({"a": Domain.of_values(1, 2)})
    inter = td1.intersect(td2)
    assert inter.domain("a") == Domain.single(1)
    assert inter.domain("b") == Domain.not_null()
    assert inter.domain("c").is_all
    # contradiction collapses to NONE
    none = td1.intersect(TupleDomain.of({"a": Domain.single(9)}))
    assert none.is_none
    assert TupleDomain.none().intersect(td1).is_none
    # column-wise union keeps only both-sided columns
    u = td1.union(td2)
    assert u.domain("a") == Domain.of_values(1, 2)
    assert u.domain("b").is_all


def test_domain_mask_numpy():
    data = np.array([1, 5, 7, 9, 3], dtype=np.int64)
    nulls = np.array([False, False, True, False, False])
    d = Domain(ValueSet.of_ranges(Range(3, True, 7, True)), False)
    assert domain_mask(data, nulls, None, d).tolist() == \
        [False, True, False, False, True]
    d2 = Domain(ValueSet.of_ranges(Range(3, True, 7, True)), True)
    assert domain_mask(data, nulls, None, d2).tolist() == \
        [False, True, True, False, True]


def test_domain_mask_pooled():
    from trino_tpu.block import Dictionary

    d = Dictionary(["AUTOMOBILE", "BUILDING", "MACHINERY"])
    data = np.array([0, 1, 2, 1], dtype=np.int32)
    dom = Domain.single("BUILDING")
    assert domain_mask(data, None, d, dom).tolist() == \
        [False, True, False, True]


# ----------------------------------------------------------- pushdown ----


def _runners(connectors, schema, catalog):
    on = LocalQueryRunner(connectors,
                          Session(catalog=catalog, schema=schema))
    sess = Session(catalog=catalog, schema=schema)
    SP.set_property(sess.properties, "filter_pushdown_enabled", False)
    off = LocalQueryRunner(connectors, sess)
    return on, off


def _scan_rows(runner, sql):
    """TableScan output rows from EXPLAIN ANALYZE operator stats."""
    res = runner.execute("explain analyze " + sql)
    rows = 0
    seen = False
    for (line,) in res.rows:
        if "TableScanOperator" in line:
            seen = True
            rows += int(line.split(":")[1].strip().split(" ")[0])
    assert seen, "no TableScanOperator line in EXPLAIN ANALYZE"
    return rows


def test_tpch_scan_pruning_by_stats():
    on, off = _runners({"tpch": TpchConnector(page_rows=2048)},
                       "micro", "tpch")
    sql = ("select count(*) from lineitem "
           "where l_shipdate <= date '1995-06-17' and l_quantity < 10")
    assert on.execute(sql).rows == off.execute(sql).rows
    pruned = _scan_rows(on, sql)
    full = _scan_rows(off, sql)
    assert pruned < full / 4, (pruned, full)
    # EXPLAIN shows the constraint on the scan
    plan = on.explain(sql)
    assert "constraint{" in plan and "l_shipdate" in plan


def test_tpcds_scan_pruning_by_stats():
    on, off = _runners({"tpcds": TpcdsConnector(page_rows=2048)},
                       "micro", "tpcds")
    sql = ("select count(*) from store_sales "
           "where ss_quantity between 1 and 20")
    assert on.execute(sql).rows == off.execute(sql).rows
    pruned = _scan_rows(on, sql)
    full = _scan_rows(off, sql)
    assert pruned < full, (pruned, full)
    assert "constraint{" in on.explain(sql)


def test_pushdown_correctness_matrix():
    on, off = _runners({"tpch": TpchConnector(page_rows=1024)},
                       "micro", "tpch")
    for sql in [
        "select count(*) from orders where o_orderkey in (1,2,3) "
        "or o_orderkey > 5000",
        "select count(*) from orders where o_orderdate <> "
        "date '1995-03-15'",
        "select count(*) from customer where c_mktsegment = 'BUILDING'",
        "select count(*) from nation where n_name > 'M' "
        "or n_name = 'CHINA'",
        "select count(*) from lineitem where l_discount between "
        "0.05 and 0.07",
        "select count(*) from part where p_size >= 10 and p_size <= 20",
        # residual + pushable mix: length() is not extractable
        "select count(*) from nation where n_regionkey = 2 "
        "and length(n_name) > 5",
        # contradiction: never matches
        "select count(*) from nation where n_regionkey = 2 "
        "and n_regionkey = 3",
    ]:
        assert on.execute(sql).rows == off.execute(sql).rows, sql


def test_pushdown_through_joins_micro():
    """Pushdown composes with join planning + dynamic filtering."""
    from trino_tpu.resources.tpch_queries import TPCH_QUERIES

    on, off = _runners({"tpch": TpchConnector(page_rows=2048)},
                       "micro", "tpch")
    for q in (3, 6, 12):
        assert sorted(on.execute(TPCH_QUERIES[q]).rows) == \
            sorted(off.execute(TPCH_QUERIES[q]).rows), f"q{q}"


def test_truncating_cast_stays_residual():
    """cast(-2.6 as bigint) truncates toward zero (-2) in the kernel;
    extraction must NOT floor it to -3 and drop the conjunct (round-4
    review finding)."""
    on, off = _runners({"tpch": TpchConnector(page_rows=512)},
                       "micro", "tpch")
    sql = ("select count(*) from nation "
           "where n_regionkey - 4 <= cast(-2.6 as bigint)")
    assert on.execute(sql).rows == off.execute(sql).rows
    # directly on a column: the cast literal is non-integral -> residual
    sql2 = ("select count(*) from nation "
            "where n_regionkey <= cast(2.6 as bigint)")
    assert on.execute(sql2).rows == off.execute(sql2).rows == [(15,)]


def test_float_ne_keeps_nan_rows():
    """d <> 5.0 keeps NaN rows under IEEE not_equal; pushdown must not
    prune them (round-4 review finding)."""
    from trino_tpu.connectors.memory import MemoryConnector

    mem = MemoryConnector()
    on, off = _runners({"mem": mem}, "default", "mem")
    on.execute("create table t (d double)")
    on.execute("insert into t values (5.0), (1.5)")
    on.execute("insert into t select nan()")
    for r in (on, off):
        rows = r.execute("select count(*) from t where d <> 5.0").rows
        assert rows == [(2,)], rows


def test_partial_enforcement_residual_refiltered():
    """ConstraintApplicationResult semantics: a connector enforcing only
    ONE of two offered column domains returns the other as the RESIDUAL
    TupleDomain; the engine keeps filtering that column itself and the
    answer stays correct (reference:
    spi/connector/ConstraintApplicationResult.java remainingFilter)."""
    from trino_tpu.connectors.memory import MemoryConnector, MemoryMetadata
    from trino_tpu.connectors.spi import negotiate_constraint

    class OneColumnMetadata(MemoryMetadata):
        offered_cols = []

        def apply_filter(self, table, constraint):
            OneColumnMetadata.offered_cols.append(
                sorted(constraint.as_dict().keys()))
            data = self.conn.tables.get((table.schema, table.table))
            if data is None:
                return None
            # the connector only knows how to prune on 'k'
            return negotiate_constraint(
                table, constraint, (c.name for c in data.columns),
                enforceable={"k"})

    class OneColumnMemory(MemoryConnector):
        def metadata(self):
            return OneColumnMetadata(self)

    mem = OneColumnMemory()
    on, off = _runners({"mem": mem}, "default", "mem")
    on.execute("create table t (k bigint, v bigint)")
    on.execute("insert into t values (1, 10), (2, 20), (3, 30), "
               "(4, 40), (5, 50)")
    sql = "select k, v from t where k >= 2 and v <= 40"
    rows_on = sorted(on.execute(sql).rows)
    assert rows_on == [(2, 20), (3, 30), (4, 40)]
    assert rows_on == sorted(off.execute(sql).rows)
    # both domains were offered; only k landed on the handle
    # both domains were offered together at least once (the iterative
    # engine may re-offer the residual alone on later passes)
    assert ["k", "v"] in OneColumnMetadata.offered_cols
    plan = on.explain(sql)
    assert "constraint{k" in plan and "constraint{k, v" not in plan
    # the residual conjunct (v) stays as an engine-side filter
    assert "v" in plan.split("TableScan")[0]
