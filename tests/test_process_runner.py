"""Multi-process runtime: coordinator + real worker processes.

Reference analog: the DistributedQueryRunner-based integration suites,
but across REAL process boundaries — task RPC, wire serde, pull-based
shuffle — plus the FT seams: failure injection with task retry, worker
death with query retry, heartbeat detection.
"""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.parallel.process_runner import ProcessQueryRunner
from trino_tpu.resources.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session

CATALOGS = {"tpch": {"connector": "tpch", "page_rows": 4096}}


@pytest.fixture(scope="module")
def cluster():
    runner = ProcessQueryRunner(
        CATALOGS, Session(catalog="tpch", schema="micro"),
        n_workers=2, desired_splits=4, broadcast_threshold=300.0)
    yield runner
    runner.close()


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=4096)},
                            Session(catalog="tpch", schema="micro"))


def _key(row):
    return tuple("\0" if v is None else str(v) for v in row)


def check(local, cluster, sql, ordered=None):
    lres = local.execute(sql)
    dres = cluster.execute(sql)
    if ordered is None:
        ordered = "order by" in sql.lower()
    lrows, drows = lres.rows, dres.rows
    if not ordered:
        lrows = sorted(lrows, key=_key)
        drows = sorted(drows, key=_key)
    assert drows == lrows, \
        f"process != local for {sql[:70]}...\n" \
        f"proc={drows[:5]}\nlocal={lrows[:5]}"


def test_scan_filter(local, cluster):
    check(local, cluster,
          "select n_name from nation where n_regionkey = 2")


def test_group_agg_strings(local, cluster):
    check(local, cluster,
          "select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
          "from lineitem group by l_returnflag, l_linestatus")


def test_join_q3(local, cluster):
    check(local, cluster, TPCH_QUERIES[3])


def test_semijoin(local, cluster):
    check(local, cluster,
          "select count(*) from lineitem where l_orderkey in "
          "(select o_orderkey from orders where "
          "o_orderpriority = '1-URGENT')")


def test_order_limit(local, cluster):
    check(local, cluster,
          "select o_custkey, o_totalprice from orders "
          "order by o_totalprice desc limit 7")


def test_heartbeat(cluster):
    assert cluster.heartbeat() == [True, True]


def test_injected_task_failure_recovers(local, cluster):
    """A task that fails on its first worker retries on another and the
    query still returns correct results (reference:
    BaseFailureRecoveryTest + FailureInjector)."""
    cluster.inject_task_failure("q", times=1)  # first task of next query
    check(local, cluster, TPCH_QUERIES[3])
    assert not any(cluster.failure_injections.values())


def test_worker_death_query_retry_and_replacement(local, cluster):
    """Killing a worker mid-cluster: the query retries on survivors AND
    the self-healing path replaces the dead worker, so capacity recovers
    instead of decaying (round-6 tentpole)."""
    victim = cluster.workers[1]
    victim.proc.kill()
    victim.proc.wait(timeout=10)
    sql = "select count(*), sum(l_quantity) from lineitem"
    res = cluster.execute(sql)
    assert res.rows == local.execute(sql).rows
    # the on-demand heal (or the background heartbeat loop) swapped in
    # a replacement process: same slot, bumped generation, alive again
    assert cluster.heal() == [True, True]
    assert cluster.workers[1].generation >= 1
    assert cluster.workers[1].proc.pid != victim.proc.pid
    # no query_retries assertion: the background monitor may mark the
    # victim dead before the query ever schedules onto it, in which
    # case the survivors answer with zero retries — both paths are
    # correct (the deterministic retry counting lives in test_chaos.py)
    # the replacement serves queries as a first-class worker
    res2 = cluster.execute(sql)
    assert res2.rows == res.rows


def test_streaming_cross_process_overlap(cluster):
    """The defining streaming witness ACROSS PROCESSES: some mid-plan
    task's first output page was drained by its consumer (another
    process) before that task finished (reference:
    PipelinedQueryScheduler's concurrent stages)."""
    res = cluster.execute(
        "select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
        "from lineitem group by l_returnflag, l_linestatus")
    overlap = res.stats["process_overlap"]
    assert len(overlap) >= 2
    assert any(overlap.values()), \
        f"no cross-process producer/consumer overlap: {overlap}"


def test_concurrent_queries_interleave(cluster):
    """Two queries submitted through the HTTP protocol run CONCURRENTLY
    against the worker processes — their execution windows overlap
    (the coordinator has no per-query serialization lock)."""
    import threading
    import time

    from trino_tpu.client import Client
    from trino_tpu.server.protocol import ProtocolServer

    srv = ProtocolServer(cluster, page_size=1000).start()
    try:
        windows = {}
        errors = []

        def run(tag, sql):
            c = Client(srv.uri)
            t0 = time.monotonic()
            try:
                res = c.execute(sql)
                windows[tag] = (t0, time.monotonic(), len(res.rows))
            except Exception as e:  # surfaces in the main thread
                errors.append(e)

        sqls = {
            "a": "select l_returnflag, count(*), sum(l_quantity) "
                 "from lineitem group by l_returnflag",
            "b": "select o_orderpriority, count(*) from orders, lineitem "
                 "where o_orderkey = l_orderkey "
                 "group by o_orderpriority",
        }
        threads = [threading.Thread(target=run, args=(tag, sql))
                   for tag, sql in sqls.items()]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=300)
        assert not errors, errors
        assert windows["a"][2] == 3 and windows["b"][2] == 5
        a0, a1, _ = windows["a"]
        b0, b1, _ = windows["b"]
        assert a0 < b1 and b0 < a1, \
            f"queries serialized: a={a0, a1} b={b0, b1}"
    finally:
        srv.stop()


def test_distributed_writes_memory_catalog():
    """INSERT/CTAS writer tasks execute ON WORKER PROCESSES (page-sink
    RPC to the coordinator's catalog), commits replicate to every
    worker, and the written table is then scanned DISTRIBUTED."""
    with ProcessQueryRunner(
            {"tpch": {"connector": "tpch", "page_rows": 4096},
             "memory": {"connector": "memory"}},
            Session(catalog="memory", schema="default"),
            n_workers=2, desired_splits=4) as c:
        res = c.execute("create table t as select n_nationkey k, n_name "
                        "from tpch.micro.nation")
        assert res.rows == [(25,)]
        res = c.execute("insert into t select n_nationkey + 100, n_name "
                        "from tpch.micro.nation where n_regionkey = 2")
        assert res.rows == [(5,)]
        # distributed read of the replicated table joins a distributed
        # catalog — the scan runs on the workers, not the coordinator
        res = c.execute("select count(*) from t")
        assert res.rows == [(30,)]
        res = c.execute(
            "select r_name, count(*) c from t, tpch.micro.nation n, "
            "tpch.micro.region r where t.k % 100 = n.n_nationkey and "
            "n.n_regionkey = r.r_regionkey group by r_name "
            "order by c desc, r_name")
        assert res.rows[0][1] == 10  # ASIA nations counted twice
        res = c.execute("delete from t where k >= 100")
        assert res.rows == [(5,)]
        assert c.execute("select count(*) from t").rows == [(25,)]
        # distributed catalogs still distribute
        res2 = c.execute("select count(*) from tpch.micro.region")
        assert res2.rows[0][0] == 5
        # retried writes must not double-apply: pages stage at the
        # coordinator and only the SUCCESSFUL attempt commits
        c.inject_task_failure("q", times=1)
        res = c.execute("insert into t select n_nationkey + 200, n_name "
                        "from tpch.micro.nation where n_regionkey = 0")
        assert res.rows == [(5,)]
        assert c.execute("select count(*) from t").rows == [(30,)]


def test_barrier_mode_task_retry():
    """With streaming off (fault-tolerant barrier shape), an injected
    task failure retries on ANOTHER worker without restarting the
    query."""
    s = Session(catalog="tpch", schema="micro")
    s.properties["streaming_execution"] = False
    with ProcessQueryRunner(CATALOGS, s, n_workers=2, desired_splits=4,
                            broadcast_threshold=300.0) as c:
        c.inject_task_failure("q", times=1)
        res = c.execute("select l_returnflag, count(*) from lineitem "
                        "group by l_returnflag")
        assert sorted(res.rows) == [("A", 1590), ("N", 2773),
                                    ("R", 1516)]
        assert not any(c.failure_injections.values())


def test_worker_snapshot_consistent_under_concurrent_replacement():
    """The guarded-by fix: query-path readers copy the worker slots
    under _heal_lock (`_worker_snapshot`) instead of iterating the
    live list while the monitor thread swaps handles in place. A
    snapshot taken during a storm of concurrent slot swaps must always
    be a complete, valid view — never torn, never resized mid-read."""
    import threading

    r = ProcessQueryRunner.__new__(ProcessQueryRunner)  # no spawn
    r._heal_lock = threading.Lock()
    slots = [object() for _ in range(4)]
    spares = [object() for _ in range(4)]
    r.workers = list(slots)
    valid = set(slots) | set(spares)

    snap = r._worker_snapshot()
    assert snap == r.workers and snap is not r.workers  # a COPY

    stop = threading.Event()

    def swapper():
        i = 0
        while not stop.is_set():
            # the _replace_worker shape: in-place swap under the lock
            with r._heal_lock:
                r.workers[i % 4] = spares[i % 4] if i % 2 \
                    else slots[i % 4]
            i += 1

    t = threading.Thread(target=swapper, daemon=True)
    t.start()
    try:
        for _ in range(2000):
            s = r._worker_snapshot()
            assert len(s) == 4
            assert all(w in valid for w in s)
    finally:
        stop.set()
        t.join(timeout=5)


def test_serde_roundtrip():
    from trino_tpu import types as T
    from trino_tpu.block import Dictionary, Page
    from trino_tpu.exec.serde import PageDeserializer, PageSerializer

    d = Dictionary()
    page = Page.from_pylists(
        [T.BIGINT, T.VARCHAR, T.DOUBLE, T.DATE],
        [[1, 2, None], ["a", None, "bb"], [1.5, None, -2.0],
         [10, None, 20]],
        dictionaries=[None, d, None, None])
    ser = PageSerializer()
    de = PageDeserializer()
    out = de.deserialize(ser.serialize(page))
    assert out.to_rows() == page.to_rows()
    # second page on the same stream: pool ships only the delta
    page2 = Page.from_pylists([T.BIGINT, T.VARCHAR, T.DOUBLE, T.DATE],
                              [[4], ["cc"], [0.0], [30]],
                              dictionaries=[None, d, None, None])
    blob1 = ser.serialize(page2)
    out2 = de.deserialize(blob1)
    assert out2.to_rows() == page2.to_rows()
    # codes decode through the RECEIVER-side pool built from deltas
    assert out2.blocks[1].dictionary is out.blocks[1].dictionary
