"""Multi-process runtime: coordinator + real worker processes.

Reference analog: the DistributedQueryRunner-based integration suites,
but across REAL process boundaries — task RPC, wire serde, pull-based
shuffle — plus the FT seams: failure injection with task retry, worker
death with query retry, heartbeat detection.
"""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.parallel.process_runner import ProcessQueryRunner
from trino_tpu.resources.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session

CATALOGS = {"tpch": {"connector": "tpch", "page_rows": 4096}}


@pytest.fixture(scope="module")
def cluster():
    runner = ProcessQueryRunner(
        CATALOGS, Session(catalog="tpch", schema="micro"),
        n_workers=2, desired_splits=4, broadcast_threshold=300.0)
    yield runner
    runner.close()


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=4096)},
                            Session(catalog="tpch", schema="micro"))


def _key(row):
    return tuple("\0" if v is None else str(v) for v in row)


def check(local, cluster, sql, ordered=None):
    lres = local.execute(sql)
    dres = cluster.execute(sql)
    if ordered is None:
        ordered = "order by" in sql.lower()
    lrows, drows = lres.rows, dres.rows
    if not ordered:
        lrows = sorted(lrows, key=_key)
        drows = sorted(drows, key=_key)
    assert drows == lrows, \
        f"process != local for {sql[:70]}...\n" \
        f"proc={drows[:5]}\nlocal={lrows[:5]}"


def test_scan_filter(local, cluster):
    check(local, cluster,
          "select n_name from nation where n_regionkey = 2")


def test_group_agg_strings(local, cluster):
    check(local, cluster,
          "select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
          "from lineitem group by l_returnflag, l_linestatus")


def test_join_q3(local, cluster):
    check(local, cluster, TPCH_QUERIES[3])


def test_semijoin(local, cluster):
    check(local, cluster,
          "select count(*) from lineitem where l_orderkey in "
          "(select o_orderkey from orders where "
          "o_orderpriority = '1-URGENT')")


def test_order_limit(local, cluster):
    check(local, cluster,
          "select o_custkey, o_totalprice from orders "
          "order by o_totalprice desc limit 7")


def test_heartbeat(cluster):
    assert cluster.heartbeat() == [True, True]


def test_injected_task_failure_recovers(local, cluster):
    """A task that fails on its first worker retries on another and the
    query still returns correct results (reference:
    BaseFailureRecoveryTest + FailureInjector)."""
    cluster.inject_task_failure("q", times=1)  # first task of next query
    check(local, cluster, TPCH_QUERIES[3])
    assert not any(cluster.failure_injections.values())


def test_worker_death_query_retry(local, cluster):
    """Killing a worker mid-cluster: heartbeat marks it dead and the
    query retries on the survivors."""
    victim = cluster.workers[1]
    victim.proc.kill()
    victim.proc.wait(timeout=10)
    sql = "select count(*), sum(l_quantity) from lineitem"
    res = cluster.execute(sql)
    assert res.rows == local.execute(sql).rows
    assert cluster.heartbeat() == [True, False]


def test_memory_catalog_routes_to_coordinator():
    """Memory-connector state lives in the coordinator process only, so
    queries touching it must run locally, not distribute to workers."""
    with ProcessQueryRunner(
            {"tpch": {"connector": "tpch", "page_rows": 4096},
             "memory": {"connector": "memory"}},
            Session(catalog="memory", schema="default"),
            n_workers=1, desired_splits=2) as c:
        c.execute("create table t as select n_nationkey k, n_name "
                  "from tpch.micro.nation")
        res = c.execute("select count(*) from t")
        assert res.rows == [(25,)]
        # distributed catalogs still distribute
        res2 = c.execute("select count(*) from tpch.micro.region")
        assert res2.rows[0][0] == 5


def test_serde_roundtrip():
    from trino_tpu import types as T
    from trino_tpu.block import Dictionary, Page
    from trino_tpu.exec.serde import PageDeserializer, PageSerializer

    d = Dictionary()
    page = Page.from_pylists(
        [T.BIGINT, T.VARCHAR, T.DOUBLE, T.DATE],
        [[1, 2, None], ["a", None, "bb"], [1.5, None, -2.0],
         [10, None, 20]],
        dictionaries=[None, d, None, None])
    ser = PageSerializer()
    de = PageDeserializer()
    out = de.deserialize(ser.serialize(page))
    assert out.to_rows() == page.to_rows()
    # second page on the same stream: pool ships only the delta
    page2 = Page.from_pylists([T.BIGINT, T.VARCHAR, T.DOUBLE, T.DATE],
                              [[4], ["cc"], [0.0], [30]],
                              dictionaries=[None, d, None, None])
    blob1 = ser.serialize(page2)
    out2 = de.deserialize(blob1)
    assert out2.to_rows() == page2.to_rows()
    # codes decode through the RECEIVER-side pool built from deltas
    assert out2.blocks[1].dictionary is out.blocks[1].dictionary
