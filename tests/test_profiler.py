"""Compiled-program profiler: registry core, EXPLAIN ANALYZE VERBOSE,
system.runtime.kernels, query progress, the flight-recorder differ,
OTLP export, and the slow-query log."""

import json
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from trino_tpu import jit_stats
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runner import LocalQueryRunner, QueryResult
from trino_tpu.sql.analyzer import Session
from trino_tpu.telemetry import profiler
from trino_tpu.telemetry.profiler import (diff_profiles, instrument,
                                          validate_profile)


@pytest.fixture(autouse=True)
def _profiler_off():
    """Every test leaves the process-global profiler disabled — other
    suites assert zero-overhead behavior."""
    yield
    profiler.enable(False)


@pytest.fixture(scope="module")
def local_runner():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=4096)},
                            Session(catalog="tpch", schema="micro"))


# -- registry core ---------------------------------------------------------


def _fresh_kernel(name):
    from functools import partial

    @partial(jax.jit, static_argnames=("n",))
    def f(x, y, n):
        return (x * 2.0 + y).reshape(n, -1)

    return instrument(name, f, static_argnames=("n",))


def _entries_for(name):
    return [e for e in profiler.snapshot() if e["name"] == name]


def test_costs_recorded_once_per_compile():
    """One registry entry per (name, signature); repeat shapes execute
    the stored program — compiles stays 1 while calls grow — and the
    recorded compile wall / cost analysis are non-trivial."""
    f = _fresh_kernel("t_registry_core")
    x = jnp.arange(8, dtype=jnp.float32)
    y = jnp.ones(8, dtype=jnp.float32)
    profiler.enable()
    try:
        r1 = f(x, y, n=2)
        r2 = f(x, y, n=2)
        assert (jnp.asarray(r1) == jnp.asarray(r2)).all()
        entries = _entries_for("t_registry_core")
        assert len(entries) == 1
        e = entries[0]
        assert e["compiles"] == 1 and e["calls"] == 2
        assert e["compile_ms"] > 0 and e["trace_ms"] > 0
        assert e["flops"] > 0
        assert e["bytes_accessed"] > 0
        assert e["fallbacks"] == 0
        # a new static value is a DIFFERENT program -> second entry
        f(x, y, n=4)
        assert len(_entries_for("t_registry_core")) == 2
        # a new shape too
        f(jnp.arange(16, dtype=jnp.float32),
          jnp.ones(16, dtype=jnp.float32), n=2)
        assert len(_entries_for("t_registry_core")) == 3
    finally:
        profiler.enable(False)


def test_dynamic_python_scalar_does_not_fragment_registry():
    """A weak-typed python scalar argument keys by type, not value —
    jax compiles one program for it and so must the registry."""

    @jax.jit
    def g(x, s):
        return x * s

    w = instrument("t_weak_scalar", g)
    x = jnp.arange(4, dtype=jnp.float32)
    profiler.enable()
    try:
        assert float(w(x, 2.0)[2]) == 4.0
        assert float(w(x, 3.5)[2]) == 7.0
        assert len(_entries_for("t_weak_scalar")) == 1
        assert _entries_for("t_weak_scalar")[0]["compiles"] == 1
    finally:
        profiler.enable(False)


def test_profiling_off_is_zero_cost():
    """Disabled, the wrapper adds no registry entries, no extra jit
    traces, and only trivial call overhead over the bare jit product."""
    f = _fresh_kernel("t_zero_overhead")
    x = jnp.arange(8, dtype=jnp.float32)
    y = jnp.ones(8, dtype=jnp.float32)
    t0 = jit_stats.total_for("nonexistent")  # keep import honest
    assert t0 == 0
    before_traces = jit_stats.thread_total()
    f(x, y, n=2)  # first call traces once, exactly like bare jit
    assert jit_stats.thread_total() == before_traces
    # (the test kernel has no bump; assert via the registry instead)
    assert _entries_for("t_zero_overhead") == []
    # repeat calls: no traces, no registry, and dispatch wall within a
    # small factor of the bare jitted callable
    jitted = f.jit
    n = 300
    t0 = time.perf_counter()
    for _ in range(n):
        jitted(x, y, n=2)
    bare = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        f(x, y, n=2)
    wrapped = time.perf_counter() - t0
    assert _entries_for("t_zero_overhead") == []
    # generous bound: the off-path is one attribute check; anything
    # past 5x bare dispatch means profiling leaked into the hot path
    assert wrapped < bare * 5 + 0.05, (wrapped, bare)


def test_profiling_scopes_refcount():
    """A plain query's no-op scope exiting must not clobber a profiled
    scope still running on another thread (the scopes refcount)."""
    plain = profiler.profiling(False)
    verbose = profiler.profiling(True)
    plain.__enter__()
    verbose.__enter__()
    plain.__exit__(None, None, None)
    assert profiler.enabled(), "no-op scope exit disabled profiling"
    verbose.__exit__(None, None, None)
    assert not profiler.enabled()
    # sticky manual enable survives scope exits
    profiler.enable()
    scope = profiler.profiling(True)
    scope.__enter__()
    scope.__exit__(None, None, None)
    assert profiler.enabled()
    profiler.enable(False)
    assert not profiler.enabled()


def test_tracer_arguments_bypass_profiling():
    """A profiled kernel invoked inside another trace stages out
    inline — nothing recorded, results exact."""
    inner = _fresh_kernel("t_tracer_bypass")

    @jax.jit
    def outer(x, y):
        return inner(x, y, n=2).sum()

    profiler.enable()
    try:
        out = outer(jnp.arange(8, dtype=jnp.float32),
                    jnp.ones(8, dtype=jnp.float32))
        assert float(out) == float((jnp.arange(8) * 2.0 + 1).sum())
        assert _entries_for("t_tracer_bypass") == []
    finally:
        profiler.enable(False)


# -- EXPLAIN ANALYZE VERBOSE ----------------------------------------------


def _explain_text(res):
    return "\n".join(r[0] for r in res.rows)


def test_explain_analyze_verbose_local(local_runner):
    sql = ("explain analyze verbose select l_returnflag, "
           "sum(l_quantity) q from lineitem group by l_returnflag")
    text = _explain_text(local_runner.execute(sql))
    assert "flops" in text and "compile" in text
    assert "Kernels:" in text
    # plain EXPLAIN ANALYZE stays cost-free (profiler off)
    plain = _explain_text(local_runner.execute(
        "explain analyze select count(*) from region"))
    assert "Kernels:" not in plain


@pytest.fixture(scope="module")
def dist_runner():
    from trino_tpu.parallel.distributed import DistributedQueryRunner

    return DistributedQueryRunner(
        {"tpch": TpchConnector(page_rows=4096)},
        Session(catalog="tpch", schema="micro"),
        n_workers=2, desired_splits=4, broadcast_threshold=300.0)


@pytest.mark.parametrize("qid", [1, 3])
def test_explain_analyze_verbose_distributed(dist_runner, qid):
    """The acceptance surface: EXPLAIN ANALYZE VERBOSE on q1/q3
    distributed shows per-operator flops/bytes/compile-ms, and a
    repeat-shape run adds ZERO new compile entries."""
    from trino_tpu.resources.tpch_queries import TPCH_QUERIES

    sql = "explain analyze verbose " + TPCH_QUERIES[qid]
    text = _explain_text(dist_runner.execute(sql))
    assert "[cost " in text and "flops" in text, text
    assert "compile" in text
    assert "Kernels:" in text
    before = profiler.totals()
    text2 = _explain_text(dist_runner.execute(sql))
    after = profiler.totals()
    assert after["compiles"] == before["compiles"], \
        "repeat-shape VERBOSE run recompiled"
    assert "0 new, 0 compiles this run" in text2, text2


def test_system_runtime_kernels_sql(local_runner):
    # VERBOSE above populated the registry; the catalog serves it
    res = local_runner.execute(
        "select name, compiles, compile_ms, flops from "
        "system.runtime.kernels")
    assert res.rows, "kernels table empty after a profiled run"
    names = {r[0] for r in res.rows}
    assert "page_processor" in names
    for _name, compiles, compile_ms, _flops in res.rows:
        assert compiles >= 1
        assert compile_ms >= 0.0


# -- query progress --------------------------------------------------------


def test_progress_monotonic_unit():
    from trino_tpu.telemetry.progress import QueryProgress

    p = QueryProgress("q1", total_rows=100)
    seen = [p.fraction()]
    for _ in range(12):
        p.add_rows(17)  # overshoots the estimate deliberately
        seen.append(p.fraction())
    assert seen == sorted(seen), "progress moved backwards"
    assert seen[-1] == 1.0
    p.state = "FINISHED"
    assert p.fraction() == 1.0
    d = p.to_dict()
    assert d["rows_scanned"] == 204 and d["total_rows_estimate"] == 100


def test_progress_fed_by_execution(local_runner):
    from trino_tpu.telemetry.progress import QueryProgress

    p = QueryProgress("t_exec")
    res = local_runner.execute(
        "select count(*) from lineitem", progress=p)
    assert res.rows[0][0] > 0
    assert p.state == "FINISHED"
    assert p.rows_scanned > 0
    assert p.total_rows > 0, "connector statistics estimate missing"
    assert p.tasks_done == p.tasks_total > 0
    assert p.fraction() == 1.0


def test_protocol_live_query_info_serves_partial_stats():
    """GET /v1/query/{id} on a RUNNING query returns live state +
    progress instead of the old stats:null placeholder."""
    from trino_tpu.server.protocol import ProtocolServer

    started = threading.Event()
    release = threading.Event()

    class StubRunner:
        session = None

        def execute(self, sql, user=None, progress=None):
            if progress is not None:
                progress.state = "RUNNING"
                progress.total_rows = 10
                progress.add_rows(4)
            started.set()
            assert release.wait(10)
            return QueryResult(["c"], [], [(1,)])

    server = ProtocolServer(StubRunner(), port=0)
    try:
        doc = server.submit("select 1")
        qid = doc["id"]
        assert started.wait(10)
        info = server.query_info(qid)
        assert info["state"] in ("QUEUED", "RUNNING")
        assert info["stats"] is not None, "live query served no stats"
        assert info["stats"]["elapsed_ms"] >= 0
        prog = info["stats"]["progress"]
        assert prog["rows_scanned"] == 4
        assert prog["fraction"] == pytest.approx(0.4)
        release.set()
        deadline = time.time() + 10
        while time.time() < deadline:
            info = server.query_info(qid)
            if info and info.get("state") == "FINISHED":
                break
            time.sleep(0.02)
        assert info["state"] == "FINISHED"
    finally:
        release.set()
        server.stop()


# -- flight recorder -------------------------------------------------------


def _profile_doc(kernels):
    compiles = sum(k.get("compiles", 0) for k in kernels)
    compile_ms = sum(k.get("compile_ms", 0.0) for k in kernels)
    return {"version": 1, "role": "test", "kernels": kernels,
            "totals": {"programs": len(kernels), "compiles": compiles,
                       "compile_ms": compile_ms}}


def _kernel(name, key="k0", compiles=1, compile_ms=10.0, flops=100.0,
            bytes_accessed=1000.0):
    return {"name": name, "key": key, "compiles": compiles,
            "calls": 3, "trace_ms": 1.0, "compile_ms": compile_ms,
            "execute_ms": 1.0, "flops": flops,
            "bytes_accessed": bytes_accessed, "output_bytes": 0,
            "temp_bytes": 0, "argument_bytes": 0, "code_bytes": 0,
            "fallbacks": 0}


def test_differ_names_the_kernel_that_moved():
    old = _profile_doc([_kernel("join_probe"), _kernel("agg")])
    # synthetic regression: agg's bytes double AND it recompiled a new
    # shape; join untouched
    new = _profile_doc([
        _kernel("join_probe"),
        _kernel("agg", key="k0"),
        _kernel("agg", key="k1", bytes_accessed=3000.0),
    ])
    moved = diff_profiles(old, new)
    assert moved, "regression not detected"
    assert all(m["kernel"] == "agg" for m in moved), moved
    changes = {m["change"] for m in moved}
    assert "recompiled" in changes
    assert "bytes_accessed-grew" in changes
    # identical artifacts: clean diff
    assert diff_profiles(old, old) == []


def test_differ_flags_new_and_vanished_kernels():
    old = _profile_doc([_kernel("a")])
    new = _profile_doc([_kernel("b")])
    changes = {(m["kernel"], m["change"])
               for m in diff_profiles(old, new)}
    assert ("a", "vanished") in changes
    assert ("b", "new-kernel") in changes


def test_validate_profile_rejects_empty_and_disconnected():
    assert validate_profile({}) != []
    assert validate_profile({"kernels": []}) != []
    assert validate_profile(
        {"kernels": [_kernel("x", compiles=0, compile_ms=0.0)],
         "totals": {"compiles": 0, "compile_ms": 0.0}}) != []
    good = _profile_doc([_kernel("x")])
    assert validate_profile(good) == []
    # round-trips through JSON (the artifact is a file)
    assert validate_profile(json.loads(json.dumps(good))) == []


def test_profile_document_shape():
    f = _fresh_kernel("t_doc")
    profiler.enable()
    try:
        f(jnp.arange(8, dtype=jnp.float32),
          jnp.ones(8, dtype=jnp.float32), n=2)
    finally:
        profiler.enable(False)
    doc = profiler.profile_document("unit")
    assert validate_profile(doc) == []
    assert doc["role"] == "unit"
    assert any(k["name"] == "t_doc" for k in doc["kernels"])


# -- OTLP export -----------------------------------------------------------


class _FakeCollector:
    """Stdlib OTLP collector: captures POSTed bodies."""

    def __init__(self):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                outer.bodies.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

        self.bodies = []
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.endpoint = (f"http://127.0.0.1:"
                         f"{self.httpd.server_address[1]}/v1/traces")
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _spans():
    from trino_tpu.telemetry.tracing import Tracer

    t = Tracer(process="coordinator")
    with t.span("query") as root:
        with t.span("plan", parent=root):
            pass
    return t.finished()


def test_otlp_export_to_fake_collector():
    from trino_tpu.telemetry.tracing import export_otlp

    collector = _FakeCollector()
    try:
        spans = _spans()
        assert export_otlp(collector.endpoint, spans) is True
        assert len(collector.bodies) == 1
        body = collector.bodies[0]
        rs = body["resourceSpans"]
        otlp_spans = [s for r in rs
                      for sc in r["scopeSpans"] for s in sc["spans"]]
        assert len(otlp_spans) == len(spans)
        for s in otlp_spans:
            assert len(s["traceId"]) == 32
            assert len(s["spanId"]) == 16
            assert int(s["endTimeUnixNano"]) >= \
                int(s["startTimeUnixNano"])
        # exactly one root (no parentSpanId)
        assert sum("parentSpanId" not in s for s in otlp_spans) == 1
    finally:
        collector.stop()


def test_otlp_export_failures_are_silent():
    from trino_tpu.telemetry.tracing import export_otlp

    # refused connection, junk endpoint, empty input: never raises
    assert export_otlp("http://127.0.0.1:9/v1/traces", _spans()) is False
    assert export_otlp("not a url", _spans()) is False
    assert export_otlp("", _spans()) is False
    assert export_otlp("http://127.0.0.1:9/v1/traces", []) is False


# -- slow-query log --------------------------------------------------------


def test_slow_query_log_local():
    runner = LocalQueryRunner(
        {"tpch": TpchConnector(page_rows=4096)},
        Session(catalog="tpch", schema="micro",
                properties={"slow_query_log_threshold": 1e-9}))
    runner.execute("select count(*) from region")
    last = runner.event_manager.history(1)[-1]
    slow = (last.stats or {}).get("slow_query")
    assert slow is not None, "slow-query record missing from event"
    assert slow["wall_ms"] > 0
    assert slow["threshold_s"] == 1e-9
    # surfaced in system.runtime.queries history (the `slow` column)
    res = runner.execute(
        "select query, slow from system.runtime.queries "
        "where state = 'FINISHED'")
    flagged = [r for r in res.rows if r[1] is not None]
    assert flagged, "slow column empty in system.runtime.queries"
    assert "wall=" in flagged[0][1]


def test_fast_queries_not_flagged(local_runner):
    local_runner.execute("select count(*) from region")
    last = local_runner.event_manager.history(1)[-1]
    assert "slow_query" not in (last.stats or {})
