"""Client protocol: POST /v1/statement + nextUri paging + CLI.

Reference analog: TestQueryResource / the StatementClientV1 polling
contract — submit, follow nextUri, typed JSON rows, error propagation.
"""

import pytest

from trino_tpu.client import Client
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.server.protocol import ProtocolServer
from trino_tpu.sql.analyzer import Session
from trino_tpu.types import TrinoError


@pytest.fixture(scope="module")
def server():
    runner = LocalQueryRunner({"tpch": TpchConnector(page_rows=4096)},
                              Session(catalog="tpch", schema="micro"))
    srv = ProtocolServer(runner, page_size=10).start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def client(server):
    return Client(server.uri)


def test_simple_query(client):
    res = client.execute("select count(*) c from orders")
    assert res.column_names == ["c"]
    assert res.rows == [[1500]]
    assert res.stats["state"] == "FINISHED"


def test_typed_values(client):
    res = client.execute(
        "select o_orderdate, o_totalprice from orders "
        "where o_orderkey = 1")
    [[date, price]] = res.rows
    assert isinstance(date, str) and date.count("-") == 2  # ISO date
    assert isinstance(price, str)  # decimals travel as strings
    assert res.columns[0]["type"] == "date"


def test_next_uri_paging(client):
    # page_size=10 forces multiple nextUri hops for 25 nations
    res = client.execute("select n_name from nation order by n_name")
    assert len(res.rows) == 25
    assert res.rows == sorted(res.rows)


def test_error_propagates(client):
    with pytest.raises(TrinoError) as exc:
        client.execute("select no_such_column from orders")
    assert "no_such_column" in str(exc.value)


def test_final_stats_exposed(client):
    res = client.execute(
        "select l_returnflag, count(*) from lineitem group by 1")
    assert "memory" in res.stats
    assert res.stats["memory"]["peak_bytes"] > 0


def test_session_statements(client):
    res = client.execute("show session")
    names = [r[0] for r in res.rows]
    assert "enable_dynamic_filtering" in names


def test_cli_embedded(capsys):
    from trino_tpu.cli import main

    rc = main(["--embedded", "--catalog", "tpch", "--schema", "micro",
               "-e", "select count(*) c from region"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "c" in out and "5" in out and "(1 row)" in out


def test_cli_against_server(server, capsys):
    from trino_tpu.cli import main

    rc = main(["--server", server.uri,
               "-e", "select 1 one, 'x' tag"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "one" in out and "tag" in out


def test_info_endpoints(server):
    import json
    import urllib.request

    with urllib.request.urlopen(server.uri + "/v1/info") as r:
        info = json.loads(r.read())
    assert info["coordinator"] is True
