"""Plan/result caching + admission batching (round 13).

The invalidation matrix is the heart: identical re-submission must HIT
(and perform zero jit traces — the compiled-pipeline reuse is the whole
point), while DDL on a referenced table, a connector version bump
(write), and a differing session fingerprint must all MISS and
recompute correct answers.  Batched execution must be byte-equal to
serial, the result cache must stay inside its memory-governance
budget, and every counter must be scrapeable through the PR 6 metrics
surface (SQL over system.runtime.metrics included)."""

import threading
import time

import pytest

from trino_tpu import jit_stats
from trino_tpu.cache import (QueryCache, ResultCache, is_deterministic,
                             normalize_statement, statement_catalogs)
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql import ast
from trino_tpu.sql.analyzer import Session
from trino_tpu.sql.parser import parse_statement


def _mem_runner(**kwargs):
    return LocalQueryRunner({"memory": MemoryConnector()},
                            Session(catalog="memory", schema="default"),
                            **kwargs)


@pytest.fixture(scope="module")
def runner():
    r = _mem_runner()
    r.execute("create table t (k bigint, v bigint)")
    r.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    return r


# ---------------------------------------------------------------------------
# shape normalization


def test_normalize_parameterizes_literals():
    a = parse_statement("select v from t where k = 5 and v > 1.5")
    b = parse_statement("select v from t where k = 9 and v > 2.5")
    c = parse_statement("select v from t where k = 5 or v > 1.5")
    sa, la = normalize_statement(a)
    sb, lb = normalize_statement(b)
    sc, _ = normalize_statement(c)
    assert sa == sb                       # literals out -> same shape
    assert la != lb                       # the vectors differ
    assert la == (("long", 5), ("decimal", "1.5"))
    assert sa != sc                       # AND vs OR is structural
    assert hash(sa) == hash(sb)           # usable as a dict key


def test_normalize_keeps_type_distinctions():
    a = parse_statement("select * from t where k = 5")
    b = parse_statement("select * from t where k = 5.0")
    _, la = normalize_statement(a)
    _, lb = normalize_statement(b)
    # 5 types as bigint, 5.0 as decimal(2,1): the kind tag keeps their
    # plans (and result-cache entries) apart
    assert la[0][0] == "long" and lb[0][0] == "decimal"


def test_statement_catalogs_resolution():
    session = Session(catalog="memory", schema="default")
    one = parse_statement("select * from t")
    two = parse_statement(
        "select * from tpch.tiny.orders o join t on o.o_orderkey = t.k")
    with_q = parse_statement(
        "with w as (select 1 x) select * from w")
    assert statement_catalogs(one, session) == {"memory"}
    assert statement_catalogs(two, session) == {"tpch", "memory"}
    # a WITH alias over-approximates to the session catalog — extra
    # versions only cost misses, never staleness
    assert statement_catalogs(with_q, session) == {"memory"}


def test_is_deterministic():
    assert is_deterministic(parse_statement("select k from t"))
    assert not is_deterministic(parse_statement("select random()"))


# ---------------------------------------------------------------------------
# plan cache: hit path + invalidation matrix


def test_repeat_query_hits_plan_cache_with_zero_traces(runner):
    sql = "select sum(v) s from t where k >= 2"
    first = runner.execute(sql)
    assert first.rows == [(50,)]
    hits0 = runner.query_cache.plans.hits
    before = jit_stats.total()
    again = runner.execute(sql)
    assert again.rows == first.rows
    assert again.stats.get("plan_cache") == "hit"
    assert runner.query_cache.plans.hits == hits0 + 1
    # the compiled-pipeline reuse claim, machine-checked: a repeat
    # statement must not trace ANY kernel
    assert jit_stats.total() == before


def test_write_invalidates_plan_cache(runner):
    sql = "select sum(v) s from t where k >= 0"
    assert runner.execute(sql).rows == [(60,)]
    inv0 = runner.query_cache.plans.invalidations
    runner.execute("insert into t values (4, 40)")
    res = runner.execute(sql)
    assert res.rows == [(100,)]           # recomputed, not stale
    assert res.stats.get("plan_cache") != "hit"
    assert runner.query_cache.plans.invalidations > inv0
    runner.execute("delete from t where k = 4")
    assert runner.execute(sql).rows == [(60,)]


def test_ddl_on_referenced_table_invalidates(runner):
    runner.execute("create table d (x bigint)")
    runner.execute("insert into d values (7)")
    sql = "select count(*) c from d"
    assert runner.execute(sql).rows == [(1,)]
    assert runner.execute(sql).stats.get("plan_cache") == "hit"
    runner.execute("drop table d")
    runner.execute("create table d (x bigint)")
    res = runner.execute(sql)
    assert res.stats.get("plan_cache") != "hit"
    assert res.rows == [(0,)]             # the NEW (empty) table


def test_session_fingerprint_differs(runner):
    sql = "select max(v) m from t"
    runner.execute(sql)
    assert runner.execute(sql).stats.get("plan_cache") == "hit"
    runner.execute("set session desired_splits = 3")
    try:
        res = runner.execute(sql)
        assert res.stats.get("plan_cache") != "hit"   # fp moved -> miss
        assert res.rows == [(30,)]
        assert runner.execute(sql).stats.get("plan_cache") == "hit"
    finally:
        runner.session.properties.pop("desired_splits", None)


def test_plan_cache_disabled_by_property():
    r = _mem_runner()
    r.execute("create table t (k bigint)")
    r.execute("insert into t values (1)")
    r.execute("set session plan_cache_enabled = false")
    sql = "select count(*) c from t"
    r.execute(sql)
    res = r.execute(sql)
    assert res.stats.get("plan_cache") is None
    assert r.query_cache.plans.hits == 0


def test_system_catalog_uncacheable(runner):
    sql = "select count(*) c from system.runtime.metrics"
    runner.execute(sql)
    res = runner.execute(sql)
    # live catalog: no snapshot version -> never cached
    assert res.stats.get("plan_cache") is None
    assert runner.query_cache.cache_key(
        runner.query_cache.parse(sql, runner.session),
        runner.session) is None


# ---------------------------------------------------------------------------
# result cache


def test_result_cache_hit_and_write_invalidation(runner):
    runner.execute("set session result_cache_enabled = true")
    try:
        sql = "select sum(v) s from t where k <= 2"
        a = runner.execute(sql)
        b = runner.execute(sql)
        assert b.stats.get("result_cache") == "hit"
        assert b.rows == a.rows == [(30,)]
        # rows returned on a hit are a fresh list: caller mutation must
        # not corrupt the cached copy
        b.rows.append(("junk",))
        assert runner.execute(sql).rows == [(30,)]
        runner.execute("insert into t values (0, 5)")
        c = runner.execute(sql)
        assert c.stats.get("result_cache") != "hit"
        assert c.rows == [(35,)]
        runner.execute("delete from t where k = 0")
    finally:
        runner.session.properties.pop("result_cache_enabled", None)


def test_result_cache_memory_bounded():
    rc = ResultCache(max_bytes=8_192)
    rows = [(i, "x" * 40) for i in range(40)]
    for i in range(12):
        rc.store(("shape", i), ["a", "b"], None, list(rows))
    assert rc.evictions > 0
    assert rc.reserved_bytes <= 8_192
    # oversized single entry is skipped, not force-fitted
    rc.store(("big",), ["a"], None, [("y" * 200,)] * 400)
    assert rc.lookup(("big",)) is None
    assert rc.reserved_bytes <= 8_192


# ---------------------------------------------------------------------------
# admission batching


def test_execute_batch_byte_equal_and_coalesced(runner):
    sqls = ["select sum(v) s from t where k >= 1",
            "select sum(v) s from t where k >= 2",
            "select sum(v) s from t where k >= 1",   # identical: coalesces
            "select count(*) c from t"]              # shape diverges
    serial = [runner.execute(s) for s in sqls]
    co0 = runner.query_cache.coalesced
    batch = runner.execute_batch(sqls)
    assert [r.rows for r in batch] == [r.rows for r in serial]
    assert runner.query_cache.coalesced == co0 + 1


def test_execute_batch_failure_is_per_statement(runner):
    out = runner.execute_batch(["select sum(v) s from t",
                                "select no_such_column from t"])
    assert out[0].rows == [(60,)]
    assert isinstance(out[1], Exception)


def test_protocol_batch_formation_and_fallback():
    """Deterministic batch shaping: a backlog of same-shape statements
    drains as ONE batch under one admission slot; a divergent shape is
    left for its own drain (the byte-equal serial fallback)."""
    from trino_tpu.resource_groups import ResourceGroupManager
    from trino_tpu.server.protocol import ProtocolServer, _QueryState

    rg = ResourceGroupManager.from_config(
        {"groups": [{"name": "global", "max_concurrency": 4}]})
    r = _mem_runner(resource_groups=rg)
    r.execute("create table t (k bigint, v bigint)")
    r.execute("insert into t values (1, 10), (2, 20)")
    srv = ProtocolServer(r)   # not started: drive internals directly
    admitted0 = rg.roots[0].total_admitted   # setup DDL admitted too
    try:
        texts = ["select sum(v) s from t where k >= 1",
                 "select sum(v) s from t where k >= 2",
                 "select count(*) c from t"]
        states = []
        for i, sql in enumerate(texts):
            q = _QueryState(f"q{i}", sql)
            q.shape = r.query_cache.parse(sql, r.session).shape
            states.append(q)
            srv._backlog.append(q)
        srv._drain_batch()
        # first two share a shape -> one batch; the third stayed queued
        assert states[0].state == "FINISHED"
        assert states[1].state == "FINISHED"
        assert states[2].state == "QUEUED"
        srv._drain_batch()
        assert states[2].state == "FINISHED"
        assert states[0].result.rows == [(30,)]
        assert states[1].result.rows == [(20,)]
        assert states[2].result.rows == [(2,)]
        # 2 admission slots covered 3 queries: the batch amortization
        assert rg.roots[0].total_admitted - admitted0 == 2
    finally:
        srv.stop()


def test_protocol_user_header_routes_resource_group():
    from trino_tpu.client import Client
    from trino_tpu.resource_groups import ResourceGroupManager
    from trino_tpu.server.protocol import ProtocolServer

    rg = ResourceGroupManager.from_config({"groups": [
        {"name": "tenants", "user": "tenant-.*", "max_concurrency": 4},
        {"name": "global", "max_concurrency": 4}]})
    r = _mem_runner(resource_groups=rg)
    r.execute("create table t (k bigint)")
    r.execute("insert into t values (1)")
    srv = ProtocolServer(r).start()
    try:
        res = Client(srv.uri, user="tenant-3").execute(
            "select count(*) c from t")
        assert res.rows == [[1]]
        tenants = {name: adm for name, adm, _, _
                   in rg.counter_stats()}
        # the tenant header routed admission to the tenants group (the
        # setup DDL ran as the session user through "global")
        assert tenants["tenants"] == 1
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# protocol eviction + metrics surface


def test_protocol_eviction_timer_is_deterministic():
    """An abandoned (never-polled) finished query must evict on the
    TIMER — no further traffic required — so _QueryState cannot grow
    unbounded under sustained load."""
    import json
    import urllib.request

    from trino_tpu.server.protocol import ProtocolServer

    r = _mem_runner()
    srv = ProtocolServer(r, query_ttl=0.6, evict_interval=0.15).start()
    try:
        req = urllib.request.Request(srv.uri + "/v1/statement",
                                     data=b"select 1", method="POST")
        doc = json.loads(urllib.request.urlopen(req).read())
        deadline = time.time() + 10
        while doc["id"] in srv.queries and time.time() < deadline:
            time.sleep(0.1)   # no polls, no submissions: timer only
        assert doc["id"] not in srv.queries
        assert len(srv.queries) == 0
    finally:
        srv.stop()


def test_cache_counters_via_metrics_and_sql(runner):
    fams = {f["name"] for f in runner.metrics_families()}
    assert "trino_plan_cache_total" in fams
    assert "trino_result_cache_total" in fams
    assert "trino_processor_cache_total" in fams
    assert "trino_admission_batches_total" in fams
    rows = runner.execute(
        "select name, value from system.runtime.metrics "
        "where name like 'trino_plan_cache%'").rows
    assert rows, "cache counters must be queryable via SQL"
    total = {n for n, _v in rows}
    assert "trino_plan_cache_total" in total


def test_resource_group_counters_exported():
    from trino_tpu.resource_groups import ResourceGroupManager

    rg = ResourceGroupManager.from_config(
        {"groups": [{"name": "global", "max_concurrency": 2}]})
    r = _mem_runner(resource_groups=rg)
    r.execute("create table t (k bigint)")
    r.execute("select count(*) from t")
    fams = {f["name"]: f for f in r.metrics_families()}
    assert "trino_resource_group_admissions_total" in fams
    assert "trino_resource_group_queue_peak" in fams
    samples = fams["trino_resource_group_admissions_total"]["samples"]
    admitted = [v for lbl, v in samples
                if lbl.get("kind") == "admitted"]
    assert admitted and admitted[0] >= 1


def test_session_properties_registered():
    from trino_tpu import session_properties as SP

    for name, type_ in (("plan_cache_enabled", "boolean"),
                        ("plan_cache_entries", "integer"),
                        ("result_cache_enabled", "boolean"),
                        ("admission_batching_enabled", "boolean"),
                        ("admission_batch_max", "integer")):
        prop = SP.REGISTRY[name]
        assert prop.type == type_
    props = {}
    SP.set_property(props, "admission_batch_max", "8")
    assert props["admission_batch_max"] == 8
    with pytest.raises(Exception):
        SP.set_property(props, "admission_batch_max", "1")


def test_plan_cache_lru_bound(runner):
    runner.execute("set session plan_cache_entries = 4")
    try:
        for i in range(8):
            runner.execute(f"select sum(v) s from t where k > {i}")
        assert len(runner.query_cache.plans) <= 4
        assert runner.query_cache.plans.evictions >= 4
    finally:
        runner.session.properties.pop("plan_cache_entries", None)


def test_result_cache_is_user_scoped_and_rechecks_acl():
    """Cached rows must never cross a tenant ACL: the key is
    user-scoped AND every hit re-enforces SELECT, so a denied user can
    neither hit another user's entry nor keep reading after a
    revocation."""
    from trino_tpu.security import (AccessDeniedError,
                                    RuleBasedAccessControl, TableRule)

    acl = RuleBasedAccessControl([
        TableRule(user="alice", privileges=["SELECT", "INSERT",
                                            "OWNERSHIP"]),
        TableRule(user="trino", privileges=["SELECT", "INSERT",
                                            "OWNERSHIP"]),
    ])
    r = LocalQueryRunner({"memory": MemoryConnector()},
                         Session(catalog="memory", schema="default"),
                         access_control=acl)
    r.execute("create table t (k bigint)")
    r.execute("insert into t values (1)")
    r.execute("set session result_cache_enabled = true")
    sql = "select count(*) c from t"
    assert r.execute(sql, user="alice").rows == [(1,)]
    assert r.execute(sql, user="alice").stats.get(
        "result_cache") == "hit"
    # bob shares the statement text but not the ACL: user-scoped key
    # -> no hit, and the execution path denies at the table check
    with pytest.raises(AccessDeniedError):
        r.execute(sql, user="bob")
    # revocation takes effect on the next HIT, not at the next miss
    acl.rules = [rule for rule in acl.rules if rule.user != "alice"]
    with pytest.raises(AccessDeniedError):
        r.execute(sql, user="alice")


def test_execute_batch_never_coalesces_writes(runner):
    """Identical INSERT texts in one batch must each run: coalescing is
    reserved for deterministic plain queries."""
    runner.execute("create table w (k bigint)")
    try:
        out = runner.execute_batch(["insert into w values (1)",
                                    "insert into w values (1)"])
        assert not isinstance(out[0], Exception)
        assert not isinstance(out[1], Exception)
        assert runner.execute("select count(*) c from w").rows == [(2,)]
    finally:
        runner.execute("drop table w")


def test_concurrent_repeat_queries_share_processors(runner):
    """Concurrent executions of cached plans share PageProcessor
    instances — the lock added for sharing must not corrupt results."""
    sql = "select sum(v) s from t where k >= 1"
    expect = runner.execute(sql).rows
    out = [None] * 6

    def go(i):
        out[i] = runner.execute(sql).rows

    threads = [threading.Thread(target=go, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r == expect for r in out)
