"""Access control, event listeners, resource groups, config scopes.

Reference analog: ``spi/security``/``spi/eventlistener`` behaviors,
``execution/resourcegroups/TestInternalResourceGroup``, and the
``etc/``-directory bootstrap of ``server/Server.java``.
"""

import json
import threading
import time

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.events import EventListener
from trino_tpu.resource_groups import (QueryQueueFullError,
                                       ResourceGroupManager,
                                       ResourceGroupSpec)
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.security import (AccessDeniedError, RuleBasedAccessControl,
                                TableRule)
from trino_tpu.sql.analyzer import Session


def make_runner(**kw):
    return LocalQueryRunner(
        {"tpch": TpchConnector(page_rows=2048), "mem": MemoryConnector()},
        Session(catalog="tpch", schema="micro", user=kw.pop("user", "alice")),
        **kw)


# -- access control ----------------------------------------------------

ANALYST_RULES = RuleBasedAccessControl([
    TableRule(user="alice", catalog="tpch", privileges=["SELECT"]),
    TableRule(user="alice", catalog="mem", privileges=["OWNERSHIP",
                                                       "SELECT",
                                                       "INSERT"]),
    TableRule(user="bob", catalog="tpch", table="nation",
              privileges=["SELECT"], columns=["n_name", "n_regionkey"]),
])


def test_select_allowed_and_denied():
    r = make_runner(access_control=ANALYST_RULES)
    assert r.execute("select count(*) from nation").rows == [(25,)]
    r2 = make_runner(access_control=ANALYST_RULES, user="carol")
    with pytest.raises(AccessDeniedError):
        r2.execute("select count(*) from nation")


def test_column_level_rules():
    r = make_runner(access_control=ANALYST_RULES, user="bob")
    assert r.execute("select n_name from nation limit 1").rows
    with pytest.raises(AccessDeniedError):
        r.execute("select n_comment from nation limit 1")
    with pytest.raises(AccessDeniedError):
        r.execute("select count(*) from region")


def test_write_privileges():
    r = make_runner(access_control=ANALYST_RULES)
    r.execute("create table mem.default.t1 (x bigint)")
    r.execute("insert into mem.default.t1 values (1)")
    with pytest.raises(AccessDeniedError):
        r.execute("create table tpch.micro.nope (x bigint)")


def test_query_user_gate():
    ac = RuleBasedAccessControl([TableRule(privileges=["SELECT"])],
                                query_users="alice|bob")
    r = make_runner(access_control=ac, user="mallory")
    with pytest.raises(AccessDeniedError):
        r.execute("select 1")


# -- event listeners ---------------------------------------------------

class Recorder(EventListener):
    def __init__(self):
        self.created = []
        self.completed = []

    def query_created(self, e):
        self.created.append(e)

    def query_completed(self, e):
        self.completed.append(e)


def test_events_success_and_failure():
    rec = Recorder()
    r = make_runner(event_listeners=[rec])
    r.execute("select count(*) from nation")
    assert len(rec.created) == 1 and len(rec.completed) == 1
    done = rec.completed[0]
    assert done.state == "FINISHED" and done.output_rows == 1
    assert done.user == "alice" and done.wall_ms >= 0
    with pytest.raises(Exception):
        r.execute("select * from no_such_table")
    assert rec.completed[1].state == "FAILED"
    assert rec.completed[1].error_message


# -- resource groups ---------------------------------------------------

def test_resource_group_concurrency_and_queue():
    mgr = ResourceGroupManager([ResourceGroupSpec(
        "global", max_concurrency=1, max_queued=1)])
    g = mgr.select("alice")
    g.acquire()
    # one more fits the queue but times out waiting; the next rejects
    t0 = time.time()
    with pytest.raises(QueryQueueFullError):
        g.acquire(timeout=0.1)
    assert time.time() - t0 >= 0.1

    results = []

    def waiter():
        with g.run(timeout=5):
            results.append("ran")

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.05)
    g.release()
    th.join(timeout=5)
    assert results == ["ran"]


def test_resource_group_selectors_and_hierarchy():
    mgr = ResourceGroupManager.from_config({"groups": [
        {"name": "admin", "user": "admin", "max_concurrency": 5},
        {"name": "global", "max_concurrency": 2, "subgroups": [
            {"name": "etl", "user": "etl_.*", "max_concurrency": 1},
        ]},
    ]})
    assert mgr.select("admin").name == "admin"
    assert mgr.select("etl_nightly").name == "global.etl"
    assert mgr.select("alice").name == "global"
    # parent cap applies transitively
    etl = mgr.select("etl_nightly")
    alice = mgr.select("alice")
    etl.acquire()
    alice.acquire()
    with pytest.raises(QueryQueueFullError):
        mgr.select("etl_other").acquire(timeout=0.05)


def test_runner_admission():
    mgr = ResourceGroupManager([ResourceGroupSpec(
        "global", max_concurrency=1, max_queued=0)])
    r = make_runner(resource_groups=mgr)
    assert r.execute("select 1").rows == [(1,)]  # released after each


# -- config scopes -----------------------------------------------------

def test_load_etc(tmp_path):
    from trino_tpu.config import load_etc

    (tmp_path / "catalog").mkdir()
    (tmp_path / "config.properties").write_text(
        "default-catalog=tiny_tpch\n")
    (tmp_path / "catalog" / "tiny_tpch.properties").write_text(
        "connector.name=tpch\npage_rows=1024\n")
    (tmp_path / "catalog" / "scratch.properties").write_text(
        "connector.name=memory\n")
    (tmp_path / "access-control.json").write_text(json.dumps({
        "tables": [{"user": ".*", "privileges": ["SELECT"]}]}))
    (tmp_path / "resource-groups.json").write_text(json.dumps({
        "groups": [{"name": "global", "max_concurrency": 4}]}))

    cfg = load_etc(str(tmp_path))
    assert set(cfg.connectors) == {"tiny_tpch", "scratch"}
    assert cfg.default_catalog == "tiny_tpch"
    assert cfg.connectors["tiny_tpch"].page_rows == 1024
    assert cfg.resource_groups is not None

    r = LocalQueryRunner(cfg.connectors,
                         Session(catalog="tiny_tpch", schema="micro"),
                         access_control=cfg.access_control,
                         resource_groups=cfg.resource_groups)
    assert r.execute("select count(*) from region").rows == [(5,)]
    with pytest.raises(AccessDeniedError):
        r.execute("create table scratch.default.x (a bigint)")
