"""Session properties + EXPLAIN ANALYZE stats (reference analog:
SystemSessionProperties + ExplainAnalyzeOperator tests)."""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture()
def runner():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=4096)},
                            Session(catalog="tpch", schema="micro"))


def test_set_show_session(runner):
    rows = runner.execute("show session").rows
    names = [r[0] for r in rows]
    assert "task_concurrency" in names and "desired_splits" in names
    runner.execute("set session desired_splits = 2")
    rows = dict((r[0], r[1]) for r in runner.execute("show session").rows)
    assert rows["desired_splits"] == "2"
    # invalid property
    with pytest.raises(Exception):
        runner.execute("set session no_such_prop = 1")
    with pytest.raises(Exception):
        runner.execute("set session task_concurrency = 0")


def test_session_property_affects_execution(runner):
    runner.execute("set session desired_splits = 1")
    assert runner.execute("select count(*) from nation").rows == [(25,)]


def test_explain_analyze(runner):
    res = runner.execute(
        "explain analyze select n_regionkey, count(*) from nation "
        "group by n_regionkey")
    text = "\n".join(r[0] for r in res.rows)
    assert "Aggregation" in text
    assert "TableScanOperator" in text
    assert "rows" in text and "ms" in text


def test_join_distribution_type_session():
    from trino_tpu.parallel.distributed import DistributedQueryRunner

    conn = TpchConnector(page_rows=4096)
    s = Session(catalog="tpch", schema="micro")
    s.properties["join_distribution_type"] = "PARTITIONED"
    d = DistributedQueryRunner({"tpch": conn}, s, n_workers=2)
    plan = d.explain("select count(*) from nation, region "
                     "where n_regionkey = r_regionkey")
    assert "hash" in plan
    s2 = Session(catalog="tpch", schema="micro")
    s2.properties["join_distribution_type"] = "BROADCAST"
    d2 = DistributedQueryRunner({"tpch": conn}, s2, n_workers=2)
    plan2 = d2.explain("select count(*) from nation, region "
                       "where n_regionkey = r_regionkey")
    assert "broadcast" in plan2


def test_ntile_ignores_padding(runner):
    rows = runner.execute(
        "select ntile(2) over (order by n_nationkey) nt from nation").rows
    counts = {}
    for (v,) in rows:
        counts[v] = counts.get(v, 0) + 1
    assert counts == {1: 13, 2: 12}


def test_explain_ctas_does_not_create_table():
    from trino_tpu.connectors.memory import MemoryConnector

    r = LocalQueryRunner({"memory": MemoryConnector()},
                         Session(catalog="memory", schema="default"))
    r.execute("explain create table t1 as select 1 x")
    # planning must not have created t1
    res = r.execute("create table t1 as select 1 x")
    assert res.rows == [(1,)]


def test_session_property_case_insensitive(runner):
    runner.execute("set session join_distribution_type = 'broadcast'")
    vals = dict((r[0], r[1])
                for r in runner.execute("show session").rows)
    assert vals["join_distribution_type"] == "BROADCAST"
