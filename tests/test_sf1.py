"""SF1-scale correctness (slow; run with ``pytest -m slow``).

Reference analog: the benchto/TPC-H suites run at real scale factors —
these tests run q1/q3/q6/q13/q18 at SF1 (6M lineitem rows) against
expected values computed ONCE by a sqlite oracle over the same generated
data (``tests/sf1_expected.py``; regenerate with the script in that
file's history if the generator changes).  This is the scale gate the
round-2 verdict asked for: it exercises chunked join expansion, the
bounded sort, and multi-page aggregation state at sizes where padded
static shapes actually matter.
"""

import pytest

from sf1_expected import EXPECTED
from test_tpch_oracle import assert_same
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.resources.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=1 << 16)},
                            Session(catalog="tpch", schema="sf1"),
                            desired_splits=8)


@pytest.mark.parametrize("qid", sorted(EXPECTED))
def test_sf1_query_matches_oracle(qid, runner):
    sql = TPCH_QUERIES[qid]
    res = runner.execute(sql)
    assert_same(res, EXPECTED[qid], ordered="order by" in sql.lower())


def test_sf1_q18_spills_under_low_cap(runner):
    """VERDICT r2 #3 done-criterion: an SF1 q18 run completes under an
    artificially low memory cap with spill events recorded."""
    sql = TPCH_QUERIES[18]
    baseline = runner.execute(sql)
    peak = baseline.stats["memory"]["peak_bytes"]
    session = Session(catalog="tpch", schema="sf1")
    session.properties["query_max_memory_bytes"] = max(peak // 2, 64 << 20)
    session.properties["spill_enabled"] = True
    capped = LocalQueryRunner({"tpch": TpchConnector(page_rows=1 << 16)},
                              session, desired_splits=8).execute(sql)
    assert capped.rows == baseline.rows
    assert capped.stats["memory"]["spill_events"] > 0
