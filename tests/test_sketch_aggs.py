"""Sketch aggregates: HLL approx_distinct + DDSketch approx_percentile.

Reference analog: TestApproximateCountDistinct / TestApproxPercentile —
error-bounded estimates, mergeability across partial/final steps and
exchanges (the rewrite lowers sketches onto ordinary distributed
group-bys, so distribution MUST NOT change the answer), NULL handling.
"""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.block import Block, Page
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.parallel.distributed import DistributedQueryRunner
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture(scope="module")
def tpch():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=2048)},
                            Session(catalog="tpch", schema="micro"))


def test_error_bound_at_1m_distincts():
    """m=2048 registers -> standard error ~2.3%; 1M distinct values
    must estimate within 3 sigma."""
    mem = MemoryConnector()
    r = LocalQueryRunner({"mem": mem},
                         Session(catalog="mem", schema="default"))
    r.execute("create table big (x bigint)")
    data = mem.tables[("default", "big")]
    n = 1_000_000
    for lo in range(0, n, 250_000):
        vals = np.arange(lo, lo + 250_000, dtype=np.int64)
        data.pages.append(Page([Block(T.BIGINT, vals)], len(vals)))
    [(est,)] = r.execute("select approx_distinct(x) from big").rows
    assert abs(est - n) / n < 0.07, est
    # repeated values don't inflate the estimate
    [(est2,)] = r.execute(
        "select approx_distinct(x % 1000) from big").rows
    assert abs(est2 - 1000) / 1000 < 0.10, est2


def test_estimates_close_to_exact(tpch):
    pairs = [
        ("approx_distinct(l_orderkey)", "count(distinct l_orderkey)"),
        ("approx_distinct(l_partkey)", "count(distinct l_partkey)"),
        ("approx_distinct(l_shipmode)", "count(distinct l_shipmode)"),
    ]
    for approx, exact in pairs:
        [(a,)] = tpch.execute(f"select {approx} from lineitem").rows
        [(e,)] = tpch.execute(f"select {exact} from lineitem").rows
        assert abs(a - e) <= max(3, 0.1 * e), (approx, a, e)


def test_small_cardinalities_near_exact(tpch):
    """Small-range correction: tiny cardinalities estimate exactly."""
    [(a,)] = tpch.execute(
        "select approx_distinct(l_returnflag) from lineitem").rows
    assert a == 3
    [(b,)] = tpch.execute(
        "select approx_distinct(n_regionkey) from nation").rows
    assert b == 5


def test_merges_identically_across_exchange(tpch):
    """The defining mergeability property: partial/final split and hash
    exchanges must not change the estimate AT ALL (register max is
    order- and partition-independent)."""
    sql = ("select l_returnflag, approx_distinct(l_suppkey) "
           "from lineitem group by l_returnflag")
    local = sorted(tpch.execute(sql).rows)
    dist = DistributedQueryRunner(
        {"tpch": TpchConnector(page_rows=2048)},
        Session(catalog="tpch", schema="micro"), n_workers=3,
        desired_splits=8)
    assert sorted(dist.execute(sql).rows) == local


def test_nulls_and_mixing(tpch):
    rows = tpch.execute(
        "select approx_distinct(cast(null as bigint)), count(*) "
        "from orders").rows
    assert rows == [(0, 1500)]
    # combines with decomposable aggregates in one grouping
    rows = tpch.execute(
        "select l_linestatus, approx_distinct(l_orderkey), count(*), "
        "sum(l_quantity), max(l_shipdate) from lineitem "
        "group by l_linestatus order by 1").rows
    exact = tpch.execute(
        "select l_linestatus, count(distinct l_orderkey), count(*), "
        "sum(l_quantity), max(l_shipdate) from lineitem "
        "group by l_linestatus order by 1").rows
    for got, exp in zip(rows, exact):
        assert got[0] == exp[0] and got[2:] == exp[2:]
        assert abs(got[1] - exp[1]) <= 0.1 * exp[1]


def test_percentile_relative_error(tpch):
    """DDSketch contract: ~1% RELATIVE error at any percentile."""
    for p in (0.1, 0.5, 0.9, 0.99):
        [(a,)] = tpch.execute(
            f"select approx_percentile(l_extendedprice, {p}) "
            "from lineitem").rows
        # exact percentile via sorted offset
        [(n,)] = tpch.execute(
            "select count(*) from lineitem").rows
        k = max(0, int(np.ceil(p * n)) - 1)
        [(e,)] = tpch.execute(
            "select l_extendedprice from lineitem "
            f"order by l_extendedprice offset {k} limit 1").rows
        assert abs(float(a) - float(e)) / float(e) < 0.015, (p, a, e)


def test_percentile_grouped_and_typed(tpch):
    rows = tpch.execute(
        "select l_returnflag, approx_percentile(l_quantity, 0.5) "
        "from lineitem group by l_returnflag order by 1").rows
    assert len(rows) == 3
    for _, v in rows:
        assert 20 <= float(v) <= 30  # quantity uniform 1..50
    # integer argument returns an integer
    [(v,)] = tpch.execute(
        "select approx_percentile(o_custkey, 0.5) from orders").rows
    assert isinstance(v, int)


def test_percentile_validation(tpch):
    from trino_tpu.sql.analyzer import AnalysisError

    with pytest.raises(AnalysisError):
        tpch.execute("select approx_percentile(l_quantity, 1.5) "
                     "from lineitem")
    with pytest.raises(AnalysisError):
        tpch.execute("select approx_percentile(l_quantity, o_orderkey) "
                     "from lineitem, orders")
