"""Spooled exchange + retry-from-spool (fault-tolerant execution).

Reference analog: ``spi/exchange/ExchangeManager.java:42-75`` +
``FileSystemExchangeManager`` under RetryPolicy.TASK — durable stage
outputs so a failed task retries WITHOUT re-running its producer stage.
"""

import pytest

from trino_tpu import types as T
from trino_tpu.block import Page
from trino_tpu.parallel.process_runner import ProcessQueryRunner
from trino_tpu.parallel.spool import (ExchangeSink,
                                      FileSystemExchangeManager,
                                      read_spool)
from trino_tpu.sql.analyzer import Session

CATALOGS = {"tpch": {"connector": "tpch", "page_rows": 4096}}


def test_spool_roundtrip(tmp_path):
    mgr = FileSystemExchangeManager(str(tmp_path))
    sink0 = mgr.create_sink("q1", 0, task=0, n_partitions=2)
    sink1 = mgr.create_sink("q1", 0, task=1, n_partitions=2)
    p = Page.from_pylists([T.BIGINT, T.VARCHAR],
                          [[1, 2], ["a", "b"]])
    sink0.add(0, p)
    sink0.add(1, p)
    sink1.add(1, p)
    sink0.finish()
    sink1.finish()
    d = mgr.exchange_dir("q1", 0)
    assert [pg.to_rows() for pg in read_spool(d, 0)] == [p.to_rows()]
    assert len(read_spool(d, 1)) == 2  # both tasks contributed
    mgr.remove_exchange("q1", 0)
    with pytest.raises(FileNotFoundError):
        read_spool(d, 0)


def test_spool_cursor_start_page_resumes_mid_stream(tmp_path):
    """The page-range cursor seam for partial-stage retry: a consumer
    resuming with start_page=N re-decodes but does NOT re-yield the
    first N pages (serde dictionary deltas are positional), so the
    replayed stream is exactly the unconsumed tail."""
    from trino_tpu.parallel.spool import spool_task_cursor

    mgr = FileSystemExchangeManager(str(tmp_path))
    sink = mgr.create_sink("q3", 0, task=0, n_partitions=1)
    pages = [Page.from_pylists([T.BIGINT, T.VARCHAR],
                               [[i, i + 10], [f"s{i}", f"s{i + 10}"]])
             for i in range(3)]
    for p in pages:
        sink.add(0, p)
    sink.finish()
    d = mgr.exchange_dir("q3", 0)
    cur = spool_task_cursor(d, 0, 0, start_page=2)
    got = []
    while True:
        p = cur.poll()
        if p is None and cur.at_end():
            break
        got.extend(p.to_rows())
    cur.close()
    assert got == pages[2].to_rows()


def test_unfinished_sink_not_visible(tmp_path):
    """A sink that never finished (producer died) must leave nothing
    readable — write-then-rename atomicity."""
    mgr = FileSystemExchangeManager(str(tmp_path))
    sink = mgr.create_sink("q2", 0, task=0, n_partitions=1)
    sink.add(0, Page.from_pylists([T.BIGINT], [[1]]))
    # no finish()
    assert read_spool(mgr.exchange_dir("q2", 0), 0) == []
    sink.abort()


@pytest.fixture(scope="module")
def ft_cluster():
    s = Session(catalog="tpch", schema="micro")
    s.properties["streaming_execution"] = False
    s.properties["retry_policy"] = "TASK"
    with ProcessQueryRunner(CATALOGS, s, n_workers=2, desired_splits=4,
                            broadcast_threshold=300.0) as c:
        yield c


SQL = ("select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
       "from lineitem group by l_returnflag, l_linestatus")
EXPECTED_GROUPS = 4


def test_task_retry_does_not_rerun_producer(ft_cluster):
    """The retry-from-spool contract: inject a failure into a FINAL-
    stage task; the retry replays its input from the spool and the
    PRODUCER stage's tasks are launched exactly once."""
    c = ft_cluster
    qid = f"q{c._task_seq + 1}a0"
    c.inject_task_failure(f"{qid}.f1", times=1)
    c.task_launches.clear()
    res = c.execute(SQL)
    assert len(res.rows) == EXPECTED_GROUPS
    assert not any(c.failure_injections.values())
    f0 = [t for t in c.task_launches if f"{qid}.f0." in t]
    f1 = [t for t in c.task_launches if f"{qid}.f1." in t]
    assert len(f0) == 2, f"producer stage re-ran: {f0}"
    assert len(f1) == 3, f"expected one retried final task: {f1}"


def test_worker_death_recovers_from_spool(ft_cluster):
    """Kill a worker BETWEEN stages mid-query: the dead worker's final-
    stage task retries on the survivor reading the spooled producer
    output — the producer stage (partly run by the dead worker) is NOT
    re-run and the query is NOT restarted."""
    c = ft_cluster
    qid = f"q{c._task_seq + 1}a0"
    c.task_launches.clear()

    # arrange the kill after fragment 0 completes: monkey-style hook on
    # _run_fragment via failure injection is worker-side; instead kill
    # on first f1 launch by watching task_launches from a thread is
    # racy — simplest deterministic lever: kill the worker right before
    # execute of a SECOND query's final stage is impossible, so instead
    # run once to warm, then kill and verify the running query survives
    # via task retry on the survivor.
    import threading
    import time

    victim = c.workers[1]

    def killer():
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(f"{qid}.f1." in t for t in c.task_launches):
                victim.proc.kill()
                return
            time.sleep(0.001)

    th = threading.Thread(target=killer)
    th.start()
    res = c.execute(SQL)
    th.join()
    assert len(res.rows) == EXPECTED_GROUPS
    # query-level retry would show a second attempt id (a1); spool
    # retry keeps every launch inside attempt 0
    assert all("a0." in t for t in c.task_launches), c.task_launches
    f0 = [t for t in c.task_launches if f"{qid}.f0." in t]
    assert len(f0) == 2, f"producer stage re-ran: {f0}"
    c.heartbeat()
