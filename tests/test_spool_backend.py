"""Spool backend: object-store contract, CRC framing, atomic publish.

Reference analog: ``FileSystemExchangeStorage`` under the exchange SPI —
the storage half of fault-tolerant execution, where a task attempt's
published output must be atomic, immutable, and checksum-verified.
"""

import os

import pytest

from trino_tpu import types as T
from trino_tpu.block import Page
from trino_tpu.parallel.spool import SpoolCorruption
from trino_tpu.parallel.spool_backend import (
    COMMIT_MARKER, BackendSpoolCursor, LocalFileSpoolBackend,
    SpooledTaskWriter, attempt_key, committed_attempt, frame_blob,
    open_committed_partition, partition_key, unframe_blob)


def _page(i):
    return Page.from_pylists([T.BIGINT, T.VARCHAR],
                             [[i, i + 10], [f"s{i}", f"s{i + 10}"]])


def test_object_roundtrip_and_first_publish_wins(tmp_path):
    be = LocalFileSpoolBackend(str(tmp_path))
    assert be.put("q1/f0/t0/a0/p0.bin", b"hello") is True
    assert be.put("q1/f0/t0/a0/p0.bin", b"loser") is False
    assert be.get("q1/f0/t0/a0/p0.bin") == b"hello"  # first wins
    assert be.exists("q1/f0/t0/a0/p0.bin")
    with pytest.raises(KeyError):
        be.get("q1/f0/t0/a0/p9.bin")


def test_list_and_delete_prefix(tmp_path):
    be = LocalFileSpoolBackend(str(tmp_path))
    be.put("q1/f0/t0/a0/p0.bin", b"x")
    be.put("q1/f0/t0/a0/p1.bin", b"y")
    be.put("q1/f1/t0/a0/p0.bin", b"z")
    assert be.list("q1/f0/t0") == ["q1/f0/t0/a0/p0.bin",
                                   "q1/f0/t0/a0/p1.bin"]
    be.delete_prefix("q1/f0")
    assert be.list("q1/f0/t0") == []
    assert be.exists("q1/f1/t0/a0/p0.bin")  # sibling prefix untouched


def test_crc_framing_detects_corruption():
    frames = [b"frame-one", b"frame-two-longer"]
    blob = frame_blob(frames)
    assert unframe_blob(blob) == frames
    # flip a payload bit: CRC must catch it, loudly and typed
    torn = bytearray(blob)
    torn[6] ^= 0x40
    with pytest.raises(SpoolCorruption):
        unframe_blob(bytes(torn))
    # truncate mid-frame: torn read, same taxonomy
    with pytest.raises(SpoolCorruption):
        unframe_blob(blob[:-3])


def test_task_writer_commit_marker_and_cursor(tmp_path):
    be = LocalFileSpoolBackend(str(tmp_path))
    w = SpooledTaskWriter(be, "q7", 1, 0, 0, n_partitions=2)
    pages = [_page(i) for i in range(3)]
    for p in pages:
        w.add(0, p)
    w.add(1, pages[0])
    assert committed_attempt(be, "q7", 1, 0) is None  # not yet visible
    assert w.commit() is True
    assert committed_attempt(be, "q7", 1, 0) == 0
    cur = open_committed_partition(be, "q7", 1, 0, 0)
    assert [r for p in cur.pages() for r in p.to_rows()] == \
        [r for p in pages for r in p.to_rows()]
    # start_page resumes mid-stream: decoded but not re-yielded prefix
    cur2 = open_committed_partition(be, "q7", 1, 0, 0, start_page=2)
    assert [r for p in cur2.pages() for r in p.to_rows()] == \
        pages[2].to_rows()


def test_commit_race_lowest_attempt_wins(tmp_path):
    be = LocalFileSpoolBackend(str(tmp_path))
    for attempt in (1, 0):  # later attempt commits FIRST
        w = SpooledTaskWriter(be, "q8", 0, 3, attempt, n_partitions=1)
        w.add(0, _page(attempt))
        assert w.commit() is True
    # resolution is deterministic: every consumer adopts attempt 0
    assert committed_attempt(be, "q8", 0, 3) == 0


def test_aborted_writer_publishes_nothing(tmp_path):
    be = LocalFileSpoolBackend(str(tmp_path))
    w = SpooledTaskWriter(be, "q9", 0, 0, 0, n_partitions=1)
    w.add(0, _page(1))
    w.abort()
    assert w.commit() is False
    assert committed_attempt(be, "q9", 0, 0) is None
    assert be.list("q9") == []


def test_corrupt_partition_object_is_loud(tmp_path):
    be = LocalFileSpoolBackend(str(tmp_path))
    w = SpooledTaskWriter(be, "qa", 0, 0, 0, n_partitions=1)
    w.add(0, _page(5))
    w.commit()
    key = partition_key("qa", 0, 0, 0, 0)
    path = os.path.join(str(tmp_path), key)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 3)
    with pytest.raises(SpoolCorruption):
        BackendSpoolCursor(be, key).pages()


def test_key_escape_rejected(tmp_path):
    be = LocalFileSpoolBackend(str(tmp_path))
    with pytest.raises(ValueError):
        be.put("../escape", b"x")


def test_commit_marker_key_shape():
    assert attempt_key("q1", 2, 3, 1) == "q1/f2/t3/a1"
    assert partition_key("q1", 2, 3, 1, 0) == "q1/f2/t3/a1/p0.bin"
    assert COMMIT_MARKER == "COMMIT"
