"""End-to-end SQL tests through LocalQueryRunner (reference analog:
AbstractTestQueries over TpchQueryRunner)."""

from decimal import Decimal

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=4096)},
                            Session(catalog="tpch", schema="micro"))


def q(runner, sql):
    return runner.execute(sql).rows


def test_select_literal(runner):
    assert q(runner, "select 1") == [(1,)]
    assert q(runner, "select 1 + 2 * 3, 'x'") == [(7, "x")]


def test_values(runner):
    rows = q(runner, "select * from (values (1, 'a'), (2, 'b')) t(x, y) "
                     "order by x")
    assert rows == [(1, "a"), (2, "b")]


def test_scan_count(runner):
    n = q(runner, "select count(*) from nation")[0][0]
    assert n == 25


def test_filter_project(runner):
    rows = q(runner, "select n_name, n_regionkey from nation "
                     "where n_regionkey = 0 order by n_name")
    assert all(r[1] == 0 for r in rows)
    assert len(rows) == 5


def test_global_aggregation(runner):
    rows = q(runner, "select count(*), min(n_nationkey), max(n_nationkey) "
                     "from nation")
    assert rows == [(25, 0, 24)]


def test_group_by_having(runner):
    rows = q(runner, "select n_regionkey, count(*) c from nation "
                     "group by n_regionkey having count(*) >= 5 "
                     "order by n_regionkey")
    assert rows == [(0, 5), (1, 5), (2, 5), (3, 5), (4, 5)]


def test_join_explicit(runner):
    rows = q(runner, """
        select n.n_name, r.r_name from nation n
        join region r on n.n_regionkey = r.r_regionkey
        where r.r_name = 'ASIA' order by n.n_name""")
    assert len(rows) == 5
    assert all(r[1] == "ASIA" for r in rows)


def test_join_implicit(runner):
    rows = q(runner, """
        select count(*) from nation, region
        where n_regionkey = r_regionkey""")
    assert rows == [(25,)]


def test_left_join(runner):
    rows = q(runner, """
        select r_name, c from region left join (
            select n_regionkey, count(*) c from nation
            where n_nationkey < 3 group by n_regionkey) x
        on r_regionkey = n_regionkey
        order by r_name""")
    assert len(rows) == 5
    # nations 0,1,2 are in regions 0,1,1
    by_name = dict(rows)
    assert sum(1 for v in by_name.values() if v is None) == 3


def test_full_outer_join(runner):
    rows = q(runner, """
        select t.x, t.y, u.a, u.b
        from (values (1, 'p'), (2, 'q'), (3, 'r')) t(x, y)
        full outer join (values (2, 'B'), (3, 'C'), (4, 'D')) u(a, b)
        on t.x = u.a
        order by coalesce(t.x, u.a)""")
    assert rows == [(1, "p", None, None), (2, "q", 2, "B"),
                    (3, "r", 3, "C"), (None, None, 4, "D")]


def test_full_outer_join_residual(runner):
    # the residual ON conjunct unmatches BOTH sides of the x=3 pair
    rows = q(runner, """
        select t.x, u.a
        from (values (1), (2), (3)) t(x)
        full outer join (values (2), (3), (4)) u(a)
        on t.x = u.a and t.x < 3
        order by coalesce(t.x, a), coalesce(a, t.x)""")
    assert rows == [(1, None), (2, 2), (3, None),
                    (None, 3), (None, 4)]


def test_full_outer_join_duplicates_and_nulls(runner):
    # duplicate keys fan out; NULL keys never match but still emit
    rows = q(runner, """
        select t.x, u.a
        from (values (1), (1), (cast(null as integer))) t(x)
        full outer join (values (1), (cast(null as integer))) u(a)
        on t.x = u.a
        order by coalesce(t.x, -1), coalesce(u.a, -1)""")
    assert rows == [(None, None), (None, None),
                    (1, 1), (1, 1)]


def test_full_outer_join_tpch(runner):
    # region 4 (MIDDLE EAST) keeps its row even when the nation subquery
    # excludes it; the extra nation-side group keeps its row too
    rows = q(runner, """
        select r_name, c from region full outer join (
            select n_regionkey, count(*) c from nation
            where n_nationkey < 3 group by n_regionkey) x
        on r_regionkey = n_regionkey
        order by coalesce(r_regionkey, n_regionkey)""")
    assert len(rows) == 5
    by_name = dict(rows)
    assert sum(1 for v in by_name.values() if v is None) == 3


def test_order_limit_offset(runner):
    rows = q(runner, "select n_nationkey from nation "
                     "order by n_nationkey limit 3")
    assert rows == [(0,), (1,), (2,)]
    rows = q(runner, "select n_nationkey from nation "
                     "order by n_nationkey desc limit 2")
    assert rows == [(24,), (23,)]


def test_distinct(runner):
    rows = q(runner, "select distinct n_regionkey from nation "
                     "order by n_regionkey")
    assert rows == [(0,), (1,), (2,), (3,), (4,)]


def test_union(runner):
    rows = q(runner, "select 1 x union all select 2 union all select 1 "
                     "order by x")
    assert rows == [(1,), (1,), (2,)]
    rows = q(runner, "select 1 x union select 1 union select 2 order by x")
    assert rows == [(1,), (2,)]


def test_in_list(runner):
    rows = q(runner, "select count(*) from nation "
                     "where n_regionkey in (0, 2)")
    assert rows == [(10,)]


def test_in_subquery(runner):
    rows = q(runner, """
        select count(*) from nation where n_regionkey in
        (select r_regionkey from region where r_name like 'A%')""")
    # ASIA, AMERICA, AFRICA -> 15 nations
    assert rows == [(15,)]


def test_not_in_subquery(runner):
    rows = q(runner, """
        select count(*) from nation where n_regionkey not in
        (select r_regionkey from region where r_name like 'A%')""")
    assert rows == [(10,)]


def test_exists_correlated(runner):
    rows = q(runner, """
        select r_name from region r where exists (
            select 1 from nation n where n.n_regionkey = r.r_regionkey
            and n.n_nationkey < 2)
        order by r_name""")
    # nations 0,1 live in regions 0,1
    assert len(rows) == 2


def test_scalar_subquery_uncorrelated(runner):
    rows = q(runner, """
        select count(*) from nation
        where n_nationkey > (select avg(n_nationkey) from nation)""")
    assert rows == [(12,)]


def test_scalar_subquery_correlated_agg(runner):
    rows = q(runner, """
        select count(*) from nation n1
        where n_nationkey = (
            select max(n_nationkey) from nation n2
            where n2.n_regionkey = n1.n_regionkey)""")
    assert rows == [(5,)]


def test_case_expression(runner):
    rows = q(runner, """
        select sum(case when n_regionkey = 0 then 1 else 0 end)
        from nation""")
    assert rows == [(5,)]


def test_arithmetic_on_aggregates(runner):
    rows = q(runner, """
        select count(*) * 2 + 1 from nation""")
    assert rows == [(51,)]


def test_cte(runner):
    rows = q(runner, """
        with asia as (select * from region where r_name = 'ASIA')
        select n_name from nation, asia
        where n_regionkey = r_regionkey order by n_name limit 1""")
    assert len(rows) == 1


def test_show_and_explain(runner):
    catalogs = runner.execute("show catalogs").rows
    assert ("tpch",) in catalogs
    plan = runner.explain("select count(*) from nation")
    assert "Aggregation" in plan and "TableScan" in plan


# -- regressions from code review ------------------------------------------


def test_subquery_in_select_list(runner):
    rows = q(runner, """
        select r_name, (select count(*) from nation n
                        where n.n_regionkey = r.r_regionkey) c
        from region r order by r_name""")
    assert len(rows) == 5
    assert all(r[1] == 5 for r in rows)


def test_correlated_count_empty_group_is_zero(runner):
    rows = q(runner, """
        select count(*) from region r where (
            select count(*) from nation n
            where n.n_regionkey = r.r_regionkey and n.n_nationkey < 0) = 0""")
    assert rows == [(5,)]


def test_union_distinct_strings(runner):
    rows = q(runner, "select 'a' x union select 'b' union select 'a' "
                     "order by x")
    assert rows == [("a",), ("b",)]


def test_union_strings_from_tables(runner):
    rows = q(runner, "select n_name v from nation where n_nationkey = 0 "
                     "union all select r_name from region "
                     "where r_regionkey = 0 order by v")
    assert rows == [("AFRICA",), ("ALGERIA",)]


def test_not_in_with_null_in_subquery(runner):
    rows = q(runner, """
        select count(*) from nation where n_regionkey not in
        (select case when r_regionkey = 0 then null else r_regionkey end
         from region)""")
    assert rows == [(0,)]


def test_not_in_empty_subquery(runner):
    rows = q(runner, """
        select count(*) from nation where n_regionkey not in
        (select r_regionkey from region where r_name = 'NOPE')""")
    assert rows == [(25,)]


def test_all_over_empty_set_is_true(runner):
    rows = q(runner, """
        select count(*) from nation where n_nationkey > all
        (select n_nationkey from nation where n_nationkey < 0)""")
    assert rows == [(25,)]


def test_any_quantified(runner):
    rows = q(runner, """
        select count(*) from nation where n_nationkey > any
        (select r_regionkey from region)""")
    # > min(0) -> nationkey >= 1 -> 24 rows
    assert rows == [(24,)]


def test_group_by_select_alias_expression(runner):
    rows = q(runner, """
        select n_regionkey + 1 as a, count(*) from nation
        group by a order by a""")
    assert rows == [(1, 5), (2, 5), (3, 5), (4, 5), (5, 5)]


def test_order_by_expression_over_alias(runner):
    rows = q(runner, "select n_nationkey + 1 as c from nation "
                     "order by c + 1 desc limit 2")
    assert rows == [(25,), (24,)]


def test_string_not_in_with_null_item(runner):
    rows = q(runner, "select count(*) from nation "
                     "where n_name not in ('ALGERIA', null)")
    assert rows == [(0,)]


def test_string_in_with_null_item(runner):
    rows = q(runner, "select count(*) from nation "
                     "where n_name in ('ALGERIA', null)")
    assert rows == [(1,)]


def test_string_in_type_mismatch_raises(runner):
    import pytest as _pytest

    from trino_tpu.sql.analyzer import AnalysisError

    with _pytest.raises(AnalysisError):
        q(runner, "select count(*) from nation where n_name in (1, 2)")
