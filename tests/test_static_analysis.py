"""qlint analyzer tests: per-pass fixture snippets (one known-bad and
one known-good each) so the passes cannot silently go blind, plus the
tier-1 gate that runs every pass over ``trino_tpu/`` and fails on any
non-baselined finding.

The analysis package itself is pure stdlib ``ast`` (bench.py loads it
by file path to keep the bench parent jax-free); these tests must
stay fast (<30 s).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from trino_tpu.analysis import (PASSES, ProjectIndex, apply_baseline,
                                default_baseline_path, load_baseline,
                                run_passes)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "trino_tpu")


def index_of(**sources):
    """Fixture index from {module_name: dedented source}."""
    return ProjectIndex.from_sources(
        {name: textwrap.dedent(src) for name, src in sources.items()})


def rules(findings):
    return {(f.pass_id, f.rule) for f in findings}


# -- trace-purity --------------------------------------------------------

def test_trace_purity_catches_span_inside_jit():
    idx = index_of(**{"pkg.kern": """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def kernel(x, n):
            with tracer.span("kernel"):
                return helper(x)

        def helper(x):
            print("tracing", x)
            return x
    """})
    found = run_passes(idx, ["trace-purity"])
    assert ("trace-purity", "telemetry-in-trace") in rules(found)
    # interprocedural: helper's print() reached through the call graph
    assert ("trace-purity", "host-io") in rules(found)
    assert any(f.qualname == "helper" for f in found)


def test_trace_purity_call_form_entry_and_lock():
    idx = index_of(**{"pkg.build": """
        import jax, time, threading

        _lock = threading.Lock()

        def build():
            def staged(x):
                with _lock:
                    t = time.time()
                return x
            return jax.jit(staged)
    """})
    found = run_passes(idx, ["trace-purity"])
    got = rules(found)
    assert ("trace-purity", "lock-in-trace") in got
    assert ("trace-purity", "host-time") in got


def test_trace_purity_clean_kernel_and_allowlisted_counter():
    idx = index_of(**{"pkg.ok": """
        import jax
        import jax.numpy as jnp
        from .. import jit_stats

        @jax.jit
        def kernel(x):
            jit_stats.bump("kernel")   # designed trace-time counter
            return jnp.sum(x * 2)

        def host_side():
            print("fine out here")
            import time
            time.sleep(0)
    """})
    assert run_passes(idx, ["trace-purity"]) == []


def test_trace_purity_sees_through_package_init_reexports():
    """A helper re-exported through a package __init__ must stay on
    the call graph: package-__init__ relative imports resolve against
    the package itself, not its parent."""
    idx = ProjectIndex.from_sources({
        "pkg.tel": textwrap.dedent("""
            import jax

            from .inner import span_helper

            @jax.jit
            def kernel(x):
                return span_helper(x)
        """),
        "pkg.tel.inner": textwrap.dedent("""
            def span_helper(x):
                print("host effect")
                return x
        """),
    }, packages=("pkg.tel",))
    found = run_passes(idx, ["trace-purity"])
    assert ("trace-purity", "host-io") in rules(found)


def test_bare_call_in_method_binds_module_level_not_sibling_method():
    """Python scoping: `helper()` inside C.m is the module-level
    helper, never the sibling method — a misresolution here fabricates
    false lock cycles / masks real host effects."""
    idx = index_of(**{"pkg.m": """
        import jax

        def helper(x):
            print("reached")
            return x

        class C:
            @jax.jit
            def m(self, x):
                return helper(x)

            def helper(self, x):
                return x
    """})
    found = run_passes(idx, ["trace-purity"])
    assert [f.qualname for f in found] == ["helper"]
    assert found[0].rule == "host-io"


def test_trace_purity_pragma_opt_out():
    idx = index_of(**{"pkg.cfg": """
        import jax, os

        @jax.jit
        def kernel(x):
            mode = os.environ.get("MODE", "")  # qlint: ignore[trace-purity]
            return x
    """})
    assert run_passes(idx, ["trace-purity"]) == []


# -- lock-order ----------------------------------------------------------

AB_BA = """
    import threading

    class Ledger:
        def __init__(self):
            self._lock = threading.Lock()

        def demote(self, pool: "Pool"):
            with self._lock:
                pool.reserve()

        def park(self):
            with self._lock:
                pass

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()

        def reserve(self):
            with self._lock:
                pass

        def revoke(self, ledger: Ledger):
            with self._lock:
                ledger.park()
"""


def test_lock_order_catches_seeded_ab_ba_cycle():
    idx = index_of(**{"pkg.spill": AB_BA})
    found = run_passes(idx, ["lock-order"])
    cycles = [f for f in found if f.rule == "lock-cycle"]
    assert len(cycles) == 1
    assert "Ledger._lock" in cycles[0].message
    assert "Pool._lock" in cycles[0].message


def test_lock_order_consistent_order_is_clean():
    # same two locks, always acquired Ledger -> Pool: no cycle
    idx = index_of(**{"pkg.spill": """
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()

            def demote(self, pool: "Pool"):
                with self._lock:
                    pool.reserve()

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def reserve(self):
                with self._lock:
                    pass
    """})
    assert run_passes(idx, ["lock-order"]) == []


def test_lock_order_nonblocking_acquire_breaks_no_cycle():
    # PR 5's demote_across pattern: the back-edge uses
    # acquire(blocking=False), which cannot deadlock
    idx = index_of(**{"pkg.spill": AB_BA.replace(
        "ledger.park()",
        "ledger._lock.acquire(blocking=False)")})
    found = run_passes(idx, ["lock-order"])
    assert [f for f in found if f.rule == "lock-cycle"] == []


def test_lock_order_self_deadlock_and_rlock_exemption():
    idx = index_of(**{"pkg.locks": """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass

        class B:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    with self._lock:
                        pass
    """})
    found = run_passes(idx, ["lock-order"])
    subs = {f.subject for f in found if f.rule == "self-deadlock"}
    assert "self:pkg.locks.A._lock" in subs          # Lock: deadlock
    assert not any("B._lock" in s for s in subs)     # RLock: reentrant


def test_lock_order_rpc_under_lock():
    idx = index_of(**{"pkg.srv": """
        import threading, subprocess

        _lock = threading.Lock()

        def ship(frames):
            with _lock:
                subprocess.run(["scp", "x"])
    """})
    found = run_passes(idx, ["lock-order"])
    assert ("lock-order", "lock-over-rpc") in rules(found)


# -- recompile -----------------------------------------------------------

def test_recompile_unhashable_arg_and_session_read():
    idx = index_of(**{"pkg.exch": """
        from functools import lru_cache
        from .. import session_properties as SP

        @lru_cache(maxsize=8)
        def build_program(mesh, opts):
            min_c = SP.prop_value({}, "rebalance_min_collectives")
            return (mesh, opts, min_c)

        def run(mesh):
            return build_program(mesh, {"sizing": "exact"})
    """})
    found = run_passes(idx, ["recompile"])
    got = rules(found)
    assert ("recompile", "unhashable-arg") in got
    assert ("recompile", "cached-builder-reads-session") in got
    session = [f for f in found
               if f.rule == "cached-builder-reads-session"]
    assert "rebalance_min_collectives" in session[0].message


def test_recompile_traced_branch():
    idx = index_of(**{"pkg.kern": """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("exact",))
        def kernel(x, exact):
            if exact:            # static: fine
                pass
            if x > 0:            # traced: TracerBoolConversionError
                return x
            return -x
    """})
    found = run_passes(idx, ["recompile"])
    branches = [f for f in found if f.rule == "traced-branch"]
    assert len(branches) == 1
    assert "`x`" in branches[0].message


def test_recompile_static_accessors_are_clean():
    idx = index_of(**{"pkg.kern": """
        import jax

        @jax.jit
        def kernel(x, lut):
            if x.shape[0] > 128:   # shapes are static under jit
                pass
            if len(x.shape) == 2:
                pass
            if lut is None:        # pytree structure is static
                return x
            return x

        def run(mesh, key):
            return build(mesh, tuple(sorted(key)))
    """})
    assert run_passes(idx, ["recompile"]) == []


# -- session-props -------------------------------------------------------

SP_REG = """
    REGISTRY = {}

    def register(prop):
        REGISTRY[prop.name] = prop

    class SessionProperty:
        def __init__(self, name, type, default, description):
            self.name = name

    register(SessionProperty(
        "knob_used", "integer", 4, "read below"))
    register(SessionProperty(
        "knob_dead", "boolean", False, "never read"))
    register(SessionProperty(
        "knob_typo", "int", 0, "bad type vocab"))

    def value(session, name):
        return REGISTRY[name]

    def prop_value(props, name):
        return props.get(name)
"""


def test_session_props_dead_undeclared_and_bad_type():
    idx = index_of(**{
        "pkg.session_properties": SP_REG,
        "pkg.engine": """
            from . import session_properties as SP

            def plan(session):
                a = SP.value(session, "knob_used")
                b = SP.value(session, "knob_missing")
                return a, b
        """,
    })
    found = run_passes(idx, ["session-props"])
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    assert [f.subject for f in by_rule["dead-property"]] \
        == ["dead:knob_dead", "dead:knob_typo"]
    assert by_rule["undeclared-lookup"][0].subject \
        == "undeclared:knob_missing"
    assert by_rule["bad-type"][0].subject == "bad-type:knob_typo"


def test_session_props_every_declared_and_read_is_clean():
    idx = index_of(**{
        "pkg.session_properties": SP_REG.replace(
            '    register(SessionProperty(\n        "knob_dead"',
            '    _ = (lambda: None) or register(SessionProperty(\n'
            '        "knob_dead"').replace(
            '"knob_typo", "int"', '"knob_typo", "integer"'),
        "pkg.engine": """
            from . import session_properties as SP

            def plan(session):
                return (SP.value(session, "knob_used"),
                        SP.value(session, "knob_dead"),
                        SP.prop_value({}, "knob_typo"))
        """,
    })
    assert run_passes(idx, ["session-props"]) == []


# -- taxonomy ------------------------------------------------------------

def test_taxonomy_bare_raise_and_broad_swallow():
    idx = index_of(**{"pkg.parallel.worker": """
        def flush(resp):
            if not resp.get("ok"):
                raise RuntimeError("sink rejected")

        def loop():
            try:
                flush({})
            except Exception:
                pass
    """})
    found = run_passes(idx, ["taxonomy"])
    got = rules(found)
    assert ("taxonomy", "bare-raise") in got
    assert ("taxonomy", "broad-swallow") in got


def test_taxonomy_typed_raise_and_routed_handler_are_clean():
    idx = index_of(**{"pkg.parallel.worker": """
        from .fault import RemoteTaskError, serialize_failure

        def flush(resp):
            if not resp.get("ok"):
                raise RemoteTaskError("sink rejected", "INTERNAL")

        def loop(sock):
            try:
                flush({})
            except Exception as e:
                sock.send(serialize_failure(e))

        def reraise():
            try:
                flush({})
            except Exception:
                raise
    """})
    assert run_passes(idx, ["taxonomy"]) == []


def test_taxonomy_scoped_to_parallel_and_pragma():
    idx = index_of(**{
        # outside parallel/: not this pass's business
        "pkg.ops.sort": "def f():\n    raise RuntimeError('x')\n",
        # fault.py defines the vocabulary: exempt
        "pkg.parallel.fault": "def g():\n    raise RuntimeError('y')\n",
        "pkg.parallel.chaos": """
            def inject(task_id):
                raise RuntimeError(  # qlint: ignore[taxonomy]
                    f"injected failure for {task_id}")
        """,
    })
    assert run_passes(idx, ["taxonomy"]) == []


# -- blocked-protocol ----------------------------------------------------

def test_blocked_protocol_partial_channel_and_stale_token():
    idx = index_of(**{"pkg.chan": """
        class HalfChannel:
            def poll(self):
                return self._q.pop(0) if self._q else None

            def listen(self):
                return self._token

        class Source:
            def blocked_token(self):
                return self._chan.listen()   # no readiness re-check
    """})
    found = run_passes(idx, ["blocked-protocol"])
    got = rules(found)
    assert ("blocked-protocol", "channel-contract") in got
    assert ("blocked-protocol", "stale-token-park") in got
    contract = [f for f in found if f.rule == "channel-contract"]
    assert "at_end" in contract[0].message
    assert "has_page" in contract[0].message


def test_blocked_protocol_waker_under_lock():
    idx = index_of(**{"pkg.buf": """
        import threading

        class Buf:
            def __init__(self):
                self._lock = threading.Lock()
                self._listeners = []

            def enqueue(self, page):
                with self._lock:
                    self._pages.append(page)
                    for cb in self._listeners:
                        cb()      # fires under the state lock
    """})
    found = run_passes(idx, ["blocked-protocol"])
    assert ("blocked-protocol", "waker-under-lock") in rules(found)


def test_blocked_protocol_repo_idioms_are_clean():
    """The engine's own patterns pass: full quartet, snapshot-then-
    recheck blocked_token, collect-under-lock / fire-after-release."""
    idx = index_of(**{"pkg.ok": """
        import threading

        class Chan:
            def poll(self):
                return None

            def at_end(self):
                return True

            def has_page(self):
                return False

            def listen(self):
                return self._token

        class Source:
            def blocked_token(self):
                token = self._chan.listen()
                if self._chan.at_end() or self._chan.has_page():
                    return None
                return token

        class Buf:
            def __init__(self):
                self._lock = threading.Lock()
                self._listeners = []

            def _bump_locked(self):
                fired = list(self._listeners)
                self._listeners.clear()
                return fired

            def enqueue(self, page):
                with self._lock:
                    self._pages.append(page)
                    fired = self._bump_locked()
                for cb in fired:
                    cb()
    """})
    assert run_passes(idx, ["blocked-protocol"]) == []


# -- framework plumbing --------------------------------------------------

def test_unknown_pass_rejected():
    idx = index_of(**{"pkg.m": "x = 1\n"})
    with pytest.raises(ValueError, match="unknown passes"):
        run_passes(idx, ["no-such-pass"])


def test_finding_keys_are_line_stable():
    src = """
        def flush(resp):
            raise RuntimeError("boom")
    """
    a = run_passes(index_of(**{"pkg.parallel.m": src}), ["taxonomy"])
    b = run_passes(index_of(**{"pkg.parallel.m": "\n\n\n" + textwrap.dedent(src)}),
                   ["taxonomy"])
    assert [f.key for f in a] == [f.key for f in b]
    assert a[0].line != b[0].line


def test_apply_baseline_splits_new_suppressed_stale():
    idx = index_of(**{"pkg.parallel.m": """
        def f():
            raise RuntimeError("a")

        def g():
            raise Exception("b")
    """})
    found = run_passes(idx, ["taxonomy"])
    assert len(found) == 2
    baseline = {found[0].key: "triaged", "gone:key": "stale"}
    new, suppressed, stale = apply_baseline(found, baseline)
    assert [f.key for f in new] == [found[1].key]
    assert [f.key for f in suppressed] == [found[0].key]
    assert stale == ["gone:key"]


# -- the tier-1 gate -----------------------------------------------------

@pytest.fixture(scope="module")
def repo_findings():
    index = ProjectIndex.from_package(PACKAGE)
    return index, run_passes(index)


def test_gate_repo_is_clean_modulo_baseline(repo_findings):
    """THE gate: every pass over trino_tpu/, zero non-baselined
    findings, no stale baseline entries (the baseline only shrinks)."""
    _index, findings = repo_findings
    baseline = load_baseline(default_baseline_path(PACKAGE))
    new, _suppressed, stale = apply_baseline(findings, baseline)
    assert not new, "non-baselined findings:\n" + "\n".join(
        f.render() for f in new)
    assert not stale, ("baseline entries that no longer fire "
                       "(remove them): " + ", ".join(stale))
    # the baseline may only shrink: at PR 7 every first-run finding
    # was fixed instead of baselined, so any growth is a regression
    assert len(baseline) <= 0, \
        "analysis_baseline.json grew — fix new findings instead"


def test_gate_passes_are_not_blind_on_the_real_repo(repo_findings):
    """The gate is only meaningful if the passes actually index the
    engine: staged-out entry points, locks, cached builders and the
    property registry must all be visible."""
    from trino_tpu.analysis.trace_purity import jit_entries
    from trino_tpu.analysis.recompile import _cached_functions
    from trino_tpu.analysis.session_props import (_declarations,
                                                  _registry_module)
    index, _ = repo_findings
    entries = jit_entries(index)
    assert len(entries) >= 15, sorted(entries)
    assert any(e.kind == "shard_map" for e in entries.values())
    assert "trino_tpu.parallel.device_exchange:_exchange_program.prog" \
        in entries
    # the kernel-strategy entry points (round 12) must be inside the
    # trace-purity walk — the matmul probe, the global-hash claim loop,
    # and the per-key-range adaptive kernels are all hot jit'd code
    for entry in ("trino_tpu.ops.matmul_join:_matmul_lo_count",
                  "trino_tpu.ops.global_hash_agg:global_hash_insert",
                  "trino_tpu.ops.global_hash_agg:global_hash_reduce",
                  "trino_tpu.ops.aggregation:_bucket_reduction_stats",
                  "trino_tpu.parallel.mesh_query:q1_global_hash_fn.dist"):
        assert entry in entries, entry
    cached = _cached_functions(index)
    assert "trino_tpu.parallel.device_exchange:_exchange_program" \
        in cached
    declared = _declarations(_registry_module(index))
    assert len(declared) >= 30
    assert declared["retry_policy"][0] == "varchar"
    assert "page_rows" not in declared
    from trino_tpu.analysis.blocked_protocol import channel_classes
    chans = channel_classes(index)
    assert len(chans) >= 5, chans
    assert "trino_tpu.parallel.remote_exchange:RemoteExchangeChannel" \
        in chans
    assert "trino_tpu.parallel.spool:SpoolCursor" in chans
    # the compiled-program profiler (round 11) must cover the jit
    # entry points: instrument() registrations are indexed by name so
    # a dropped wrapper can't silently blind EXPLAIN ANALYZE VERBOSE,
    # system.runtime.kernels, or the bench flight recorder
    from trino_tpu.analysis.trace_purity import profiled_entries
    profiled = profiled_entries(index)
    assert len(profiled) >= 15, sorted(profiled)
    for kernel in ("page_processor", "sort_by", "window_kernel",
                   "hash_group_ids", "hash_segment_reduce",
                   "sort_group_reduce", "join_build_sorted",
                   "join_probe_counts", "join_expand_matches",
                   "matmul_join_probe", "grouped_topn_kernel",
                   "device_exchange_program", "device_exchange_count",
                   "mesh_q1_stage1", "segment_reduce_pallas"):
        assert kernel in profiled, kernel


def test_hbo_record_path_indexed_and_outside_jit(repo_findings):
    """History-based statistics (round 13): the stats-store write path
    must be VISIBLE to the index (not blind — a renamed record method
    would silently stop the check meaning anything) and every caller
    of it must be OUTSIDE the jit-reachable set: a store write that
    migrated inside traced code would fire once per compile instead of
    once per query, freezing history at trace-time values."""
    from trino_tpu.analysis.trace_purity import (jit_reachable,
                                                 recording_sites)
    index, _ = repo_findings
    sites = recording_sites(index)
    callers = {fid for fids in sites.values() for fid in fids}
    # the HboContext record facade calls record_query; the runners
    # call record/record_actuals — all must be indexed
    assert any("record_query" in chain for chain in sites), sites
    assert any("record_actuals" in chain for chain in sites), sites
    assert any(fid.startswith("trino_tpu.telemetry.stats_store:")
               for fid in callers), sorted(callers)
    reached = jit_reachable(index)
    inside = callers & reached
    assert not inside, (
        "stats-store write path reachable from jit-traced code: "
        + ", ".join(sorted(inside)))


def test_cli_runs_clean_and_json(tmp_path):
    """`python -m trino_tpu.analysis` end to end: rc 0 on the clean
    tree, JSON shape, and rc 1 + stale reporting on a bad baseline."""
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis", "--json", PACKAGE],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["new"] == []
    assert payload["stale_baseline_keys"] == []
    assert sorted(payload["passes"]) == sorted(PASSES)

    bad = tmp_path / "baseline.json"
    bad.write_text(json.dumps(
        {"findings": [{"key": "taxonomy:bare-raise:gone:f:raise",
                       "note": "stale"}]}))
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis", PACKAGE,
         "--baseline", str(bad)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 1
    assert "STALE" in out.stdout


def test_cli_pass_selection(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis",
         "--passes", "session-props,taxonomy", "--json", PACKAGE],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert json.loads(out.stdout)["passes"] == ["session-props",
                                               "taxonomy"]
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis",
         "--passes", "bogus", PACKAGE],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60)
    assert out.returncode == 2
