"""qlint analyzer tests: per-pass fixture snippets (one known-bad and
one known-good each) so the passes cannot silently go blind, plus the
tier-1 gate that runs every pass over ``trino_tpu/`` and fails on any
non-baselined finding.

The analysis package itself is pure stdlib ``ast`` (bench.py loads it
by file path to keep the bench parent jax-free); these tests must
stay fast (<30 s).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from trino_tpu.analysis import (PASSES, ProjectIndex, apply_baseline,
                                default_baseline_path, load_baseline,
                                run_passes)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "trino_tpu")


def index_of(**sources):
    """Fixture index from {module_name: dedented source}."""
    return ProjectIndex.from_sources(
        {name: textwrap.dedent(src) for name, src in sources.items()})


def rules(findings):
    return {(f.pass_id, f.rule) for f in findings}


# -- trace-purity --------------------------------------------------------

def test_trace_purity_catches_span_inside_jit():
    idx = index_of(**{"pkg.kern": """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def kernel(x, n):
            with tracer.span("kernel"):
                return helper(x)

        def helper(x):
            print("tracing", x)
            return x
    """})
    found = run_passes(idx, ["trace-purity"])
    assert ("trace-purity", "telemetry-in-trace") in rules(found)
    # interprocedural: helper's print() reached through the call graph
    assert ("trace-purity", "host-io") in rules(found)
    assert any(f.qualname == "helper" for f in found)


def test_trace_purity_call_form_entry_and_lock():
    idx = index_of(**{"pkg.build": """
        import jax, time, threading

        _lock = threading.Lock()

        def build():
            def staged(x):
                with _lock:
                    t = time.time()
                return x
            return jax.jit(staged)
    """})
    found = run_passes(idx, ["trace-purity"])
    got = rules(found)
    assert ("trace-purity", "lock-in-trace") in got
    assert ("trace-purity", "host-time") in got


def test_trace_purity_clean_kernel_and_allowlisted_counter():
    idx = index_of(**{"pkg.ok": """
        import jax
        import jax.numpy as jnp
        from .. import jit_stats

        @jax.jit
        def kernel(x):
            jit_stats.bump("kernel")   # designed trace-time counter
            return jnp.sum(x * 2)

        def host_side():
            print("fine out here")
            import time
            time.sleep(0)
    """})
    assert run_passes(idx, ["trace-purity"]) == []


def test_trace_purity_sees_through_package_init_reexports():
    """A helper re-exported through a package __init__ must stay on
    the call graph: package-__init__ relative imports resolve against
    the package itself, not its parent."""
    idx = ProjectIndex.from_sources({
        "pkg.tel": textwrap.dedent("""
            import jax

            from .inner import span_helper

            @jax.jit
            def kernel(x):
                return span_helper(x)
        """),
        "pkg.tel.inner": textwrap.dedent("""
            def span_helper(x):
                print("host effect")
                return x
        """),
    }, packages=("pkg.tel",))
    found = run_passes(idx, ["trace-purity"])
    assert ("trace-purity", "host-io") in rules(found)


def test_bare_call_in_method_binds_module_level_not_sibling_method():
    """Python scoping: `helper()` inside C.m is the module-level
    helper, never the sibling method — a misresolution here fabricates
    false lock cycles / masks real host effects."""
    idx = index_of(**{"pkg.m": """
        import jax

        def helper(x):
            print("reached")
            return x

        class C:
            @jax.jit
            def m(self, x):
                return helper(x)

            def helper(self, x):
                return x
    """})
    found = run_passes(idx, ["trace-purity"])
    assert [f.qualname for f in found] == ["helper"]
    assert found[0].rule == "host-io"


def test_trace_purity_pragma_opt_out():
    idx = index_of(**{"pkg.cfg": """
        import jax, os

        @jax.jit
        def kernel(x):
            mode = os.environ.get("MODE", "")  # qlint: ignore[trace-purity] trace-static knob
            return x
    """})
    assert run_passes(idx, ["trace-purity"]) == []


# -- lock-order ----------------------------------------------------------

AB_BA = """
    import threading

    class Ledger:
        def __init__(self):
            self._lock = threading.Lock()

        def demote(self, pool: "Pool"):
            with self._lock:
                pool.reserve()

        def park(self):
            with self._lock:
                pass

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()

        def reserve(self):
            with self._lock:
                pass

        def revoke(self, ledger: Ledger):
            with self._lock:
                ledger.park()
"""


def test_lock_order_catches_seeded_ab_ba_cycle():
    idx = index_of(**{"pkg.spill": AB_BA})
    found = run_passes(idx, ["lock-order"])
    cycles = [f for f in found if f.rule == "lock-cycle"]
    assert len(cycles) == 1
    assert "Ledger._lock" in cycles[0].message
    assert "Pool._lock" in cycles[0].message


def test_lock_order_consistent_order_is_clean():
    # same two locks, always acquired Ledger -> Pool: no cycle
    idx = index_of(**{"pkg.spill": """
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()

            def demote(self, pool: "Pool"):
                with self._lock:
                    pool.reserve()

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def reserve(self):
                with self._lock:
                    pass
    """})
    assert run_passes(idx, ["lock-order"]) == []


def test_lock_order_nonblocking_acquire_breaks_no_cycle():
    # PR 5's demote_across pattern: the back-edge uses
    # acquire(blocking=False), which cannot deadlock
    idx = index_of(**{"pkg.spill": AB_BA.replace(
        "ledger.park()",
        "ledger._lock.acquire(blocking=False)")})
    found = run_passes(idx, ["lock-order"])
    assert [f for f in found if f.rule == "lock-cycle"] == []


def test_lock_order_self_deadlock_and_rlock_exemption():
    idx = index_of(**{"pkg.locks": """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass

        class B:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    with self._lock:
                        pass
    """})
    found = run_passes(idx, ["lock-order"])
    subs = {f.subject for f in found if f.rule == "self-deadlock"}
    assert "self:pkg.locks.A._lock" in subs          # Lock: deadlock
    assert not any("B._lock" in s for s in subs)     # RLock: reentrant


def test_lock_order_rpc_under_lock():
    idx = index_of(**{"pkg.srv": """
        import threading, subprocess

        _lock = threading.Lock()

        def ship(frames):
            with _lock:
                subprocess.run(["scp", "x"])
    """})
    found = run_passes(idx, ["lock-order"])
    assert ("lock-order", "lock-over-rpc") in rules(found)


# -- recompile -----------------------------------------------------------

def test_recompile_unhashable_arg():
    idx = index_of(**{"pkg.exch": """
        from functools import lru_cache

        @lru_cache(maxsize=8)
        def build_program(mesh, opts):
            return (mesh, opts)

        def run(mesh):
            return build_program(mesh, {"sizing": "exact"})
    """})
    found = run_passes(idx, ["recompile"])
    assert ("recompile", "unhashable-arg") in rules(found)


def test_recompile_traced_branch():
    idx = index_of(**{"pkg.kern": """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("exact",))
        def kernel(x, exact):
            if exact:            # static: fine
                pass
            if x > 0:            # traced: TracerBoolConversionError
                return x
            return -x
    """})
    found = run_passes(idx, ["recompile"])
    branches = [f for f in found if f.rule == "traced-branch"]
    assert len(branches) == 1
    assert "`x`" in branches[0].message


def test_recompile_static_accessors_are_clean():
    idx = index_of(**{"pkg.kern": """
        import jax

        @jax.jit
        def kernel(x, lut):
            if x.shape[0] > 128:   # shapes are static under jit
                pass
            if len(x.shape) == 2:
                pass
            if lut is None:        # pytree structure is static
                return x
            return x

        def run(mesh, key):
            return build(mesh, tuple(sorted(key)))
    """})
    assert run_passes(idx, ["recompile"]) == []


# -- session-props -------------------------------------------------------

SP_REG = """
    REGISTRY = {}

    def register(prop):
        REGISTRY[prop.name] = prop

    class SessionProperty:
        def __init__(self, name, type, default, description):
            self.name = name

    register(SessionProperty(
        "knob_used", "integer", 4, "read below"))
    register(SessionProperty(
        "knob_dead", "boolean", False, "never read"))
    register(SessionProperty(
        "knob_typo", "int", 0, "bad type vocab"))

    def value(session, name):
        return REGISTRY[name]

    def prop_value(props, name):
        return props.get(name)
"""


def test_session_props_dead_undeclared_and_bad_type():
    idx = index_of(**{
        "pkg.session_properties": SP_REG,
        "pkg.engine": """
            from . import session_properties as SP

            def plan(session):
                a = SP.value(session, "knob_used")
                b = SP.value(session, "knob_missing")
                return a, b
        """,
    })
    found = run_passes(idx, ["session-props"])
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    assert [f.subject for f in by_rule["dead-property"]] \
        == ["dead:knob_dead", "dead:knob_typo"]
    assert by_rule["undeclared-lookup"][0].subject \
        == "undeclared:knob_missing"
    assert by_rule["bad-type"][0].subject == "bad-type:knob_typo"


def test_session_props_every_declared_and_read_is_clean():
    idx = index_of(**{
        "pkg.session_properties": SP_REG.replace(
            '    register(SessionProperty(\n        "knob_dead"',
            '    _ = (lambda: None) or register(SessionProperty(\n'
            '        "knob_dead"').replace(
            '"knob_typo", "int"', '"knob_typo", "integer"'),
        "pkg.engine": """
            from . import session_properties as SP

            def plan(session):
                return (SP.value(session, "knob_used"),
                        SP.value(session, "knob_dead"),
                        SP.prop_value({}, "knob_typo"))
        """,
    })
    assert run_passes(idx, ["session-props"]) == []


# -- taxonomy ------------------------------------------------------------

def test_taxonomy_bare_raise_and_broad_swallow():
    idx = index_of(**{"pkg.parallel.worker": """
        def flush(resp):
            if not resp.get("ok"):
                raise RuntimeError("sink rejected")

        def loop():
            try:
                flush({})
            except Exception:
                pass
    """})
    found = run_passes(idx, ["taxonomy"])
    got = rules(found)
    assert ("taxonomy", "bare-raise") in got
    assert ("taxonomy", "broad-swallow") in got


def test_taxonomy_typed_raise_and_routed_handler_are_clean():
    idx = index_of(**{"pkg.parallel.worker": """
        from .fault import RemoteTaskError, serialize_failure

        def flush(resp):
            if not resp.get("ok"):
                raise RemoteTaskError("sink rejected", "INTERNAL")

        def loop(sock):
            try:
                flush({})
            except Exception as e:
                sock.send(serialize_failure(e))

        def reraise():
            try:
                flush({})
            except Exception:
                raise
    """})
    assert run_passes(idx, ["taxonomy"]) == []


def test_taxonomy_scoped_to_parallel_and_pragma():
    idx = index_of(**{
        # outside parallel/: not this pass's business
        "pkg.ops.sort": "def f():\n    raise RuntimeError('x')\n",
        # fault.py defines the vocabulary: exempt
        "pkg.parallel.fault": "def g():\n    raise RuntimeError('y')\n",
        "pkg.parallel.chaos": """
            def inject(task_id):
                raise RuntimeError(  # qlint: ignore[taxonomy] chaos-injected
                    f"injected failure for {task_id}")
        """,
    })
    assert run_passes(idx, ["taxonomy"]) == []


def test_taxonomy_covers_telemetry_and_cache():
    """Round 14 scope extension: telemetry/ and the serving cache are
    runtime paths too — an erased error type there silently disables a
    surface instead of reaching dispatch."""
    idx = index_of(**{
        "pkg.telemetry.metrics": """
            def scrape(fn):
                try:
                    return fn()
                except Exception:
                    return None
        """,
        "pkg.cache": """
            def lookup(key):
                raise RuntimeError("bad key")
        """,
        # fault.py-style exemption preserved
        "pkg.telemetry.fault": "def g():\n    raise RuntimeError('y')\n",
    })
    found = run_passes(idx, ["taxonomy"])
    assert ("taxonomy", "broad-swallow") in rules(found)
    assert ("taxonomy", "bare-raise") in rules(found)
    assert not any(f.module == "pkg.telemetry.fault" for f in found)


# -- blocked-protocol ----------------------------------------------------

def test_blocked_protocol_partial_channel_and_stale_token():
    idx = index_of(**{"pkg.chan": """
        class HalfChannel:
            def poll(self):
                return self._q.pop(0) if self._q else None

            def listen(self):
                return self._token

        class Source:
            def blocked_token(self):
                return self._chan.listen()   # no readiness re-check
    """})
    found = run_passes(idx, ["blocked-protocol"])
    got = rules(found)
    assert ("blocked-protocol", "channel-contract") in got
    assert ("blocked-protocol", "stale-token-park") in got
    contract = [f for f in found if f.rule == "channel-contract"]
    assert "at_end" in contract[0].message
    assert "has_page" in contract[0].message


def test_blocked_protocol_waker_under_lock():
    idx = index_of(**{"pkg.buf": """
        import threading

        class Buf:
            def __init__(self):
                self._lock = threading.Lock()
                self._listeners = []

            def enqueue(self, page):
                with self._lock:
                    self._pages.append(page)
                    for cb in self._listeners:
                        cb()      # fires under the state lock
    """})
    found = run_passes(idx, ["blocked-protocol"])
    assert ("blocked-protocol", "waker-under-lock") in rules(found)


def test_blocked_protocol_repo_idioms_are_clean():
    """The engine's own patterns pass: full quartet, snapshot-then-
    recheck blocked_token, collect-under-lock / fire-after-release."""
    idx = index_of(**{"pkg.ok": """
        import threading

        class Chan:
            def poll(self):
                return None

            def at_end(self):
                return True

            def has_page(self):
                return False

            def listen(self):
                return self._token

        class Source:
            def blocked_token(self):
                token = self._chan.listen()
                if self._chan.at_end() or self._chan.has_page():
                    return None
                return token

        class Buf:
            def __init__(self):
                self._lock = threading.Lock()
                self._listeners = []

            def _bump_locked(self):
                fired = list(self._listeners)
                self._listeners.clear()
                return fired

            def enqueue(self, page):
                with self._lock:
                    self._pages.append(page)
                    fired = self._bump_locked()
                for cb in fired:
                    cb()
    """})
    assert run_passes(idx, ["blocked-protocol"]) == []


# -- alias tracking (round 14 core) --------------------------------------

def test_alias_local_rebind_resolves_lock_identity():
    """`lk = self._lock; with lk:` used to scope the lock to the
    function (invisible); alias expansion recovers the class identity,
    so the self-routed re-acquire is a caught deadlock."""
    idx = index_of(**{"pkg.locks": """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                lk = self._lock
                with lk:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """})
    found = run_passes(idx, ["lock-order"])
    assert any(f.rule == "self-deadlock"
               and "pkg.locks.A._lock" in f.subject for f in found)


def test_alias_rebound_name_never_unifies():
    """A name bound twice is NOT a must-alias: no finding may be
    fabricated from it."""
    idx = index_of(**{"pkg.locks": """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._other = threading.RLock()

            def outer(self):
                lk = self._lock
                lk = self._other
                with lk:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """})
    assert run_passes(idx, ["lock-order"]) == []


def test_attr_types_resolve_cross_instance_calls():
    """`self.ledger.park()` resolves through the __init__-typed
    attribute — the old 3-part-chain dead end."""
    idx = index_of(**{"pkg.m": """
        import jax

        class Ledger:
            def park(self):
                print("host effect")

        class Ctx:
            def __init__(self, ledger: Ledger):
                self.ledger = ledger

            @jax.jit
            def kernel(self, x):
                self.ledger.park()
                return x
    """})
    found = run_passes(idx, ["trace-purity"])
    assert any(f.qualname == "Ledger.park" for f in found)


def test_attr_types_ambiguity_tombstones():
    """An attribute assigned two different types must resolve to
    NOTHING (no finding can be fabricated from a may-alias)."""
    idx = index_of(**{"pkg.m": """
        import jax

        class Ledger:
            def park(self):
                print("host effect")

        class Other:
            def park(self):
                return 1

        class Ctx:
            def __init__(self, ledger: Ledger, other: Other, flag):
                if flag:
                    self.dep = ledger
                else:
                    self.dep = other

            @jax.jit
            def kernel(self, x):
                self.dep.park()
                return x
    """})
    assert run_passes(idx, ["trace-purity"]) == []


def test_attr_types_untyped_rebind_tombstones():
    """An attribute rebound from an UNannotated name (or a lowercase
    factory call) is ambiguous — the earlier typed assignment must not
    survive, or a may-alias could fabricate findings."""
    idx = index_of(**{"pkg.m": """
        import jax

        class Ledger:
            def park(self):
                print("host effect")

        class Ctx:
            def __init__(self):
                self.dep = Ledger()

            def adopt(self, thing):
                self.dep = thing

            @jax.jit
            def kernel(self, x):
                self.dep.park()
                return x
    """})
    assert run_passes(idx, ["trace-purity"]) == []


def test_returned_attribute_accessor_names_the_lock():
    """`with ctx.lock():` where lock() returns self._lock acquires the
    target class's attribute — visible in the acquisition graph."""
    from trino_tpu.analysis.lock_order import build_lock_graph
    idx = index_of(**{"pkg.m": """
        import threading

        class Ctx:
            def __init__(self):
                self._lock = threading.Lock()

            def lock(self):
                return self._lock

        class Spiller:
            def __init__(self):
                self._lock = threading.Lock()

            def spill(self, ctx: Ctx):
                with self._lock:
                    with ctx.lock():
                        pass
    """})
    lg = build_lock_graph(idx)
    assert "pkg.m.Ctx._lock" in lg.graph.get("pkg.m.Spiller._lock", set())
    assert ("pkg.m.Spiller._lock", "pkg.m.Ctx._lock") \
        in lg.cross_instance_edges


# -- lock-order: cross-instance + parametric flow -------------------------

CROSS_AB_BA = """
    import threading

    class Ledger:
        def __init__(self):
            self._lock = threading.Lock()

        def demote(self, ctx: "Ctx"):
            with self._lock:
                spill_pages([], lock=ctx._lock)

        def park(self):
            with self._lock:
                pass

    def spill_pages(pages, lock=None):
        with lock:
            return pages

    class Ctx:
        def __init__(self, ledger: Ledger):
            self._lock = threading.Lock()
            self.ledger = ledger

        def finish(self):
            with self._lock:
                self.ledger.park()
"""


def test_lock_order_cross_instance_ab_ba_via_argument_flow():
    """The seeded cycle the OLD pass provably missed on both edges:
    the forward edge needs parametric lock flow (`lock=ctx._lock` into
    `with lock:` — the old pass scoped the param lock to
    spill_pages), the back edge needs typed-attribute resolution
    (`self.ledger.park()` — the old pass dropped 3-part chains)."""
    from trino_tpu.analysis.lock_order import build_lock_graph
    idx = index_of(**{"pkg.spill": CROSS_AB_BA})
    found = run_passes(idx, ["lock-order"])
    cycles = [f for f in found if f.rule == "lock-cycle"]
    assert len(cycles) == 1
    assert "Ledger._lock" in cycles[0].message
    assert "Ctx._lock" in cycles[0].message
    assert "cross-instance" in cycles[0].message
    lg = build_lock_graph(idx)
    assert ("pkg.spill.Ledger._lock", "pkg.spill.Ctx._lock") \
        in lg.cross_instance_edges


def test_lock_order_param_flow_nonblocking_stays_clean():
    """The same shape with a non-blocking try on the flowed lock (the
    demote_across idiom) must not cycle."""
    idx = index_of(**{"pkg.spill": CROSS_AB_BA.replace(
        "with lock:\n            return pages",
        "ok = lock.acquire(blocking=False)\n        return pages")})
    found = run_passes(idx, ["lock-order"])
    assert [f for f in found if f.rule == "lock-cycle"] == []


def test_lock_order_parametric_must_alias_self_deadlock():
    """Passing the HELD lock itself into a helper that blocking-
    acquires its parameter is a must-alias self-deadlock — provable
    only through argument flow."""
    idx = index_of(**{"pkg.m": """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    helper(self._lock)

        def helper(lock):
            lock.acquire()
    """})
    found = run_passes(idx, ["lock-order"])
    assert any(f.rule == "self-deadlock"
               and "flows through a call argument" in f.message
               for f in found)


def test_lock_order_direct_nested_two_instances_not_self_deadlock():
    """Hand-over-hand locking of TWO instances of one class directly
    nested in one body (`with self._lock: with other._lock:`) is
    ordered locking, not a self-cycle: structural id equality alone
    must not report — only identical source chains prove same-object."""
    idx = index_of(**{"pkg.m": """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def transfer(self, other: "Pool"):
                with self._lock:
                    with other._lock:
                        pass

            def reacquire(self):
                with self._lock:
                    with self._lock:
                        pass
    """})
    found = run_passes(idx, ["lock-order"])
    subs = [f for f in found if f.rule == "self-deadlock"]
    assert [f.qualname for f in subs] == ["Pool.reacquire"]


def test_lock_order_via_self_on_peer_lock_not_self_deadlock():
    """Holding a PEER instance's structurally-equal lock
    (`self.other._lock`) while self-calling a method that takes this
    instance's own lock is ordered locking — via_self alone must not
    report; both sides must be the instance's OWN attribute."""
    idx = index_of(**{"pkg.m": """
        import threading

        class Pool:
            def __init__(self, other: "Pool" = None):
                self._lock = threading.Lock()
                self.other = other

            def f(self):
                with self.other._lock:
                    self.park()

            def park(self):
                with self._lock:
                    pass
    """})
    found = run_passes(idx, ["lock-order"])
    assert [f for f in found if f.rule == "self-deadlock"] == []


def test_lock_order_alias_to_unlockish_name_still_acquires():
    """`lock = self._mu; with lock:` — the RAW name qualifies even
    when the canonical target's name doesn't look lockish; dropping it
    would lose lock-over-rpc/cycle detection the old pass had."""
    idx = index_of(**{"pkg.srv": """
        import threading, subprocess

        class S:
            def __init__(self):
                self._mu = threading.Lock()

            def ship(self):
                lock = self._mu
                with lock:
                    subprocess.run(["scp", "x"])
    """})
    found = run_passes(idx, ["lock-order"])
    assert ("lock-order", "lock-over-rpc") in rules(found)


def test_lock_order_param_flow_of_peer_lock_not_self_deadlock():
    """Handing a DIFFERENT instance's structurally-equal lock to a
    blocking helper while holding your own is a cross-instance
    hand-off: the must-alias claim requires the flowed argument's
    source chain to BE the held chain."""
    idx = index_of(**{"pkg.m": """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def transfer(self, other: "A"):
                with self._lock:
                    grab(other._lock)

        def grab(lock):
            lock.acquire()
    """})
    found = run_passes(idx, ["lock-order"])
    assert [f for f in found if f.rule == "self-deadlock"] == []


def test_lock_order_rebound_head_defeats_same_object_claim():
    """Two textually-identical chains whose head is REBOUND between
    the acquisitions (`ctx = self._next`) are not the same object —
    chain equality needs a non-rebindable head."""
    idx = index_of(**{"pkg.m": """
        import threading

        class Ctx:
            def __init__(self):
                self.lock = threading.Lock()
                self._next = None

        class W:
            def drain(self, ctx: Ctx):
                with ctx.lock:
                    ctx = ctx._next
                    with ctx.lock:
                        pass
    """})
    found = run_passes(idx, ["lock-order"])
    assert [f for f in found if f.rule == "self-deadlock"] == []


def test_lock_order_with_item_call_joins_the_graph():
    """A call made INSIDE a with-item expression (`with enter_chan():`)
    must reach the call graph — its transitive acquisitions close
    real AB-BA cycles."""
    idx = index_of(**{"pkg.m": """
        import threading

        LOCK_A = threading.Lock()
        LOCK_B = threading.Lock()

        def enter_chan():
            LOCK_B.acquire()
            return open("/dev/null")

        def forward():
            with LOCK_A:
                with enter_chan():
                    pass

        def backward():
            with LOCK_B:
                with LOCK_A:
                    pass
    """})
    found = run_passes(idx, ["lock-order"])
    assert any(f.rule == "lock-cycle" for f in found), \
        [f.render() for f in found]


def test_bind_args_respects_varargs_and_kwonly():
    """`helper(1, self._lock)` into `def helper(x, *args, lock=None)`
    puts the lock in *args at runtime — binding it to the kwonly
    `lock` would fabricate a must-alias self-deadlock."""
    idx = index_of(**{"pkg.m": """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    helper(1, self._lock)

        def helper(x, *args, lock=None):
            if lock is not None:
                lock.acquire()
    """})
    found = run_passes(idx, ["lock-order"])
    assert [f for f in found if f.rule == "self-deadlock"] == []


def test_lock_order_two_instances_same_class_not_conflated():
    """Structural identity must NOT turn two instances of one class
    into a self-cycle: a tree of Pools locking parent-then-child is
    fine."""
    idx = index_of(**{"pkg.m": """
        import threading

        class Pool:
            def __init__(self, parent: "Pool" = None):
                self._lock = threading.Lock()
                self.parent = parent

            def charge(self, other: "Pool"):
                with self._lock:
                    other.snapshot()

            def snapshot(self):
                with self._lock:
                    pass
    """})
    found = run_passes(idx, ["lock-order"])
    assert [f for f in found if f.rule == "self-deadlock"] == []


# -- cache-coherence -------------------------------------------------------

def test_cache_coherence_lru_session_read_min_collectives_class():
    """THE acceptance fixture: a session-property read inside an
    lru_cache'd builder whose key omits it fails the pass (the PR 5
    `min_collectives` bug class)."""
    idx = index_of(**{"pkg.exch": """
        from functools import lru_cache
        from .. import session_properties as SP

        @lru_cache(maxsize=8)
        def build_program(mesh, n):
            min_c = SP.prop_value({}, "rebalance_min_collectives")
            return (mesh, n, min_c)
    """})
    found = run_passes(idx, ["cache-coherence"])
    hits = [f for f in found if f.rule == "unkeyed-session-read"]
    assert len(hits) == 1
    assert "rebalance_min_collectives" in hits[0].message
    assert "build_program" in hits[0].message


def test_cache_coherence_memo_env_and_global_reads():
    idx = index_of(**{"pkg.progcache": """
        import os

        _MODE = "auto"

        def set_mode(m):
            global _MODE
            _MODE = m

        class Builder:
            def __init__(self):
                self._programs = {}

            def get(self, key):
                hit = self._programs.get(key)
                if hit is None:
                    flavor = os.environ.get("FLAVOR", "")
                    hit = self._programs[key] = (key, flavor, _MODE)
                return hit
    """})
    found = run_passes(idx, ["cache-coherence"])
    got = rules(found)
    assert ("cache-coherence", "unkeyed-env-read") in got
    assert ("cache-coherence", "unkeyed-global-read") in got
    env = [f for f in found if f.rule == "unkeyed-env-read"]
    assert "'FLAVOR'" in env[0].message


def test_cache_coherence_interprocedural_reach():
    """A helper the builder calls reads the property: flagged with the
    builder named (the read is reachable from memoized code)."""
    idx = index_of(**{"pkg.exch": """
        from functools import lru_cache
        from .. import session_properties as SP

        def pick_sizing():
            return SP.prop_value({}, "device_exchange_sizing")

        @lru_cache(maxsize=8)
        def build_program(mesh):
            return (mesh, pick_sizing())
    """})
    found = run_passes(idx, ["cache-coherence"])
    hits = [f for f in found if f.rule == "unkeyed-session-read"]
    assert len(hits) == 1
    assert "reached from cached builder build_program" in hits[0].message
    assert hits[0].qualname == "pick_sizing"


def test_cache_coherence_keyed_reads_are_clean():
    """Hoisting the read into the key (the canonical fix) and
    constant globals produce no findings; a caller reading props
    OUTSIDE the builder is the designed shape."""
    idx = index_of(**{"pkg.ok": """
        from functools import lru_cache
        from .. import session_properties as SP

        _CONST = 8

        @lru_cache(maxsize=8)
        def build_program(mesh, min_c):
            return (mesh, min_c, _CONST)

        def run(mesh, session):
            min_c = SP.value(session, "rebalance_min_collectives")
            return build_program(mesh, min_c)

        class Builder:
            def __init__(self):
                self._programs = {}

            def get(self, key, flavor):
                hit = self._programs.get((key, flavor))
                if hit is None:
                    hit = self._programs[(key, flavor)] = (key, flavor)
                return hit
    """})
    assert run_passes(idx, ["cache-coherence"]) == []


def test_cache_coherence_memo_read_in_key_is_coherent():
    """A memo builder whose env/session read flows INTO the memo key
    is coherent by construction — the read cannot leave get-or-build
    there, so the pass must recognize it in place (the lru fix of
    'hoist into the key' has no memo equivalent)."""
    idx = index_of(**{"pkg.ok": """
        import os

        class Builder:
            def __init__(self):
                self._programs = {}

            def get(self, key):
                flavor = os.environ.get("FLAVOR", "")
                k = (key, flavor)
                hit = self._programs.get(k)
                if hit is None:
                    hit = self._programs[k] = (key, flavor)
                return hit
    """})
    assert run_passes(idx, ["cache-coherence"]) == []


def test_cache_coherence_inline_key_read_and_aliased_container():
    """A read INLINE in the key expression, and a container reached
    through a local alias, are both keyed — coherent."""
    idx = index_of(**{"pkg.ok": """
        import os

        class B:
            def __init__(self):
                self._programs = {}

            def inline(self, key):
                hit = self._programs.get(
                    (key, os.environ.get("FLAVOR", "")))
                if hit is None:
                    self._programs[(key, "x")] = key
                return hit

        class C:
            def __init__(self):
                self._programs = {}

            def aliased(self, key):
                d = self._programs
                flavor = os.environ.get("FLAVOR", "")
                k = (key, flavor)
                hit = d.get(k)
                if hit is None:
                    hit = d[k] = (key, flavor)
                return hit
    """})
    assert run_passes(idx, ["cache-coherence"]) == []


def test_cache_coherence_global_container_not_its_own_input():
    """A lazily-initialized/resettable `global _CACHE` container is
    the cache itself, not an input missing from its own key."""
    idx = index_of(**{"pkg.m": """
        _CACHE = None

        def reset():
            global _CACHE
            _CACHE = None

        def get_prog(key):
            global _CACHE
            if _CACHE is None:
                _CACHE = {}
            v = _CACHE.get(key)
            if v is None:
                v = _CACHE[key] = (key,)
            return v
    """})
    assert run_passes(idx, ["cache-coherence"]) == []


def test_cache_coherence_rmw_accumulators_are_not_builders():
    """Refcounts/EWMAs (`d[k] = d.get(k, 0) + 1`) cache nothing: a
    session read beside one must not be flagged — tightening here is
    what keeps product code from contorting around the pass."""
    from trino_tpu.analysis.cache_coherence import cached_builders
    idx = index_of(**{"pkg.w": """
        from .. import session_properties as SP

        class W:
            def __init__(self):
                self._refs = {}

            def acquire(self, qid, session):
                self._refs[qid] = self._refs.get(qid, 0) + 1
                return SP.prop_value(session, "query_max_memory_bytes")
    """})
    assert run_passes(idx, ["cache-coherence"]) == []
    assert cached_builders(idx) == {}


def test_cache_coherence_pragma_opt_out():
    idx = index_of(**{"pkg.exch": """
        import os
        from functools import lru_cache

        @lru_cache(maxsize=8)
        def build(mesh):
            mode = os.environ.get("MODE", "")  # qlint: ignore[cache-coherence] trace-static
            return (mesh, mode)
    """})
    assert run_passes(idx, ["cache-coherence"]) == []


# -- resource-lifecycle ----------------------------------------------------

SPOOLY = """
    class SpoolCursor:
        def __init__(self, path):
            self.path = path

        def poll(self):
            return None

        def close(self):
            pass

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            self.close()
"""


def test_resource_lifecycle_leak_and_conditional_close():
    idx = index_of(**{"pkg.spool": SPOOLY + """
    def leak(path):
        cur = SpoolCursor(path)
        return cur.poll()

    def racy(path):
        cur = SpoolCursor(path)
        page = cur.poll()
        cur.close()
        return page

    def dropped(path):
        SpoolCursor(path)
    """})
    found = run_passes(idx, ["resource-lifecycle"])
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    leaks = by_rule.get("leaked-closeable", [])
    assert any(f.qualname == "leak" for f in leaks)
    assert any(f.qualname == "dropped" for f in leaks)
    conds = by_rule.get("close-not-guaranteed", [])
    assert [f.qualname for f in conds] == ["racy"]


def test_resource_lifecycle_satisfied_shapes_are_clean():
    """with / finally / teardown-list registration / weakref.finalize /
    escape (return, self-store, container) all discharge the
    obligation — the engine's own idioms."""
    idx = index_of(**{"pkg.spool": SPOOLY + """
    import weakref

    def with_managed(path):
        with SpoolCursor(path) as cur:
            return cur.poll()

    def finally_closed(path):
        cur = SpoolCursor(path)
        try:
            return cur.poll()
        finally:
            cur.close()

    def registered(path, state):
        cur = SpoolCursor(path)
        state.channels.append(cur)

    def finalized(path):
        cur = SpoolCursor(path)
        weakref.finalize(cur, print, path)
        return cur

    def factory(path):
        return SpoolCursor(path)

    class Owner:
        def __init__(self, path):
            self._cur = SpoolCursor(path)

        def close(self):
            self._cur.close()
    """})
    assert run_passes(idx, ["resource-lifecycle"]) == []


def test_resource_lifecycle_factory_propagates():
    """A caller of a closeable FACTORY holds a closeable exactly as if
    it had called the constructor."""
    idx = index_of(**{"pkg.spool": SPOOLY + """
    def spool_channel(path):
        return SpoolCursor(path)

    def consumer(path):
        chan = spool_channel(path)
        chan.poll()
    """})
    found = run_passes(idx, ["resource-lifecycle"])
    assert any(f.rule == "leaked-closeable" and f.qualname == "consumer"
               for f in found)


def test_resource_lifecycle_open_builtin_and_pragma():
    idx = index_of(**{"pkg.io": """
    def bad(path):
        f = open(path)
        return f.read()

    def opted(path):
        f = open(path)  # qlint: ignore[resource-lifecycle] fd handed to C extension
        return f.read()

    def good(path):
        with open(path) as f:
            return f.read()
    """})
    found = run_passes(idx, ["resource-lifecycle"])
    assert [f.qualname for f in found] == ["bad"]


# -- pragma audit ----------------------------------------------------------

def test_pragma_audit_flags_bare_and_accepts_reasoned():
    idx = index_of(**{"pkg.m": """
        def f():
            x = 1  # qlint: ignore[taxonomy]
            y = 2  # qlint: ignore[trace-purity] deliberate trace-time read
            return x + y
    """})
    found = run_passes(idx, ["taxonomy"])
    bare = [f for f in found if f.pass_id == "pragma"]
    assert len(bare) == 1
    assert bare[0].rule == "missing-reason"
    assert "taxonomy" in bare[0].message


# -- framework plumbing --------------------------------------------------

def test_unknown_pass_rejected():
    idx = index_of(**{"pkg.m": "x = 1\n"})
    with pytest.raises(ValueError, match="unknown passes"):
        run_passes(idx, ["no-such-pass"])


def test_finding_keys_are_line_stable():
    src = """
        def flush(resp):
            raise RuntimeError("boom")
    """
    a = run_passes(index_of(**{"pkg.parallel.m": src}), ["taxonomy"])
    b = run_passes(index_of(**{"pkg.parallel.m": "\n\n\n" + textwrap.dedent(src)}),
                   ["taxonomy"])
    assert [f.key for f in a] == [f.key for f in b]
    assert a[0].line != b[0].line


def test_apply_baseline_splits_new_suppressed_stale():
    idx = index_of(**{"pkg.parallel.m": """
        def f():
            raise RuntimeError("a")

        def g():
            raise Exception("b")
    """})
    found = run_passes(idx, ["taxonomy"])
    assert len(found) == 2
    baseline = {found[0].key: "triaged", "gone:key": "stale"}
    new, suppressed, stale = apply_baseline(found, baseline)
    assert [f.key for f in new] == [found[1].key]
    assert [f.key for f in suppressed] == [found[0].key]
    assert stale == ["gone:key"]


# -- the tier-1 gate -----------------------------------------------------

@pytest.fixture(scope="module")
def repo_findings():
    index = ProjectIndex.from_package(PACKAGE)
    return index, run_passes(index)


def test_gate_repo_is_clean_modulo_baseline(repo_findings):
    """THE gate: every pass over trino_tpu/, zero non-baselined
    findings, no stale baseline entries (the baseline only shrinks)."""
    _index, findings = repo_findings
    baseline = load_baseline(default_baseline_path(PACKAGE))
    new, _suppressed, stale = apply_baseline(findings, baseline)
    assert not new, "non-baselined findings:\n" + "\n".join(
        f.render() for f in new)
    assert not stale, ("baseline entries that no longer fire "
                       "(remove them): " + ", ".join(stale))
    # the baseline may only shrink: at PR 7 every first-run finding
    # was fixed instead of baselined, so any growth is a regression
    assert len(baseline) <= 0, \
        "analysis_baseline.json grew — fix new findings instead"


def test_gate_passes_are_not_blind_on_the_real_repo(repo_findings):
    """The gate is only meaningful if the passes actually index the
    engine: staged-out entry points, locks, cached builders and the
    property registry must all be visible."""
    from trino_tpu.analysis.trace_purity import jit_entries
    from trino_tpu.analysis.recompile import _cached_functions
    from trino_tpu.analysis.session_props import (_declarations,
                                                  _registry_module)
    index, _ = repo_findings
    entries = jit_entries(index)
    assert len(entries) >= 15, sorted(entries)
    assert any(e.kind == "shard_map" for e in entries.values())
    assert "trino_tpu.parallel.device_exchange:_exchange_program.prog" \
        in entries
    # round 16: the vmapped batch entry (jax.jit(jax.vmap(_run, ...)))
    # must stay inside the trace-purity walk — the vmap unwrapping in
    # jit_entries is what keeps the batched path not-blind
    assert "trino_tpu.expr.compiler:PageProcessor._run" in entries
    # the kernel-strategy entry points (round 12) must be inside the
    # trace-purity walk — the matmul probe, the global-hash claim loop,
    # and the per-key-range adaptive kernels are all hot jit'd code
    for entry in ("trino_tpu.ops.matmul_join:_matmul_lo_count",
                  "trino_tpu.ops.global_hash_agg:global_hash_insert",
                  "trino_tpu.ops.global_hash_agg:global_hash_reduce",
                  "trino_tpu.ops.aggregation:_bucket_reduction_stats",
                  "trino_tpu.parallel.mesh_query:q1_global_hash_fn.dist"):
        assert entry in entries, entry
    cached = _cached_functions(index)
    assert "trino_tpu.parallel.device_exchange:_exchange_program" \
        in cached
    declared = _declarations(_registry_module(index))
    assert len(declared) >= 30
    assert declared["retry_policy"][0] == "varchar"
    assert "page_rows" not in declared
    from trino_tpu.analysis.blocked_protocol import channel_classes
    chans = channel_classes(index)
    assert len(chans) >= 5, chans
    assert "trino_tpu.parallel.remote_exchange:RemoteExchangeChannel" \
        in chans
    assert "trino_tpu.parallel.spool:SpoolCursor" in chans
    # round 14: the cache-coherence pass must see the engine's caches
    # (lru program builders AND hand-rolled memo dicts) ...
    from trino_tpu.analysis.cache_coherence import cached_builders
    builders = cached_builders(index)
    assert len(builders) >= 10, sorted(builders)
    assert "trino_tpu.parallel.device_exchange:_exchange_program" \
        in builders
    assert builders[
        "trino_tpu.parallel.device_exchange:_exchange_program"].kind \
        == "lru"
    assert "trino_tpu.cache:ProcessorCache.get" in builders
    assert "trino_tpu.cache:QueryCache.parse" in builders
    assert "trino_tpu.parallel.mesh_query:_cached_program" in builders
    # round 20: the HBO plan-exploration sites must stay visible.  The
    # optimizer's per-run region-estimate memo is a cached builder
    # (an unkeyed session/env read inside it would poison every
    # optimize() of the process) ...
    assert "trino_tpu.planner.memo:RuleContext.region_stats" in builders
    assert builders[
        "trino_tpu.planner.memo:RuleContext.region_stats"].kind == "memo"
    # ... and the broadcast-vs-partitioned DISTRIBUTION decision site
    # is indexed, including its history-flip counter call — a rename
    # would silently blind the cache-coherence walk to the decision
    vjoin = next(
        (f for f in index.iter_functions()
         if f.module == "trino_tpu.planner.exchanges"
         and f.qualname == "ExchangePlanner._v_JoinNode"), None)
    assert vjoin is not None
    assert any(c.chain.split(".")[-1] == "note_plan_flip"
               for c in vjoin.calls), \
        sorted(c.chain for c in vjoin.calls)
    # the plan-exploration session gates are declared with read sites
    # in the modules that enforce them
    assert declared["hbo_reorder_joins_enabled"][0] == "boolean"
    assert declared["hbo_distribution_enabled"][0] == "boolean"
    # ... the resource-lifecycle pass must see the closeables ...
    from trino_tpu.analysis.resource_lifecycle import (
        closeable_classes, closeable_factories)
    closeables = closeable_classes(index)
    assert len(closeables) >= 5, sorted(closeables)
    for cls in ("SpoolCursor", "_ChainedSpoolCursor",
                "RemoteExchangeChannel", "DiskSpiller",
                "QueryMemoryPool"):
        assert cls in closeables, cls
    factories = closeable_factories(index, closeables)
    assert "trino_tpu.parallel.spool:spool_channel" in factories
    assert "trino_tpu.parallel.spool:spool_task_cursor" in factories
    # ... and alias tracking must resolve CROSS-INSTANCE acquisition
    # edges on the real lock graph (the carried ROADMAP follow-on:
    # the old pass excluded these structurally)
    from trino_tpu.analysis.lock_order import build_lock_graph
    lg = build_lock_graph(index)
    assert lg.cross_instance_edges, "no cross-instance lock edges"
    assert ("trino_tpu.parallel.worker.WorkerServer._lock",
            "trino_tpu.exec.memory.NodeMemoryPool._lock") \
        in lg.cross_instance_edges, sorted(lg.cross_instance_edges)
    # the compiled-program profiler (round 11) must cover the jit
    # entry points: instrument() registrations are indexed by name so
    # a dropped wrapper can't silently blind EXPLAIN ANALYZE VERBOSE,
    # system.runtime.kernels, or the bench flight recorder
    from trino_tpu.analysis.trace_purity import profiled_entries
    profiled = profiled_entries(index)
    assert len(profiled) >= 15, sorted(profiled)
    for kernel in ("page_processor", "page_processor_batched",
                   "sort_by", "window_kernel",
                   "hash_group_ids", "hash_segment_reduce",
                   "sort_group_reduce", "join_build_sorted",
                   "join_probe_counts", "join_expand_matches",
                   "matmul_join_probe", "grouped_topn_kernel",
                   "device_exchange_program", "device_exchange_count",
                   "mesh_q1_stage1", "segment_reduce_pallas",
                   # round 17: masked agg/join lanes register through
                   # the _batched_kernel facade (jit(vmap(...)) wraps)
                   # — the facade-resolving walker must NOT go blind
                   "batched_agg_partial", "batched_agg_merge",
                   "batched_agg_finalize", "batched_join_probe",
                   "batched_join_expand", "batched_join_semi"):
        assert kernel in profiled, kernel
    assert all(m == "trino_tpu.exec.batched"
               for m in profiled["batched_agg_partial"])


def test_hbo_record_path_indexed_and_outside_jit(repo_findings):
    """History-based statistics (round 13): the stats-store write path
    must be VISIBLE to the index (not blind — a renamed record method
    would silently stop the check meaning anything) and every caller
    of it must be OUTSIDE the jit-reachable set: a store write that
    migrated inside traced code would fire once per compile instead of
    once per query, freezing history at trace-time values."""
    from trino_tpu.analysis.trace_purity import (jit_reachable,
                                                 recording_sites)
    index, _ = repo_findings
    sites = recording_sites(index)
    callers = {fid for fids in sites.values() for fid in fids}
    # the HboContext record facade calls record_query; the runners
    # call record/record_actuals — all must be indexed
    assert any("record_query" in chain for chain in sites), sites
    assert any("record_actuals" in chain for chain in sites), sites
    assert any(fid.startswith("trino_tpu.telemetry.stats_store:")
               for fid in callers), sorted(callers)
    reached = jit_reachable(index)
    inside = callers & reached
    assert not inside, (
        "stats-store write path reachable from jit-traced code: "
        + ", ".join(sorted(inside)))


# -- guarded-by ----------------------------------------------------------

def test_guarded_by_bare_write_from_timer_thread():
    """Known-bad: an attribute mutated under a lock on the main path
    but written bare from a Timer-thread callback."""
    idx = index_of(**{"pkg.srv": """
        import threading

        class Sweeper:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                threading.Timer(5.0, self._tick).start()

            def _tick(self):
                self.count += 1      # bare write on the timer thread

            def bump(self):
                with self._lock:
                    self.count += 1

            def bump_again(self):
                with self._lock:
                    self.count += 1
    """})
    found = run_passes(idx, ["guarded-by"])
    assert ("guarded-by", "guarded-by") in rules(found)
    assert any(f.qualname == "Sweeper._tick" for f in found)
    # the message names the inferred guard and the guarded sites
    msg = next(f.message for f in found
               if f.qualname == "Sweeper._tick")
    assert "_lock" in msg and "timer" in msg


def test_guarded_by_interprocedural_lockset_is_clean():
    """A helper that mutates ONLY under callers that hold the lock
    inherits the lockset through the summary fixpoint — no finding."""
    idx = index_of(**{"pkg.srv": """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    self._merge(1)

            def record(self):
                with self._lock:
                    self._merge(2)

            def _merge(self, v):
                self.total += v      # guarded via every caller
    """})
    assert run_passes(idx, ["guarded-by"]) == []


def test_guarded_by_check_then_act_on_shared_dict():
    idx = index_of(**{"pkg.memo": """
        import threading

        class Memo:
            def __init__(self):
                self.memo = {}
                threading.Thread(target=self._sweep).start()

            def _sweep(self):
                for k in list(self.memo):
                    del self.memo[k]

            def get_or_build(self, k):
                if k not in self.memo:    # unlocked test-then-mutate
                    self.memo[k] = object()
                return self.memo[k]
    """})
    found = run_passes(idx, ["guarded-by"])
    assert ("guarded-by", "check-then-act") in rules(found)
    assert any(f.qualname == "Memo.get_or_build" for f in found)


def test_guarded_by_check_then_act_sees_tuple_unpack_store():
    """The body scan shares the site recorder's target predicate:
    a container store hidden inside a tuple unpack still counts."""
    idx = index_of(**{"pkg.memo": """
        import threading

        class Memo:
            def __init__(self):
                self.memo = {}
                self.other = 0
                threading.Thread(target=self._sweep).start()

            def _sweep(self):
                for k in list(self.memo):
                    del self.memo[k]

            def get_or_build(self, k):
                if k not in self.memo:
                    self.memo[k], self.other = (1, 2)
                return self.memo[k]
    """})
    found = run_passes(idx, ["guarded-by"])
    assert ("guarded-by", "check-then-act") in rules(found)


def test_guarded_by_locked_check_then_act_is_clean():
    idx = index_of(**{"pkg.memo": """
        import threading

        class Memo:
            def __init__(self):
                self.memo = {}
                self._lock = threading.Lock()
                threading.Thread(target=self._sweep).start()

            def _sweep(self):
                with self._lock:
                    self.memo.clear()

            def get_or_build(self, k):
                with self._lock:
                    if k not in self.memo:
                        self.memo[k] = object()
                    return self.memo[k]
    """})
    assert run_passes(idx, ["guarded-by"]) == []


def test_guarded_by_immutable_after_init_exempt():
    """Assigned solely in __init__ BEFORE the spawn: publication
    happens-before the thread — reads anywhere are clean. The same
    attribute assigned AFTER the spawn is the `init-race` rule: the
    spawned thread can run before the store lands."""
    clean = index_of(**{"pkg.a": """
        import threading

        class Ok:
            def __init__(self):
                self._lock = threading.Lock()
                self.config = {"a": 1}
                threading.Thread(target=self._loop).start()

            def _loop(self):
                return self.config.get("a")
    """})
    assert run_passes(clean, ["guarded-by"]) == []

    racy = index_of(**{"pkg.b": """
        import threading

        class Bad:
            def __init__(self):
                threading.Thread(target=self._loop).start()
                self.config = {"a": 1}     # spawned thread reads this

            def _loop(self):
                return self.config.get("a")
    """})
    found = run_passes(racy, ["guarded-by"])
    assert ("guarded-by", "init-race") in rules(found)
    assert any(f.qualname == "Bad.__init__" for f in found)
    # a post-spawn store the spawned thread never touches stays clean
    untouched = index_of(**{"pkg.c": """
        import threading

        class Meh:
            def __init__(self):
                threading.Thread(target=self._loop).start()
                self.unrelated = 3

            def _loop(self):
                return 1
    """})
    assert run_passes(untouched, ["guarded-by"]) == []


def test_guarded_by_single_entry_exempt():
    """Every site on ONE entry (the fetch loop owns its cursors):
    sequential within the thread — exempt even with a lock elsewhere
    in the class."""
    idx = index_of(**{"pkg.chan": """
        import threading

        class Channel:
            def __init__(self):
                self._lock = threading.Lock()
                self._queue = []
                self._cursor = 0
                threading.Thread(target=self._fetch).start()

            def _fetch(self):
                self._cursor += 1     # only this thread touches it
                with self._lock:
                    self._queue.append(self._cursor)

            def poll(self):
                with self._lock:
                    if self._queue:
                        return self._queue.pop()
    """})
    found = run_passes(idx, ["guarded-by"])
    assert not any("_cursor" in f.message for f in found), found


def test_guarded_by_pragma_opt_out():
    idx = index_of(**{"pkg.srv": """
        import threading

        class Gauge:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self.n += 1  # qlint: ignore[guarded-by] monotonic gauge, torn reads acceptable

            def a(self):
                with self._lock:
                    self.n += 1

            def b(self):
                with self._lock:
                    self.n += 1
    """})
    assert run_passes(idx, ["guarded-by"]) == []


def test_guarded_by_condition_guards_like_a_lock():
    """`with self._cond:` (threading.Condition) is mutual exclusion —
    the construction site registers the identity past the lockish-name
    heuristic."""
    idx = index_of(**{"pkg.q": """
        import threading

        class Queue:
            def __init__(self):
                self._cond = threading.Condition()
                self.items = []
                threading.Thread(target=self._drain).start()

            def _drain(self):
                with self._cond:
                    if self.items:
                        self.items.pop()

            def offer(self, x):
                with self._cond:
                    self.items.append(x)
    """})
    assert run_passes(idx, ["guarded-by"]) == []


def test_guarded_by_sees_closure_self_in_nested_thread_target():
    """A nested def that captures the method's `self` (the per-task
    `run_one` shape) is attributed to the enclosing class — bare
    closure accesses cannot hide from the pass."""
    idx = index_of(**{"pkg.srv": """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self.slots = [1, 2]

            def swap(self, i):
                with self._lock:
                    self.slots[i] = 0

            def swap2(self, i):
                with self._lock:
                    self.slots[i] = 1

            def launch(self):
                def run_one(t):
                    return [s for s in self.slots if s]  # bare, closure
                threading.Thread(target=run_one, args=(0,)).start()
    """})
    found = run_passes(idx, ["guarded-by"])
    assert ("guarded-by", "guarded-by") in rules(found)
    assert any(f.qualname.endswith("run_one") for f in found), found


def test_thread_entry_kinds_taxonomy():
    """Every entry kind the index models: thread / timer / executor /
    rpc-handler / finalizer."""
    from trino_tpu.analysis.core import thread_entries
    idx = index_of(**{"pkg.m": """
        import threading
        import weakref
        from socketserver import BaseRequestHandler

        class H(BaseRequestHandler):
            def handle(self):
                pass

        class S:
            def __init__(self, pool):
                threading.Thread(target=self._loop).start()
                threading.Timer(1.0, self._tick).start()
                pool.submit(self._job)
                weakref.finalize(self, self._fin)

            def _loop(self): pass
            def _tick(self): pass
            def _job(self): pass
            def _fin(self): pass
    """})
    entries = thread_entries(idx)
    kinds = {e.func_id.split(":")[-1]: e.kind
             for e in entries.values()}
    assert kinds == {"S._loop": "thread", "S._tick": "timer",
                     "S._job": "executor", "S._fin": "finalizer",
                     "H.handle": "rpc-handler"}


def test_guarded_by_not_blind_on_the_real_repo(repo_findings):
    """The pass is only meaningful if it actually sees the engine's
    thread structure: the entry index, the guard inference and the
    named shared-state classes must all be populated."""
    from trino_tpu.analysis.core import thread_entries
    from trino_tpu.analysis.guarded_by import analyze
    index, _ = repo_findings
    entries = thread_entries(index)
    assert len(entries) >= 8, sorted(entries)
    mods = {e.func_id.split(":")[0] for e in entries.values()}
    assert len(mods) >= 4, sorted(mods)
    # the known thread-spawning modules must all contribute entries
    for mod in ("trino_tpu.exec.task_executor",
                "trino_tpu.parallel.process_runner",
                "trino_tpu.parallel.remote_exchange",
                "trino_tpu.parallel.worker",
                "trino_tpu.server.protocol"):
        assert mod in mods, sorted(mods)
    kinds = {e.kind for e in entries.values()}
    assert {"thread", "executor", "rpc-handler", "finalizer"} <= kinds
    analysis = analyze(index)
    assert len(analysis.guards) >= 10, sorted(analysis.guards)
    # the engine's known guarded families resolve to their locks
    assert analysis.guards[
        "trino_tpu.parallel.remote_exchange.RemoteExchangeChannel"
        "._queue"].endswith("RemoteExchangeChannel._lock")
    assert analysis.guards[
        "trino_tpu.parallel.process_runner.ProcessQueryRunner"
        ".workers"].endswith("ProcessQueryRunner._heal_lock")
    # the shared-state classes the pass exists for are indexed — a
    # rename that dropped them would blind the pass silently
    for probe in ("trino_tpu.parallel.worker._RetainedStream.frames",
                  "trino_tpu.server.protocol._QueryState.state",
                  "trino_tpu.exec.memory.HostSpillLedger"
                  ".resident_bytes"):
        assert probe in analysis.sites, probe
    assert analysis.guards[
        "trino_tpu.exec.memory.HostSpillLedger.resident_bytes"] \
        .endswith("HostSpillLedger._lock")


def test_guarded_by_sees_hybrid_join_partition_table(repo_findings):
    """The hybrid hash join's partition table mutates from whatever
    thread happens to hit the pool's revocation callback mid-reserve —
    exactly the shape the guarded-by pass exists for.  Reachability
    cannot see through the ``ctx._revoke_cb`` indirection, so the
    single-entry exemption (not a resolved guard) is the expected
    steady state; the floor pins what the pass DOES see: the class is
    indexed, and every post-init access of the partition-table family
    lexically holds ``HybridJoinState._lock``.  If the callback edge
    ever becomes visible, the exemption must flip to the real guard,
    never to a blind spot."""
    from trino_tpu.analysis.guarded_by import analyze

    index, _ = repo_findings
    analysis = analyze(index)
    base = "trino_tpu.ops.join.HybridJoinState."
    lock = base + "_lock"
    for attr in ("resident", "spilled_build", "spilled_probe",
                 "spilled_build_rows", "total_build_rows",
                 "demotions", "repartitions", "max_depth_seen"):
        ss = analysis.sites.get(base + attr)
        assert ss, f"guarded-by pass is blind to {base + attr}"
        post = [s for s in ss if not s.in_init]
        assert post, f"{attr}: no post-init sites indexed"
        for s in post:
            assert lock in s.lexical, (
                f"{attr} touched outside the partition-table lock at "
                f"{s.func_id}:{s.line}")
        guard = analysis.guards.get(base + attr)
        if guard is None:
            assert analysis.exempt.get(base + attr) == "single-entry", \
                (attr, analysis.exempt.get(base + attr))
        else:
            assert guard == lock, (attr, guard)


def test_nine_passes_registered():
    assert sorted(PASSES) == sorted([
        "trace-purity", "lock-order", "recompile", "session-props",
        "taxonomy", "blocked-protocol", "cache-coherence",
        "resource-lifecycle", "guarded-by"])


def test_analyzer_wall_clock_ratchet():
    """The suite is a pre-commit gate: a FULL fresh run (index + all
    nine passes + pragma audit) must stay under 10 s on CPU. A pass
    that regresses this turns the tier-1 gate and the bench pre-flight
    into the slow path everyone skips. Measured as PROCESS CPU time —
    the analyzer is single-threaded pure Python, so this equals wall
    on an idle host but cannot flake under CI contention (the same
    reason the QPS ratchet gates on a self-normalizing ratio)."""
    import time
    t0 = time.process_time()
    index = ProjectIndex.from_package(PACKAGE)
    run_passes(index)
    elapsed = time.process_time() - t0
    assert elapsed < 10.0, f"qlint full run took {elapsed:.2f}s CPU"


def test_cli_runs_clean_and_json(tmp_path):
    """`python -m trino_tpu.analysis` end to end: rc 0 on the clean
    tree, SARIF 2.1.0 shape, and rc 1 + stale reporting on a bad
    baseline."""
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis", "--json", PACKAGE],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["version"] == "2.1.0"
    assert "sarif" in payload["$schema"]
    run = payload["runs"][0]
    assert run["tool"]["driver"]["name"] == "qlint"
    assert run["results"] == []
    props = run["properties"]
    assert props["new"] == []
    assert props["stale_baseline_keys"] == []
    assert sorted(props["passes"]) == sorted(PASSES)

    bad = tmp_path / "baseline.json"
    bad.write_text(json.dumps(
        {"findings": [{"key": "taxonomy:bare-raise:gone:f:raise",
                       "note": "stale"}]}))
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis", PACKAGE,
         "--baseline", str(bad)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 1
    assert "STALE" in out.stdout


def test_cli_pass_selection(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis",
         "--passes", "session-props,taxonomy", "--json", PACKAGE],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["runs"][0]["properties"]["passes"] == \
        ["session-props", "taxonomy"]
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis",
         "--passes", "bogus", PACKAGE],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60)
    assert out.returncode == 2


def test_cli_changed_since(tmp_path):
    """Diff-aware pre-commit mode: full-index analysis, report
    filtered to files the git diff touched; SARIF results carry the
    same filter."""
    pkg = tmp_path / "pkg"
    (pkg / "parallel").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "parallel" / "__init__.py").write_text("")
    (pkg / "parallel" / "a.py").write_text(
        "def fa():\n    raise RuntimeError('a')\n")
    (pkg / "parallel" / "b.py").write_text(
        "def fb():\n    raise RuntimeError('b')\n")

    def git(*args):
        out = subprocess.run(
            ["git", "-C", str(tmp_path), "-c", "user.name=t",
             "-c", "user.email=t@t", *args],
            capture_output=True, text=True, timeout=30)
        assert out.returncode == 0, out.stderr
        return out

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")
    # touch ONLY a.py: its finding reports, b.py's is filtered out
    (pkg / "parallel" / "a.py").write_text(
        "def fa():\n    x = 1\n    raise RuntimeError('a')\n")

    # an UNTRACKED new module must be part of the changed set too: a
    # pre-commit gate that can't see files before `git add` is useless
    (pkg / "parallel" / "c.py").write_text(
        "def fc():\n    raise RuntimeError('c')\n")

    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis", str(pkg),
         "--no-baseline", "--changed-since", "HEAD"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "pkg.parallel.a" in out.stdout
    assert "pkg.parallel.c" in out.stdout
    assert "pkg.parallel.b" not in out.stdout
    assert "changed-since HEAD" in out.stderr

    # the full run still sees both
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis", str(pkg),
         "--no-baseline"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 1
    assert "pkg.parallel.a" in out.stdout
    assert "pkg.parallel.b" in out.stdout

    # a docs-only diff must exit 0 with an EXPLICIT no-analyzable-
    # changes note (distinguishable from an analyzed-and-clean run in
    # CI logs), even though the tree still has findings
    git("add", "-A")
    git("commit", "-qm", "tree with findings")
    (tmp_path / "NOTES.md").write_text("docs only\n")
    out = subprocess.run(
        [sys.executable, "-m", "trino_tpu.analysis", str(pkg),
         "--no-baseline", "--changed-since", "HEAD"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "no analyzable changes" in out.stderr
    assert "touches no Python files" in out.stderr
