"""Cost/stats framework tests (reference analog: cost/TestStatsCalculator,
TestFilterStatsCalculator, TestJoinStatsRule)."""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.planner.logical_planner import LogicalPlanner, Metadata
from trino_tpu.planner.optimizer import optimize
from trino_tpu.planner.stats import StatsCalculator
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session
from trino_tpu.sql.parser import parse_statement


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(page_rows=4096)


@pytest.fixture(scope="module")
def metadata(conn):
    return Metadata({"tpch": conn})


def plan_of(metadata, sql, schema="sf1"):
    session = Session(catalog="tpch", schema=schema)
    planner = LogicalPlanner(metadata, session)
    root = planner.plan(parse_statement(sql))
    return optimize(root, metadata, planner.allocator)


def stats_of(metadata, sql, schema="sf1"):
    root = plan_of(metadata, sql, schema)
    return StatsCalculator(metadata).stats(root.source)


def test_scan_rows(metadata):
    s = stats_of(metadata, "select * from lineitem")
    assert 5_500_000 < s.row_count < 6_500_000  # ~6M at SF1
    assert s.confident


def test_equality_selectivity_uses_ndv(metadata):
    s = stats_of(metadata,
                 "select * from customer where c_mktsegment = 'BUILDING'")
    base = stats_of(metadata, "select * from customer")
    assert abs(s.row_count - base.row_count / 5) / base.row_count < 0.01


def test_range_selectivity_uses_min_max(metadata):
    # l_quantity uniform over [1, 50]: < 25 (raw 2500) ~ half
    s = stats_of(metadata,
                 "select * from lineitem where l_quantity < 25")
    base = stats_of(metadata, "select * from lineitem")
    assert 0.4 < s.row_count / base.row_count < 0.6


def test_join_cardinality_fk(metadata):
    # orders JOIN customer on the FK: output ~ |orders|
    s = stats_of(metadata, """
        select * from orders, customer where o_custkey = c_custkey""")
    orders = stats_of(metadata, "select * from orders")
    assert 0.5 < s.row_count / orders.row_count < 2.0


def test_group_by_ndv_caps_output(metadata):
    s = stats_of(metadata, """
        select l_returnflag, l_linestatus, count(*) from lineitem
        group by l_returnflag, l_linestatus""")
    assert s.row_count <= 6 + 1  # 3 * 2 ndv product


def test_join_order_puts_filtered_small_side_on_build():
    """q3-shape: the planner should NOT pick a join order that crosses
    the two big tables first; correctness smoke + plan sanity."""
    conn = TpchConnector(page_rows=4096)
    r = LocalQueryRunner({"tpch": conn},
                         Session(catalog="tpch", schema="micro"))
    rows = r.execute("""
        select o_orderkey, sum(l_extendedprice) rev
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
          and l_orderkey = o_orderkey
        group by o_orderkey order by rev desc limit 5""").rows
    assert len(rows) == 5
