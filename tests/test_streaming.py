"""Streaming pipelined execution: all stages concurrent, pages flowing
through exchanges with backpressure, blocked tasks parking on listen
tokens.

Reference analog: ``execution/scheduler/PipelinedQueryScheduler.java``
(stage overlap), ``operator/Driver.java:380-486`` + ``Operator.java``
isBlocked (blocked futures), ``execution/buffer/`` (bounded output
buffers). Round-3 verdict #2: the engine previously barriered at every
fragment boundary.
"""

import threading
import time

import pytest

from trino_tpu import session_properties as SP
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.ops.output import ExchangeChannel, OutputBuffer
from trino_tpu.parallel.distributed import DistributedQueryRunner
from trino_tpu.resources.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


def make_dist(streaming: bool, **props):
    sess = Session(catalog="tpch", schema="micro")
    SP.set_property(sess.properties, "streaming_execution", streaming)
    for k, v in props.items():
        SP.set_property(sess.properties, k, v)
    return DistributedQueryRunner({"tpch": TpchConnector(page_rows=512)},
                                  sess, n_workers=4)


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=2048)},
                            Session(catalog="tpch", schema="micro"))


def test_streaming_q3_overlaps_and_matches(local):
    """The verdict's done-criterion: distributed q3 where a consumer
    stage dequeues pages BEFORE its producer stage finished (witnessed
    by the buffer's first_poll/no_more timestamps), with results
    identical to local execution."""
    want = sorted(local.execute(TPCH_QUERIES[3]).rows)
    res = make_dist(True).execute(TPCH_QUERIES[3])
    assert sorted(res.rows) == want
    overlap = res.stats["streaming_overlap"]
    assert any(overlap.values()), (
        f"no stage overlap observed: {overlap}")


@pytest.mark.slow  # 3 full distributed queries x 2 modes (~40s)
def test_streaming_matches_barrier_mode(local):
    for q in (1, 10, 18):
        want = sorted(make_dist(False).execute(TPCH_QUERIES[q]).rows)
        got = sorted(make_dist(True).execute(TPCH_QUERIES[q]).rows)
        assert got == want, f"q{q} streaming != barrier"


def test_streaming_error_propagates_without_deadlock(local):
    """A task dying mid-stream must fail the query (not deadlock
    consumers parked on its buffer), and the runner stays usable."""
    sess = Session(catalog="tpch", schema="micro")
    SP.set_property(sess.properties, "streaming_execution", True)
    conn = TpchConnector(page_rows=512)
    orig = conn.page_source
    state = {"calls": 0, "arm": True}

    def failing_page_source(split, cols):
        state["calls"] += 1
        if state["arm"] and state["calls"] > 2:
            raise RuntimeError("injected scan failure")
        return orig(split, cols)

    conn.page_source = failing_page_source
    r = DistributedQueryRunner({"tpch": conn}, sess, n_workers=4)
    with pytest.raises(RuntimeError, match="injected scan failure"):
        r.execute(TPCH_QUERIES[3])
    state["arm"] = False
    # the runner is reusable after a failed query
    assert r.execute("select count(*) from nation").rows == [(25,)]


def test_bounded_buffer_backpressure_and_listen():
    from trino_tpu.block import Page
    from trino_tpu import types as T

    buf = OutputBuffer(1, max_pending_pages=2)
    page = Page.from_pylists([T.BIGINT], [[1, 2, 3]])
    buf.enqueue(0, page)
    buf.enqueue(0, page)
    assert buf.full()
    fired = []
    buf.listen().on_ready(lambda: fired.append("space"))
    assert not fired
    chan = ExchangeChannel(buf, 0, 0)
    assert chan.poll() is page     # drain one
    assert fired == ["space"]      # producer listener woke
    assert not buf.full()
    # end-of-stream plumbing
    assert not chan.at_end()
    buf.set_no_more_pages()
    assert chan.poll() is page
    assert chan.poll() is None
    assert chan.at_end()
    assert buf.overlapped  # polled before no_more


def test_listen_token_fires_immediately_when_stale():
    from trino_tpu.block import Page
    from trino_tpu import types as T

    buf = OutputBuffer(1, max_pending_pages=8)
    token = buf.listen()
    buf.enqueue(0, Page.from_pylists([T.BIGINT], [[1]]))
    fired = []
    token.on_ready(lambda: fired.append(1))  # version moved: immediate
    assert fired == [1]


def test_task_executor_parks_blocked_entries():
    """A Blocked yield parks the entry (no busy spin); the token wakeup
    re-offers it exactly once."""
    from trino_tpu.exec.task_executor import Blocked, TaskExecutor

    ex = TaskExecutor(num_threads=2, name="test-exec")
    buf = OutputBuffer(1, max_pending_pages=4)
    from trino_tpu.block import Page
    from trino_tpu import types as T

    steps = []

    def consumer():
        chan = ExchangeChannel(buf, 0, 0)
        while True:
            p = chan.poll()
            if p is not None:
                steps.append("page")
            elif chan.at_end():
                steps.append("end")
                return
            else:
                token = chan.listen()
                if chan.at_end() or chan.has_page():
                    continue
                steps.append("park")
                yield Blocked([token])

    fut = ex.submit(consumer())
    time.sleep(0.3)
    assert steps == ["park"], f"consumer should park: {steps}"
    buf.enqueue(0, Page.from_pylists([T.BIGINT], [[7]]))
    time.sleep(0.3)
    assert "page" in steps
    buf.set_no_more_pages()
    fut.result(timeout=10)
    assert steps[-1] == "end"
    ex.close()


def test_abort_unblocks_producers_and_consumers():
    from trino_tpu.block import Page
    from trino_tpu import types as T

    buf = OutputBuffer(1, max_pending_pages=1)
    buf.enqueue(0, Page.from_pylists([T.BIGINT], [[1]]))
    assert buf.full()
    fired = []
    buf.listen().on_ready(lambda: fired.append(1))
    buf.abort()
    assert fired == [1]
    assert not buf.full()
    chan = ExchangeChannel(buf, 0, 0)
    assert chan.poll() is None and chan.at_end()
