"""Streaming pipelined execution: all stages concurrent, pages flowing
through exchanges with backpressure, blocked tasks parking on listen
tokens.

Reference analog: ``execution/scheduler/PipelinedQueryScheduler.java``
(stage overlap), ``operator/Driver.java:380-486`` + ``Operator.java``
isBlocked (blocked futures), ``execution/buffer/`` (bounded output
buffers). Round-3 verdict #2: the engine previously barriered at every
fragment boundary.
"""

import threading
import time

import pytest

from trino_tpu import session_properties as SP
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.ops.output import ExchangeChannel, OutputBuffer
from trino_tpu.parallel.distributed import DistributedQueryRunner
from trino_tpu.resources.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


def make_dist(streaming: bool, **props):
    sess = Session(catalog="tpch", schema="micro")
    SP.set_property(sess.properties, "streaming_execution", streaming)
    for k, v in props.items():
        SP.set_property(sess.properties, k, v)
    return DistributedQueryRunner({"tpch": TpchConnector(page_rows=512)},
                                  sess, n_workers=4)


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=2048)},
                            Session(catalog="tpch", schema="micro"))


def test_streaming_q3_overlaps_and_matches(local):
    """The verdict's done-criterion: distributed q3 where a consumer
    stage dequeues pages BEFORE its producer stage finished (witnessed
    by the buffer's first_poll/no_more timestamps), with results
    identical to local execution."""
    want = sorted(local.execute(TPCH_QUERIES[3]).rows)
    res = make_dist(True).execute(TPCH_QUERIES[3])
    assert sorted(res.rows) == want
    overlap = res.stats["streaming_overlap"]
    assert any(overlap.values()), (
        f"no stage overlap observed: {overlap}")


@pytest.mark.slow  # 3 full distributed queries x 2 modes (~40s)
def test_streaming_matches_barrier_mode(local):
    for q in (1, 10, 18):
        want = sorted(make_dist(False).execute(TPCH_QUERIES[q]).rows)
        got = sorted(make_dist(True).execute(TPCH_QUERIES[q]).rows)
        assert got == want, f"q{q} streaming != barrier"


def test_streaming_error_propagates_without_deadlock(local):
    """A task dying mid-stream must fail the query (not deadlock
    consumers parked on its buffer), and the runner stays usable."""
    sess = Session(catalog="tpch", schema="micro")
    SP.set_property(sess.properties, "streaming_execution", True)
    conn = TpchConnector(page_rows=512)
    orig = conn.page_source
    state = {"calls": 0, "arm": True}

    def failing_page_source(split, cols):
        state["calls"] += 1
        if state["arm"] and state["calls"] > 2:
            raise RuntimeError("injected scan failure")
        return orig(split, cols)

    conn.page_source = failing_page_source
    r = DistributedQueryRunner({"tpch": conn}, sess, n_workers=4)
    with pytest.raises(RuntimeError, match="injected scan failure"):
        r.execute(TPCH_QUERIES[3])
    state["arm"] = False
    # the runner is reusable after a failed query
    assert r.execute("select count(*) from nation").rows == [(25,)]


def test_bounded_buffer_backpressure_and_listen():
    from trino_tpu.block import Page
    from trino_tpu import types as T

    buf = OutputBuffer(1, max_pending_pages=2)
    page = Page.from_pylists([T.BIGINT], [[1, 2, 3]])
    buf.enqueue(0, page)
    buf.enqueue(0, page)
    assert buf.full()
    fired = []
    buf.listen().on_ready(lambda: fired.append("space"))
    assert not fired
    chan = ExchangeChannel(buf, 0, 0)
    assert chan.poll() is page     # drain one
    assert fired == ["space"]      # producer listener woke
    assert not buf.full()
    # end-of-stream plumbing
    assert not chan.at_end()
    buf.set_no_more_pages()
    assert chan.poll() is page
    assert chan.poll() is None
    assert chan.at_end()
    assert buf.overlapped  # polled before no_more


def test_listen_token_fires_immediately_when_stale():
    from trino_tpu.block import Page
    from trino_tpu import types as T

    buf = OutputBuffer(1, max_pending_pages=8)
    token = buf.listen()
    buf.enqueue(0, Page.from_pylists([T.BIGINT], [[1]]))
    fired = []
    token.on_ready(lambda: fired.append(1))  # version moved: immediate
    assert fired == [1]


def test_task_executor_parks_blocked_entries():
    """A Blocked yield parks the entry (no busy spin); the token wakeup
    re-offers it exactly once."""
    from trino_tpu.exec.task_executor import Blocked, TaskExecutor

    ex = TaskExecutor(num_threads=2, name="test-exec")
    buf = OutputBuffer(1, max_pending_pages=4)
    from trino_tpu.block import Page
    from trino_tpu import types as T

    steps = []

    def consumer():
        chan = ExchangeChannel(buf, 0, 0)
        while True:
            p = chan.poll()
            if p is not None:
                steps.append("page")
            elif chan.at_end():
                steps.append("end")
                return
            else:
                token = chan.listen()
                if chan.at_end() or chan.has_page():
                    continue
                steps.append("park")
                yield Blocked([token])

    fut = ex.submit(consumer())
    time.sleep(0.3)
    assert steps == ["park"], f"consumer should park: {steps}"
    buf.enqueue(0, Page.from_pylists([T.BIGINT], [[7]]))
    time.sleep(0.3)
    assert "page" in steps
    buf.set_no_more_pages()
    fut.result(timeout=10)
    assert steps[-1] == "end"
    ex.close()


def test_abort_unblocks_producers_and_consumers():
    from trino_tpu.block import Page
    from trino_tpu import types as T

    buf = OutputBuffer(1, max_pending_pages=1)
    buf.enqueue(0, Page.from_pylists([T.BIGINT], [[1]]))
    assert buf.full()
    fired = []
    buf.listen().on_ready(lambda: fired.append(1))
    buf.abort()
    assert fired == [1]
    assert not buf.full()
    chan = ExchangeChannel(buf, 0, 0)
    assert chan.poll() is None and chan.at_end()


# ---------------------------------------------------------------------------
# Pipelined-overlap suite (round 9): the ack-based streaming cursor
# protocol — first-page latency, reconnect replay byte-equality, and
# the merge exchange preserving order end-to-end.
# ---------------------------------------------------------------------------


def _stream_server():
    """A real WorkerServer (in-process, no subprocess spawn) with one
    manually-registered streaming task state: the smallest harness that
    exercises the REAL get_page_stream cursor protocol + retained-frame
    replay against the REAL RemoteExchangeChannel."""
    from trino_tpu.parallel.worker import WorkerServer, _TaskState

    server = WorkerServer(0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    state = _TaskState()
    state.buffer = OutputBuffer(1, max_pending_pages=64)
    server.tasks["t0"] = state
    return server, state, ("127.0.0.1", server.port)


def _pages(n, rows_per=8):
    from trino_tpu.block import Page
    from trino_tpu import types as T

    out = []
    for i in range(n):
        base = i * rows_per
        out.append(Page.from_pylists(
            [T.BIGINT, T.VARCHAR],
            [[base + j for j in range(rows_per)],
             [f"v{base + j}" for j in range(rows_per)]]))
    return out


def _drain(chan, deadline_s=30):
    rows = []
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        p = chan.poll()
        if p is not None:
            rows.extend(p.to_rows())
        elif chan.at_end():
            return rows
        else:
            time.sleep(0.01)
    raise AssertionError("stream never ended")


def test_first_page_latency_consumer_receives_page_0_while_running():
    """The pipelining witness at the protocol level: the consumer holds
    page 0 while the producing task is still running (long before EOS),
    and the channel's first_page_ms stat records the latency."""
    from trino_tpu.parallel.remote_exchange import RemoteExchangeChannel

    server, state, addr = _stream_server()
    pages = _pages(3)
    try:
        state.buffer.enqueue(0, pages[0])
        chan = RemoteExchangeChannel([(addr, "t0")], 0, poll_wait=0.1)
        try:
            deadline = time.time() + 20
            got = None
            while got is None and time.time() < deadline:
                got = chan.poll()
                time.sleep(0.005)
            # page 0 arrived while the producer is STILL RUNNING
            assert got is not None
            assert state.status == "running"
            assert not state.buffer._no_more
            assert got.to_rows() == pages[0].to_rows()
            for p in pages[1:]:
                state.buffer.enqueue(0, p)
            state.status = "finished"
            state.buffer.set_no_more_pages()
            rest = _drain(chan)
            assert rest == [r for p in pages[1:] for r in p.to_rows()]
            stats = chan.stats
            assert stats["first_page_ms"] is not None
            assert stats["pages"] == 3
        finally:
            chan.close()
    finally:
        server.server.shutdown()


def test_ack_replay_reconnect_byte_equality():
    """Torn connections mid-frame on the streaming pull: the producer
    retains unacked frames, the channel reconnects and replays them —
    the reassembled stream equals the enqueued pages exactly (incl.
    dictionary-pool deltas), with the reconnect/replay counters up and
    acked frames released server-side."""
    from trino_tpu.parallel.remote_exchange import RemoteExchangeChannel

    server, state, addr = _stream_server()
    pages = _pages(6)
    want = [r for p in pages for r in p.to_rows()]
    try:
        for p in pages[:2]:
            state.buffer.enqueue(0, p)
        state.drop_results = 2   # tear the next two replies mid-frame
        chan = RemoteExchangeChannel([(addr, "t0")], 0, poll_wait=0.1)
        try:
            got = []
            deadline = time.time() + 30
            while len(got) < 2 * 8 and time.time() < deadline:
                p = chan.poll()
                if p is not None:
                    got.extend(p.to_rows())
                time.sleep(0.005)
            for p in pages[2:]:
                state.buffer.enqueue(0, p)
            state.status = "finished"
            state.buffer.set_no_more_pages()
            got.extend(_drain(chan))
            assert got == want
            assert chan.reconnects >= 1
            assert chan.replayed_frames >= 1
            # the consumer's acks released retained frames: the stream
            # cursor advanced past the replayed range
            rs = state.streams[(0, 0)]
            assert rs.base >= 2
        finally:
            chan.close()
    finally:
        server.server.shutdown()


def test_unreachable_peer_exhausts_reconnect_budget():
    """A peer that STAYS unreachable (nothing listening) escalates to
    ExchangeConnectionLost after the reconnect budget, instead of
    retrying forever — the query-retry path still exists for real
    worker death."""
    import socket

    from trino_tpu.parallel.remote_exchange import (
        ExchangeConnectionLost, RemoteExchangeChannel)

    # grab a port with no listener
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    chan = RemoteExchangeChannel([(("127.0.0.1", port), "t0")], 0,
                                 rpc_timeout=1.0)
    try:
        deadline = time.time() + 30
        with pytest.raises(ExchangeConnectionLost):
            while time.time() < deadline:
                chan.poll()
                if chan.at_end():
                    break
                time.sleep(0.02)
        assert chan.reconnects > RemoteExchangeChannel.RECONNECT_ATTEMPTS
    finally:
        chan.close()


def test_order_by_merge_streams_exact_order(local):
    """Distributed ORDER BY runs as sort-per-task + k-way streaming
    merge (no gather-then-resort): row ORDER equals the local oracle
    exactly, streaming and barrier modes agree."""
    sql = ("select o_orderkey, o_totalprice from orders "
           "order by o_orderkey")
    want = local.execute(sql).rows
    got_stream = make_dist(True).execute(sql).rows
    got_barrier = make_dist(False).execute(sql).rows
    assert got_stream == want      # exact order, not set equality
    assert got_barrier == want


def test_order_by_merge_overlaps_producer(local):
    """The merge boundary itself streams: the consumer's k-way merge
    dequeues sorted-run pages while producer tasks are still running
    (the fragment's buffer overlap witness)."""
    sql = ("select l_orderkey, l_extendedprice from lineitem "
           "order by l_orderkey, l_linenumber")
    want = local.execute(sql).rows
    r = make_dist(True)
    res = r.execute(sql)
    assert res.rows == want
    overlap = res.stats["streaming_overlap"]
    assert any(overlap.values()), f"no stage overlap: {overlap}"
