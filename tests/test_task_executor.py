"""TaskExecutor: cooperative quanta, multilevel feedback, concurrent
query time-sharing (reference analog:
execution/executor/TestTaskExecutor).
"""

import threading
import time

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.task_executor import (LEVEL_THRESHOLDS_S,
                                          MultilevelSplitQueue,
                                          TaskExecutor, _Entry)
from trino_tpu.parallel.distributed import DistributedQueryRunner
from trino_tpu.sql.analyzer import Session


def test_executor_runs_generators_to_completion():
    ex = TaskExecutor(num_threads=2, name="t1")
    log = []

    def gen(tag, steps):
        for i in range(steps):
            log.append((tag, i))
            yield

    ex.run_all([gen("a", 5), gen("b", 3)], timeout=30)
    assert sorted(log) == [("a", i) for i in range(5)] \
        + [("b", i) for i in range(3)]
    ex.close()


def test_executor_propagates_errors():
    ex = TaskExecutor(num_threads=1, name="t2")

    def boom():
        yield
        raise ValueError("kaput")

    with pytest.raises(ValueError, match="kaput"):
        ex.run_all([boom()], timeout=30)
    ex.close()


def test_executor_interleaves_tasks():
    """With ONE worker thread, a long task must not starve a short one:
    steps of both tasks interleave through the queue."""
    ex = TaskExecutor(num_threads=1, name="t3")
    order = []

    def gen(tag, steps):
        for _ in range(steps):
            order.append(tag)
            yield

    ex.run_all([gen("long", 40), gen("short", 3)], timeout=30)
    # the short task's last step must land before the long task's last
    # step: strictly sequential execution would put all 'short' after
    # 'long' only if submitted later AND never requeued fairly
    last_short = max(i for i, t in enumerate(order) if t == "short")
    assert last_short < len(order) - 1
    ex.close()


def test_level_assignment():
    e = _Entry(iter(()))
    assert e.level == 0
    e.scheduled_ns = int(LEVEL_THRESHOLDS_S[2] * 1e9) + 1
    assert e.level == 2
    e.scheduled_ns = int(LEVEL_THRESHOLDS_S[4] * 1e9) + 1
    assert e.level == 4


def test_queue_weighted_pick_never_starves_deep_levels():
    q = MultilevelSplitQueue()
    shallow = []
    deep = []
    for i in range(40):
        e = _Entry(iter(()))
        q.offer(e)
        shallow.append(e)
    for i in range(5):
        e = _Entry(iter(()))
        e.scheduled_ns = int(400e9)  # level 4
        q.offer(e)
        deep.append(e)
    taken = [q.take() for _ in range(45)]
    # the deep entries all surface despite the shallow backlog
    assert all(d in taken for d in deep)
    q.close()
    assert q.take() is None


def test_concurrent_queries_share_executor():
    conn = TpchConnector(page_rows=2048)
    runners = [DistributedQueryRunner(
        {"tpch": conn}, Session(catalog="tpch", schema="micro"),
        n_workers=2, desired_splits=4, broadcast_threshold=300.0)
        for _ in range(2)]
    results = [None, None]
    errors = []

    def go(i):
        try:
            results[i] = runners[i].execute(
                "select l_shipmode, count(*) from lineitem "
                "group by l_shipmode order by l_shipmode").rows
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=go, args=(i,)) for i in range(2)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors
    assert results[0] == results[1] and results[0]
