"""End-to-end query telemetry: distributed trace spans, the cluster
metrics registry, and system.runtime introspection.

Reference analog: the reference's OpenTelemetry span instrumentation +
JMX/metrics exposition + QuerySystemTable/TaskSystemTable, exercised
across REAL process boundaries: a 2-worker ProcessQueryRunner produces
one connected trace tree per query (coordinator + worker spans merged
via RPC piggyback), a Prometheus scrape surface, and SQL-queryable
runtime state.  The module-scoped cluster keeps worker spawns to one
pair; the kill-worker chaos case runs LAST (its replacement worker is
cold).
"""

import json
import threading
import time

import pytest

from trino_tpu.parallel.process_runner import ProcessQueryRunner
from trino_tpu.sql.analyzer import Session
from trino_tpu.telemetry.metrics import (ClusterMetrics, MetricsRegistry,
                                         parse_prometheus,
                                         render_prometheus)
from trino_tpu.telemetry.tracing import (NULL_TRACER, Tracer, span_tree,
                                         stage_overlap, to_chrome_trace,
                                         trace_line)

CATALOGS = {"tpch": {"connector": "tpch", "page_rows": 4096}}

Q3ISH = ("select c.c_custkey, o.o_orderkey from customer c "
         "join orders o on c.c_custkey = o.o_custkey "
         "where c.c_mktsegment = 'BUILDING' "
         "order by o.o_orderkey limit 10")


@pytest.fixture(scope="module")
def cluster():
    runner = ProcessQueryRunner(
        CATALOGS, Session(catalog="tpch", schema="micro"),
        n_workers=2, desired_splits=4, broadcast_threshold=300.0,
        heartbeat_interval=None)
    yield runner
    runner.close()


# -- tracer / metrics core ------------------------------------------------


def test_null_tracer_zero_cost():
    from trino_tpu.parallel.rpc import with_trace

    with NULL_TRACER.span("query") as s:
        assert s.context() is None and not s
        with NULL_TRACER.span("child", parent=s) as c:
            c.set("k", 1)
    assert NULL_TRACER.finished() == []
    req = with_trace({"op": "run_task"}, s)
    assert "trace" not in req  # nothing ships when tracing is off


def test_cross_process_parenting():
    t = Tracer(process="coordinator")
    with t.span("query") as root:
        ctx = root.context(attempt=2, speculative=False)
        assert ctx["trace_id"] == t.trace_id
        assert ctx["traceparent"].startswith(f"00-{t.trace_id}-")
        w = Tracer(process="worker-9", trace_id=ctx["trace_id"])
        with w.span("task x", parent=ctx) as task:
            assert task.parent_id == root.span_id
            assert task.trace_id == t.trace_id
        t.add_finished(w.finished())
    roots, children, orphans = span_tree(t.finished())
    assert len(roots) == 1 and not orphans
    assert children[root.span_id][0]["name"] == "task x"


def test_stage_overlap_from_timelines():
    def task(frag, start, end):
        return {"trace_id": "t", "span_id": f"{frag}{start}",
                "parent_id": None, "name": "task", "process": "w",
                "start": start, "end": end,
                "attrs": {"span_kind": "task", "fragment": frag}}

    # frag1 active [0,2], frag2 [1,3]: busy union 3s, overlap [1,2]
    spans = [task(1, 0.0, 2.0), task(2, 1.0, 3.0)]
    assert abs(stage_overlap(spans) - 1 / 3) < 1e-9
    # barrier shape: no concurrency across fragments
    assert stage_overlap([task(1, 0.0, 1.0), task(2, 1.0, 2.0)]) == 0.0
    assert stage_overlap([task(1, 0.0, 1.0)]) == 0.0


def test_metrics_exposition_roundtrip():
    reg = MetricsRegistry()
    reg.counter("trino_t_total", "help").inc(2, kind="a")
    reg.counter("trino_t_total").inc(3, kind="b")
    reg.gauge("trino_g").set(1.25)
    reg.histogram("trino_h").observe(0.4)
    reg.gauge_fn("trino_live", "pull-time", lambda: 7.0)
    cm = ClusterMetrics()
    cm.update(0, [{"name": "trino_g", "type": "gauge", "help": "",
                   "samples": [[{}, 9.0]]}])
    text = render_prometheus(cm.collect(reg.collect()))
    parsed = parse_prometheus(text)
    assert parsed["trino_t_total"][
        '{kind="a",process="coordinator"}'] == 2.0
    # worker sample merged under its own labels, same family
    assert parsed["trino_g"][
        '{process="worker",worker="0"}'] == 9.0
    assert parsed["trino_h_count"]['{process="coordinator"}'] == 1.0
    assert parsed["trino_live"]['{process="coordinator"}'] == 7.0


def test_event_history_ring_and_stats_payload():
    from trino_tpu.events import EventListenerManager, QueryMonitor

    mgr = EventListenerManager(history_capacity=2)
    m1 = QueryMonitor(mgr, "alice", "select 1")
    m1.created()
    assert [e.query_id for e in mgr.running()] == [m1.query_id]
    m1.completed(5, stats={"peak_memory_bytes": 123, "wall_ms": 1.5})
    assert mgr.running() == []
    for i in range(3):  # ring: capacity 2 evicts the oldest
        m = QueryMonitor(mgr, "alice", f"select {i}")
        m.created()
        m.completed(1)
    hist = mgr.history(10)
    assert len(hist) == 2
    assert all(e.state == "FINISHED" for e in hist)
    # the first query's stats payload was ring-evicted with it; a fresh
    # completion still carries stats through
    m2 = QueryMonitor(mgr, "bob", "select 2")
    m2.created()
    m2.completed(1, stats={"peak_memory_bytes": 7})
    assert mgr.history(1)[0].stats == {"peak_memory_bytes": 7}


# -- distributed trace assembly -------------------------------------------


def test_q3_distributed_trace_tree(cluster):
    res = cluster.execute(Q3ISH)
    assert len(res.rows) == 10
    spans = res.stats["trace"]
    roots, children, orphans = span_tree(spans)
    assert len(roots) == 1 and roots[0]["name"] == "query"
    assert orphans == [], [s["name"] for s in orphans]
    assert len({s["trace_id"] for s in spans}) == 1
    workers = {s["process"] for s in spans
               if s["process"].startswith("worker-")}
    assert len(workers) >= 2, workers
    # worker task spans exist for every non-output fragment and carry
    # their fragment id (the stage_overlap input)
    tasks = [s for s in spans
             if s["attrs"].get("span_kind") == "task"
             and s["process"].startswith("worker-")]
    assert tasks and all(s["attrs"].get("fragment") is not None
                         for s in tasks)
    assert trace_line(spans).startswith("Trace: ")
    # streaming execution: upstream fragments overlap the output stage
    assert stage_overlap(spans) > 0.0


def test_barrier_operator_spans_account_for_task_wall(cluster):
    """In barrier mode a task's wall is spent INSIDE operator calls
    (exchange pulls included), so per-task operator busy must sum to
    within 10% of the exec span."""
    cluster.session.properties["streaming_execution"] = False
    try:
        res = cluster.execute(Q3ISH)
    finally:
        cluster.session.properties.pop("streaming_execution", None)
    spans = res.stats["trace"]
    _, children, orphans = span_tree(spans)
    assert orphans == []
    execs = [s for s in spans if s["attrs"].get("span_kind") == "exec"
             and s["process"].startswith("worker-")]
    assert execs
    wall = sum(e["end"] - e["start"] for e in execs)
    busy = sum(o["end"] - o["start"]
               for e in execs
               for o in children.get(e["span_id"], ())
               if o["attrs"].get("span_kind") == "operator")
    assert wall > 0
    assert busy >= 0.9 * wall, \
        f"operator spans {busy * 1e3:.1f}ms vs exec {wall * 1e3:.1f}ms"


def test_chrome_trace_artifact_schema(cluster):
    res = cluster.execute("select count(*) from lineitem")
    doc = to_chrome_trace(res.stats["trace"])
    blob = json.loads(json.dumps(doc))  # JSON-serializable end to end
    events = blob["traceEvents"]
    assert events
    pids = set()
    for e in events:
        # the trace-event schema: phase, name, pid/tid always; complete
        # ("X") events add microsecond ts + dur
        assert e["ph"] in ("X", "M")
        assert isinstance(e["name"], str)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            pids.add(e["pid"])
        else:
            assert e["name"] in ("process_name", "thread_name")
    named = {e["pid"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert pids <= named  # every used pid lane is named for Perfetto


def test_explain_analyze_trace_line(cluster):
    res = cluster.execute("explain analyze " + Q3ISH)
    text = "\n".join(r[0] for r in res.rows)
    assert "Trace: " in text and "critical path" in text


def test_tracing_disabled_is_clean(cluster):
    cluster.session.properties["query_tracing_enabled"] = False
    try:
        res = cluster.execute("select count(*) from nation")
    finally:
        cluster.session.properties.pop("query_tracing_enabled", None)
    assert "trace" not in (res.stats or {})


# -- metrics + system.runtime ---------------------------------------------


def test_protocol_scrape_end_to_end(cluster):
    """CI smoke: boot ProtocolServer over the live cluster, run a query
    through the HTTP protocol, scrape /v1/metrics and /v1/query/{id}."""
    import urllib.error
    import urllib.request

    from trino_tpu.client import Client
    from trino_tpu.server.protocol import ProtocolServer

    cluster.heartbeat()  # pull worker metric snapshots in
    srv = ProtocolServer(cluster, page_size=100).start()
    try:
        expected = cluster.execute(
            "select count(*) from lineitem").rows[0][0]
        res = Client(srv.uri).execute(
            "select count(*) c from lineitem")
        assert res.rows == [[expected]]
        with urllib.request.urlopen(srv.uri + "/v1/metrics") as r:
            assert "text/plain" in r.headers["Content-Type"]
            text = r.read().decode()
        parsed = parse_prometheus(text)
        # exchange, memory, recovery AND per-worker series all present
        assert "trino_exchange_splits_total" in parsed
        assert "trino_recovery_events_total" in parsed
        assert "trino_cluster_memory_bytes" in parsed
        assert any('process="worker"' in lbl
                   for lbl in parsed.get("trino_node_memory_bytes", {}))
        assert "trino_http_statements_total" in parsed
        # /v1/query/{id}: the finished query's stats tree, with trace
        qid = list(srv.finished)[-1]
        with urllib.request.urlopen(srv.uri + f"/v1/query/{qid}") as r:
            info = json.loads(r.read())
        assert info["state"] == "FINISHED" and info["rows"] == 1
        assert info["stats"]["wall_ms"] > 0
        assert info["stats"]["trace"], "trace spans missing from stats"
        srv.evict_query(qid)
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(srv.uri + f"/v1/query/{qid}")
        assert err.value.code == 404
    finally:
        srv.stop()


def test_system_runtime_shows_running_query(cluster):
    """A concurrently-executing query must appear in
    system.runtime.queries with state RUNNING, and its tasks in
    system.runtime.tasks — live introspection, not post-hoc history."""
    marker = "select c_custkey from customer where c_custkey < 77"
    qid = f"q{cluster._task_seq + 1}a0"
    cluster.fault_schedule.add(f"{qid}.f", "delay", times=2,
                               delay_s=3.0)
    done = {}

    def run():
        done["res"] = cluster.execute(marker)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    deadline = time.monotonic() + 10
    saw_running = saw_tasks = False
    while time.monotonic() < deadline and not (saw_running
                                               and saw_tasks):
        rows = cluster.execute(
            "select query, state from system.runtime.queries "
            "where state = 'RUNNING'").rows
        saw_running = saw_running or any(r[0] == marker for r in rows)
        trows = cluster.execute(
            "select task_id, worker, state "
            "from system.runtime.tasks").rows
        saw_tasks = saw_tasks or len(trows) > 0
        time.sleep(0.1)
    th.join(timeout=30)
    assert saw_running, "running query never surfaced"
    assert saw_tasks, "its tasks never surfaced"
    assert len(done["res"].rows) == 76
    # completed: the history-backed row carries rows + wall
    hist = cluster.execute(
        "select query, state, rows from system.runtime.queries "
        f"where query = '{marker.replace(chr(39), chr(39) * 2)}' "
        "and state = 'FINISHED'").rows
    assert hist and hist[-1][2] == 76


def test_system_runtime_metrics_sql(cluster):
    rows = cluster.execute(
        "select name, labels, value from system.runtime.metrics "
        "where name = 'trino_recovery_events_total'").rows
    kinds = {r[1] for r in rows}
    assert any("task_attempts" in k for k in kinds)
    assert all(r[2] >= 0 for r in rows)
    # completed-query counter reflects this module's activity
    rows = cluster.execute(
        "select value from system.runtime.metrics "
        "where name = 'trino_queries_total' "
        "and labels like '%FINISHED%'").rows
    assert rows and rows[0][0] >= 1


def test_completed_event_carries_stats_payload(cluster):
    cluster.execute("select count(*) from orders")
    last = cluster.event_manager.history(1)[0]
    assert last.state == "FINISHED"
    assert last.stats["wall_ms"] > 0
    assert last.stats["recovery"] is not None
    assert last.stats["wall_breakdown"]  # coordinator span breakdown


# -- chaos: retried attempts as sibling spans (runs LAST: the killed
# -- worker's replacement is cold) ----------------------------------------


def test_retried_attempt_is_sibling_span_tagged_with_taxonomy(cluster):
    qid = f"q{cluster._task_seq + 1}a0"
    cluster.fault_schedule.add(f"{qid}.f1.t0", "kill-worker")
    cluster.session.properties.update(
        streaming_execution=False, retry_policy="TASK",
        speculative_execution_enabled=False)
    try:
        res = cluster.execute(
            "select l_returnflag, count(*) from lineitem "
            "group by l_returnflag")
    finally:
        for k in ("streaming_execution", "retry_policy",
                  "speculative_execution_enabled"):
            cluster.session.properties.pop(k, None)
    assert len(res.rows) == 3
    spans = res.stats["trace"]
    _, children, orphans = span_tree(spans)
    assert orphans == []
    attempts = [s for s in spans
                if s["attrs"].get("span_kind") == "attempt"
                and f"{qid}.f1.t0" in s["attrs"].get("task_id", "")]
    assert len(attempts) >= 2, [s["name"] for s in spans]
    # all attempts of the task are SIBLINGS under one fragment span
    assert len({s["parent_id"] for s in attempts}) == 1
    failed = [s for s in attempts if s["attrs"].get("error_type")]
    won = [s for s in attempts if not s["attrs"].get("error_type")]
    assert failed and won
    assert failed[0]["attrs"]["error_type"] == "EXTERNAL"  # taxonomy
    assert failed[0]["attrs"]["attempt"] == 0
    assert won[0]["attrs"]["attempt"] >= 1
    cluster.heal()  # restore 2 live workers for any later module