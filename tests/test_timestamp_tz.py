"""TIMESTAMP WITH TIME ZONE: literals, AT TIME ZONE, casts, DST-aware
arithmetic, instant-semantics grouping, wire serde.

Reference analog: ``spi/type/TimestampWithTimeZoneType.java`` +
``type/TestTimestampWithTimeZone.java``. The TPU design stores UTC
micros on device with the zone as column metadata (see expr/tz.py).
"""

import datetime

import pytest

from trino_tpu import types as T
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.expr import tz
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=4096)},
                            Session(catalog="tpch", schema="micro"))


def one(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0]


def test_tzif_offsets():
    jul = int(datetime.datetime(
        2020, 7, 1, tzinfo=datetime.timezone.utc).timestamp() * 1e6)
    jan = int(datetime.datetime(
        2020, 1, 15, tzinfo=datetime.timezone.utc).timestamp() * 1e6)
    assert tz.offset_at("America/New_York", jul) == -4 * 3600 * 1_000_000
    assert tz.offset_at("America/New_York", jan) == -5 * 3600 * 1_000_000
    assert tz.parse_fixed_offset_micros("+05:30") == 19800 * 1_000_000


def test_literal_named_zone(runner):
    (v,) = one(runner,
               "select timestamp '2020-01-15 10:00:00 America/New_York'")
    assert v.year == 2020 and v.hour == 10
    assert v.utcoffset() == datetime.timedelta(hours=-5)


def test_literal_fixed_offset(runner):
    (v,) = one(runner, "select timestamp '2020-01-15 10:00:00 +02:00'")
    assert v.hour == 10
    assert v.utcoffset() == datetime.timedelta(hours=2)


def test_at_time_zone(runner):
    # session zone is UTC: 10:00 UTC == 05:00 EST
    (v,) = one(runner, "select timestamp '2020-01-15 10:00:00' "
                       "AT TIME ZONE 'America/New_York'")
    assert (v.hour, v.minute) == (5, 0)
    assert v.utcoffset() == datetime.timedelta(hours=-5)


def test_cast_to_timestamp_wall_clock(runner):
    (v,) = one(runner, "select cast(timestamp "
                       "'2020-07-15 12:00:00 America/New_York' "
                       "as timestamp)")
    # wall clock preserved: 2020-07-15T12:00:00 in micros
    assert v == 1594814400000000


def test_extract_uses_wall_clock(runner):
    y, d = one(runner,
               "select extract(year from ts), extract(day from ts) from "
               "(values timestamp '2020-12-31 23:00:00 -05:00') t(ts)")
    assert (y, d) == (2020, 31)


def test_interval_day_is_instant_arithmetic(runner):
    # +2 days across the US spring-forward gap: 48 real hours
    (v,) = one(runner, "select timestamp "
                       "'2020-03-07 12:00:00 America/New_York' "
                       "+ interval '2' day")
    assert (v.month, v.day, v.hour) == (3, 9, 13)


def test_group_by_instant_semantics(runner):
    # same instant in two zones lands in ONE group
    rows = runner.execute(
        "select count(*) from (values "
        "timestamp '2020-01-01 00:00:00 UTC', "
        "timestamp '2019-12-31 19:00:00 -05:00') t(x) group by x").rows
    assert rows == [(2,)]


def test_order_by_instant(runner):
    rows = runner.execute(
        "select x from (values "
        "timestamp '2020-01-01 12:00:00 +09:00', "
        "timestamp '2020-01-01 12:00:00 UTC', "
        "timestamp '2020-01-01 12:00:00 -05:00') t(x) order by x").rows
    instants = [v.timestamp() for (v,) in rows]
    assert instants == sorted(instants)


def test_wire_serde_preserves_zone():
    from trino_tpu.block import Block, Page
    from trino_tpu.exec.serde import PageDeserializer, PageSerializer

    t = T.timestamp_tz_type("America/New_York")
    page = Page([Block.from_pylist(t, [0, 1_600_000_000_000_000, None])], 3)
    frame = PageSerializer().serialize(page)
    out = PageDeserializer().deserialize(frame)
    assert out.blocks[0].type.is_timestamp_tz
    assert out.blocks[0].type.zone == "America/New_York"
    assert out.to_rows()[1][0].utcoffset() == datetime.timedelta(hours=-4)


def test_current_timestamp_is_tz(runner):
    (v,) = one(runner, "select current_timestamp")
    assert isinstance(v, datetime.datetime)
    assert v.tzinfo is not None


def test_create_table_with_tz_column(runner):
    from trino_tpu.connectors.memory import MemoryConnector

    r = LocalQueryRunner(
        {"mem": MemoryConnector(), "tpch": TpchConnector(page_rows=512)},
        Session(catalog="mem", schema="default"))
    r.execute("create table events (id bigint, "
              "at timestamp(6) with time zone)")
    r.execute("insert into events values "
              "(1, timestamp '2020-06-01 08:00:00 +01:00')")
    rows = r.execute("select id, at from events").rows
    assert rows[0][0] == 1
    assert rows[0][1].utcoffset() is not None


def test_ambiguous_wall_time_resolves_to_earlier_offset(runner):
    """Fall-back overlap: 01:30 on 2025-11-02 in New York exists at
    both EDT (-4) and EST (-5); the reference (Joda convertLocalToUTC)
    picks the EARLIER offset — EDT — so the instant is 05:30 UTC."""
    (v,) = one(runner, "select timestamp "
                       "'2025-11-02 01:30:00 America/New_York' "
                       "AT TIME ZONE 'UTC'")
    assert (v.hour, v.minute) == (5, 30)
    # spring-forward gap: 02:30 never happens; carried across the gap
    # with the pre-transition offset (EST) -> 07:30 UTC
    (v,) = one(runner, "select timestamp "
                       "'2025-03-09 02:30:00 America/New_York' "
                       "AT TIME ZONE 'UTC'")
    assert (v.hour, v.minute) == (7, 30)


def test_tzif_footer_extends_past_table():
    """TZif v2+ footer TZ string must keep DST alternation alive past
    the last tabulated transition (~2037 for fat tzdata)."""
    jul = int(datetime.datetime(
        2050, 7, 1, tzinfo=datetime.timezone.utc).timestamp() * 1e6)
    jan = int(datetime.datetime(
        2050, 1, 15, tzinfo=datetime.timezone.utc).timestamp() * 1e6)
    assert tz.offset_at("America/New_York", jul) == -4 * 3600 * 1_000_000
    assert tz.offset_at("America/New_York", jan) == -5 * 3600 * 1_000_000
    # southern hemisphere: DST in January
    assert tz.offset_at("Australia/Sydney", jan) == 11 * 3600 * 1_000_000
    assert tz.offset_at("Australia/Sydney", jul) == 10 * 3600 * 1_000_000


def test_unixtime_session_zone():
    """from_unixtime renders in the session zone; to_unixtime reads a
    plain TIMESTAMP's wall clock in the session zone (reference:
    DateTimeFunctions.java)."""
    r = LocalQueryRunner({"tpch": TpchConnector(page_rows=256)},
                         Session(catalog="tpch", schema="micro",
                                 timezone="America/New_York"))
    (v,) = one(r, "select from_unixtime(1579082400)")  # 2020-01-15 10:00 UTC
    assert (v.hour, v.utcoffset()) == (5, datetime.timedelta(hours=-5))
    # wall 05:00 EST == 10:00 UTC == 1579082400
    (u,) = one(r, "select to_unixtime(timestamp '2020-01-15 05:00:00')")
    assert u == 1579082400.0
