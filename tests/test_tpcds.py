"""TPC-DS: connector integrity + q64/q72 cross-checked against sqlite.

Reference analog: ``plugin/trino-tpcds`` tests + the benchto TPC-DS
harness (BASELINE.md lists TPC-DS q64/q72 as a target config). Reuses
the TPC-H oracle machinery (same H2QueryRunner-style contract).
"""

import sqlite3
import re

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connectors.tpcds import (TpcdsConnector, _counts, _inv_items,
                                        _SCHEMAS)
from trino_tpu.resources.tpcds_queries import TPCDS_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session

from test_tpch_oracle import _days_to_iso, assert_same, to_sqlite

SCHEMA = "micro"


@pytest.fixture(scope="module")
def conn():
    return TpcdsConnector(page_rows=8192)


@pytest.fixture(scope="module")
def runner(conn):
    return LocalQueryRunner({"tpcds": conn},
                            Session(catalog="tpcds", schema=SCHEMA))


@pytest.fixture(scope="module")
def oracle(conn):
    db = sqlite3.connect(":memory:")
    meta = conn.metadata()
    for table in meta.list_tables(SCHEMA):
        handle = meta.get_table_handle(SCHEMA, table)
        cols = meta.get_columns(handle)
        names = [c.name for c in cols]
        db.execute(f"create table {table} ({', '.join(names)})")
        for split in conn.split_manager().get_splits(handle, 1):
            src = conn.page_source(split, cols)
            while True:
                page = src.get_next_page()
                if page is None:
                    break
                lists = [b.to_pylist() for b in page.blocks]
                for i, c in enumerate(cols):
                    if c.type == T.DATE:
                        lists[i] = [None if v is None else _days_to_iso(v)
                                    for v in lists[i]]
                    elif c.type.is_decimal:
                        lists[i] = [None if v is None else float(v)
                                    for v in lists[i]]
                rows = list(zip(*lists))
                ph = ", ".join(["?"] * len(cols))
                db.executemany(f"insert into {table} values ({ph})", rows)
    db.commit()
    return db


_COL_INTERVAL = re.compile(
    r"([a-z_0-9.]+)\s*\+\s*interval\s+'(\d+)'\s+day", re.IGNORECASE)


def to_sqlite_ds(sql: str) -> str:
    """TPC-DS additions on top of the TPC-H translation: column-relative
    date intervals (ISO strings in sqlite, so use date())."""
    sql = _COL_INTERVAL.sub(lambda m: f"date({m.group(1)}, "
                                      f"'+{m.group(2)} days')", sql)
    return to_sqlite(sql)


def test_row_counts(conn):
    c = _counts(_SCHEMAS[SCHEMA])
    meta = conn.metadata()
    for table in ("date_dim", "item", "store_sales", "catalog_sales",
                  "inventory"):
        handle = meta.get_table_handle(SCHEMA, table)
        cols = meta.get_columns(handle)
        total = 0
        for split in conn.split_manager().get_splits(handle, 4):
            src = conn.page_source(split, cols[:1])
            while True:
                page = src.get_next_page()
                if page is None:
                    break
                total += page.num_rows
        assert total == c[table], table


def test_returns_join_parents(conn):
    """Every store_returns row must hit its originating sale on
    (item_sk, ticket_number) — the q64 join contract."""
    meta = conn.metadata()
    sf = _SCHEMAS[SCHEMA]

    def load(table, colnames):
        handle = meta.get_table_handle(SCHEMA, table)
        cols = [c for c in meta.get_columns(handle) if c.name in colnames]
        out = {c.name: [] for c in cols}
        for split in conn.split_manager().get_splits(handle, 1):
            src = conn.page_source(split, cols)
            while True:
                page = src.get_next_page()
                if page is None:
                    break
                for c, b in zip(cols, page.blocks):
                    out[c.name].extend(b.to_pylist())
        return out

    ss = load("store_sales", {"ss_item_sk", "ss_ticket_number"})
    sr = load("store_returns", {"sr_item_sk", "sr_ticket_number"})
    sales = set(zip(ss["ss_item_sk"], ss["ss_ticket_number"]))
    assert len(sr["sr_item_sk"]) == _counts(sf)["store_returns"]
    for pair in zip(sr["sr_item_sk"], sr["sr_ticket_number"]):
        assert pair in sales


def test_inventory_lattice(conn):
    """inventory covers every (week, item-prefix, warehouse) cell."""
    sf = _SCHEMAS[SCHEMA]
    t = conn.table("inventory")
    n = _counts(sf)["inventory"]
    page = t.generate(sf, 0, min(n, 4096),
                      ["inv_item_sk", "inv_warehouse_sk"])
    items = np.asarray(page.blocks[0].data)
    whs = np.asarray(page.blocks[1].data)
    assert items.min() >= 1 and items.max() <= _inv_items(sf)
    assert whs.min() >= 1 and whs.max() <= _counts(sf)["warehouse"]


def test_simple_scan_agg(runner):
    rows = runner.execute(
        "select d_year, count(*) from date_dim group by d_year "
        "order by d_year").rows
    assert [r[0] for r in rows] == [1998, 1999, 2000, 2001, 2002]
    assert sum(r[1] for r in rows) == 1826


@pytest.mark.parametrize("qid", sorted(TPCDS_QUERIES))
def test_tpcds_query_matches_oracle(qid, runner, oracle):
    sql = TPCDS_QUERIES[qid]
    res = runner.execute(sql)
    want = oracle.execute(to_sqlite_ds(sql)).fetchall()
    ordered = "order by" in sql.lower()
    # the micro generator is tuned so neither benchmark query is a
    # vacuous 0=0 match (see connectors/tpcds.py selectivity biases)
    assert len(res.rows) > 0
    assert_same(res, want, ordered)


def test_string_key_join_aligned_pool(runner):
    # upper() produces an ALIGNED pool that may hold duplicate values
    # under distinct codes; the join must canonicalize codes on both
    # sides or silently drop matches
    from trino_tpu.connectors.memory import MemoryConnector

    r = LocalQueryRunner({"mem": MemoryConnector()},
                         Session(catalog="mem", schema="default"))
    r.execute("create table big (a varchar)")
    r.execute("insert into big values ('FOO'), ('FOO'), ('FOO'), "
              "('FOO'), ('FOO')")
    r.execute("create table small (b varchar)")
    r.execute("insert into small values ('foo'), ('FOO')")
    rows = r.execute("select count(*) from big join small "
                     "on big.a = upper(small.b)").rows
    assert rows == [(10,)]


def test_string_key_join(runner):
    # joins on varchar columns (q64 joins store_name/zip): probe-side
    # dictionary codes remap into the build pool
    rows = runner.execute(
        "select count(*) from store s1 join store s2 "
        "on s1.s_store_name = s2.s_store_name").rows
    assert rows[0][0] >= _counts(_SCHEMAS[SCHEMA])["store"]
