"""TPC-H correctness: every query cross-checked against a sqlite3 oracle.

Reference analog: the H2 cross-check oracle (``testing/trino-testing/.../
H2QueryRunner.java`` + ``QueryAssertions``) used by AbstractTestQueries.
The engine runs the Trino-dialect text; sqlite runs a mechanically
translated variant (date literals folded, EXTRACT/SUBSTRING rewritten).
"""

import datetime
import math
import re
import sqlite3
from decimal import Decimal

import pytest

from trino_tpu import types as T
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.resources.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session

EPOCH = datetime.date(1970, 1, 1)
SCHEMA = "micro"


def _days_to_iso(d):
    return (EPOCH + datetime.timedelta(days=d)).isoformat()


@pytest.fixture(scope="module", autouse=True)
def _fresh_jax_caches():
    """Late in a full tier-1 run this module's q17 compile aborts
    inside XLA (SIGABRT in backend_compile, CPU, single process,
    ~600 compiled programs accumulated; reproduces identically on the
    pre-PR-14 tree and with a cold persistent cache, passes when the
    module runs alone). Dropping the in-process jit caches before the
    module bounds the accumulated-executable state the crash needs;
    the queries recompile from the persistent on-disk cache, so the
    cost is seconds, not a cold trace."""
    import jax

    jax.clear_caches()


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(page_rows=8192)


@pytest.fixture(scope="module")
def runner(conn):
    return LocalQueryRunner({"tpch": conn},
                            Session(catalog="tpch", schema=SCHEMA))


@pytest.fixture(scope="module")
def oracle(conn):
    """sqlite3 loaded with the same generated data."""
    db = sqlite3.connect(":memory:")
    meta = conn.metadata()
    for table in meta.list_tables(SCHEMA):
        handle = meta.get_table_handle(SCHEMA, table)
        cols = meta.get_columns(handle)
        names = [c.name for c in cols]
        db.execute(f"create table {table} ({', '.join(names)})")
        for split in conn.split_manager().get_splits(handle, 1):
            src = conn.page_source(split, cols)
            while True:
                page = src.get_next_page()
                if page is None:
                    break
                lists = [b.to_pylist() for b in page.blocks]
                for i, c in enumerate(cols):
                    if c.type == T.DATE:
                        lists[i] = [None if v is None else _days_to_iso(v)
                                    for v in lists[i]]
                    elif c.type.is_decimal:
                        lists[i] = [None if v is None else float(v)
                                    for v in lists[i]]
                rows = list(zip(*lists))
                ph = ", ".join(["?"] * len(cols))
                db.executemany(
                    f"insert into {table} values ({ph})", rows)
    db.commit()
    return db


_DATE_INTERVAL = re.compile(
    r"date\s+'(\d+-\d+-\d+)'\s*([+-])\s*interval\s+'(\d+)'\s+"
    r"(day|month|year)", re.IGNORECASE)
_DATE_LIT = re.compile(r"date\s+'(\d+-\d+-\d+)'", re.IGNORECASE)
_EXTRACT = re.compile(r"extract\s*\(\s*year\s+from\s+([a-z_0-9.]+)\s*\)",
                      re.IGNORECASE)
_SUBSTRING = re.compile(
    r"substring\s*\(\s*([a-z_0-9.]+)\s+from\s+(\d+)\s+for\s+(\d+)\s*\)",
    re.IGNORECASE)


def _shift(date_text: str, sign: str, n: int, unit: str) -> str:
    y, m, d = map(int, date_text.split("-"))
    n = n if sign == "+" else -n
    if unit == "day":
        return (datetime.date(y, m, d)
                + datetime.timedelta(days=n)).isoformat()
    months = y * 12 + (m - 1) + n * (12 if unit == "year" else 1)
    ny, nm = divmod(months, 12)
    nm += 1
    # clamp day like civil-calendar addition
    while True:
        try:
            return datetime.date(ny, nm, d).isoformat()
        except ValueError:
            d -= 1


_DEC_ARITH = re.compile(r"(\d+\.\d+)\s*([-+])\s*(\d+\.\d+)")


def to_sqlite(sql: str) -> str:
    sql = _DATE_INTERVAL.sub(
        lambda m: "'" + _shift(m.group(1), m.group(2), int(m.group(3)),
                               m.group(4).lower()) + "'", sql)
    sql = _DATE_LIT.sub(lambda m: "'" + m.group(1) + "'", sql)
    sql = _EXTRACT.sub(
        lambda m: f"CAST(strftime('%Y', {m.group(1)}) AS INTEGER)", sql)
    sql = _SUBSTRING.sub(
        lambda m: f"substr({m.group(1)}, {m.group(2)}, {m.group(3)})", sql)
    # fold literal decimal arithmetic exactly: sqlite's float '0.06 + 0.01'
    # is 0.069999..., which breaks BETWEEN bounds the engine computes in
    # exact decimals
    sql = _DEC_ARITH.sub(
        lambda m: str(Decimal(m.group(1)) + Decimal(m.group(3))
                      if m.group(2) == "+"
                      else Decimal(m.group(1)) - Decimal(m.group(3))), sql)
    return sql


def _norm(v, type_=None):
    if v is None:
        return None
    if isinstance(v, Decimal):
        return float(v)
    if type_ == T.DATE and isinstance(v, int):
        return _days_to_iso(v)
    return v


def _close(a, b) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        try:
            fa, fb = float(a), float(b)
        except (TypeError, ValueError):
            return a == b
        # abs_tol 0.011 tolerates half-up (engine decimals) vs half-even
        # (python round) on exact .5 ties at scale 2
        return math.isclose(fa, fb, rel_tol=1e-6, abs_tol=0.011)
    return a == b


def _sort_key(row):
    return tuple("\0" if v is None else
                 (f"{v:.4f}" if isinstance(v, float) else str(v))
                 for v in row)


def assert_same(engine_res, oracle_rows, ordered: bool):
    got = [tuple(_norm(v, t) for v, t in zip(row, engine_res.types))
           for row in engine_res.rows]

    def quantize(v, t):
        # engine decimals round to their declared scale (Trino: avg over
        # decimal(p,s) returns decimal(p,s)); match the oracle to it
        if v is not None and t is not None and t.is_decimal and \
                isinstance(v, float):
            return round(v, t.scale)
        return v

    want = [tuple(quantize(_norm(v), t)
                  for v, t in zip(row, engine_res.types))
            for row in oracle_rows]
    assert len(got) == len(want), \
        f"row count {len(got)} != oracle {len(want)}\n" \
        f"got={got[:5]}\nwant={want[:5]}"
    if not ordered:
        got = sorted(got, key=_sort_key)
        want = sorted(want, key=_sort_key)
    for i, (g, w) in enumerate(zip(got, want)):
        assert len(g) == len(w), f"row {i} arity"
        for j, (a, b) in enumerate(zip(g, w)):
            assert _close(a, b), \
                f"row {i} col {j}: engine={a!r} oracle={b!r}\n" \
                f"engine row={g}\noracle row={w}"


@pytest.mark.parametrize("qid", sorted(TPCH_QUERIES))
def test_tpch_query_matches_oracle(qid, runner, oracle):
    sql = TPCH_QUERIES[qid]
    res = runner.execute(sql)
    want = oracle.execute(to_sqlite(sql)).fetchall()
    ordered = "order by" in sql.lower()
    assert_same(res, want, ordered)
