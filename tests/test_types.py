from decimal import Decimal

import numpy as np
import pytest

from trino_tpu import types as T


def test_parse_simple_types():
    assert T.parse_type("bigint") is T.BIGINT
    assert T.parse_type("BOOLEAN") is T.BOOLEAN
    assert T.parse_type("double") is T.DOUBLE
    assert T.parse_type("date") is T.DATE
    assert T.parse_type("varchar") == T.VARCHAR


def test_parse_parameterized():
    v = T.parse_type("varchar(25)")
    assert v.is_string and v.length == 25
    d = T.parse_type("decimal(12, 2)")
    assert d.is_decimal and d.precision == 12 and d.scale == 2
    assert d.storage == np.dtype(np.int64)


def test_decimal_raw_roundtrip():
    d = T.decimal_type(12, 2)
    assert d.to_raw("123.45") == 12345
    assert d.from_raw(12345) == Decimal("123.45")
    assert d.to_raw(7) == 700


def test_decimal_over_18_rejected():
    with pytest.raises(T.TypeError_):
        T.decimal_type(19, 0)


def test_common_super_type():
    assert T.common_super_type(T.INTEGER, T.BIGINT) is T.BIGINT
    assert T.common_super_type(T.BIGINT, T.DOUBLE) is T.DOUBLE
    assert T.common_super_type(T.UNKNOWN, T.DATE) is T.DATE
    d1 = T.decimal_type(10, 2)
    d2 = T.decimal_type(5, 0)
    c = T.common_super_type(d1, d2)
    assert c.precision == 10 and c.scale == 2
    assert T.common_super_type(d1, T.BIGINT).scale == 2
    assert T.common_super_type(T.parse_type("varchar(3)"), T.VARCHAR) == T.VARCHAR
    assert T.common_super_type(T.DATE, T.TIMESTAMP) is T.TIMESTAMP


def test_storage_dtypes():
    assert T.BIGINT.storage == np.dtype(np.int64)
    assert T.DATE.storage == np.dtype(np.int32)
    assert T.VARCHAR.storage == np.dtype(np.int32)  # dictionary codes
