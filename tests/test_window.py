"""Window function tests (reference analog: AbstractTestWindowQueries)."""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.analyzer import Session


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner({"tpch": TpchConnector(page_rows=4096)},
                            Session(catalog="tpch", schema="micro"))


def q(runner, sql):
    return runner.execute(sql).rows


def test_row_number(runner):
    rows = q(runner, """
        select n_name, row_number() over (partition by n_regionkey
                                          order by n_name) rn
        from nation where n_regionkey = 1 order by rn""")
    assert [r[1] for r in rows] == [1, 2, 3, 4, 5]
    assert rows[0][0] < rows[1][0]


def test_rank_dense_rank_with_ties():
    from trino_tpu.block import Page
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu import types as T

    mem = MemoryConnector()
    r = LocalQueryRunner({"memory": mem},
                         Session(catalog="memory", schema="default"))
    r.execute("create table t (g bigint, v bigint)")
    r.execute("insert into t values (1, 10), (1, 10), (1, 20), "
              "(2, 5), (2, 6), (2, 6), (2, 7)")
    rows = q(r, """
        select g, v, rank() over (partition by g order by v) rk,
               dense_rank() over (partition by g order by v) dr
        from t order by g, v""")
    assert rows == [(1, 10, 1, 1), (1, 10, 1, 1), (1, 20, 3, 2),
                    (2, 5, 1, 1), (2, 6, 2, 2), (2, 6, 2, 2),
                    (2, 7, 4, 3)]
    # running sum: RANGE default includes peers
    rows = q(r, """
        select g, v, sum(v) over (partition by g order by v) s
        from t order by g, v""")
    assert rows == [(1, 10, 20), (1, 10, 20), (1, 20, 40),
                    (2, 5, 5), (2, 6, 17), (2, 6, 17), (2, 7, 24)]
    # ROWS frame: exact per-row prefix
    rows = q(r, """
        select g, v, sum(v) over (partition by g order by v
            rows unbounded preceding) s
        from t order by g, v, s""")
    assert rows == [(1, 10, 10), (1, 10, 20), (1, 20, 40),
                    (2, 5, 5), (2, 6, 11), (2, 6, 17), (2, 7, 24)]


def test_partition_total_and_avg(runner):
    rows = q(runner, """
        select distinct n_regionkey,
               count(*) over (partition by n_regionkey) c
        from nation order by n_regionkey""")
    assert rows == [(0, 5), (1, 5), (2, 5), (3, 5), (4, 5)]


def test_lag_lead(runner):
    rows = q(runner, """
        select n_nationkey,
               lag(n_nationkey) over (order by n_nationkey) lg,
               lead(n_nationkey, 2) over (order by n_nationkey) ld
        from nation order by n_nationkey limit 4""")
    assert rows == [(0, None, 2), (1, 0, 3), (2, 1, 4), (3, 2, 5)]


def test_first_value_and_ntile(runner):
    rows = q(runner, """
        select n_nationkey,
               first_value(n_name) over (partition by n_regionkey
                                         order by n_nationkey) fv,
               ntile(2) over (order by n_nationkey) nt
        from nation order by n_nationkey""")
    assert rows[0][2] == 1 and rows[-1][2] == 2
    assert isinstance(rows[0][1], str)


def test_window_over_aggregate(runner):
    rows = q(runner, """
        select n_regionkey, count(*) c,
               sum(count(*)) over () total
        from nation group by n_regionkey order by n_regionkey""")
    assert all(r[2] == 25 for r in rows)
    assert sum(r[1] for r in rows) == 25


def test_window_in_subquery_topn_pattern(runner):
    # the classic top-n-per-group pattern
    rows = q(runner, """
        select n_regionkey, n_name from (
            select n_regionkey, n_name,
                   row_number() over (partition by n_regionkey
                                      order by n_name) rn
            from nation) t
        where rn = 1 order by n_regionkey""")
    assert len(rows) == 5


def _mem_runner():
    from trino_tpu.connectors.memory import MemoryConnector

    mem = MemoryConnector()
    r = LocalQueryRunner({"memory": mem},
                         Session(catalog="memory", schema="default"))
    r.execute("create table t (g bigint, v bigint)")
    r.execute("insert into t values (1, 10), (1, 20), (1, 30), (1, 40), "
              "(2, 5), (2, 6), (2, 7)")
    return r


def test_last_value_default_frame():
    # default frame = RANGE UNBOUNDED..CURRENT: last_value is the
    # current peer run's end, NOT the partition end
    r = _mem_runner()
    rows = q(r, """
        select g, v, last_value(v) over (partition by g order by v) lv
        from t order by g, v""")
    assert [x[2] for x in rows] == [10, 20, 30, 40, 5, 6, 7]
    rows = q(r, """
        select g, v, last_value(v) over (partition by g order by v
            rows between unbounded preceding and unbounded following) lv
        from t order by g, v""")
    assert [x[2] for x in rows] == [40, 40, 40, 40, 7, 7, 7]


def test_nth_value():
    r = _mem_runner()
    rows = q(r, """
        select g, v, nth_value(v, 2) over (partition by g order by v
            rows between unbounded preceding and unbounded following) nv
        from t order by g, v""")
    assert [x[2] for x in rows] == [20, 20, 20, 20, 6, 6, 6]
    # default running frame: nth row not yet in frame => NULL
    rows = q(r, """
        select g, v, nth_value(v, 3) over (partition by g order by v) nv
        from t order by g, v""")
    assert [x[2] for x in rows] == [None, None, 30, 30, None, None, 7]


def test_bounded_rows_moving_sum_and_avg():
    r = _mem_runner()
    rows = q(r, """
        select g, v,
               sum(v) over (partition by g order by v
                            rows between 1 preceding and 1 following) s,
               count(*) over (partition by g order by v
                              rows between 1 preceding and 1 following) c
        from t order by g, v""")
    assert [x[2] for x in rows] == [30, 60, 90, 70, 11, 18, 13]
    assert [x[3] for x in rows] == [2, 3, 3, 2, 2, 3, 2]


def test_bounded_rows_min_max():
    r = _mem_runner()
    rows = q(r, """
        select g, v,
               min(v) over (partition by g order by v
                            rows between 2 preceding and current row) mn,
               max(v) over (partition by g order by v
                            rows between current row and 2 following) mx
        from t order by g, v""")
    assert [x[2] for x in rows] == [10, 10, 10, 20, 5, 5, 5]
    assert [x[3] for x in rows] == [30, 40, 40, 40, 7, 7, 7]


def test_preceding_to_unbounded_following():
    r = _mem_runner()
    rows = q(r, """
        select g, v, sum(v) over (partition by g order by v
            rows between 1 preceding and unbounded following) s
        from t order by g, v""")
    assert [x[2] for x in rows] == [100, 100, 90, 70, 18, 18, 13]


def test_empty_frame_is_null():
    r = _mem_runner()
    rows = q(r, """
        select g, v, sum(v) over (partition by g order by v
            rows between 3 following and 4 following) s
        from t order by g, v""")
    assert [x[2] for x in rows] == [40, None, None, None, None, None,
                                    None]
