"""trino_tpu — a TPU-native distributed SQL query engine.

A brand-new MPP SQL engine with the capabilities of Trino (reference:
verdantforce/trino, surveyed in SURVEY.md), designed TPU-first:

- columnar Page/Block batches live on device as padded ``jax`` arrays
  (reference analog: ``core/trino-spi/src/main/java/io/trino/spi/Page.java``)
- operator hot paths (filter/project, group-by aggregation, hash join
  build/probe, partitioned output) are jit-compiled XLA programs
  (reference analog: runtime bytecode generation in
  ``core/trino-main/.../sql/gen/``)
- stage-boundary hash repartitioning is an XLA ``all_to_all`` over a
  ``jax.sharding.Mesh`` (reference analog: the HTTP page shuffle in
  ``core/trino-main/.../operator/DirectExchangeClient.java``)

The control plane (parser, analyzer, planner, scheduler, protocol) is
Python; the data plane is XLA.
"""

import jax

# SQL semantics need exact 64-bit integers (BIGINT keys, DECIMAL-as-scaled-
# int64 accumulators) and true DOUBLE. TPU emulates s64/f64; hot kernels
# narrow to 32-bit lanes where the data allows.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
