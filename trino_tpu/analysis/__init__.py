"""qlint — repo-native static analysis for the engine's load-bearing
invariants.

The engine carries several correctness invariants that exist only as
prose in docstrings and PR descriptions; each was a hand-found bug
once.  This package machine-checks them with stdlib ``ast`` (no JAX
import, no new deps) over a shared module-index/call-graph core
(``core.py``, alias-aware since round 14, thread-entry-aware since
round 15) and nine passes:

- ``trace-purity`` — no host side-effects (spans, metrics, locks,
  ``time.*``, IO, ``print``) reachable inside jit'd/shard_map'd/Pallas
  code (PR 6's "spans never open inside jit'd code" claim);
- ``lock-order`` — no cycles in the interprocedural lock-acquisition
  graph, no blocking RPC/subprocess calls under a held lock (the PR 5
  ``HostSpillLedger`` finalizer-deadlock class);
- ``recompile`` — no unhashable arguments into ``lru_cache``'d program
  builders, no Python ``if`` on traced values inside jit'd functions,
  no session-property reads inside cached builders (the PR 5
  ``min_collectives`` stale-cache class);
- ``session-props`` — every property looked up against the registry is
  declared, every declared property has a read site, declared types
  come from the registry vocabulary;
- ``taxonomy`` — in ``parallel/``, ``telemetry/`` and ``cache.py``
  (fault.py exempt), no bare ``raise RuntimeError`` / ``raise
  Exception`` and no broad ``except Exception`` handlers that swallow
  without routing through ``parallel/fault.py``;
- ``blocked-protocol`` — the streaming driver's Blocked/listen-token
  contract: channels implement the full poll/at_end/has_page/listen
  quartet, ``blocked_token`` re-checks readiness after its ``listen()``
  snapshot, waker callbacks never fire under a held lock;
- ``cache-coherence`` — every mutable input a cached builder reads
  (session properties, env vars, rebindable module globals) is part of
  its cache key (the PR 5 ``min_collectives`` bug class, generalized
  to memo-dict builders and interprocedural reach);
- ``resource-lifecycle`` — every constructed closeable (spool cursors,
  exchange channels, spillers, ``open()`` files) reaches ``close()``
  on all paths: ``with``, ``finally``, teardown-list registration or
  ``weakref.finalize`` all count (the PR 8 leaked-cursor class);
- ``guarded-by`` — Eraser-style lockset inference (round 15): a
  thread-entry index (Thread/Timer targets, executor submits, RPC
  handler methods, finalizer callbacks) plus interprocedural
  must-alias locksets infer each attribute's guard from the lock held
  at a qualifying majority of its mutating sites, then report bare
  reads/writes from a DIFFERENT thread entry (the stats_store EWMA
  merge / stream_results done-race / ProcessorCache ``_cache_lock``
  class), with a check-then-act sub-rule for unlocked test-then-mutate
  on shared containers.

The shared core is alias-aware (round 14): single-assignment local
rebinds, ``__init__``-typed ``self.*`` attributes, returned-attribute
accessors and call-argument flow all canonicalize to one identity, so
lock-order resolves CROSS-INSTANCE acquisition edges structurally.

Checked-in suppressions live in ``analysis_baseline.json`` at the repo
root (pre-existing, triaged findings only — the file may only shrink);
line-level opt-outs use ``# qlint: ignore[<pass>] <reason>`` for
effects that are deliberate (e.g. trace-time-only counters). The
trailing reason is MANDATORY: a bare pragma is itself reported by the
always-on framework audit (``pragma/missing-reason``).

CLI: ``python -m trino_tpu.analysis [--json] [--passes a,b] [path]``.
Tier-1 gate: ``tests/test_static_analysis.py`` runs every pass over
``trino_tpu/`` and fails on any non-baselined finding.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional

from .core import Finding, ProjectIndex

__all__ = ["Finding", "ProjectIndex", "PASSES", "run_passes",
           "load_baseline", "apply_baseline", "default_baseline_path"]


def _pass_trace_purity(index):
    from .trace_purity import run
    return run(index)


def _pass_lock_order(index):
    from .lock_order import run
    return run(index)


def _pass_recompile(index):
    from .recompile import run
    return run(index)


def _pass_session_props(index):
    from .session_props import run
    return run(index)


def _pass_taxonomy(index):
    from .taxonomy import run
    return run(index)


def _pass_blocked_protocol(index):
    from .blocked_protocol import run
    return run(index)


def _pass_cache_coherence(index):
    from .cache_coherence import run
    return run(index)


def _pass_resource_lifecycle(index):
    from .resource_lifecycle import run
    return run(index)


def _pass_guarded_by(index):
    from .guarded_by import run
    return run(index)


#: pass slug -> runner(index) -> List[Finding]; slugs are the names
#: used by --passes, pragmas and baseline keys
PASSES = {
    "trace-purity": _pass_trace_purity,
    "lock-order": _pass_lock_order,
    "recompile": _pass_recompile,
    "session-props": _pass_session_props,
    "taxonomy": _pass_taxonomy,
    "blocked-protocol": _pass_blocked_protocol,
    "cache-coherence": _pass_cache_coherence,
    "resource-lifecycle": _pass_resource_lifecycle,
    "guarded-by": _pass_guarded_by,
}


def _audit_pragmas(index: ProjectIndex) -> List[Finding]:
    """Framework-level audit (always on, every run): a ``# qlint:
    ignore[...]`` pragma with no trailing reason is itself a finding —
    a suppression nobody can review is a suppression that outlives its
    justification."""
    findings: List[Finding] = []
    for mod_name in sorted(index.modules):
        mod = index.modules[mod_name]
        ordinals: dict = {}
        for line in sorted(mod.pragmas):
            if mod.pragma_reasons.get(line, ""):
                continue
            passes = ",".join(sorted(mod.pragmas[line]))
            info = mod.enclosing_function(line)
            qual = info.qualname if info is not None else ""
            n = ordinals.get((qual, passes), 0)
            ordinals[(qual, passes)] = n + 1
            findings.append(Finding(
                "pragma", "missing-reason", mod_name, qual, line,
                f"bare `# qlint: ignore[{passes}]` without a trailing "
                f"reason — state WHY the effect is deliberate so the "
                f"suppression stays reviewable",
                f"bare:{passes}:{n}"))
    return findings


def run_passes(index: ProjectIndex,
               passes: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the selected passes (all by default) plus the always-on
    pragma audit, and return pragma-filtered findings, stable-sorted
    for deterministic output."""
    selected = list(passes) if passes is not None else list(PASSES)
    unknown = [p for p in selected if p not in PASSES]
    if unknown:
        raise ValueError(f"unknown passes {unknown}; "
                         f"expected from {sorted(PASSES)}")
    findings: List[Finding] = []
    for name in selected:
        for f in PASSES[name](index):
            if not index.suppressed(f.module, f.line, f.pass_id):
                findings.append(f)
    findings.extend(_audit_pragmas(index))
    findings.sort(key=lambda f: (f.module, f.line, f.pass_id, f.rule,
                                 f.subject))
    return findings


def default_baseline_path(package_path: str) -> str:
    """``analysis_baseline.json`` next to the scanned package (the repo
    root for ``trino_tpu/``)."""
    return os.path.join(os.path.dirname(os.path.abspath(package_path)),
                        "analysis_baseline.json")


def load_baseline(path: str) -> Dict[str, str]:
    """baseline key -> triage note. Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out: Dict[str, str] = {}
    for entry in data.get("findings", ()):
        out[entry["key"]] = entry.get("note", "")
    return out


def apply_baseline(findings: List[Finding], baseline: Dict[str, str]):
    """Split findings into (new, suppressed, stale_keys).

    ``stale_keys`` are baseline entries that no longer fire — the
    baseline is only allowed to shrink, so the gate reports them for
    removal instead of letting dead suppressions accumulate."""
    new: List[Finding] = []
    suppressed: List[Finding] = []
    fired = set()
    for f in findings:
        if f.key in baseline:
            fired.add(f.key)
            suppressed.append(f)
        else:
            new.append(f)
    stale = sorted(k for k in baseline if k not in fired)
    return new, suppressed, stale
