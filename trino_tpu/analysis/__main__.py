"""``python -m trino_tpu.analysis`` — run qlint over a package.

Exit codes: 0 clean (every finding baselined), 1 non-baselined
findings OR stale baseline entries (the baseline may only shrink),
2 usage error. The analysis package itself is pure stdlib ``ast``
(never imports the analyzed code or JAX); note that ``-m`` entry
pays the PARENT package's ``import jax`` — a context where that
could hang (the bench parent) must load this package by file path
instead (see bench.py ``_load_qlint``).

``--changed-since <rev>`` is the pre-commit gate shape: the FULL
index is still built (call graphs are whole-program — a pass run on a
file subset would silently lose interprocedural findings), but only
findings located in files the git diff touched are reported. Stale-
baseline enforcement is skipped in that mode (a partial view cannot
prove an entry dead).

``--json`` emits a SARIF 2.1.0 document (one run, one result per
non-baselined finding, baselined findings carried with an external
suppression) so editors/CI ingest it directly; qlint's native payload
rides in ``runs[0].properties``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from . import (PASSES, ProjectIndex, apply_baseline, default_baseline_path,
               load_baseline, run_passes)

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                 "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def _git_toplevel(start_dir: str):
    """The git working-tree root governing ``start_dir`` — diff paths
    are relative to THIS, not to the analyzed package's parent (a
    package nested below the git root would otherwise never
    intersect the diff and the gate would silently pass)."""
    try:
        out = subprocess.run(
            ["git", "-C", start_dir, "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    top = out.stdout.strip()
    return top or None


def _changed_files(git_root: str, rev: str):
    """git-root-relative paths changed since ``rev`` (committed +
    working tree + UNTRACKED — a brand-new module's findings must not
    silently skip the pre-commit gate before `git add`), or None on
    git failure (caller reports rc 2)."""
    try:
        diff = subprocess.run(
            ["git", "-C", git_root, "diff", "--name-only", rev, "--"],
            capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "-C", git_root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0 or untracked.returncode != 0:
        return None
    return {line.strip()
            for out in (diff.stdout, untracked.stdout)
            for line in out.splitlines() if line.strip()}


def _module_paths(index: ProjectIndex, repo_root: str):
    """module name -> repo-relative source path. Both sides resolve
    symlinks: `git rev-parse --show-toplevel` reports the PHYSICAL
    path, so a checkout reached through a symlink (macOS /tmp, linked
    worktrees) would otherwise never intersect the diff and the gate
    would silently pass."""
    root = os.path.realpath(repo_root)
    out = {}
    for name, mod in index.modules.items():
        if mod.path and mod.path != "<memory>":
            rel = os.path.relpath(os.path.realpath(mod.path), root)
            # git (and SARIF artifact URIs) always use forward
            # slashes; a Windows os.sep would never intersect the
            # diff and silently pass the gate
            out[name] = rel.replace(os.sep, "/")
    return out


def to_sarif(package_path: str, passes, new, suppressed, stale,
             module_paths) -> dict:
    """SARIF 2.1.0 shape: new findings as plain results, baselined
    ones as results with an external suppression; the legacy qlint
    payload rides in run properties."""
    rule_ids = sorted({f"{f.pass_id}/{f.rule}"
                       for f in list(new) + list(suppressed)})

    def result(f, suppressed_entry: bool) -> dict:
        uri = module_paths.get(f.module,
                               f.module.replace(".", "/") + ".py")
        out = {
            "ruleId": f"{f.pass_id}/{f.rule}",
            "level": "error",
            "message": {"text": f.render()},
            "locations": [{"physicalLocation": {
                "artifactLocation": {"uri": uri},
                "region": {"startLine": f.line}}}],
            "partialFingerprints": {"qlintKey": f.key},
        }
        if suppressed_entry:
            out["suppressions"] = [{"kind": "external",
                                    "justification": "baselined"}]
        return out

    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "qlint",
                "rules": [{"id": r} for r in rule_ids],
            }},
            "results": [result(f, False) for f in new]
            + [result(f, True) for f in suppressed],
            "properties": {
                "package": package_path,
                "passes": list(passes),
                "new": [f.to_dict() for f in new],
                "suppressed": [f.to_dict() for f in suppressed],
                "stale_baseline_keys": list(stale),
            },
        }],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m trino_tpu.analysis",
        description="qlint: repo-native static analysis "
                    "(trace-purity, lock-order, recompile, "
                    "session-props, taxonomy, blocked-protocol, "
                    "cache-coherence, resource-lifecycle, "
                    "guarded-by)")
    parser.add_argument("path", nargs="?", default=None,
                        help="package directory to analyze "
                             "(default: the trino_tpu package)")
    parser.add_argument("--passes", default=None,
                        help="comma-separated pass subset "
                             f"(default: all of {','.join(PASSES)})")
    parser.add_argument("--json", action="store_true",
                        help="SARIF 2.1.0 on stdout (qlint payload in "
                             "runs[0].properties)")
    parser.add_argument("--changed-since", default=None, metavar="REV",
                        help="report only findings in files the git "
                             "diff since REV touched (full-index "
                             "analysis, diff-filtered report — the "
                             "fast pre-commit gate)")
    parser.add_argument("--baseline", default=None,
                        help="suppression file "
                             "(default: analysis_baseline.json next "
                             "to the scanned package)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignore the "
                             "baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="bootstrap/retriage: write ALL current "
                             "findings to the baseline file (each "
                             "entry still needs a hand-written triage "
                             "note before it is reviewable)")
    args = parser.parse_args(argv)

    package_path = args.path or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(package_path):
        print(f"not a directory: {package_path}", file=sys.stderr)
        return 2
    passes = None
    if args.passes:
        passes = [p.strip() for p in args.passes.split(",") if p.strip()]
        unknown = [p for p in passes if p not in PASSES]
        if unknown:
            print(f"unknown passes: {', '.join(unknown)} "
                  f"(expected from {', '.join(PASSES)})",
                  file=sys.stderr)
            return 2
        if args.write_baseline:
            # a subset run would rewrite the file WITHOUT the other
            # passes' triaged entries — silently destroying them
            print("--write-baseline requires a full run "
                  "(drop --passes)", file=sys.stderr)
            return 2
    if args.write_baseline and args.changed_since:
        print("--write-baseline requires a full report "
              "(drop --changed-since)", file=sys.stderr)
        return 2

    repo_root = os.path.dirname(os.path.abspath(package_path))
    changed = None
    if args.changed_since:
        # the git probe runs BEFORE the index build: a docs-only diff
        # must not pay the full multi-second analysis in a pre-commit
        # hook just to discover there was nothing to analyze. Diff
        # paths are relative to the GIT top-level, which is not
        # necessarily the package's parent directory
        git_root = _git_toplevel(repo_root) or repo_root
        changed = _changed_files(git_root, args.changed_since)
        if changed is None:
            print(f"git diff --name-only {args.changed_since} failed "
                  f"under {git_root}", file=sys.stderr)
            return 2
        repo_root = git_root
        if not any(p.endswith(".py") for p in changed):
            # a docs/config-only diff is NOT the same log line as an
            # empty-findings clean run: say so explicitly so CI logs
            # distinguish "nothing to analyze" from "analyzed, clean"
            print(f"qlint: no analyzable changes — the diff since "
                  f"{args.changed_since} touches no Python files "
                  f"({len(changed)} file(s) changed)", file=sys.stderr)
            if args.json:
                print(json.dumps(to_sarif(
                    package_path, passes or list(PASSES), [], [], [],
                    {}), indent=1))
            return 0

    index = ProjectIndex.from_package(package_path)
    findings = run_passes(index, passes)
    module_paths = _module_paths(index, repo_root)

    changed_note = ""
    if changed is not None:
        before = len(findings)
        findings = [f for f in findings
                    if module_paths.get(f.module) in changed]
        changed_note = (f" [changed-since {args.changed_since}: "
                        f"{len(changed)} file(s), "
                        f"{before - len(findings)} finding(s) outside "
                        f"the diff]")

    baseline_path = args.baseline or default_baseline_path(package_path)
    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    new, suppressed, stale = apply_baseline(findings, baseline)
    if args.changed_since:
        # a diff-filtered run cannot prove a baseline entry dead
        stale = []

    if args.write_baseline:
        # preserve existing triage notes even under --no-baseline
        # (which only affects reporting, not the file's contents)
        notes = load_baseline(baseline_path)
        payload = {"comment": "qlint suppressions — pre-existing "
                              "findings only; this file may only "
                              "shrink",
                   "findings": [{"key": f.key,
                                 "note": notes.get(f.key,
                                                   "TODO: triage")}
                                for f in findings]}
        with open(baseline_path, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=False)
            fh.write("\n")
        print(f"wrote {len(findings)} entries to {baseline_path}",
              file=sys.stderr)

    if args.json:
        print(json.dumps(to_sarif(
            package_path, passes or list(PASSES), new, suppressed,
            stale, module_paths), indent=1))
    else:
        for f in new:
            print(f.render())
        for key in stale:
            print(f"STALE baseline entry no longer fires "
                  f"(remove it): {key}")
        print(f"qlint: {len(new)} finding(s), "
              f"{len(suppressed)} baselined, {len(stale)} stale "
              f"baseline entr{'y' if len(stale) == 1 else 'ies'} "
              f"over {len(index.modules)} modules{changed_note}",
              file=sys.stderr)
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
