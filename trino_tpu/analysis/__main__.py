"""``python -m trino_tpu.analysis`` — run qlint over a package.

Exit codes: 0 clean (every finding baselined), 1 non-baselined
findings OR stale baseline entries (the baseline may only shrink),
2 usage error. The analysis package itself is pure stdlib ``ast``
(never imports the analyzed code or JAX); note that ``-m`` entry
pays the PARENT package's ``import jax`` — a context where that
could hang (the bench parent) must load this package by file path
instead (see bench.py ``_load_qlint``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (PASSES, ProjectIndex, apply_baseline, default_baseline_path,
               load_baseline, run_passes)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m trino_tpu.analysis",
        description="qlint: repo-native static analysis "
                    "(trace-purity, lock-order, recompile, "
                    "session-props, taxonomy)")
    parser.add_argument("path", nargs="?", default=None,
                        help="package directory to analyze "
                             "(default: the trino_tpu package)")
    parser.add_argument("--passes", default=None,
                        help="comma-separated pass subset "
                             f"(default: all of {','.join(PASSES)})")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON on stdout")
    parser.add_argument("--baseline", default=None,
                        help="suppression file "
                             "(default: analysis_baseline.json next "
                             "to the scanned package)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignore the "
                             "baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="bootstrap/retriage: write ALL current "
                             "findings to the baseline file (each "
                             "entry still needs a hand-written triage "
                             "note before it is reviewable)")
    args = parser.parse_args(argv)

    package_path = args.path or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(package_path):
        print(f"not a directory: {package_path}", file=sys.stderr)
        return 2
    passes = None
    if args.passes:
        passes = [p.strip() for p in args.passes.split(",") if p.strip()]
        unknown = [p for p in passes if p not in PASSES]
        if unknown:
            print(f"unknown passes: {', '.join(unknown)} "
                  f"(expected from {', '.join(PASSES)})",
                  file=sys.stderr)
            return 2
        if args.write_baseline:
            # a subset run would rewrite the file WITHOUT the other
            # passes' triaged entries — silently destroying them
            print("--write-baseline requires a full run "
                  "(drop --passes)", file=sys.stderr)
            return 2

    index = ProjectIndex.from_package(package_path)
    findings = run_passes(index, passes)

    baseline_path = args.baseline or default_baseline_path(package_path)
    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    new, suppressed, stale = apply_baseline(findings, baseline)

    if args.write_baseline:
        # preserve existing triage notes even under --no-baseline
        # (which only affects reporting, not the file's contents)
        notes = load_baseline(baseline_path)
        payload = {"comment": "qlint suppressions — pre-existing "
                              "findings only; this file may only "
                              "shrink",
                   "findings": [{"key": f.key,
                                 "note": notes.get(f.key,
                                                   "TODO: triage")}
                                for f in findings]}
        with open(baseline_path, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=False)
            fh.write("\n")
        print(f"wrote {len(findings)} entries to {baseline_path}",
              file=sys.stderr)

    if args.json:
        print(json.dumps({
            "package": package_path,
            "passes": passes or list(PASSES),
            "new": [f.to_dict() for f in new],
            "suppressed": [f.to_dict() for f in suppressed],
            "stale_baseline_keys": stale,
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        for key in stale:
            print(f"STALE baseline entry no longer fires "
                  f"(remove it): {key}")
        print(f"qlint: {len(new)} finding(s), "
              f"{len(suppressed)} baselined, {len(stale)} stale "
              f"baseline entr{'y' if len(stale) == 1 else 'ies'} "
              f"over {len(index.modules)} modules",
              file=sys.stderr)
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
