"""blocked-protocol pass: the Blocked/listen-token contract the
streaming driver leans on.

The pipelined execution path (round 9) parks a task only on listen
tokens collected after a no-progress quantum; three structural
mistakes silently break that into a deadlock or a busy spin, and each
is machine-checkable:

- ``channel-contract``: a streaming channel must implement the WHOLE
  ``poll`` / ``at_end`` / ``has_page`` / ``listen`` quartet. A class
  that implements ``poll`` plus only part of the rest duck-types as a
  channel at the planner seam (``hasattr(x, "poll")``) and then
  crashes — or worse, never parks — once the driver blocks on it.
- ``stale-token-park``: a ``blocked_token`` method that returns a
  ``.listen()`` token WITHOUT re-checking readiness (``at_end`` /
  ``has_page`` / ``full``) afterwards. Tokens snapshot a state
  version; state that changed between the last ``poll()`` and the
  ``listen()`` snapshot is invisible to the token, so the task parks
  on a version that may never move again. Snapshot-then-recheck is the
  load-bearing idiom (see ExchangeSourceOperator.blocked_token).
- ``waker-under-lock``: invoking listener callbacks while a lock is
  held. Wakers run arbitrary downstream code (a parked driver's
  re-offer); firing them under the state lock hands that code the
  lock's criticality — the repo-wide idiom is collect-under-lock
  (``fired = self._bump_locked()``), fire AFTER release.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, FunctionInfo, ModuleInfo, ProjectIndex, \
    dotted_chain

PASS_ID = "blocked-protocol"

#: the streaming channel quartet (ops/output.ExchangeChannel contract)
_QUARTET = ("poll", "at_end", "has_page", "listen")

#: readiness re-checks that make a listen-token snapshot safe to park on
_RECHECKS = {"at_end", "has_page", "full"}

#: iterable names whose elements are waker callbacks
_WAKER_NAMES = ("listener", "callback", "waiter", "waker", "fired")


def _lockish(chain: Optional[str]) -> bool:
    return bool(chain) and "lock" in chain.split(".")[-1].lower()


def _wakerish(chain: Optional[str]) -> bool:
    if not chain:
        return False
    last = chain.split(".")[-1].lower()
    return any(w in last for w in _WAKER_NAMES)


def _class_defs(mod: ModuleInfo) -> Dict[str, ast.ClassDef]:
    return {node.name: node for node in ast.walk(mod.tree)
            if isinstance(node, ast.ClassDef)}


def channel_classes(index: ProjectIndex) -> List[str]:
    """Fully-quartet-implementing channel classes, as ``module:Class``
    — the not-blind witness for the tier-1 gate (a refactor that hides
    the channels from the index would silence the pass)."""
    out: List[str] = []
    for name in sorted(index.modules):
        mod = index.modules[name]
        for cls, node in sorted(_class_defs(mod).items()):
            methods = {s.name for s in node.body
                       if isinstance(s, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            if all(m in methods for m in _QUARTET):
                out.append(f"{name}:{cls}")
    return out


def _check_channel_contract(mod: ModuleInfo,
                            findings: List[Finding]) -> None:
    for name, node in sorted(_class_defs(mod).items()):
        methods = {s.name for s in node.body
                   if isinstance(s, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        present = [m for m in _QUARTET if m in methods]
        # only classes that ENTER the contract are checked: `poll` plus
        # at least one sibling claims channel-ness (a lone unrelated
        # poll() method — e.g. the HTTP protocol's long-poll — is not a
        # streaming channel)
        if "poll" not in methods or len(present) < 2:
            continue
        missing = [m for m in _QUARTET if m not in methods]
        if missing:
            findings.append(Finding(
                PASS_ID, "channel-contract", mod.name, name,
                node.lineno,
                f"class {name} implements {present} but not "
                f"{missing}: a partial streaming channel duck-types at "
                f"the planner seam and breaks the driver's "
                f"Blocked/park loop",
                f"contract:{name}:{','.join(missing)}"))


def _check_stale_token(mod: ModuleInfo, func: FunctionInfo,
                       findings: List[Finding]) -> None:
    if func.qualname.split(".")[-1] != "blocked_token":
        return
    listens = [c for c in func.calls
               if c.chain.split(".")[-1] == "listen"]
    if not listens:
        return
    rechecks = any(c.chain.split(".")[-1] in _RECHECKS
                   for c in func.calls)
    if not rechecks:
        findings.append(Finding(
            PASS_ID, "stale-token-park", func.module, func.qualname,
            listens[0].line,
            "blocked_token returns a listen() token without re-checking "
            "readiness (at_end/has_page/full) after the snapshot: state "
            "that moved between poll() and listen() is invisible to the "
            "token, so the task can park forever",
            f"stale:{func.qualname}"))


class _WakerVisitor(ast.NodeVisitor):
    """Track lexically-held locks and for-targets bound from waker
    collections; flag waker calls made while a lock is held."""

    def __init__(self, mod: ModuleInfo, func: FunctionInfo,
                 findings: List[Finding]):
        self.mod = mod
        self.func = func
        self.findings = findings
        self._held = 0
        self._waker_names: Set[str] = set()

    def visit_With(self, node: ast.With):
        locked = any(_lockish(dotted_chain(i.context_expr))
                     for i in node.items)
        if locked:
            self._held += 1
        for stmt in node.body:
            self.visit(stmt)
        if locked:
            self._held -= 1

    visit_AsyncWith = visit_With

    @staticmethod
    def _target_names(target) -> List[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            return [e.id for e in target.elts
                    if isinstance(e, ast.Name)]
        return []

    def visit_For(self, node: ast.For):
        names = self._target_names(node.target) \
            if _wakerish(dotted_chain(node.iter)) else []
        self._waker_names.update(names)
        self.generic_visit(node)
        self._waker_names.difference_update(names)

    def visit_Call(self, node: ast.Call):
        if self._held:
            name = node.func.id if isinstance(node.func, ast.Name) \
                else None
            chain = dotted_chain(node.func)
            if (name in self._waker_names) or \
                    (chain and chain.split(".")[-1] == "on_ready"):
                self.findings.append(Finding(
                    PASS_ID, "waker-under-lock", self.func.module,
                    self.func.qualname, node.lineno,
                    f"waker callback `{chain or name}()` fired while a "
                    f"lock is held: the parked task's re-offer runs "
                    f"under the state lock (collect under the lock, "
                    f"fire after release)",
                    f"waker:{self.func.qualname}:{chain or name}"))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if node is not self.func.node:
            return  # nested def: analyzed via its own FunctionInfo
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def run(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for name in sorted(index.modules):
        _check_channel_contract(index.modules[name], findings)
    for func in index.iter_functions():
        mod = index.modules[func.module]
        _check_stale_token(mod, func, findings)
        v = _WakerVisitor(mod, func, findings)
        for stmt in func.body:
            v.visit(stmt)
    return findings
