"""cache-coherence pass: every mutable input a cached builder reads
must be represented in its cache key.

The bug class that bit ``min_collectives`` in PR 5 and forced PR 10's
session fingerprint: a memoized builder (an ``lru_cache``'d program
builder, a get-or-build memo dict like ``mesh_query._PROGRAM_CACHE``,
``ProcessorCache.get``, ``QueryCache.parse``, the sizing histories)
reads state that can CHANGE between calls — a session property, an
environment variable, a rebindable module global — without that state
being part of the key it is memoized under. The first caller's setting
is baked into the cached value and every later caller silently gets
it. The fix is always the same: hoist the read into the key
(parameters for ``lru_cache``, the key tuple for memo dicts) — which
also makes the finding disappear, because the read moves to the
caller.

Builders are indexed two ways (``cached_builders``):

- ``lru``: ``functools.lru_cache`` / ``functools.cache`` decorated
  functions — the whole parameter list is the key;
- ``memo``: a function that BOTH loads (``D.get(k)`` / ``D[k]``) and
  stores (``D[k] = v`` / ``D.setdefault``) through one container
  reached from ``self.*`` or a module-level name — the hand-rolled
  get-or-build idiom.

From every builder the pass walks resolved call-graph edges (stopping
at other builders: their reads are their own findings) and flags:

- ``unkeyed-session-read``: ``SP.value`` / ``prop_value`` /
  session-property reads (subsumes and extends the old recompile rule
  to memo builders and interprocedural reach);
- ``unkeyed-env-read``: ``os.environ`` / ``os.getenv`` reads — env
  mutates at runtime (tests, workers) but never re-keys the cache;
- ``unkeyed-global-read``: reads of a module global some function
  REBINDS via ``global X`` — the one mutable-global shape that is
  provably not constant.

Deliberate trace-static reads opt out per line with
``# qlint: ignore[cache-coherence] <reason>``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, FunctionInfo, ModuleInfo, ProjectIndex,
                   dotted_chain, own_nodes)
from .recompile import _cached_functions

PASS_ID = "cache-coherence"

_SESSION_READ_LASTS = {"value", "prop_value"}


@dataclass
class BuilderInfo:
    func: FunctionInfo
    kind: str                     # "lru" | "memo"
    container: Optional[str] = None   # memo: the container chain


def _container_base_ok(mod: ModuleInfo, func: FunctionInfo,
                       chain: str) -> bool:
    """A memo container must outlive the call: ``self.*`` state or a
    module-level binding (a local dict rebuilt per call caches
    nothing)."""
    head = chain.split(".")[0]
    if head in ("self", "cls"):
        return True
    return head in mod.module_assigns or head in mod.scopes.get("", {})


def cached_builders(index: ProjectIndex) -> Dict[str, BuilderInfo]:
    """Every memoizing builder in the package, keyed by function id —
    also the not-blind witness the tier-1 gate asserts over (an engine
    where the caches went invisible would gut the pass)."""
    out: Dict[str, BuilderInfo] = {}
    # the shared lru index lives in recompile (its unhashable-arg rule
    # keys off the same decorator set — one vocabulary, two passes)
    for fid, func in _cached_functions(index).items():
        out[fid] = BuilderInfo(func, "lru")
    for func in index.iter_functions():
        if func.id in out:
            continue
        mod = index.modules[func.module]
        loads: Set[str] = set()
        #: container -> saw at least one NON-read-modify-write store
        #: (a store whose value re-reads the same container is an
        #: accumulator — `d[k] = d.get(k, 0) + 1` refcounts/EWMAs
        #: cache nothing and must not classify as builders)
        build_stores: Set[str] = set()
        for node in own_nodes(func.node):
            if isinstance(node, ast.Call):
                chain = dotted_chain(node.func)
                if chain is None:
                    continue
                parts = chain.split(".")
                if len(parts) < 2:
                    continue
                base = ".".join(parts[:-1])
                if parts[-1] == "get" and node.args:
                    loads.add(base)
                elif parts[-1] == "setdefault" and len(node.args) >= 2:
                    if not _reads_container(node.args[1], base):
                        build_stores.add(base)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        base = dotted_chain(t.value)
                        if base is not None and \
                                not _reads_container(node.value, base):
                            build_stores.add(base)
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load):
                base = dotted_chain(node.value)
                if base is not None:
                    loads.add(base)
        for base in sorted(loads & build_stores):
            base_c = index.canonical_chain(func, base)
            if _container_base_ok(mod, func, base_c):
                out[func.id] = BuilderInfo(func, "memo", base_c)
                break
    return out


def _reads_container(value: ast.AST, base: str) -> bool:
    """True when ``value`` re-reads ``base`` (``d.get(k)`` /
    ``d[k]`` / a bare reference) — the store is then read-modify-write
    accumulation, not get-or-build."""
    for node in ast.walk(value):
        chain = None
        if isinstance(node, (ast.Attribute, ast.Name)):
            chain = dotted_chain(node)
        if chain == base:
            return True
    return False


def _mutated_globals(index: ProjectIndex) -> Dict[str, Set[str]]:
    """module -> names some function rebinds via ``global X; X = ...``
    — the one provably-mutable module-global shape."""
    out: Dict[str, Set[str]] = {}
    for name, mod in index.modules.items():
        muted: Set[str] = set()
        for func in mod.functions.values():
            declared: Set[str] = set()
            for node in own_nodes(func.node):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            if not declared:
                continue
            for node in own_nodes(func.node):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in declared:
                        muted.add(t.id)
        if muted:
            out[name] = muted
    return out


def _env_read(node: ast.AST) -> Optional[str]:
    """The env-var name (or "<dynamic>") when ``node`` reads the
    process environment."""
    chain = None
    args: Tuple = ()
    if isinstance(node, ast.Call):
        chain = dotted_chain(node.func)
        args = tuple(node.args)
        if chain is None:
            return None
        if chain in ("os.getenv", "getenv"):
            pass
        elif chain.split(".")[-2:] == ["environ", "get"]:
            pass
        else:
            return None
    elif isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, ast.Load):
        chain = dotted_chain(node.value)
        if chain is None or chain.split(".")[-1] != "environ":
            return None
        args = (node.slice,)
    else:
        return None
    for a in args:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    return "<dynamic>"


def _session_read(call_chain: str, target: Optional[str]) -> bool:
    resolved = target or ""
    if resolved.endswith((":value", ":prop_value")) \
            and "session_properties" in resolved:
        return True
    if call_chain.split(".")[-1] in _SESSION_READ_LASTS:
        head = call_chain.split(".")[0]
        return head in ("SP", "session_properties")
    return False


def _const_arg(call: ast.Call) -> str:
    for a in call.args:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    return ""


def _keyed_reads(index: ProjectIndex, builder: BuilderInfo) -> Set[int]:
    """``id()`` of every AST node inside the builder's own body whose
    value flows into the memo KEY: a read that IS part of the key is
    coherent by construction (`flavor = os.environ.get(...); k =
    (key, flavor); d.get(k)` — the prescribed fix for an lru builder
    is hoisting the read into the key; for a memo builder the read
    necessarily stays inside get-or-build, so the pass must recognize
    it there). Name flow closes transitively through single-name
    assignments, bounded."""
    if builder.kind != "memo" or builder.container is None:
        return set()
    func = builder.func
    # names appearing inside the container's get/subscript key exprs;
    # the container chain is matched CANONICALLY (a local alias
    # `d = self._programs; d.get(k)` names the same container)
    keyed: Set[str] = set()
    key_exprs: List[ast.AST] = []
    for node in own_nodes(func.node):
        if isinstance(node, ast.Call):
            chain = dotted_chain(node.func)
            if chain is not None and "." in chain \
                    and index.canonical_chain(
                        func, chain.rsplit(".", 1)[0]) \
                    == builder.container \
                    and chain.split(".")[-1] in ("get", "setdefault") \
                    and node.args:
                key_exprs.append(node.args[0])
        elif isinstance(node, ast.Subscript):
            base = dotted_chain(node.value)
            if base is not None and \
                    index.canonical_chain(func, base) \
                    == builder.container:
                key_exprs.append(node.slice)
    out: Set[int] = set()
    for e in key_exprs:
        for n in ast.walk(e):
            # a read INLINE in the key expression is keyed directly
            out.add(id(n))
            if isinstance(n, ast.Name):
                keyed.add(n.id)
    # transitive closure through plain-name assignments, then collect
    # the node ids of every value expression feeding a keyed name
    assigns = [n for n in own_nodes(func.node)
               if isinstance(n, ast.Assign) and len(n.targets) == 1
               and isinstance(n.targets[0], ast.Name)]
    for _ in range(5):
        grew = False
        for a in assigns:
            if a.targets[0].id in keyed:
                for n in ast.walk(a.value):
                    if isinstance(n, ast.Name) and n.id not in keyed:
                        keyed.add(n.id)
                        grew = True
        if not grew:
            break
    for a in assigns:
        if a.targets[0].id in keyed:
            for n in ast.walk(a.value):
                out.add(id(n))
    return out


def run(index: ProjectIndex) -> List[Finding]:
    builders = cached_builders(index)
    mutated = _mutated_globals(index)
    findings: List[Finding] = []
    seen: Set[Tuple[str, str, str]] = set()

    def emit(builder: BuilderInfo, func: FunctionInfo, rule: str,
             line: int, what: str, subject: str):
        key = (builder.func.id, rule, subject)
        if key in seen:
            return
        seen.add(key)
        via = "" if func.id == builder.func.id else \
            f" (reached from cached builder {builder.func.qualname})"
        keyname = "its parameters" if builder.kind == "lru" \
            else f"the `{builder.container}` key"
        findings.append(Finding(
            PASS_ID, rule, func.module, func.qualname, line,
            f"{builder.kind}-cached `{builder.func.qualname}` reads "
            f"{what}{via} without it being part of {keyname} — the "
            f"first caller's value is baked into the cached entry",
            subject))

    for fid in sorted(builders):
        builder = builders[fid]
        keyed = _keyed_reads(index, builder)
        stack = [fid]
        visited: Set[str] = set()
        while stack:
            cur = stack.pop()
            if cur in visited:
                continue
            visited.add(cur)
            if cur != fid and cur in builders:
                continue   # a nested builder owns its own reads
            func = index.functions.get(cur)
            if func is None:
                continue
            mod = index.modules[func.module]
            for call in func.calls:
                if _session_read(call.chain, call.target):
                    if cur == fid and id(call.node) in keyed:
                        continue   # the read IS part of the memo key
                    prop = _const_arg(call.node)
                    emit(builder, func, "unkeyed-session-read",
                         call.line,
                         f"session property "
                         f"{prop or '<dynamic>'!r}",
                         f"session:{prop or call.chain}")
                elif call.target and call.target in index.functions:
                    stack.append(call.target)
            for node in own_nodes(func.node):
                env = _env_read(node)
                if env is not None:
                    if cur == fid and id(node) in keyed:
                        continue   # the read IS part of the memo key
                    emit(builder, func, "unkeyed-env-read",
                         node.lineno,
                         f"environment variable {env!r}",
                         f"env:{env}")
                    continue
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in mutated.get(func.module, ()):
                    if cur == fid and id(node) in keyed:
                        continue   # the read IS part of the memo key
                    if node.id == builder.container:
                        # the builder's OWN container: a lazily-
                        # initialized/resettable `global _CACHE` is
                        # the cache, not an input missing from its key
                        continue
                    emit(builder, func, "unkeyed-global-read",
                         node.lineno,
                         f"mutable module global `{node.id}` "
                         f"(rebound via `global` elsewhere)",
                         f"global:{func.module}.{node.id}")
    return findings
