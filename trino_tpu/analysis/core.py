"""Shared static-analysis core: module index, function table, call
graph, alias tracking, pragma handling.

Everything is stdlib ``ast`` over source text — the analyzed package is
never imported, so the analyzer runs without JAX (and cannot be fooled
by import-time machinery). Resolution is deliberately conservative:
only unambiguous targets (same-scope names, ``self.`` methods on the
enclosing class, imported-module attributes, annotated parameters)
resolve to call-graph edges; everything else stays a raw dotted chain
for pattern-based checks. Over-approximating the graph would flood the
purity/lock passes with false paths, under-approximating loses real
ones — unambiguous-only is the tested middle ground, and the fixture
tests in ``tests/test_static_analysis.py`` pin what each pass must
still catch through it.

Alias tracking (round 14) extends resolution with MUST-alias facts
only — every fact is a definite "these two names denote the same
object", never a may-alias guess, so the lock/lifecycle passes can
unify identities without fabricating false cycles:

- ``FunctionInfo.aliases``: single-assignment locals bound from a
  dotted chain (``lock = self._lock``) expand in place during
  resolution (``canonical_chain``);
- ``ModuleInfo.attr_types``: ``self.x = <annotated param>`` /
  ``self.x = ClassName(...)`` / ``self.x: ClassName`` in a method body
  types the attribute, so chains like ``self.ledger.park()`` or
  ``pool.host_ledger.charge()`` resolve through ``instance_type`` —
  the seam that lets lock-order unify CROSS-INSTANCE lock identities
  structurally (ambiguous re-assignments tombstone the attr);
- ``FunctionInfo.returns_chain``: a method whose every return is the
  same ``self.<attr>`` chain is a returned-attribute accessor —
  ``obj.lock()`` in a with-item denotes the target class's attribute;
- ``bind_args``: maps a resolved call's actual arguments onto the
  callee's parameters, so a lock/resource flowing through
  ``spill_pages(..., lock=ctx.lock)`` keeps its caller-side identity
  inside the callee (parametric substitution in lock-order).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple


@dataclass(frozen=True)
class Finding:
    pass_id: str
    rule: str
    module: str          # dotted module name
    qualname: str        # enclosing function ("" = module level)
    line: int
    message: str
    subject: str         # stable discriminator (no line numbers)

    @property
    def key(self) -> str:
        """Stable baseline key — no line numbers, so unrelated edits
        don't churn the baseline."""
        return (f"{self.pass_id}:{self.rule}:{self.module}:"
                f"{self.qualname or '<module>'}:{self.subject}")

    def render(self) -> str:
        return (f"{self.module}:{self.line} [{self.pass_id}/{self.rule}] "
                f"{(self.qualname + ': ') if self.qualname else ''}"
                f"{self.message}")

    def to_dict(self) -> dict:
        return {"pass": self.pass_id, "rule": self.rule,
                "module": self.module, "qualname": self.qualname,
                "line": self.line, "message": self.message,
                "key": self.key}


@dataclass
class CallSite:
    chain: str                   # dotted source text of the callee
    node: ast.Call
    target: Optional[str] = None  # resolved function id, if unambiguous

    @property
    def line(self) -> int:
        return self.node.lineno


@dataclass
class FunctionInfo:
    module: str
    qualname: str                # Class.method / func / outer.inner
    node: ast.AST                # FunctionDef | AsyncFunctionDef | Lambda
    class_name: Optional[str]
    scope: str                   # enclosing scope qualname ("" = module)
    params: List[str] = field(default_factory=list)
    #: parameter name -> annotated class name (string), best-effort
    annotations: Dict[str, str] = field(default_factory=dict)
    decorators: List[ast.expr] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    #: single-assignment local name -> the dotted chain it MUST alias
    #: (``lock = self._lock``); names bound more than once, bound by
    #: loops/with/aug-assign, or shadowing a parameter never enter
    aliases: Dict[str, str] = field(default_factory=dict)
    #: per-name binding counts in this body (shared with lock-order's
    #: chain-stability check — computed once, alongside ``aliases``)
    bindings: Dict[str, int] = field(default_factory=dict)
    #: when every ``return`` in the body returns the SAME dotted chain
    #: (``return self._lock``) — the returned-attribute-accessor seam
    returns_chain: Optional[str] = None

    @property
    def id(self) -> str:
        return f"{self.module}:{self.qualname}"

    @property
    def line(self) -> int:
        return self.node.lineno

    @property
    def body(self) -> list:
        return self.node.body


_PRAGMA_RE = re.compile(r"#\s*qlint:\s*ignore\[([a-z*,\s-]+)\]\s*(.*)$")


def own_nodes(func_node) -> Iterator[ast.AST]:
    """Walk a function body EXCLUDING nested function bodies (they get
    their own FunctionInfo); lambdas stay attributed to this frame —
    the same ownership rule ``_collect_calls`` uses."""
    stack: List[ast.AST] = [func_node]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                continue
            stack.append(child)


class ModuleInfo:
    def __init__(self, name: str, source: str, path: str = "<memory>",
                 is_package: bool = False):
        self.name = name
        self.path = path
        #: True for a package __init__: its relative imports resolve
        #: against the package itself, not the parent
        self.is_package = is_package
        self.tree = ast.parse(source, filename=path)
        #: alias -> dotted module (``import a.b as c`` and
        #: ``from pkg import mod`` both land here when mod is a module)
        self.imports: Dict[str, str] = {}
        #: name -> (dotted module, original name) for from-imports
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        #: names bound by module-level assignments (not defs/imports)
        self.module_assigns: Set[str] = set()
        for stmt in self.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    self.module_assigns.add(t.id)
        #: scope qualname -> {visible def name -> qualname}
        self.scopes: Dict[str, Dict[str, str]] = {"": {}}
        #: class name -> {method name -> qualname}
        self.classes: Dict[str, Dict[str, str]] = {}
        #: line -> set of pass slugs suppressed there
        self.pragmas: Dict[int, Set[str]] = {}
        #: line -> the trailing reason text after the pragma ("" = bare
        #: pragma, which the framework audit reports as a finding)
        self.pragma_reasons: Dict[int, str] = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = _PRAGMA_RE.search(text)
            if m:
                self.pragmas[i] = {p.strip()
                                   for p in m.group(1).split(",")}
                self.pragma_reasons[i] = m.group(2).strip()
        #: class name -> {attr name -> class name} typed from method
        #: bodies (``self.x = <annotated param>`` / ``= ClassName()`` /
        #: ``self.x: ClassName``); conflicting assignments tombstone
        #: the attr with "" so ambiguity never resolves
        self.attr_types: Dict[str, Dict[str, str]] = {}
        self._collect()

    # -- collection ------------------------------------------------------

    def _collect(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or
                                 alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                target = self._resolve_from(node)
                if target is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.from_imports[alias.asname or alias.name] = \
                        (target, alias.name)
        self._walk_scope(self.tree.body, scope="", class_name=None)
        self._collect_attr_types()

    def _collect_attr_types(self):
        """Type ``self.<attr>`` from method bodies: an annotated-param
        store, a direct ``ClassName(...)`` construction, or an
        annotated ``self.x: T`` assignment each give a definite class;
        two different candidates for one attr tombstone it ("") —
        must-alias or nothing."""
        def note(cls: str, attr: str, type_name: Optional[str]):
            if type_name is None:
                return
            attrs = self.attr_types.setdefault(cls, {})
            if attrs.get(attr, type_name) != type_name:
                attrs[attr] = ""      # ambiguous: never resolves
            else:
                attrs[attr] = type_name

        for info in self.functions.values():
            if info.class_name is None:
                continue
            for node in own_nodes(info.node):
                target = None
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1:
                    target = node.targets[0]
                elif isinstance(node, ast.AnnAssign):
                    target = node.target
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    continue
                attr = target.attr
                if isinstance(node, ast.AnnAssign):
                    # unresolvable annotations (containers, unions)
                    # tombstone rather than silently keeping a type
                    note(info.class_name, attr,
                         _annotation_name(node.annotation) or "")
                    continue
                value = node.value
                if isinstance(value, ast.Constant) \
                        and value.value is None:
                    continue   # `self.x = None` idles a typed attr
                candidate = ""   # default: untypeable -> tombstone
                if isinstance(value, ast.Name):
                    # a rebind from an UNannotated name makes the attr
                    # ambiguous — tombstone, don't keep a stale type
                    candidate = info.annotations.get(value.id) or ""
                elif isinstance(value, ast.Call):
                    chain = dotted_chain(value.func)
                    if chain is not None:
                        last = chain.split(".")[-1]
                        if last[:1].isupper():
                            candidate = last
                note(info.class_name, attr, candidate)

    def enclosing_function(self, line: int) -> Optional["FunctionInfo"]:
        """Innermost function whose def spans ``line`` (None = module
        level) — shared by every pass that anchors a finding to its
        enclosing function."""
        best = None
        for info in self.functions.values():
            node = info.node
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                if best is None or node.lineno > best.node.lineno:
                    best = info
        return best

    def _resolve_from(self, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        # relative import: strip ``level`` trailing components from
        # this module's dotted name. A leaf module's level=1 is its
        # package; a package __init__'s level=1 is the package ITSELF
        # (model it as a phantom leaf)
        parts = self.name.split(".")
        if self.is_package:
            parts = parts + ["__init__"]
        if node.level > len(parts):
            return None
        base = parts[:len(parts) - node.level]
        if node.module:
            base.append(node.module)
        return ".".join(base)

    def _walk_scope(self, body: Sequence[ast.stmt], scope: str,
                    class_name: Optional[str]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{scope}.{stmt.name}" if scope else stmt.name
                info = FunctionInfo(self.name, qual, stmt, class_name,
                                    scope)
                info.params = [a.arg for a in
                               stmt.args.posonlyargs + stmt.args.args
                               + stmt.args.kwonlyargs]
                for a in stmt.args.posonlyargs + stmt.args.args \
                        + stmt.args.kwonlyargs:
                    ann = _annotation_name(a.annotation)
                    if ann:
                        info.annotations[a.arg] = ann
                info.decorators = list(stmt.decorator_list)
                info.calls = _collect_calls(stmt)
                info.bindings = _binding_counts(stmt)
                info.aliases = _collect_aliases(info)
                info.returns_chain = _returns_chain(stmt)
                self.functions[qual] = info
                self.scopes.setdefault(scope, {})[stmt.name] = qual
                if class_name is not None and scope == class_name:
                    self.classes.setdefault(class_name, {})[stmt.name] \
                        = qual
                # nested defs live in the function's scope; a method's
                # class context does not extend to its inner functions
                self._walk_scope(stmt.body, qual, None)
            elif isinstance(stmt, ast.ClassDef):
                self.classes.setdefault(stmt.name, {})
                self.scopes.setdefault(scope, {})[stmt.name] = stmt.name
                self._walk_scope(stmt.body, stmt.name, stmt.name)
            elif isinstance(stmt, (ast.If, ast.Try, ast.With, ast.For,
                                   ast.While)):
                # statements nested in control flow at any scope
                for field_name in ("body", "orelse", "finalbody"):
                    self._walk_scope(getattr(stmt, field_name, []) or [],
                                     scope, class_name)
                for handler in getattr(stmt, "handlers", []) or []:
                    self._walk_scope(handler.body, scope, class_name)


def _annotation_name(node) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split(".")[-1].strip('"')
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript) \
            and _annotation_name(node.value) == "Optional":
        # Optional[X] types an attribute that idles at None — the
        # `self._cur: Optional[SpoolCursor] = None` idiom
        return _annotation_name(node.slice)
    return None


def _binding_counts(func_node) -> Dict[str, int]:
    """How many times each local name is BOUND in this function's own
    body — a must-alias requires exactly one binding (loops, tuple
    unpacks, walrus, with-as and except-as all count as bindings)."""
    counts: Dict[str, int] = {}

    def bump(target):
        if isinstance(target, ast.Name):
            counts[target.id] = counts.get(target.id, 0) + 1
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                bump(e)
        elif isinstance(target, ast.Starred):
            bump(target.value)

    for node in own_nodes(func_node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                bump(t)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                               ast.NamedExpr)):
            bump(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            bump(node.target)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    bump(item.optional_vars)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            counts[node.name] = counts.get(node.name, 0) + 1
        elif isinstance(node, ast.comprehension):
            bump(node.target)
    return counts


def _collect_aliases(info: "FunctionInfo") -> Dict[str, str]:
    """``name -> chain`` for single-assignment locals bound from a
    dotted chain: the ``lock = self._lock`` rebind that used to hide a
    lock's identity from the passes."""
    counts = info.bindings
    out: Dict[str, str] = {}
    for node in own_nodes(info.node):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not isinstance(t, ast.Name):
            continue
        chain = dotted_chain(node.value)
        if chain is None or chain.split(".")[0] == t.id:
            continue
        if counts.get(t.id, 0) == 1 and t.id not in info.params:
            out[t.id] = chain
    return out


def _returns_chain(func_node) -> Optional[str]:
    """The one ``self.<attr>`` chain every return in the body returns,
    or None — the returned-attribute accessor (``def lock(self):
    return self._lock``) that lets ``obj.lock()`` denote the target
    class's attribute in with-items."""
    chains: Set[Optional[str]] = set()
    saw_return = False
    for node in own_nodes(func_node):
        if isinstance(node, ast.Return):
            saw_return = True
            chains.add(dotted_chain(node.value)
                       if node.value is not None else None)
    if not saw_return or len(chains) != 1:
        return None
    chain = chains.pop()
    if chain and chain.startswith("self.") and len(chain.split(".")) == 2:
        return chain
    return None


def bind_args(callee: "FunctionInfo", call: ast.Call, chain: str,
              index: Optional["ProjectIndex"] = None,
              mod: Optional["ModuleInfo"] = None) -> Dict[str, ast.expr]:
    """Map a resolved call's actual argument expressions onto the
    callee's parameter names, the way Python would: positionals bind
    only positional parameters (never keyword-only), excess
    positionals fall into ``*args`` (unbindable — dropped, since a
    wrong binding would fabricate a must-alias fact), and ``self`` is
    skipped for attribute-form calls UNLESS the attribute base is a
    class (an unbound ``Class.method(obj, ...)`` call binds ``self``
    positionally). The seam that keeps an object flowing through
    ``spill_pages(..., lock=ctx.lock)`` identified with the caller's
    ``ctx.lock`` inside the callee."""
    args_node = callee.node.args
    pos_names = [a.arg for a in
                 args_node.posonlyargs + args_node.args]
    if pos_names and pos_names[0] in ("self", "cls") and "." in chain:
        head = chain.split(".")[0]
        unbound = (index is not None and mod is not None
                   and index._class_site(mod, head) is not None)
        if not unbound:
            pos_names = pos_names[1:]
    bound: Dict[str, ast.expr] = {}
    for name, arg in zip(pos_names, call.args):
        if isinstance(arg, ast.Starred):
            break   # splat: everything from here is position-unknown
        bound[name] = arg
    for kw in call.keywords:
        if kw.arg is not None:
            bound[kw.arg] = kw.value
    return bound


def dotted_chain(node) -> Optional[str]:
    """``a.b.c`` source chain for a Name/Attribute expression, or None
    when the base is a call/subscript (unresolvable)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _collect_calls(func_node) -> List[CallSite]:
    """Every Call in the function body, EXCLUDING nested function
    bodies (those get their own FunctionInfo)."""
    calls: List[CallSite] = []

    class V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call):
            chain = dotted_chain(node.func)
            if chain is not None:
                calls.append(CallSite(chain, node))
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            if node is not func_node:
                return  # nested def: its calls belong to it
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            # lambdas stay attributed to the enclosing function: they
            # are deferred but almost always invoked from this frame
            self.generic_visit(node)

    V().visit(func_node)
    return calls


#: request-handler base classes whose methods run on server threads
#: (stdlib socketserver / http.server dispatch)
_HANDLER_BASES = {"BaseRequestHandler", "StreamRequestHandler",
                  "DatagramRequestHandler", "BaseHTTPRequestHandler",
                  "SimpleHTTPRequestHandler"}

#: chains that construct a thread whose ``target=`` runs off-thread
_THREAD_CTORS = {"threading.Thread", "Thread"}
_TIMER_CTORS = {"threading.Timer", "Timer"}


@dataclass(frozen=True)
class ThreadEntry:
    """One function that runs OFF the main thread, and how it gets
    there — the entry half of the guarded-by pass's thread model."""
    func_id: str
    kind: str            # thread | timer | executor | rpc-handler |
    #                      finalizer
    spawn_module: str    # where the spawn/registration happens
    spawn_line: int


def _spawn_call(node: ast.Call,
                chain: str) -> Optional[Tuple[str, Optional[ast.expr]]]:
    """(entry kind, callable expression) when ``node`` hands work to
    another thread — THE one spawn predicate, shared by the entry
    index and the spawn-site map so the two can never drift. The
    callable expr is None for a spawn whose target is absent."""
    parts = chain.split(".")
    if chain in _THREAD_CTORS:
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        return "thread", target
    if chain in _TIMER_CTORS and len(node.args) >= 2:
        return "timer", node.args[1]
    if parts[-1] == "submit" and len(parts) > 1 and node.args:
        # executor-shaped: the callable is the first argument; a
        # data-carrying .submit(obj) never resolves to a function,
        # so it cannot enter the index
        return "executor", node.args[0]
    if parts[-1] == "finalize" and parts[0] in ("weakref", "finalize") \
            and len(node.args) >= 2:
        return "finalizer", node.args[1]
    return None


def _spawn_scan(index: "ProjectIndex"
                ) -> Tuple[Dict[str, ThreadEntry],
                           Dict[str, List[int]]]:
    """ONE project walk feeding both spawn views: the thread-entry
    index (resolved targets only — must-alias, an unresolvable
    callable can never fabricate an entry; first spawn site wins) and
    the per-function spawn-line map (every spawn-shaped call,
    resolved or not — an unresolvable Thread target still publishes
    ``self`` to another thread)."""
    entries: Dict[str, ThreadEntry] = {}
    spawns: Dict[str, List[int]] = {}

    def add(target: Optional[str], kind: str, mod: "ModuleInfo",
            line: int):
        if target is not None and target in index.functions \
                and target not in entries:
            entries[target] = ThreadEntry(target, kind, mod.name, line)

    for mod_name in sorted(index.modules):
        mod = index.modules[mod_name]
        # spawn call forms, anywhere in the module tree (module-level
        # spawns live outside any FunctionInfo)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                bases = {(dotted_chain(b) or "").split(".")[-1]
                         for b in node.bases}
                if bases & _HANDLER_BASES:
                    for name, qual in mod.classes.get(node.name,
                                                      {}).items():
                        if name == "handle" or name.startswith("do_"):
                            add(f"{mod.name}:{qual}", "rpc-handler",
                                mod, node.lineno)
                continue
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_chain(node.func)
            if chain is None:
                continue
            hit = _spawn_call(node, chain)
            if hit is None:
                continue
            kind, target_expr = hit
            info = mod.enclosing_function(node.lineno)
            if info is not None:
                spawns.setdefault(info.id, []).append(node.lineno)
            target_chain = dotted_chain(target_expr) \
                if target_expr is not None else None
            if target_chain is not None:
                add(index.resolve(mod, info, target_chain), kind,
                    mod, node.lineno)
    return entries, spawns


def thread_entries(index: "ProjectIndex") -> Dict[str, ThreadEntry]:
    """Every function the project hands to another thread, keyed by
    function id: ``threading.Thread(target=f)`` / ``Timer(..., f)``
    targets, ``<executor>.submit(f, ...)`` callables, methods of
    ``*RequestHandler`` subclasses (``handle`` / ``do_*`` run on server
    threads), and ``weakref.finalize(obj, f, ...)`` callbacks (GC runs
    them on whichever thread drops the last reference)."""
    return _spawn_scan(index)[0]


def thread_reachable(index: "ProjectIndex",
                     entries: Optional[Dict[str, ThreadEntry]] = None
                     ) -> Dict[str, Set[str]]:
    """function id -> the set of thread-entry ids that reach it over
    resolved call edges (entries reach themselves). Functions absent
    from the map run only where their callers run — for a zero-in-edge
    function, the main thread."""
    if entries is None:
        entries = thread_entries(index)
    reached: Dict[str, Set[str]] = {}
    for eid in sorted(entries):
        stack = [eid]
        while stack:
            cur = stack.pop()
            tags = reached.setdefault(cur, set())
            if eid in tags:
                continue
            tags.add(eid)
            func = index.functions.get(cur)
            if func is None:
                continue
            for call in func.calls:
                if call.target and call.target in index.functions:
                    stack.append(call.target)
    return reached


def spawn_sites(index: "ProjectIndex") -> Dict[str, List[int]]:
    """function id -> lines inside that function where a thread is
    spawned/registered (the shared ``_spawn_call`` predicate). The
    immutable-after-init exemption needs this: a ``self.x = ...`` in
    ``__init__`` AFTER a spawn line already races the spawned
    thread."""
    return _spawn_scan(index)[1]


class ProjectIndex:
    """All modules of one package, with cross-module call resolution."""

    def __init__(self, modules: Dict[str, ModuleInfo]):
        self.modules = modules
        self.functions: Dict[str, FunctionInfo] = {}
        for mod in modules.values():
            for info in mod.functions.values():
                self.functions[info.id] = info
        for mod in modules.values():
            for info in mod.functions.values():
                for call in info.calls:
                    call.target = self.resolve(mod, info, call.chain)

    # -- construction ----------------------------------------------------

    @classmethod
    def from_package(cls, package_path: str,
                     package_name: Optional[str] = None,
                     exclude: Sequence[str] = ("analysis",)
                     ) -> "ProjectIndex":
        """Index every .py under ``package_path``. ``exclude`` names
        top-level subpackages to skip (the analyzer does not analyze
        itself by default — it would only find its own pattern
        tables)."""
        package_path = os.path.abspath(package_path)
        if package_name is None:
            package_name = os.path.basename(package_path.rstrip("/"))
        modules: Dict[str, ModuleInfo] = {}
        for root, dirs, files in os.walk(package_path):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__"
                             and not (root == package_path
                                      and d in exclude))
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(root, fn)
                rel = os.path.relpath(path, package_path)
                parts = rel[:-3].split(os.sep)
                is_package = parts[-1] == "__init__"
                if is_package:
                    parts = parts[:-1]
                name = ".".join([package_name] + parts) if parts \
                    else package_name
                with open(path, encoding="utf-8") as f:
                    modules[name] = ModuleInfo(name, f.read(), path,
                                               is_package=is_package)
        return cls(modules)

    @classmethod
    def from_sources(cls, sources: Dict[str, str],
                     packages: Sequence[str] = ()) -> "ProjectIndex":
        """Fixture entry: {dotted module name: source text};
        ``packages`` names entries that model a package __init__."""
        return cls({name: ModuleInfo(name, src,
                                     is_package=name in packages)
                    for name, src in sources.items()})

    # -- resolution ------------------------------------------------------

    def canonical_chain(self, info: Optional[FunctionInfo],
                        chain: str) -> str:
        """Expand leading single-assignment aliases in place:
        ``lock.acquire`` with ``lock = self._lock`` canonicalizes to
        ``self._lock.acquire`` (bounded — a pathological alias chain
        stops expanding rather than looping)."""
        if info is None:
            return chain
        for _ in range(5):
            parts = chain.split(".")
            expansion = info.aliases.get(parts[0])
            if expansion is None:
                return chain
            chain = ".".join([expansion] + parts[1:])
        return chain

    def _class_site(self, mod: ModuleInfo,
                    name: str) -> Optional[Tuple[str, str]]:
        """(module, class) where ``name`` is defined, seen from
        ``mod`` (local class or from-import)."""
        if name in mod.classes:
            return (mod.name, name)
        if name in mod.from_imports:
            target_mod, orig = mod.from_imports[name]
            target = self.modules.get(target_mod)
            if target is not None and orig in target.classes:
                return (target_mod, orig)
        return None

    def instance_type(self, mod: ModuleInfo,
                      info: Optional[FunctionInfo],
                      parts: Sequence[str]
                      ) -> Optional[Tuple[str, str]]:
        """(module, class) of the INSTANCE a dotted chain denotes —
        ``self`` / an annotated parameter at the head, then typed
        attributes (``attr_types``) for every further hop. None
        whenever any hop is unknown or ambiguous: must-alias only."""
        head = parts[0]
        if head in ("self", "cls"):
            if info is None or not info.class_name:
                return None
            site: Optional[Tuple[str, str]] = (mod.name, info.class_name)
        elif info is not None and head in info.annotations:
            site = self._class_site(mod, info.annotations[head])
        else:
            return None
        for attr in parts[1:]:
            if site is None:
                return None
            owner = self.modules.get(site[0])
            if owner is None:
                return None
            type_name = owner.attr_types.get(site[1], {}).get(attr)
            if not type_name:
                return None
            site = self._class_site(owner, type_name)
        return site

    def resolve(self, mod: ModuleInfo, info: Optional[FunctionInfo],
                chain: str) -> Optional[str]:
        """Resolve a dotted call chain to a function id, or None."""
        chain = self.canonical_chain(info, chain)
        parts = chain.split(".")
        head = parts[0]
        if head in ("self", "cls") and info is not None \
                and info.class_name and len(parts) == 2:
            hit = self._method(mod.name, info.class_name, parts[1])
            if hit:
                return hit
        if len(parts) == 1:
            return self._resolve_bare(mod, info, head)
        # instance-typed resolution: self./annotated-param head plus
        # typed-attribute hops (``self.ledger.park()``,
        # ``pool.host_ledger.charge()``)
        site = self.instance_type(mod, info, parts[:-1])
        if site is not None:
            hit = self._method(site[0], site[1], parts[-1])
            if hit:
                return hit
        # annotated parameter: other._lock-style method calls
        if info is not None and head in info.annotations \
                and len(parts) == 2:
            hit = self._method_anywhere(mod, info.annotations[head],
                                        parts[1])
            if hit:
                return hit
        # local or imported class attribute: Class.method
        if len(parts) == 2:
            hit = self._method_anywhere(mod, head, parts[1])
            if hit:
                return hit
        # imported module attribute: mod.func / pkg.mod.func
        for split in range(len(parts) - 1, 0, -1):
            target_mod = self._module_for(mod, parts[:split])
            if target_mod is None:
                continue
            rest = ".".join(parts[split:])
            target = self.modules.get(target_mod)
            if target is not None and rest in target.functions:
                return f"{target_mod}:{rest}"
        return None

    def _resolve_bare(self, mod: ModuleInfo, info: Optional[FunctionInfo],
                      name: str) -> Optional[str]:
        # nearest enclosing scope first: nested defs shadow module level
        if info is not None:
            scope = info.qualname
            while True:
                # class scopes do not participate in bare-name
                # resolution (Python binds `helper()` in a method to
                # the module-level helper, never the sibling method)
                if scope not in mod.classes:
                    names = mod.scopes.get(scope, {})
                    if name in names and names[name] in mod.functions:
                        return f"{mod.name}:{names[name]}"
                if not scope:
                    break
                scope = scope.rsplit(".", 1)[0] if "." in scope else ""
        elif name in mod.scopes.get("", {}):
            qual = mod.scopes[""][name]
            if qual in mod.functions:
                return f"{mod.name}:{qual}"
        if name in mod.from_imports:
            target_mod, orig = mod.from_imports[name]
            target = self.modules.get(target_mod)
            if target is not None and orig in target.functions:
                return f"{target_mod}:{orig}"
        return None

    def _method(self, module: str, class_name: str,
                method: str) -> Optional[str]:
        mod = self.modules.get(module)
        if mod is None:
            return None
        qual = mod.classes.get(class_name, {}).get(method)
        return f"{module}:{qual}" if qual else None

    def _method_anywhere(self, mod: ModuleInfo, class_name: str,
                         method: str) -> Optional[str]:
        hit = self._method(mod.name, class_name, method)
        if hit:
            return hit
        if class_name in mod.from_imports:
            target_mod, orig = mod.from_imports[class_name]
            return self._method(target_mod, orig, method)
        return None

    def _module_for(self, mod: ModuleInfo,
                    parts: Sequence[str]) -> Optional[str]:
        head = parts[0]
        base = None
        if head in mod.imports:
            base = mod.imports[head]
        elif head in mod.from_imports:
            target_mod, orig = mod.from_imports[head]
            candidate = f"{target_mod}.{orig}"
            if candidate in self.modules:
                base = candidate
            elif target_mod in self.modules and orig not in \
                    self.modules[target_mod].functions:
                return None
        if base is None:
            return None
        full = ".".join([base] + list(parts[1:]))
        if full in self.modules:
            return full
        # single-part chains keep their mapped module even when it is
        # external (callers None-check membership themselves)
        return base if len(parts) == 1 else None

    # -- queries ---------------------------------------------------------

    def iter_functions(self) -> Iterator[FunctionInfo]:
        for mod_name in sorted(self.modules):
            mod = self.modules[mod_name]
            for qual in sorted(mod.functions):
                yield mod.functions[qual]

    def suppressed(self, module: str, line: int, pass_id: str) -> bool:
        mod = self.modules.get(module)
        if mod is None:
            return False
        passes = mod.pragmas.get(line)
        return bool(passes) and (pass_id in passes or "*" in passes)

    def decorator_chain(self, dec: ast.expr) -> Optional[str]:
        """Dotted chain of a decorator expression; for a decorator
        CALL (``@partial(jax.jit, ...)``) the called chain."""
        if isinstance(dec, ast.Call):
            return dotted_chain(dec.func)
        return dotted_chain(dec)
