"""Shared static-analysis core: module index, function table, call
graph, pragma handling.

Everything is stdlib ``ast`` over source text — the analyzed package is
never imported, so the analyzer runs without JAX (and cannot be fooled
by import-time machinery). Resolution is deliberately conservative:
only unambiguous targets (same-scope names, ``self.`` methods on the
enclosing class, imported-module attributes, annotated parameters)
resolve to call-graph edges; everything else stays a raw dotted chain
for pattern-based checks. Over-approximating the graph would flood the
purity/lock passes with false paths, under-approximating loses real
ones — unambiguous-only is the tested middle ground, and the fixture
tests in ``tests/test_static_analysis.py`` pin what each pass must
still catch through it.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple


@dataclass(frozen=True)
class Finding:
    pass_id: str
    rule: str
    module: str          # dotted module name
    qualname: str        # enclosing function ("" = module level)
    line: int
    message: str
    subject: str         # stable discriminator (no line numbers)

    @property
    def key(self) -> str:
        """Stable baseline key — no line numbers, so unrelated edits
        don't churn the baseline."""
        return (f"{self.pass_id}:{self.rule}:{self.module}:"
                f"{self.qualname or '<module>'}:{self.subject}")

    def render(self) -> str:
        return (f"{self.module}:{self.line} [{self.pass_id}/{self.rule}] "
                f"{(self.qualname + ': ') if self.qualname else ''}"
                f"{self.message}")

    def to_dict(self) -> dict:
        return {"pass": self.pass_id, "rule": self.rule,
                "module": self.module, "qualname": self.qualname,
                "line": self.line, "message": self.message,
                "key": self.key}


@dataclass
class CallSite:
    chain: str                   # dotted source text of the callee
    node: ast.Call
    target: Optional[str] = None  # resolved function id, if unambiguous

    @property
    def line(self) -> int:
        return self.node.lineno


@dataclass
class FunctionInfo:
    module: str
    qualname: str                # Class.method / func / outer.inner
    node: ast.AST                # FunctionDef | AsyncFunctionDef | Lambda
    class_name: Optional[str]
    scope: str                   # enclosing scope qualname ("" = module)
    params: List[str] = field(default_factory=list)
    #: parameter name -> annotated class name (string), best-effort
    annotations: Dict[str, str] = field(default_factory=dict)
    decorators: List[ast.expr] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)

    @property
    def id(self) -> str:
        return f"{self.module}:{self.qualname}"

    @property
    def line(self) -> int:
        return self.node.lineno

    @property
    def body(self) -> list:
        return self.node.body


_PRAGMA_RE = re.compile(r"#\s*qlint:\s*ignore\[([a-z*,\s-]+)\]")


class ModuleInfo:
    def __init__(self, name: str, source: str, path: str = "<memory>",
                 is_package: bool = False):
        self.name = name
        self.path = path
        #: True for a package __init__: its relative imports resolve
        #: against the package itself, not the parent
        self.is_package = is_package
        self.tree = ast.parse(source, filename=path)
        #: alias -> dotted module (``import a.b as c`` and
        #: ``from pkg import mod`` both land here when mod is a module)
        self.imports: Dict[str, str] = {}
        #: name -> (dotted module, original name) for from-imports
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        #: names bound by module-level assignments (not defs/imports)
        self.module_assigns: Set[str] = set()
        for stmt in self.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    self.module_assigns.add(t.id)
        #: scope qualname -> {visible def name -> qualname}
        self.scopes: Dict[str, Dict[str, str]] = {"": {}}
        #: class name -> {method name -> qualname}
        self.classes: Dict[str, Dict[str, str]] = {}
        #: line -> set of pass slugs suppressed there
        self.pragmas: Dict[int, Set[str]] = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = _PRAGMA_RE.search(text)
            if m:
                self.pragmas[i] = {p.strip()
                                   for p in m.group(1).split(",")}
        self._collect()

    # -- collection ------------------------------------------------------

    def _collect(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or
                                 alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                target = self._resolve_from(node)
                if target is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.from_imports[alias.asname or alias.name] = \
                        (target, alias.name)
        self._walk_scope(self.tree.body, scope="", class_name=None)

    def enclosing_function(self, line: int) -> Optional["FunctionInfo"]:
        """Innermost function whose def spans ``line`` (None = module
        level) — shared by every pass that anchors a finding to its
        enclosing function."""
        best = None
        for info in self.functions.values():
            node = info.node
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                if best is None or node.lineno > best.node.lineno:
                    best = info
        return best

    def _resolve_from(self, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        # relative import: strip ``level`` trailing components from
        # this module's dotted name. A leaf module's level=1 is its
        # package; a package __init__'s level=1 is the package ITSELF
        # (model it as a phantom leaf)
        parts = self.name.split(".")
        if self.is_package:
            parts = parts + ["__init__"]
        if node.level > len(parts):
            return None
        base = parts[:len(parts) - node.level]
        if node.module:
            base.append(node.module)
        return ".".join(base)

    def _walk_scope(self, body: Sequence[ast.stmt], scope: str,
                    class_name: Optional[str]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{scope}.{stmt.name}" if scope else stmt.name
                info = FunctionInfo(self.name, qual, stmt, class_name,
                                    scope)
                info.params = [a.arg for a in
                               stmt.args.posonlyargs + stmt.args.args
                               + stmt.args.kwonlyargs]
                for a in stmt.args.posonlyargs + stmt.args.args \
                        + stmt.args.kwonlyargs:
                    ann = _annotation_name(a.annotation)
                    if ann:
                        info.annotations[a.arg] = ann
                info.decorators = list(stmt.decorator_list)
                info.calls = _collect_calls(stmt)
                self.functions[qual] = info
                self.scopes.setdefault(scope, {})[stmt.name] = qual
                if class_name is not None and scope == class_name:
                    self.classes.setdefault(class_name, {})[stmt.name] \
                        = qual
                # nested defs live in the function's scope; a method's
                # class context does not extend to its inner functions
                self._walk_scope(stmt.body, qual, None)
            elif isinstance(stmt, ast.ClassDef):
                self.classes.setdefault(stmt.name, {})
                self.scopes.setdefault(scope, {})[stmt.name] = stmt.name
                self._walk_scope(stmt.body, stmt.name, stmt.name)
            elif isinstance(stmt, (ast.If, ast.Try, ast.With, ast.For,
                                   ast.While)):
                # statements nested in control flow at any scope
                for field_name in ("body", "orelse", "finalbody"):
                    self._walk_scope(getattr(stmt, field_name, []) or [],
                                     scope, class_name)
                for handler in getattr(stmt, "handlers", []) or []:
                    self._walk_scope(handler.body, scope, class_name)


def _annotation_name(node) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split(".")[-1].strip('"')
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def dotted_chain(node) -> Optional[str]:
    """``a.b.c`` source chain for a Name/Attribute expression, or None
    when the base is a call/subscript (unresolvable)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _collect_calls(func_node) -> List[CallSite]:
    """Every Call in the function body, EXCLUDING nested function
    bodies (those get their own FunctionInfo)."""
    calls: List[CallSite] = []

    class V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call):
            chain = dotted_chain(node.func)
            if chain is not None:
                calls.append(CallSite(chain, node))
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            if node is not func_node:
                return  # nested def: its calls belong to it
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            # lambdas stay attributed to the enclosing function: they
            # are deferred but almost always invoked from this frame
            self.generic_visit(node)

    V().visit(func_node)
    return calls


class ProjectIndex:
    """All modules of one package, with cross-module call resolution."""

    def __init__(self, modules: Dict[str, ModuleInfo]):
        self.modules = modules
        self.functions: Dict[str, FunctionInfo] = {}
        for mod in modules.values():
            for info in mod.functions.values():
                self.functions[info.id] = info
        for mod in modules.values():
            for info in mod.functions.values():
                for call in info.calls:
                    call.target = self.resolve(mod, info, call.chain)

    # -- construction ----------------------------------------------------

    @classmethod
    def from_package(cls, package_path: str,
                     package_name: Optional[str] = None,
                     exclude: Sequence[str] = ("analysis",)
                     ) -> "ProjectIndex":
        """Index every .py under ``package_path``. ``exclude`` names
        top-level subpackages to skip (the analyzer does not analyze
        itself by default — it would only find its own pattern
        tables)."""
        package_path = os.path.abspath(package_path)
        if package_name is None:
            package_name = os.path.basename(package_path.rstrip("/"))
        modules: Dict[str, ModuleInfo] = {}
        for root, dirs, files in os.walk(package_path):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__"
                             and not (root == package_path
                                      and d in exclude))
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(root, fn)
                rel = os.path.relpath(path, package_path)
                parts = rel[:-3].split(os.sep)
                is_package = parts[-1] == "__init__"
                if is_package:
                    parts = parts[:-1]
                name = ".".join([package_name] + parts) if parts \
                    else package_name
                with open(path, encoding="utf-8") as f:
                    modules[name] = ModuleInfo(name, f.read(), path,
                                               is_package=is_package)
        return cls(modules)

    @classmethod
    def from_sources(cls, sources: Dict[str, str],
                     packages: Sequence[str] = ()) -> "ProjectIndex":
        """Fixture entry: {dotted module name: source text};
        ``packages`` names entries that model a package __init__."""
        return cls({name: ModuleInfo(name, src,
                                     is_package=name in packages)
                    for name, src in sources.items()})

    # -- resolution ------------------------------------------------------

    def resolve(self, mod: ModuleInfo, info: Optional[FunctionInfo],
                chain: str) -> Optional[str]:
        """Resolve a dotted call chain to a function id, or None."""
        parts = chain.split(".")
        head = parts[0]
        if head in ("self", "cls") and info is not None \
                and info.class_name and len(parts) == 2:
            return self._method(mod.name, info.class_name, parts[1])
        if len(parts) == 1:
            return self._resolve_bare(mod, info, head)
        # annotated parameter: other._lock-style method calls
        if info is not None and head in info.annotations \
                and len(parts) == 2:
            hit = self._method_anywhere(mod, info.annotations[head],
                                        parts[1])
            if hit:
                return hit
        # local or imported class attribute: Class.method
        if len(parts) == 2:
            hit = self._method_anywhere(mod, head, parts[1])
            if hit:
                return hit
        # imported module attribute: mod.func / pkg.mod.func
        for split in range(len(parts) - 1, 0, -1):
            target_mod = self._module_for(mod, parts[:split])
            if target_mod is None:
                continue
            rest = ".".join(parts[split:])
            target = self.modules.get(target_mod)
            if target is not None and rest in target.functions:
                return f"{target_mod}:{rest}"
        return None

    def _resolve_bare(self, mod: ModuleInfo, info: Optional[FunctionInfo],
                      name: str) -> Optional[str]:
        # nearest enclosing scope first: nested defs shadow module level
        if info is not None:
            scope = info.qualname
            while True:
                # class scopes do not participate in bare-name
                # resolution (Python binds `helper()` in a method to
                # the module-level helper, never the sibling method)
                if scope not in mod.classes:
                    names = mod.scopes.get(scope, {})
                    if name in names and names[name] in mod.functions:
                        return f"{mod.name}:{names[name]}"
                if not scope:
                    break
                scope = scope.rsplit(".", 1)[0] if "." in scope else ""
        elif name in mod.scopes.get("", {}):
            qual = mod.scopes[""][name]
            if qual in mod.functions:
                return f"{mod.name}:{qual}"
        if name in mod.from_imports:
            target_mod, orig = mod.from_imports[name]
            target = self.modules.get(target_mod)
            if target is not None and orig in target.functions:
                return f"{target_mod}:{orig}"
        return None

    def _method(self, module: str, class_name: str,
                method: str) -> Optional[str]:
        mod = self.modules.get(module)
        if mod is None:
            return None
        qual = mod.classes.get(class_name, {}).get(method)
        return f"{module}:{qual}" if qual else None

    def _method_anywhere(self, mod: ModuleInfo, class_name: str,
                         method: str) -> Optional[str]:
        hit = self._method(mod.name, class_name, method)
        if hit:
            return hit
        if class_name in mod.from_imports:
            target_mod, orig = mod.from_imports[class_name]
            return self._method(target_mod, orig, method)
        return None

    def _module_for(self, mod: ModuleInfo,
                    parts: Sequence[str]) -> Optional[str]:
        head = parts[0]
        base = None
        if head in mod.imports:
            base = mod.imports[head]
        elif head in mod.from_imports:
            target_mod, orig = mod.from_imports[head]
            candidate = f"{target_mod}.{orig}"
            if candidate in self.modules:
                base = candidate
            elif target_mod in self.modules and orig not in \
                    self.modules[target_mod].functions:
                return None
        if base is None:
            return None
        full = ".".join([base] + list(parts[1:]))
        if full in self.modules:
            return full
        # single-part chains keep their mapped module even when it is
        # external (callers None-check membership themselves)
        return base if len(parts) == 1 else None

    # -- queries ---------------------------------------------------------

    def iter_functions(self) -> Iterator[FunctionInfo]:
        for mod_name in sorted(self.modules):
            mod = self.modules[mod_name]
            for qual in sorted(mod.functions):
                yield mod.functions[qual]

    def suppressed(self, module: str, line: int, pass_id: str) -> bool:
        mod = self.modules.get(module)
        if mod is None:
            return False
        passes = mod.pragmas.get(line)
        return bool(passes) and (pass_id in passes or "*" in passes)

    def decorator_chain(self, dec: ast.expr) -> Optional[str]:
        """Dotted chain of a decorator expression; for a decorator
        CALL (``@partial(jax.jit, ...)``) the called chain."""
        if isinstance(dec, ast.Call):
            return dotted_chain(dec.func)
        return dotted_chain(dec)
