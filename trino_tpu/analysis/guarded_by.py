"""guarded-by pass: Eraser-style lockset inference over must-alias
static facts — "this attribute is guarded by this lock, and this
thread touches it bare".

The engine mutates shared state from many threads (heartbeat/heal
loops, the protocol server's eviction timer and executor drains,
streaming task threads, finalizer-driven spill demotions, retained-
stream replay, the shared processor caches), and the previous eight
passes verify lock ORDERING and LIFECYCLE but never lock COVERAGE.
This pass closes that family — the stats_store EWMA merge, the
stream_results done-race and the ProcessorCache ``_cache_lock`` were
all hand-found instances of it.

Model, in three steps:

1. **Thread-entry index** (``core.thread_entries``): functions handed
   to other threads — ``Thread(target=...)`` / ``Timer(...)`` /
   executor ``submit`` / ``*RequestHandler`` methods /
   ``weakref.finalize`` callbacks — each tagged with its entry kind,
   plus the reachable closure over resolved call edges
   (``core.thread_reachable``). A function not in the closure runs
   only where its callers run.

2. **Lockset inference**: for every ``self.<attr>`` load/store site,
   the set of must-alias locks held there — lexically (the ``with
   self._lock:`` stack, identities via lock-order's ``_Identities``)
   plus interprocedurally: a summary fixpoint propagates the
   INTERSECTION of every resolved caller's held-set into the callee
   (a lock held on only some call paths is not held). Parametric lock
   tokens are dropped rather than guessed: must-alias or nothing.
   Sites live in methods AND in nested defs that capture the
   enclosing method's ``self`` as a closure (``_owning_class`` — the
   per-task ``run_one`` thread-target shape), so closure accesses
   cannot hide from the pass.

3. **Guard inference + report**: an attribute's candidate guard is
   the lock held at a QUALIFYING MAJORITY of its post-``__init__``
   mutating sites (>= 2 guarded sites, strictly more than half). A
   finding is a bare read/write of a guarded attribute from a
   thread-entry-reachable function whose entry set is DIFFERENT from
   the guarded sites' — same-thread sequential access never reports.

Conservatism (mirrors v2): must-alias identities only; attributes
assigned solely in ``__init__`` BEFORE any thread spawn are exempt
(immutable-after-init — publication happens-before the spawn);
attributes whose every site runs on one entry are exempt
(single-entry); every report names the inferred guard, sample guarded
sites and the bare site. Deliberate lock-free designs opt out with
``# qlint: ignore[guarded-by] <reason>``.

A **check-then-act** sub-rule catches the TOCTOU shape on shared
dict/list/set containers (the ``_QueryState`` / memo-dict pattern):
an ``if`` whose test reads ``self.<container>`` and whose body
mutates it, with NO lock held, on a container accessed from more
than one entry.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .core import (Finding, FunctionInfo, ModuleInfo, ProjectIndex,
                   _spawn_scan, dotted_chain, own_nodes,
                   thread_reachable)
from .lock_order import _Identities, _is_param

PASS_ID = "guarded-by"

#: container methods that mutate in place — a call through
#: ``self.<attr>.<m>(...)`` is a WRITE site of the attribute
_MUTATORS = {"append", "appendleft", "add", "extend", "insert",
             "remove", "discard", "pop", "popleft", "popitem",
             "clear", "update", "setdefault", "move_to_end"}

#: constructors that type an attribute as a shared container for the
#: check-then-act sub-rule
_CONTAINER_CTORS = {"dict", "list", "set", "OrderedDict", "deque",
                    "defaultdict", "collections.OrderedDict",
                    "collections.deque", "collections.defaultdict"}

#: constructors whose result is a mutual-exclusion context manager —
#: ``with self._cond:`` guards exactly like ``with self._lock:`` (a
#: Condition embeds a lock), but its name defeats the lockish-name
#: heuristic, so construction sites register the identity explicitly
_LOCK_CTORS = {"threading.Lock", "threading.RLock",
               "threading.Condition", "Lock", "RLock", "Condition"}


def _known_locks(index: ProjectIndex, ids: _Identities) -> Set[str]:
    """Lock ids of every attribute/name ASSIGNED from a Lock/RLock/
    Condition constructor — the identities ``with`` acceptance trusts
    beyond the name heuristic."""
    known: Set[str] = set()
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            if dotted_chain(node.value.func) not in _LOCK_CTORS:
                continue
            for t in node.targets:
                chain = dotted_chain(t)
                if chain is None:
                    continue
                func = mod.enclosing_function(node.lineno)
                known.add(ids.lock_id(mod, func, chain))
    return known


@dataclass
class AccessSite:
    attr_id: str            # module.Class.attr
    func_id: str
    line: int
    kind: str               # "read" | "write"
    #: lexically held lock ids at the site (with-stack snapshot)
    lexical: FrozenSet[str]
    in_init: bool
    #: entry ids spawned EARLIER in the same ``__init__`` body — a
    #: write carrying any is a post-publication init write (the
    #: ``init-race`` rule's subject)
    post_spawn_entries: Tuple[str, ...] = ()


@dataclass
class _FuncAccesses(ast.NodeVisitor):
    """One method's ``self.<attr>`` access sites with the lexical
    with-held lock stack, plus the held-set snapshot at every resolved
    call (the interprocedural propagation input). Mirrors lock-order's
    ``_FuncLocks`` walk so the two passes agree on what "held" means."""

    index: ProjectIndex
    mod: ModuleInfo
    func: FunctionInfo
    ids: _Identities
    #: constructor-known lock identities (Condition and friends)
    known: Set[str] = field(default_factory=set)
    #: the class whose instance `self` denotes here — the function's
    #: own class for methods, the ENCLOSING method's class for nested
    #: defs that capture `self` as a closure (thread targets like
    #: `run_one` are exactly this shape)
    owner_class: Optional[str] = None
    sites: List[AccessSite] = field(default_factory=list)
    #: (callee id, frozenset of held lock ids) per resolved call
    calls_held: List[Tuple[str, FrozenSet[str]]] = \
        field(default_factory=list)
    #: (If line, attrs read in test, attrs written in body,
    #:  held lock ids at the If)
    check_acts: List[Tuple[int, Set[str], Set[str], FrozenSet[str]]] = \
        field(default_factory=list)
    _held: List[str] = field(default_factory=list)

    def __post_init__(self):
        self._in_init = self.func.qualname.endswith("__init__")

    def _held_now(self) -> FrozenSet[str]:
        return frozenset(t for t in self._held if not _is_param(t))

    def _attr_id(self, attr: str) -> Optional[str]:
        if not self.owner_class:
            return None
        return f"{self.mod.name}.{self.owner_class}.{attr}"

    def _site(self, attr: str, line: int, kind: str):
        aid = self._attr_id(attr)
        if aid is not None:
            self.sites.append(AccessSite(aid, self.func.id, line, kind,
                                         self._held_now(),
                                         self._in_init))

    # -- lock stack (the _FuncLocks shape) ------------------------------

    def visit_With(self, node: ast.With):
        pushed = 0
        for item in node.items:
            self.visit(item.context_expr)
            hit = self.ids.item_lock_id(self.mod, self.func,
                                        item.context_expr)
            if hit is None:
                # name heuristic missed: accept identities PROVEN by a
                # Lock/RLock/Condition construction site (`self._cond`)
                chain = dotted_chain(item.context_expr)
                if chain is not None:
                    canonical = self.index.canonical_chain(self.func,
                                                           chain)
                    lid = self.ids.lock_id(self.mod, self.func,
                                           canonical)
                    if lid in self.known:
                        hit = (lid, canonical)
            if hit is not None:
                self._held.append(hit[0])
                pushed += 1
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self._held.pop()

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node):
        if node is not self.func.node:
            return   # nested defs own their accesses (no self binding)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- access classification ------------------------------------------

    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    @classmethod
    def _target_attr(cls, target: ast.AST) -> Optional[str]:
        """The self-attribute a single (non-compound) store target
        writes: ``self.x`` rebinds, ``self.d[k]`` container stores —
        THE one predicate both the site recorder and the
        check-then-act body scan share, so they cannot drift."""
        attr = cls._self_attr(target)
        if attr is None and isinstance(target, ast.Subscript):
            # ``self.d[k] = v`` mutates the container self.d holds
            attr = cls._self_attr(target.value)
        return attr

    def _store_target(self, target: ast.AST, line: int):
        attr = self._target_attr(target)
        if attr is not None:
            self._site(attr, line, "write")
            if isinstance(target, ast.Subscript):
                self.visit(target.slice)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._store_target(e, line)
            return
        if isinstance(target, ast.Starred):
            self._store_target(target.value, line)
            return
        self.visit(target)

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._store_target(t, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign):
        # ``self.x += 1`` is the classic lost-update read-modify-write:
        # one write site (the read is implied by the same site)
        self._store_target(node.target, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._store_target(node.target, node.lineno)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            attr = self._self_attr(t)
            if attr is None and isinstance(t, ast.Subscript):
                attr = self._self_attr(t.value)
            if attr is not None:
                self._site(attr, node.lineno, "write")
            else:
                self.visit(t)

    def visit_Call(self, node: ast.Call):
        chain = dotted_chain(node.func)
        if chain is not None:
            parts = chain.split(".")
            if len(parts) == 3 and parts[0] == "self" \
                    and parts[-1] in _MUTATORS:
                # self.<attr>.append(...) — in-place mutation
                self._site(parts[1], node.lineno, "write")
                for a in node.args:
                    self.visit(a)
                for kw in node.keywords:
                    self.visit(kw.value)
                return
            target = self.index.resolve(self.mod, self.func, chain)
            if target is not None and target in self.index.functions:
                self.calls_held.append((target, self._held_now()))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        attr = self._self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self._site(attr, node.lineno, "read")
        self.generic_visit(node)

    def visit_If(self, node: ast.If):
        test_reads = self._attrs_in(node.test)
        if test_reads:
            body_writes: Set[str] = set()
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    body_writes |= self._write_attrs(sub)
            if test_reads & body_writes:
                self.check_acts.append(
                    (node.lineno, test_reads, body_writes,
                     self._held_now()))
        self.generic_visit(node)

    def _attrs_in(self, expr: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for n in ast.walk(expr):
            attr = self._self_attr(n)
            if attr is not None:
                aid = self._attr_id(attr)
                if aid is not None:
                    out.add(aid)
        return out

    def _write_attrs(self, node: ast.AST) -> Set[str]:
        """ALL attr ids ``node`` writes (rebind, subscript store incl.
        inside tuple/list unpacks, del, in-place mutator call) — same
        ``_target_attr`` predicate the site recorder uses, so the two
        scans cannot drift."""
        attrs: Set[str] = set()
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            stack = list(node.targets) \
                if not isinstance(node, ast.AugAssign) \
                else [node.target]
            while stack:
                t = stack.pop()
                if isinstance(t, (ast.Tuple, ast.List)):
                    stack.extend(t.elts)
                elif isinstance(t, ast.Starred):
                    stack.append(t.value)
                else:
                    attr = self._target_attr(t)
                    if attr is not None:
                        attrs.add(attr)
        elif isinstance(node, ast.Call):
            chain = dotted_chain(node.func)
            if chain is not None:
                parts = chain.split(".")
                if len(parts) == 3 and parts[0] == "self" \
                        and parts[-1] in _MUTATORS:
                    attrs.add(parts[1])
        return {aid for aid in (self._attr_id(a) for a in attrs)
                if aid is not None}


@dataclass
class GuardAnalysis:
    """Everything the findings (and the not-blind floors) consume."""
    entries: Dict[str, object]
    #: func id -> entry ids reaching it
    reachable: Dict[str, Set[str]]
    #: attr id -> access sites (init included, marked)
    sites: Dict[str, List[AccessSite]]
    #: attr id -> inferred guard lock id
    guards: Dict[str, str]
    #: attr id -> why it is exempt (immutable-after-init | single-entry)
    exempt: Dict[str, str]
    per_func: Dict[str, _FuncAccesses] = field(default_factory=dict)
    #: func id -> interprocedural context lockset (held at EVERY
    #: resolved call path into it)
    context: Dict[str, FrozenSet[str]] = field(default_factory=dict)


def _context_locksets(index: ProjectIndex,
                      per_func: Dict[str, _FuncAccesses],
                      entries: Dict[str, object]
                      ) -> Dict[str, FrozenSet[str]]:
    """Meet-over-callers fixpoint: a lock is in a function's context
    only when EVERY resolved call site reaching it holds the lock
    (callers contribute their own context plus their lexical held-set
    at the call). Roots — functions with no resolved in-edges, and
    thread entries — start empty: nothing is known to be held there."""
    in_edges: Dict[str, int] = {}
    for fa in per_func.values():
        for callee, _held in fa.calls_held:
            in_edges[callee] = in_edges.get(callee, 0) + 1
    ctx: Dict[str, Optional[FrozenSet[str]]] = {}
    for fid in per_func:
        if fid in entries or in_edges.get(fid, 0) == 0:
            ctx[fid] = frozenset()
        else:
            ctx[fid] = None   # TOP: no caller seen yet
    for _ in range(50):
        changed = False
        for fid, fa in per_func.items():
            base = ctx.get(fid)
            if base is None:
                continue
            for callee, held in fa.calls_held:
                if callee not in ctx:
                    continue
                incoming = base | held
                cur = ctx[callee]
                new = incoming if cur is None else (cur & incoming)
                if new != cur:
                    ctx[callee] = new
                    changed = True
        if not changed:
            break
    # functions never reached from a root (unresolved-only callers in
    # a cycle) stay TOP: treat as unknown-empty — their sites cannot
    # claim guarded-ness they did not prove
    return {fid: (c if c is not None else frozenset())
            for fid, c in ctx.items()}


def _owning_class(index: ProjectIndex,
                  func: FunctionInfo) -> Optional[str]:
    """The class whose instance ``self`` denotes inside ``func``: its
    own class for a method, and for a nested def that does NOT bind
    its own ``self`` parameter, the enclosing method's class (closure
    capture — `def run_one(t): ... self.workers ...` inside a method
    reads the method's instance). None when no enclosing method
    resolves: an unattributable `self` must not fabricate sites."""
    cur = func
    for _ in range(5):
        if "self" in cur.params:
            return cur.class_name if cur.class_name else None
        if not cur.scope:
            return None
        nxt = index.functions.get(f"{cur.module}:{cur.scope}")
        if nxt is None:
            return None
        cur = nxt
    return None


def analyze(index: ProjectIndex) -> GuardAnalysis:
    ids = _Identities(index)
    known = _known_locks(index, ids)
    per_func: Dict[str, _FuncAccesses] = {}
    for func in index.iter_functions():
        owner = _owning_class(index, func)
        if owner is None:
            continue
        mod = index.modules[func.module]
        fa = _FuncAccesses(index, mod, func, ids, known, owner)
        for stmt in func.body:
            fa.visit(stmt)
        per_func[func.id] = fa
    # ONE spawn walk feeds the entry index AND the spawn-line map (the
    # analyzer rides a <10s pre-commit CPU ratchet, and one predicate
    # cannot drift against itself)
    entries, spawns = _spawn_scan(index)
    context = _context_locksets(index, per_func, entries)
    reachable = thread_reachable(index, entries)

    # entries spawned inside each function, by line — an __init__
    # write AFTER one of these lines races the spawned thread
    spawned_in: Dict[str, List[Tuple[int, str]]] = {}
    for eid, e in entries.items():
        mod = index.modules.get(e.spawn_module)
        if mod is None:
            continue
        info = mod.enclosing_function(e.spawn_line)
        if info is not None:
            spawned_in.setdefault(info.id, []).append(
                (e.spawn_line, eid))

    sites: Dict[str, List[AccessSite]] = {}
    for fid, fa in per_func.items():
        inherited = context.get(fid, frozenset())
        for s in fa.sites:
            if inherited:
                s.lexical = s.lexical | inherited
            if s.in_init and s.kind == "write":
                s.post_spawn_entries = tuple(sorted(
                    eid for line, eid in spawned_in.get(fid, ())
                    if line < s.line))
            sites.setdefault(s.attr_id, []).append(s)

    guards: Dict[str, str] = {}
    exempt: Dict[str, str] = {}
    for attr_id, ss in sites.items():
        post_init = [s for s in ss if not s.in_init]
        writes = [s for s in post_init if s.kind == "write"]
        if not writes:
            # assigned solely in __init__ — immutable after init,
            # UNLESS some __init__ store lands after a thread spawn in
            # the same body (the spawned thread may already read it;
            # with a RESOLVED spawn target that shape is reported
            # directly by the init-race rule in run()). ANY spawn line
            # kills the exemption, resolved or not — an unresolvable
            # target still publishes `self`
            racy = any(s.kind == "write" and s.in_init
                       and any(ln < s.line
                               for ln in spawns.get(s.func_id, ()))
                       for s in ss)
            if not racy:
                exempt[attr_id] = "immutable-after-init"
                continue
            writes = [s for s in ss if s.kind == "write"]
        tags: Set[str] = set()
        for s in post_init or ss:
            tags |= reachable.get(s.func_id, {"<main>"}) or {"<main>"}
        if len(tags) <= 1:
            exempt[attr_id] = "single-entry"
            continue
        counts: Dict[str, int] = {}
        for s in writes:
            for lock in s.lexical:
                counts[lock] = counts.get(lock, 0) + 1
        if not counts:
            continue
        best = max(sorted(counts), key=lambda k: counts[k])
        if counts[best] >= 2 and counts[best] * 2 > len(writes):
            guards[attr_id] = best
    return GuardAnalysis(entries, reachable, sites, guards, exempt,
                         per_func, context)


def _fmt_sites(ss: List[AccessSite], guard: str, limit: int = 3) -> str:
    picks = [s for s in ss if guard in s.lexical][:limit]
    return ", ".join(f"{s.func_id.split(':')[-1]}:{s.line}"
                     for s in picks)


def _entry_names(analysis: GuardAnalysis, tags: Set[str]) -> str:
    out = []
    for t in sorted(tags):
        e = analysis.entries.get(t)
        kind = getattr(e, "kind", None)
        name = t.split(":")[-1] if ":" in t else t
        out.append(f"{name} [{kind}]" if kind else name)
    return ", ".join(out)


def run(index: ProjectIndex) -> List[Finding]:
    analysis = analyze(index)
    findings: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()

    def effective(fid: str) -> Set[str]:
        """Thread identities a function runs on: the entries reaching
        it, or the caller's thread (``<main>``) when none do —
        main-thread code is a thread too, not a blind spot."""
        return analysis.reachable.get(fid) or {"<main>"}

    for attr_id in sorted(analysis.guards):
        guard = analysis.guards[attr_id]
        ss = analysis.sites[attr_id]
        guarded = [s for s in ss if guard in s.lexical]
        for s in ss:
            if s.in_init or guard in s.lexical:
                continue
            bare_tags = effective(s.func_id)
            # the concurrent counterpart: for a bare READ, the guarded
            # WRITES it can observe torn; for a bare WRITE, every
            # guarded site (reads see the torn write too)
            counter = [g for g in guarded
                       if s.kind == "write" or g.kind == "write"]
            counter_tags: Set[str] = set()
            for g in counter:
                counter_tags |= effective(g.func_id)
            if len(bare_tags | counter_tags) <= 1:
                continue   # one thread identity total: sequential
            func = index.functions[s.func_id]
            key = (attr_id, s.func_id)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                PASS_ID, "guarded-by", func.module, func.qualname,
                s.line,
                f"`{attr_id.rsplit('.', 1)[-1]}` is guarded by "
                f"`{guard}` at {len(guarded)} site(s) "
                f"({_fmt_sites(ss, guard)}) but this {s.kind} holds "
                f"no lock — it runs on "
                f"{_entry_names(analysis, bare_tags)} against guarded "
                f"sites on {_entry_names(analysis, counter_tags)}",
                f"bare:{attr_id}:{func.qualname}"))

    # init-race: an __init__ store AFTER a thread spawn in the same
    # body, where the spawned thread('s reachable closure) touches the
    # same attribute — publication happened before initialization
    # finished, so the new thread can observe the pre-store value (or
    # a torn sequence of them). This is exactly the case the
    # immutable-after-init exemption must NOT cover.
    for attr_id in sorted(analysis.sites):
        ss = analysis.sites[attr_id]
        #: entry id -> a sample non-init site it reaches
        touched_by: Dict[str, AccessSite] = {}
        for s in ss:
            if s.in_init:
                continue
            for e in analysis.reachable.get(s.func_id, ()):
                touched_by.setdefault(e, s)
        for s in ss:
            racing = [e for e in s.post_spawn_entries
                      if e in touched_by]
            if not racing:
                continue
            func = index.functions[s.func_id]
            key = (f"initrace:{attr_id}", s.func_id)
            if key in seen:
                continue
            seen.add(key)
            reader = touched_by[racing[0]]
            reader_fn = reader.func_id.split(":")[-1]
            findings.append(Finding(
                PASS_ID, "init-race", func.module, func.qualname,
                s.line,
                f"`{attr_id.rsplit('.', 1)[-1]}` is stored AFTER "
                f"__init__ already spawned "
                f"{_entry_names(analysis, set(racing))}, which "
                f"reaches a {reader.kind} of it "
                f"({reader_fn}:{reader.line}) — the thread can run "
                f"before this store lands",
                f"initrace:{attr_id}"))

    # check-then-act on shared containers: unlocked test-then-mutate
    containers = _container_attrs(index)
    for fid in sorted(analysis.per_func):
        fa = analysis.per_func[fid]
        inherited = analysis.context.get(fid, frozenset())
        func = index.functions[fid]
        for line, test_reads, body_writes, held in fa.check_acts:
            if held | inherited:
                continue   # some lock held: not the unlocked shape
            for attr_id in sorted(test_reads & body_writes):
                if attr_id not in containers:
                    continue
                ss = analysis.sites.get(attr_id, [])
                tags: Set[str] = set()
                for s in ss:
                    if not s.in_init:
                        tags |= analysis.reachable.get(
                            s.func_id, {"<main>"}) or {"<main>"}
                if len(tags) <= 1:
                    continue   # single-entry container: sequential
                key = (f"cta:{attr_id}", fid)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    PASS_ID, "check-then-act", func.module,
                    func.qualname, line,
                    f"unlocked test-then-mutate on shared container "
                    f"`{attr_id.rsplit('.', 1)[-1]}` (accessed from "
                    f"{len(tags)} entries): the check and the "
                    f"mutation can interleave with another thread's",
                    f"cta:{attr_id}:{func.qualname}"))
    return findings


def _container_attrs(index: ProjectIndex) -> Set[str]:
    """attr ids constructed as dict/list/set/deque/OrderedDict
    literals or calls anywhere in their class — the shapes
    check-then-act applies to."""
    out: Set[str] = set()
    for func in index.iter_functions():
        if func.class_name is None:
            continue
        for node in own_nodes(func.node):
            if not isinstance(node, ast.Assign) \
                    or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            v = node.value
            is_container = isinstance(v, (ast.Dict, ast.List, ast.Set))
            if isinstance(v, ast.Call):
                chain = dotted_chain(v.func)
                if chain in _CONTAINER_CTORS:
                    is_container = True
            if is_container:
                out.add(f"{func.module}.{func.class_name}.{t.attr}")
    return out
