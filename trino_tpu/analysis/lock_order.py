"""lock-order pass: AB-BA cycles and blocking calls under a held lock.

Lock identity is structural: ``with self._lock:`` in a method of class
``C`` in module ``M`` names the lock ``M.C._lock``; module-level locks
name ``M.<name>``; locals/parameters stay scoped to their function (no
cross-function aliasing is assumed, so they can never fabricate a
cycle). While a lock is lexically held, every further acquisition —
in the same body or transitively through resolved call-graph edges —
adds an edge to the acquisition-order graph; a cycle in that graph is
the PR 5 ``HostSpillLedger`` finalizer-deadlock class. Self-edges are
reported only for locks constructed as ``threading.Lock()`` (an RLock
re-entering itself is fine and the spill ledger does exactly that).

Non-blocking tries (``acquire(blocking=False)``) are excluded
everywhere: they cannot wait, so they can neither close a cycle nor
stall an RPC — ``demote_across``'s cross-list lock hops rely on this.

A second rule flags blocking RPC / subprocess / socket traffic while
holding any lock (``lock-over-rpc``): the PR 3 worker-loss detector
turns a worker stuck on a peer into a cascading replacement storm if
its server threads serialize behind a lock held across the wire.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, FunctionInfo, ModuleInfo, ProjectIndex,
                   dotted_chain)

PASS_ID = "lock-order"

_RPC_PREFIXES = ("subprocess.", "socket.")
_RPC_LASTS = {"send_msg", "recv_msg", "check_output", "check_call"}
_RPC_TARGET_SUFFIXES = (":call",)   # trino_tpu.parallel.rpc:call


def _lockish(chain: Optional[str]) -> bool:
    return bool(chain) and "lock" in chain.split(".")[-1].lower()


def _lock_id(mod: ModuleInfo, func: Optional[FunctionInfo],
             chain: str) -> str:
    parts = chain.split(".")
    if parts[0] in ("self", "cls") and func is not None:
        owner = func.class_name or func.qualname
        return f"{mod.name}.{owner}.{'.'.join(parts[1:])}"
    if len(parts) == 1 and func is not None \
            and parts[0] not in mod.module_assigns \
            and parts[0] not in mod.scopes.get("", {}) \
            and parts[0] not in mod.from_imports:
        # local or parameter: scope to the function so distinct
        # callers' locks never unify into a false shared node
        return f"{mod.name}:{func.qualname}.{parts[0]}"
    return f"{mod.name}.{chain}"


def _collect_lock_kinds(index: ProjectIndex) -> Dict[str, str]:
    """lock id -> 'lock' | 'rlock' from ``X = threading.(R)Lock()``
    construction sites."""
    kinds: Dict[str, str] = {}
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            ctor = dotted_chain(node.value.func)
            if ctor not in ("threading.Lock", "threading.RLock",
                            "Lock", "RLock"):
                continue
            kind = "rlock" if ctor.endswith("RLock") else "lock"
            for t in node.targets:
                chain = dotted_chain(t)
                if chain is None:
                    continue
                func = mod.enclosing_function(node.lineno)
                kinds[_lock_id(mod, func, chain)] = kind
    return kinds


def _nonblocking(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant):
            return kw.value.value is False
    return False


class _FuncLocks(ast.NodeVisitor):
    """One function's lock behaviour: direct acquisitions, ordered
    edges, calls made under a lock, RPC-ish calls under a lock."""

    def __init__(self, index: ProjectIndex, mod: ModuleInfo,
                 func: FunctionInfo):
        self.index = index
        self.mod = mod
        self.func = func
        self.acquired: Set[str] = set()
        self.edges: List[Tuple[str, str, int]] = []
        #: (held lock, resolved target, line, call was via ``self.``)
        self.calls_under: List[Tuple[str, str, int, bool]] = []
        self.rpc_under: List[Tuple[str, str, int]] = []     # (lock, chain, line)
        self._held: List[str] = []

    def _acquire(self, lock: str, line: int):
        self.acquired.add(lock)
        for held in self._held:
            self.edges.append((held, lock, line))

    def visit_With(self, node: ast.With):
        pushed = 0
        for item in node.items:
            chain = dotted_chain(item.context_expr)
            if _lockish(chain):
                lock = _lock_id(self.mod, self.func, chain)
                self._acquire(lock, node.lineno)
                self._held.append(lock)
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self._held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call):
        chain = dotted_chain(node.func)
        if chain is not None:
            parts = chain.split(".")
            if parts[-1] == "acquire" and len(parts) > 1 \
                    and not _nonblocking(node):
                lock = _lock_id(self.mod, self.func,
                                ".".join(parts[:-1]))
                self._acquire(lock, node.lineno)
            elif self._held:
                target = self.index.resolve(self.mod, self.func, chain)
                if target is not None:
                    via_self = parts[0] in ("self", "cls")
                    for held in self._held:
                        self.calls_under.append(
                            (held, target, node.lineno, via_self))
                if self._rpcish(chain, target):
                    for held in self._held:
                        self.rpc_under.append(
                            (held, chain, node.lineno))
        self.generic_visit(node)

    @staticmethod
    def _rpcish(chain: str, target: Optional[str]) -> bool:
        if target and target.endswith(_RPC_TARGET_SUFFIXES):
            return True
        return chain.startswith(_RPC_PREFIXES) \
            or chain.split(".")[-1] in _RPC_LASTS

    def visit_FunctionDef(self, node):
        if node is not self.func.node:
            return
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _transitive_acquisitions(per_func: Dict[str, "_FuncLocks"],
                             index: ProjectIndex
                             ) -> Dict[str, Set[str]]:
    trans = {fid: set(fl.acquired) for fid, fl in per_func.items()}
    changed = True
    while changed:
        changed = False
        for fid, fl in per_func.items():
            cur = trans[fid]
            before = len(cur)
            for call in index.functions[fid].calls:
                if call.target in trans:
                    cur |= trans[call.target]
            if len(cur) != before:
                changed = True
    return trans


def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components with >1 node, canonically
    rotated; self-loop filtering happens at the caller (RLocks)."""
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strong(v: str):
        worklist = [(v, iter(sorted(graph.get(v, ()))))]
        idx[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while worklist:
            node, it = worklist[-1]
            advanced = False
            for w in it:
                if w not in graph:
                    continue
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    worklist.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on:
                    low[node] = min(low[node], idx[w])
            if advanced:
                continue
            worklist.pop()
            if worklist:
                parent = worklist[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == idx[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    pivot = min(comp)
                    i = comp.index(pivot)
                    out.append(comp[i:] + comp[:i])

    for v in sorted(graph):
        if v not in idx:
            strong(v)
    return out


def run(index: ProjectIndex) -> List[Finding]:
    per_func: Dict[str, _FuncLocks] = {}
    for func in index.iter_functions():
        mod = index.modules[func.module]
        fl = _FuncLocks(index, mod, func)
        for stmt in func.body:
            fl.visit(stmt)
        per_func[func.id] = fl

    trans = _transitive_acquisitions(per_func, index)
    kinds = _collect_lock_kinds(index)

    graph: Dict[str, Set[str]] = {}
    edge_site: Dict[Tuple[str, str], Tuple[str, str, int]] = {}

    def add_edge(a: str, b: str, func: FunctionInfo, line: int):
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
        edge_site.setdefault((a, b), (func.module, func.qualname, line))

    findings: List[Finding] = []
    for fid, fl in per_func.items():
        func = index.functions[fid]
        for a, b, line in fl.edges:
            if a == b:
                if kinds.get(a, "rlock") == "lock":
                    findings.append(Finding(
                        PASS_ID, "self-deadlock", func.module,
                        func.qualname, line,
                        f"non-reentrant lock `{a}` re-acquired while "
                        f"held (threading.Lock deadlocks on itself)",
                        f"self:{a}"))
                continue
            add_edge(a, b, func, line)
        for held, target, line, via_self in fl.calls_under:
            for b in trans.get(target, ()):
                if b != held:
                    add_edge(held, b, func, line)
            # re-acquiring the held lock through a method of the SAME
            # instance (``self.``-routed, so the lock objects cannot
            # differ) deadlocks a non-reentrant Lock; cross-instance
            # calls are excluded — structural identity would conflate
            # two objects' locks into a false self-cycle
            callee = per_func.get(target)
            if via_self and callee is not None \
                    and held in callee.acquired \
                    and kinds.get(held, "rlock") == "lock":
                findings.append(Finding(
                    PASS_ID, "self-deadlock", func.module,
                    func.qualname, line,
                    f"calls `{target.split(':')[-1]}` which "
                    f"re-acquires held non-reentrant `{held}` "
                    f"(threading.Lock deadlocks on itself)",
                    f"self:{held}"))
        for held, chain, line in fl.rpc_under:
            findings.append(Finding(
                PASS_ID, "lock-over-rpc", func.module, func.qualname,
                line,
                f"blocking call `{chain}()` while holding `{held}`: "
                f"a slow peer stalls every thread behind this lock",
                f"rpc:{held}:{chain}"))

    for comp in _cycles(graph):
        mod_name, qual, line = edge_site.get(
            (comp[0], comp[1] if len(comp) > 1 else comp[0]),
            (comp[0].split(":")[0].rsplit(".", 1)[0], "", 1))
        cyc = " -> ".join(comp + [comp[0]])
        findings.append(Finding(
            PASS_ID, "lock-cycle", mod_name, qual, line,
            f"lock acquisition cycle: {cyc} (AB-BA deadlock when the "
            f"orders interleave)", f"cycle:{'|'.join(comp)}"))
    return findings
