"""lock-order pass: AB-BA cycles and blocking calls under a held lock.

Lock identity is structural AND alias-aware (round 14): ``with
self._lock:`` in a method of class ``C`` in module ``M`` names the lock
``M.C._lock``; an acquisition through a TYPED instance chain —
``ctx.lock`` with ``ctx: OperatorMemoryContext``, or
``pool.host_ledger._lock`` through ``__init__``-typed attributes —
names the OWNING class's lock, so cross-instance acquisition edges
(e.g. ``HostSpillLedger`` under a per-operator context lock) resolve
structurally instead of being excluded. A lock flowing through a call
argument (``spill_pages(..., lock=ctx.lock)``) is tracked
parametrically: the callee's acquisitions of its ``lock`` parameter
instantiate to the caller's actual lock identity at every resolved
call site (transitively — an actual that is itself a parameter keeps
flowing). Local rebinds (``lock = self._lock``) and returned-attribute
accessors (``with obj.lock():`` where ``def lock(self): return
self._lock``) canonicalize through the core's alias facts.

Locals/parameters that never resolve stay scoped to their function, so
unknown objects can never fabricate a cycle: every unification is a
must-alias fact. While a lock is lexically held, every further
acquisition — in the same body or transitively through resolved
call-graph edges — adds an edge to the acquisition-order graph; a
cycle in that graph is the PR 5 ``HostSpillLedger`` finalizer-deadlock
class. Self-edges are reported only for locks constructed as
``threading.Lock()`` and only when the re-acquisition is provably the
SAME object: ``self.``-routed, or a parametric flow of the held lock
itself. Two instances of one class are never conflated into a false
self-cycle.

Non-blocking tries (``acquire(blocking=False)``) are excluded
everywhere: they cannot wait, so they can neither close a cycle nor
stall an RPC — ``demote_across``'s cross-list lock hops rely on this.

A second rule flags blocking RPC / subprocess / socket traffic while
holding any lock (``lock-over-rpc``): the PR 3 worker-loss detector
turns a worker stuck on a peer into a cascading replacement storm if
its server threads serialize behind a lock held across the wire.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, FunctionInfo, ModuleInfo, ProjectIndex,
                   bind_args, dotted_chain)

PASS_ID = "lock-order"

_RPC_PREFIXES = ("subprocess.", "socket.")
_RPC_LASTS = {"send_msg", "recv_msg", "check_output", "check_call"}
_RPC_TARGET_SUFFIXES = (":call",)   # trino_tpu.parallel.rpc:call

_PARAM_PREFIX = "<param:"


def _param_token(fid: str, name: str) -> str:
    return f"{_PARAM_PREFIX}{fid}:{name}>"


def _is_param(token: str) -> bool:
    return token.startswith(_PARAM_PREFIX)


def _lockish(chain: Optional[str]) -> bool:
    return bool(chain) and "lock" in chain.split(".")[-1].lower()


class _Identities:
    """Shared lock-identity context: id strings plus, for class-scoped
    ids, the owning ``module.Class`` (the cross-instance witness)."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        #: lock id -> owning "module.Class" when the id is an attribute
        #: of a resolved class (self-scoped or instance-typed)
        self.owners: Dict[str, str] = {}

    def lock_id(self, mod: ModuleInfo, func: Optional[FunctionInfo],
                chain: str) -> str:
        chain = self.index.canonical_chain(func, chain)
        parts = chain.split(".")
        head = parts[0]
        if func is not None and head in func.params \
                and head not in ("self", "cls"):
            if len(parts) == 1:
                # the lock IS a parameter: parametric — instantiated
                # per call site from the caller's actual argument
                return _param_token(func.id, head)
            if head not in func.annotations:
                # attribute of an untyped parameter: scope to the
                # function so distinct callers never unify falsely
                return f"{mod.name}:{func.qualname}.{chain}"
        if len(parts) >= 2:
            site = self.index.instance_type(mod, func, parts[:-1])
            if site is not None:
                owner = f"{site[0]}.{site[1]}"
                lid = f"{owner}.{parts[-1]}"
                self.owners[lid] = owner
                return lid
        if head in ("self", "cls") and func is not None:
            owner_cls = func.class_name or func.qualname
            owner = f"{mod.name}.{owner_cls}"
            lid = f"{owner}.{'.'.join(parts[1:])}"
            self.owners[lid] = owner
            return lid
        if func is not None \
                and head not in mod.module_assigns \
                and head not in mod.scopes.get("", {}) \
                and head not in mod.from_imports \
                and head not in mod.imports:
            # local or unresolved base: scope to the function so
            # distinct callers' locks never unify into a false node
            return f"{mod.name}:{func.qualname}.{chain}"
        return f"{mod.name}.{chain}"

    def item_lock_id(self, mod: ModuleInfo,
                     func: Optional[FunctionInfo],
                     expr: ast.expr
                     ) -> Optional[Tuple[str, Optional[str]]]:
        """(lock id, canonical source chain) of a with-item: a dotted
        chain, or a returned-attribute accessor call (``with
        obj.lock():``). The lockish-name heuristic accepts the RAW
        chain or its alias expansion (`lk = self._lock; with lk:`
        qualifies either way — `lock = self._mu` must too)."""
        chain = dotted_chain(expr)
        if chain is not None:
            canonical = self.index.canonical_chain(func, chain)
            if _lockish(chain) or _lockish(canonical):
                return self.lock_id(mod, func, canonical), canonical
        if isinstance(expr, ast.Call) and not expr.args:
            call_chain = dotted_chain(expr.func)
            if not _lockish(call_chain):
                return None
            target = self.index.resolve(mod, func, call_chain)
            callee = self.index.functions.get(target or "")
            if callee is not None and callee.returns_chain \
                    and callee.class_name:
                attr = callee.returns_chain.split(".", 1)[1]
                owner = f"{callee.module}.{callee.class_name}"
                lid = f"{owner}.{attr}"
                self.owners[lid] = owner
                return lid, call_chain
        return None


def _collect_lock_kinds(index: ProjectIndex,
                        ids: _Identities) -> Dict[str, str]:
    """lock id -> 'lock' | 'rlock' from ``X = threading.(R)Lock()``
    construction sites."""
    kinds: Dict[str, str] = {}
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            ctor = dotted_chain(node.value.func)
            if ctor not in ("threading.Lock", "threading.RLock",
                            "Lock", "RLock"):
                continue
            kind = "rlock" if ctor.endswith("RLock") else "lock"
            for t in node.targets:
                chain = dotted_chain(t)
                if chain is None:
                    continue
                func = mod.enclosing_function(node.lineno)
                kinds[ids.lock_id(mod, func, chain)] = kind
    return kinds


def _nonblocking(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant):
            return kw.value.value is False
    return False


@dataclass
class _CallUnder:
    target: str                  # resolved callee function id
    node: ast.Call
    chain: str
    line: int
    #: (lock token, canonical source chain) held at the call — the
    #: chain distinguishes THIS instance's lock (`self._lock`) from a
    #: structurally-equal other instance's (`self.other._lock`)
    held: Tuple[Tuple[str, Optional[str]], ...]
    via_self: bool

    @property
    def held_ids(self) -> Tuple[str, ...]:
        return tuple(h for h, _ in self.held)


class _FuncLocks(ast.NodeVisitor):
    """One function's lock behaviour: direct acquisitions, ordered
    edges, resolved calls (with the held-lock snapshot), RPC-ish calls
    under a lock. Tokens are concrete lock ids or parametric
    ``<param:fid:name>`` placeholders."""

    def __init__(self, index: ProjectIndex, mod: ModuleInfo,
                 func: FunctionInfo, ids: _Identities):
        self.index = index
        self.mod = mod
        self.func = func
        self.ids = ids
        self.acquired: Set[str] = set()
        #: the subset acquired through THIS instance's own attribute
        #: (`self._lock` — not a structurally-equal peer's)
        self.own_acquired: Set[str] = set()
        self.pairs: List[Tuple[str, str, int]] = []
        #: (lock id, line) of direct re-acquisitions PROVEN same-object
        #: (identical canonical source chains) — structural id equality
        #: alone (two instances of one class) never lands here
        self.self_pairs: List[Tuple[str, int]] = []
        self.calls: List[_CallUnder] = []
        self.rpc_under: List[Tuple[str, str, int]] = []
        #: stack of (lock id, canonical source chain)
        self._held: List[Tuple[str, Optional[str]]] = []
        self._bindings = func.bindings

    @property
    def held_ids(self) -> Tuple[str, ...]:
        return tuple(h for h, _ in self._held)

    def stable_chain(self, chain: Optional[str]) -> bool:
        """A chain denotes ONE object across the whole frame only when
        its head can never be rebound here: ``self``/``cls``, or a
        name with zero bindings in this function (parameters never
        reassigned, module globals never shadowed). A rebindable head
        (`ctx = self._next` between two `ctx.lock` acquisitions) makes
        chain equality meaningless — no same-object claim."""
        if chain is None:
            return False
        head = chain.split(".")[0]
        if head in ("self", "cls"):
            return True
        return self._bindings.get(head, 0) == 0

    def _acquire(self, lock: str, line: int,
                 chain: Optional[str] = None):
        self.acquired.add(lock)
        if chain is not None and chain.startswith("self.") \
                and len(chain.split(".")) == 2:
            self.own_acquired.add(lock)
        for held, held_chain in self._held:
            if held == lock:
                # same structural id: a deadlock only when the SOURCE
                # chains prove the same object — identical chains with
                # a non-rebindable head (`self._lock` twice);
                # `self._lock` vs `other._lock` is two instances of
                # one class — ordered locking, not a self-cycle
                if chain is not None and chain == held_chain \
                        and self.stable_chain(chain):
                    self.self_pairs.append((lock, line))
            else:
                self.pairs.append((held, lock, line))

    def visit_With(self, node: ast.With):
        pushed = 0
        for item in node.items:
            # the item EXPRESSION runs before its acquisition: calls
            # inside it (`with enter_chan():`) must enter the call
            # graph or their transitive acquisitions vanish
            self.visit(item.context_expr)
            hit = self.ids.item_lock_id(self.mod, self.func,
                                        item.context_expr)
            if hit is not None:
                lock, chain = hit
                self._acquire(lock, node.lineno, chain)
                self._held.append((lock, chain))
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self._held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call):
        chain = dotted_chain(node.func)
        if chain is not None:
            parts = chain.split(".")
            if parts[-1] == "acquire" and len(parts) > 1 \
                    and not _nonblocking(node):
                base = self.index.canonical_chain(
                    self.func, ".".join(parts[:-1]))
                lock = self.ids.lock_id(self.mod, self.func, base)
                self._acquire(lock, node.lineno, base)
            else:
                target = self.index.resolve(self.mod, self.func, chain)
                if target is not None:
                    self.calls.append(_CallUnder(
                        target, node, chain, node.lineno,
                        tuple(self._held),
                        parts[0] in ("self", "cls")))
                if self._held and self._rpcish(chain, target):
                    for held in self.held_ids:
                        self.rpc_under.append(
                            (held, chain, node.lineno))
        self.generic_visit(node)

    @staticmethod
    def _rpcish(chain: str, target: Optional[str]) -> bool:
        if target and target.endswith(_RPC_TARGET_SUFFIXES):
            return True
        return chain.startswith(_RPC_PREFIXES) \
            or chain.split(".")[-1] in _RPC_LASTS

    def visit_FunctionDef(self, node):
        if node is not self.func.node:
            return
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


@dataclass
class LockGraph:
    """The interprocedural acquisition-order graph plus everything the
    findings (and the not-blind tests) need: edge sample sites, lock
    kinds, class owners, the cross-instance edge subset, and the
    must-alias self-deadlocks parametric flow proved."""

    graph: Dict[str, Set[str]] = field(default_factory=dict)
    edge_site: Dict[Tuple[str, str], Tuple[str, str, int]] = \
        field(default_factory=dict)
    kinds: Dict[str, str] = field(default_factory=dict)
    owners: Dict[str, str] = field(default_factory=dict)
    per_func: Dict[str, _FuncLocks] = field(default_factory=dict)
    #: (held, acquired) edges whose endpoints belong to two DIFFERENT
    #: resolved classes — the cross-instance witness set
    cross_instance_edges: Set[Tuple[str, str]] = field(
        default_factory=set)
    #: (lock, func, line) where a parametric flow proved the held lock
    #: itself is re-acquired (must-alias self-deadlock)
    param_self_cycles: List[Tuple[str, str, int]] = \
        field(default_factory=list)


def build_lock_graph(index: ProjectIndex) -> LockGraph:
    ids = _Identities(index)
    lg = LockGraph(owners=ids.owners)
    for func in index.iter_functions():
        mod = index.modules[func.module]
        fl = _FuncLocks(index, mod, func, ids)
        for stmt in func.body:
            fl.visit(stmt)
        lg.per_func[func.id] = fl
    lg.kinds = _collect_lock_kinds(index, ids)

    # summary fixpoint: each function's transitive acquisitions and
    # ordered pairs, with parametric tokens instantiated per call site
    acq: Dict[str, Set[str]] = {fid: set(fl.acquired)
                                for fid, fl in lg.per_func.items()}
    pairs: Dict[str, Set[Tuple[str, str]]] = {
        fid: {(a, b) for a, b, _ in fl.pairs}
        for fid, fl in lg.per_func.items()}
    site: Dict[Tuple[str, str], Tuple[str, str, int]] = {}
    for fid, fl in lg.per_func.items():
        func = index.functions[fid]
        for a, b, line in fl.pairs:
            site.setdefault((a, b), (func.module, func.qualname, line))

    def substitute(token: str, callee: FunctionInfo,
                   call: _CallUnder, caller: FunctionInfo,
                   caller_mod: ModuleInfo
                   ) -> Tuple[Optional[str], Optional[str]]:
        """(instantiated token, canonical source chain of the actual
        argument) — the chain is what same-object claims compare; a
        bare structural-id match proves nothing across instances."""
        if not _is_param(token):
            return token, None
        inner = token[len(_PARAM_PREFIX):-1]
        owner_fid, name = inner.rsplit(":", 1)
        if owner_fid != callee.id:
            return None, None  # a deeper frame's parameter: not ours
        bound = bind_args(callee, call.node, call.chain,
                          index=index, mod=caller_mod)
        arg = bound.get(name)
        argchain = dotted_chain(arg) if arg is not None else None
        if argchain is None:
            return None, None
        canonical = index.canonical_chain(caller, argchain)
        return ids.lock_id(caller_mod, caller, canonical), canonical

    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for fid, fl in lg.per_func.items():
            caller = index.functions[fid]
            caller_mod = index.modules[caller.module]
            for call in fl.calls:
                callee = index.functions.get(call.target)
                if callee is None:
                    continue
                sub_cache: Dict[str, Tuple[Optional[str],
                                           Optional[str]]] = {}

                def sub(token: str) -> Tuple[Optional[str],
                                             Optional[str]]:
                    if token not in sub_cache:
                        sub_cache[token] = substitute(
                            token, callee, call, caller, caller_mod)
                    return sub_cache[token]

                for token in list(acq.get(call.target, ())):
                    s, s_chain = sub(token)
                    if s is None:
                        continue
                    if s not in acq[fid]:
                        acq[fid].add(s)
                        changed = True
                    for held, held_chain in call.held:
                        if held == s:
                            # must-alias ONLY when the flowed argument
                            # is the held lock's own source chain (and
                            # that chain can't have been rebound):
                            # `grab(other._lock)` under `self._lock`
                            # is a cross-instance hand-off, not a
                            # self-deadlock
                            if _is_param(token) \
                                    and s_chain is not None \
                                    and s_chain == held_chain \
                                    and fl.stable_chain(s_chain) \
                                    and lg.kinds.get(s, "rlock") \
                                    == "lock":
                                rec = (s, fid, call.line)
                                if rec not in lg.param_self_cycles:
                                    lg.param_self_cycles.append(rec)
                            continue
                        if (held, s) not in pairs[fid]:
                            pairs[fid].add((held, s))
                            changed = True
                        site.setdefault(
                            (held, s),
                            (caller.module, caller.qualname,
                             call.line))
                for a, b in list(pairs.get(call.target, ())):
                    if not (_is_param(a) or _is_param(b)):
                        continue   # concrete pairs stand on their own
                    sa, sb = sub(a)[0], sub(b)[0]
                    if sa is None or sb is None or sa == sb:
                        continue
                    if (sa, sb) not in pairs[fid]:
                        pairs[fid].add((sa, sb))
                        changed = True
                    site.setdefault(
                        (sa, sb),
                        (caller.module, caller.qualname, call.line))

    for fid, pp in pairs.items():
        for a, b in pp:
            if _is_param(a) or _is_param(b) or a == b:
                continue
            lg.graph.setdefault(a, set()).add(b)
            lg.graph.setdefault(b, set())
            lg.edge_site.setdefault(
                (a, b), site.get((a, b),
                                 (index.functions[fid].module,
                                  index.functions[fid].qualname, 1)))
            owner_a = lg.owners.get(a)
            owner_b = lg.owners.get(b)
            if owner_a and owner_b and owner_a != owner_b:
                lg.cross_instance_edges.add((a, b))
    return lg


def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components with >1 node, canonically
    rotated; self-loop filtering happens at the caller (RLocks)."""
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strong(v: str):
        worklist = [(v, iter(sorted(graph.get(v, ()))))]
        idx[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while worklist:
            node, it = worklist[-1]
            advanced = False
            for w in it:
                if w not in graph:
                    continue
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    worklist.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on:
                    low[node] = min(low[node], idx[w])
            if advanced:
                continue
            worklist.pop()
            if worklist:
                parent = worklist[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == idx[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    pivot = min(comp)
                    i = comp.index(pivot)
                    out.append(comp[i:] + comp[:i])

    for v in sorted(graph):
        if v not in idx:
            strong(v)
    return out


def run(index: ProjectIndex) -> List[Finding]:
    lg = build_lock_graph(index)
    findings: List[Finding] = []

    for fid, fl in sorted(lg.per_func.items()):
        func = index.functions[fid]
        for lock, line in fl.self_pairs:
            if lg.kinds.get(lock, "rlock") == "lock":
                findings.append(Finding(
                    PASS_ID, "self-deadlock", func.module,
                    func.qualname, line,
                    f"non-reentrant lock `{lock}` re-acquired while "
                    f"held (threading.Lock deadlocks on itself)",
                    f"self:{lock}"))
        # re-acquiring the held lock through a method of the SAME
        # instance (``self.``-routed call AND a held lock that is THIS
        # instance's own attribute — `self._lock`, not a structurally-
        # equal `self.other._lock`) deadlocks a non-reentrant Lock;
        # structurally-same ids on two instances are NOT conflated —
        # only must-alias routes (self, or parametric flow) report
        for call in fl.calls:
            if not call.via_self:
                continue
            callee = lg.per_func.get(call.target)
            if callee is None:
                continue
            for held, held_chain in call.held:
                own_attr = (held_chain is not None
                            and held_chain.startswith("self.")
                            and len(held_chain.split(".")) == 2)
                if own_attr and held in callee.own_acquired \
                        and lg.kinds.get(held, "rlock") == "lock":
                    findings.append(Finding(
                        PASS_ID, "self-deadlock", func.module,
                        func.qualname, call.line,
                        f"calls `{call.target.split(':')[-1]}` which "
                        f"re-acquires held non-reentrant `{held}` "
                        f"(threading.Lock deadlocks on itself)",
                        f"self:{held}"))
        for held, chain, line in fl.rpc_under:
            findings.append(Finding(
                PASS_ID, "lock-over-rpc", func.module, func.qualname,
                line,
                f"blocking call `{chain}()` while holding `{held}`: "
                f"a slow peer stalls every thread behind this lock",
                f"rpc:{held}:{chain}"))

    for lock, fid, line in lg.param_self_cycles:
        func = index.functions[fid]
        findings.append(Finding(
            PASS_ID, "self-deadlock", func.module, func.qualname, line,
            f"non-reentrant `{lock}` flows through a call argument "
            f"into a blocking re-acquire while held (must-alias: the "
            f"parameter IS the held lock)",
            f"self:{lock}"))

    for comp in _cycles(lg.graph):
        mod_name, qual, line = lg.edge_site.get(
            (comp[0], comp[1] if len(comp) > 1 else comp[0]),
            (comp[0].split(":")[0].rsplit(".", 1)[0], "", 1))
        cyc = " -> ".join(comp + [comp[0]])
        cross = [f"{a} -> {b}" for a, b in sorted(
            lg.cross_instance_edges)
            if a in comp and b in comp]
        detail = f" [cross-instance: {'; '.join(cross)}]" if cross \
            else ""
        findings.append(Finding(
            PASS_ID, "lock-cycle", mod_name, qual, line,
            f"lock acquisition cycle: {cyc} (AB-BA deadlock when the "
            f"orders interleave){detail}", f"cycle:{'|'.join(comp)}"))
    return findings
