"""recompile pass: hazards that defeat program-cache reuse.

Two rules, both instances of one failure mode — the cache key and the
traced program disagree, so the engine either retraces per page
(interpreter-speed slide, the classic silent JAX perf bug) or serves a
stale compiled program:

- ``unhashable-arg``: a dict/list/set display (or comprehension)
  flowing into an ``lru_cache``'d builder call — the call raises
  ``TypeError: unhashable`` at runtime, or the caller "fixes" it by
  rebuilding per call and the cache silently never hits.
- ``traced-branch``: Python ``if``/``while`` on a non-static parameter
  inside a jit'd function — branching on a traced value either raises
  ``TracerBoolConversionError`` or, with shape-dependent guards,
  retraces per distinct outcome. Attribute guards on ``.shape`` /
  ``.dtype`` / ``.ndim`` / ``len()`` are static and exempt.

The third rule qlint shipped with (``cached-builder-reads-session``,
the PR 5 ``min_collectives`` bug) moved to the ``cache-coherence``
pass (round 14), which generalizes it beyond ``lru_cache`` to memo-
dict builders, env vars and mutable globals — with interprocedural
reach. ``_cached_functions`` stays here as the shared lru index.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, FunctionInfo, ProjectIndex, dotted_chain
from .trace_purity import jit_entries

PASS_ID = "recompile"

_CACHE_CHAINS = {"lru_cache", "functools.lru_cache", "cache",
                 "functools.cache"}
_UNHASHABLE = (ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp,
               ast.SetComp, ast.GeneratorExp)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}


def _cached_functions(index: ProjectIndex) -> Dict[str, FunctionInfo]:
    out: Dict[str, FunctionInfo] = {}
    for func in index.iter_functions():
        for dec in func.decorators:
            if index.decorator_chain(dec) in _CACHE_CHAINS:
                out[func.id] = func
    return out


def _dynamic_param_refs(test: ast.expr, params: Set[str]) -> List[str]:
    """Parameter names referenced in ``test`` other than through
    static accessors (``x.shape[0]``, ``len(x)``, ``x is None``)."""
    static_ids: Set[int] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) \
                and node.attr in _STATIC_ATTRS:
            for inner in ast.walk(node.value):
                static_ids.add(id(inner))
        elif isinstance(node, ast.Call):
            chain = dotted_chain(node.func)
            if chain in ("len", "isinstance", "type", "getattr",
                         "hasattr"):
                for arg in node.args:
                    for inner in ast.walk(arg):
                        static_ids.add(id(inner))
        elif isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot))
                for op in node.ops):
            for inner in ast.walk(node):
                static_ids.add(id(inner))
    hits = []
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in params \
                and id(node) not in static_ids:
            hits.append(node.id)
    return hits


def run(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    cached = _cached_functions(index)

    # (a) unhashable arguments into cached builders
    for func in index.iter_functions():
        for call in func.calls:
            if call.target not in cached:
                continue
            builder = cached[call.target]
            exprs = list(call.node.args) \
                + [kw.value for kw in call.node.keywords]
            for e in exprs:
                if isinstance(e, _UNHASHABLE):
                    findings.append(Finding(
                        PASS_ID, "unhashable-arg", func.module,
                        func.qualname, e.lineno,
                        f"dict/list/set argument into lru_cache'd "
                        f"`{builder.qualname}` — unhashable cache "
                        f"key (pass a tuple / frozen value)",
                        f"unhashable:{builder.qualname}"))

    # (b) Python branches on traced (non-static) parameters
    for entry in jit_entries(index).values():
        func = entry.func
        dynamic = set(func.params) - entry.static_params
        if func.class_name:
            dynamic.discard("self")
        for node in ast.walk(func.node):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            refs = _dynamic_param_refs(node.test, dynamic)
            for name in sorted(set(refs)):
                findings.append(Finding(
                    PASS_ID, "traced-branch", func.module,
                    func.qualname, node.lineno,
                    f"Python `{type(node).__name__.lower()}` on "
                    f"traced parameter `{name}` inside jit'd "
                    f"`{func.qualname}` — use lax.cond/jnp.where, "
                    f"or declare it static",
                    f"branch:{name}"))
    return findings
