"""resource-lifecycle pass: every constructed closeable reaches
``close()`` on all paths.

PR 8's post-review rounds fixed leaked spool cursors, unclosed
channels, and a finalizer resurrection race BY HAND; this pass turns
that review into structure. A CLOSEABLE is any class in the package
that defines ``close()`` (``SpoolCursor``, exchange channels, spillers,
retained streams, sinks) plus the ``open()`` builtin; factory
functions whose every return is a closeable construction
(``spool_channel``, ``spool_task_cursor``) propagate closeability to
their callers.

A construction site is SATISFIED when the object provably cannot leak:

- constructed in a ``with`` item (``__exit__`` owns it);
- ``close()``d inside a ``finally`` block (every path runs it);
- registered into a teardown collection (``state.channels.append`` /
  ``.extend``) or handed to ``weakref.finalize`` — modeled as any use
  of the object as a call ARGUMENT (ownership transfer: the callee or
  the registry is now responsible);
- escaping the frame: returned/yielded, stored into ``self.*`` / a
  module global / a container (the owner's own ``close()`` is its
  contract), or re-aliased into an escaping name.

Otherwise:

- ``leaked-closeable``: no ``close()`` on any path and no escape — the
  object dies by GC at an arbitrary point (fds/files/retained frames
  outlive the query; under refcount pressure the PR 5 finalizer class
  fires at arbitrary stack depths);
- ``close-not-guaranteed``: a straight-line ``close()`` exists but not
  under ``finally``/``with`` — any exception between construction and
  close leaks it.

Deliberate transfers the analysis cannot see opt out per line with
``# qlint: ignore[resource-lifecycle] <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, FunctionInfo, ModuleInfo, ProjectIndex,
                   dotted_chain, own_nodes)

PASS_ID = "resource-lifecycle"

#: methods that discharge a closeable beside close() itself
_CLOSE_METHODS = {"close", "abort", "finish", "release", "stop"}


def closeable_classes(index: ProjectIndex) -> Dict[str, List[str]]:
    """class name -> defining modules, for every class in the package
    with a ``close()`` method — the not-blind witness for the tier-1
    gate (≥5 on the real repo: cursors, channels, spillers, sinks)."""
    out: Dict[str, List[str]] = {}
    for name in sorted(index.modules):
        mod = index.modules[name]
        for cls, methods in sorted(mod.classes.items()):
            if "close" in methods:
                out.setdefault(cls, []).append(name)
    return out


def closeable_factories(index: ProjectIndex,
                        classes: Dict[str, List[str]]) -> Set[str]:
    """Function ids whose every return is a construction (or factory
    call) of a closeable — callers of ``spool_channel(...)`` hold a
    closeable exactly as if they had called the constructor."""
    out: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for func in index.iter_functions():
            if func.id in out:
                continue
            mod = index.modules[func.module]
            returns = [n for n in own_nodes(func.node)
                       if isinstance(n, ast.Return)]
            if not returns:
                continue
            all_closeable = True
            for r in returns:
                if not (isinstance(r.value, ast.Call) and
                        _constructs(index, mod, func, r.value,
                                    classes, out)):
                    all_closeable = False
                    break
            if all_closeable:
                out.add(func.id)
                changed = True
    return out


def _constructs(index: ProjectIndex, mod: ModuleInfo,
                func: FunctionInfo, call: ast.Call,
                classes: Dict[str, List[str]],
                factories: Set[str]) -> Optional[str]:
    """The closeable class/factory name when ``call`` constructs a
    closeable this pass tracks, else None. Constructor resolution is
    must-alias: the called name must resolve to an INDEXED class with
    ``close()`` (same module or from-import), to a known factory, or
    be the ``open`` builtin."""
    chain = dotted_chain(call.func)
    if chain is None:
        return None
    if chain == "open":
        return "open"
    target = index.resolve(mod, func, chain)
    if target is not None and target in factories:
        return target.split(":")[-1]
    parts = chain.split(".")
    name = parts[-1]
    if name not in classes:
        return None
    site = index._class_site(mod, name)
    if site is not None and name in classes \
            and site[0] in classes[name]:
        return name
    return None


class _Lifecycle(ast.NodeVisitor):
    """Track one function's closeable locals: construction sites,
    closes (and whether they sit under a ``finally``), escapes."""

    def __init__(self, index: ProjectIndex, mod: ModuleInfo,
                 func: FunctionInfo, classes: Dict[str, List[str]],
                 factories: Set[str]):
        self.index = index
        self.mod = mod
        self.func = func
        self.classes = classes
        self.factories = factories
        #: var -> (class name, line)
        self.constructed: Dict[str, Tuple[str, int]] = {}
        self.with_managed: Set[str] = set()
        self.closed_finally: Set[str] = set()
        self.closed_plain: Set[str] = set()
        self.escaped: Set[str] = set()
        #: constructions whose value is immediately dropped
        self.dropped: List[Tuple[str, int]] = []
        self._finally_depth = 0

    # -- helpers ---------------------------------------------------------

    def _note_escapes_in(self, expr: Optional[ast.AST]):
        """Names ESCAPING through ``expr``: bare name references and
        call arguments transfer ownership; the receiver of a method
        call (``cur.poll()``) and attribute/item READS
        (``cursor.path``) are uses, not escapes."""
        if expr is None:
            return
        stack: List[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    self.escaped.add(node.id)
                continue
            if isinstance(node, ast.Call):
                stack.extend(node.args)
                stack.extend(kw.value for kw in node.keywords)
                if not isinstance(node.func, ast.Attribute):
                    stack.append(node.func)
                continue
            if isinstance(node, (ast.Attribute, ast.Subscript)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    # -- visitors --------------------------------------------------------

    def visit_Assign(self, node: ast.Assign):
        value = node.value
        is_ctor = isinstance(value, ast.Call) and _constructs(
            self.index, self.mod, self.func, value, self.classes,
            self.factories)
        plain_local = (len(node.targets) == 1
                       and isinstance(node.targets[0], ast.Name))
        if is_ctor and plain_local:
            name = node.targets[0].id
            self.constructed.setdefault(
                name, (is_ctor, node.lineno))
            # arguments to the constructor itself are ordinary uses
            for a in list(value.args) + [kw.value
                                         for kw in value.keywords]:
                self._note_escapes_in(a)
        else:
            # value flowing into an attribute/subscript/module target
            # escapes (ownership transfer); re-aliasing `b = a` makes
            # `a` escape conservatively (b's fate is untracked)
            self._note_escapes_in(value)
            if is_ctor and not plain_local:
                pass   # self.x = C(...): ownership moved to the object
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        chain = dotted_chain(node.func)
        if chain is not None:
            parts = chain.split(".")
            if len(parts) == 2 and parts[1] in _CLOSE_METHODS \
                    and parts[0] in self.constructed:
                if self._finally_depth > 0:
                    self.closed_finally.add(parts[0])
                else:
                    self.closed_plain.add(parts[0])
        # any value used as a call ARGUMENT transfers ownership
        # (append into a teardown list, weakref.finalize, a consumer)
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            self._note_escapes_in(a)
        self.generic_visit(node)

    def visit_With(self, node: ast.With):
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call) and _constructs(
                    self.index, self.mod, self.func, expr,
                    self.classes, self.factories):
                if isinstance(item.optional_vars, ast.Name):
                    self.with_managed.add(item.optional_vars.id)
                # anonymous `with C():` is managed by __exit__ — fine
            elif isinstance(expr, ast.Name):
                # `with cursor:` on an already-constructed local
                self.with_managed.add(expr.id)
        self.generic_visit(node)

    visit_AsyncWith = visit_With

    def visit_Try(self, node: ast.Try):
        for part in (node.body, node.orelse):
            for stmt in part:
                self.visit(stmt)
        for handler in node.handlers:
            for stmt in handler.body:
                self.visit(stmt)
        self._finally_depth += 1
        for stmt in node.finalbody:
            self.visit(stmt)
        self._finally_depth -= 1

    def visit_Return(self, node: ast.Return):
        self._note_escapes_in(node.value)
        self.generic_visit(node)

    def visit_Yield(self, node: ast.Yield):
        self._note_escapes_in(node.value)
        self.generic_visit(node)

    def visit_YieldFrom(self, node: ast.YieldFrom):
        self._note_escapes_in(node.value)
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr):
        value = node.value
        if isinstance(value, ast.Call):
            ctor = _constructs(self.index, self.mod, self.func, value,
                               self.classes, self.factories)
            if ctor:
                self.dropped.append((ctor, node.lineno))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if node is not self.func.node:
            return   # nested def: analyzed via its own FunctionInfo
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def run(index: ProjectIndex) -> List[Finding]:
    classes = closeable_classes(index)
    factories = closeable_factories(index, classes)
    findings: List[Finding] = []
    for func in index.iter_functions():
        mod = index.modules[func.module]
        lc = _Lifecycle(index, mod, func, classes, factories)
        for stmt in func.body:
            lc.visit(stmt)
        for name, (cls, line) in sorted(lc.constructed.items()):
            if name in lc.with_managed or name in lc.closed_finally \
                    or name in lc.escaped:
                continue
            if name in lc.closed_plain:
                findings.append(Finding(
                    PASS_ID, "close-not-guaranteed", func.module,
                    func.qualname, line,
                    f"`{name}` ({cls}) is closed on the straight-line "
                    f"path only — an exception between construction "
                    f"and close() leaks it (use with/finally, or "
                    f"register it in a teardown list)",
                    f"plain-close:{cls}:{name}"))
            else:
                findings.append(Finding(
                    PASS_ID, "leaked-closeable", func.module,
                    func.qualname, line,
                    f"`{name}` ({cls}) is constructed but never "
                    f"reaches close() on any path and never escapes "
                    f"this frame — it leaks until GC",
                    f"leak:{cls}:{name}"))
        for cls, line in lc.dropped:
            findings.append(Finding(
                PASS_ID, "leaked-closeable", func.module,
                func.qualname, line,
                f"constructed {cls} is dropped on the floor — nothing "
                f"can ever close it",
                f"drop:{cls}"))
    return findings
