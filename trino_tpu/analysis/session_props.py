"""session-props pass: the registry and its readers cannot drift.

The registry module (any indexed module ending in
``session_properties``) declares properties via module-level
``register(SessionProperty("name", "type", ...))`` calls; engine code
reads them through ``value(session, "name")`` / ``prop_value(props,
"name")`` / ``set_property(d, "name", v)``. Three rules:

- ``undeclared-lookup``: a literal property name read somewhere in the
  package that the registry does not declare — ``value()`` would
  ``KeyError`` at query time (or ``set_property`` reject the SET).
- ``dead-property``: a declared property with zero literal read sites
  in the package — a knob users can SET that changes nothing (the
  ``page_rows`` class: its readers moved to connector config and the
  session property kept validating silently).
- ``bad-type``: a declared type outside the registry vocabulary
  (integer | double | boolean | varchar) — ``_parse`` silently falls
  through to ``str()``, so an "integer" typo'd as "int" coerces
  nothing and validation runs against the raw string.

Dynamic lookups (non-literal name expressions, the registry module's
own generic plumbing) are ignored; they cannot be checked textually.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .core import Finding, ProjectIndex

PASS_ID = "session-props"

_TYPE_VOCAB = {"integer", "double", "boolean", "varchar"}
_READ_LASTS = {"value", "prop_value", "set_property"}


def _registry_module(index: ProjectIndex):
    for name in sorted(index.modules):
        if name.endswith("session_properties"):
            return index.modules[name]
    return None


def _declarations(mod) -> Dict[str, Tuple[str, int]]:
    """name -> (declared type, line) from register(SessionProperty(..))
    calls anywhere at module level (including inside try/if blocks)."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "register" and node.args):
            continue
        inner = node.args[0]
        if not (isinstance(inner, ast.Call)
                and isinstance(inner.func, ast.Name)
                and inner.func.id == "SessionProperty"):
            continue
        consts = [a.value for a in inner.args[:2]
                  if isinstance(a, ast.Constant)
                  and isinstance(a.value, str)]
        if len(consts) == 2:
            out[consts[0]] = (consts[1], inner.lineno)
    return out


def run(index: ProjectIndex) -> List[Finding]:
    reg = _registry_module(index)
    if reg is None:
        return []
    declared = _declarations(reg)
    findings: List[Finding] = []

    for name, (type_, line) in sorted(declared.items()):
        if type_ not in _TYPE_VOCAB:
            findings.append(Finding(
                PASS_ID, "bad-type", reg.name, "", line,
                f"property `{name}` declares type {type_!r} outside "
                f"the registry vocabulary {sorted(_TYPE_VOCAB)} — "
                f"_parse silently treats it as varchar",
                f"bad-type:{name}"))

    reads: Dict[str, List[Tuple[str, str, int]]] = {}
    for func in index.iter_functions():
        if func.module == reg.name:
            continue   # the registry's own generic plumbing
        for call in func.calls:
            last = call.chain.split(".")[-1]
            if last not in _READ_LASTS:
                continue
            resolved = call.target or ""
            ok = "session_properties" in resolved
            if not ok:
                head = call.chain.split(".")[0]
                ok = head in ("SP", "session_properties")
            if not ok:
                continue
            for a in call.node.args:
                if isinstance(a, ast.Constant) \
                        and isinstance(a.value, str):
                    reads.setdefault(a.value, []).append(
                        (func.module, func.qualname, call.line))
                    break

    for name in sorted(reads):
        if name not in declared:
            mod, qual, line = reads[name][0]
            findings.append(Finding(
                PASS_ID, "undeclared-lookup", mod, qual, line,
                f"lookup of session property `{name}` which the "
                f"registry does not declare — value() raises "
                f"KeyError at query time",
                f"undeclared:{name}"))

    for name, (_type, line) in sorted(declared.items()):
        if name not in reads:
            findings.append(Finding(
                PASS_ID, "dead-property", reg.name, "", line,
                f"property `{name}` has no read site in the package "
                f"— a SET SESSION knob that changes nothing",
                f"dead:{name}"))
    return findings
