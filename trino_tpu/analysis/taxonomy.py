"""taxonomy pass: every failure on the runtime paths is taxonomy-typed.

PR 3's retry machinery dispatches on error TYPE (USER fails fast,
infra faults consume the budget, INSUFFICIENT_RESOURCES escalates
memory) — so an untyped failure is not a style problem, it changes
recovery behaviour. Two rules, scoped to ``parallel/``, ``telemetry/``
and the serving cache (``cache.py``) — the three places an erased
error type reaches retry dispatch or silently disables a surface
(``fault.py`` itself is exempt: it defines the vocabulary):

- ``bare-raise``: ``raise RuntimeError(...)`` / ``raise Exception(...)``
  — the coordinator classifies these INTERNAL by default, which makes
  a deterministic condition (aborted task, rejected sink) consume
  retry budget it can never benefit from. Raise ``TrinoError`` with a
  code or ``RemoteTaskError`` with an explicit type instead.
- ``broad-swallow``: an ``except Exception:`` / ``except
  BaseException:`` handler that neither re-raises nor routes the
  exception through the fault vocabulary (``serialize_failure`` /
  ``classify_exception`` / ``RemoteTaskError``) — the failure's type
  is erased exactly where the retry machinery needed it.

Deliberate cases (chaos-harness injected faults, speculative losers)
opt out per line with ``# qlint: ignore[taxonomy] <reason>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import Finding, ModuleInfo, ProjectIndex, dotted_chain

PASS_ID = "taxonomy"

_BARE = {"RuntimeError", "Exception"}
_BROAD = {"Exception", "BaseException"}
_FAULT_API = {"serialize_failure", "classify_exception",
              "classify_error_code", "RemoteTaskError",
              "from_response", "is_retryable"}


def _in_scope(name: str) -> bool:
    parts = name.split(".")
    if parts[-1] == "fault":
        return False   # defines the vocabulary
    return ("parallel" in parts[1:] or "telemetry" in parts[1:]
            or parts[-1] == "cache")


def _raised_name(node: ast.Raise) -> Optional[str]:
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    chain = dotted_chain(exc) if exc is not None else None
    return chain


def _routes_through_fault(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            chain = dotted_chain(node.func)
            if chain and chain.split(".")[-1] in _FAULT_API:
                return True
    return False


def _broad_types(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    if t is None:
        return ["<bare except>"]
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        chain = dotted_chain(e)
        if chain in _BROAD:
            out.append(chain)
    return out


def _enclosing_qualname(mod: ModuleInfo, line: int) -> str:
    info = mod.enclosing_function(line)
    return info.qualname if info is not None else ""


def run(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for name in sorted(index.modules):
        if not _in_scope(name):
            continue
        mod = index.modules[name]
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Raise):
                raised = _raised_name(node)
                if raised in _BARE:
                    qual = _enclosing_qualname(mod, node.lineno)
                    findings.append(Finding(
                        PASS_ID, "bare-raise", name, qual,
                        node.lineno,
                        f"bare `raise {raised}` on a parallel-runtime "
                        f"path — classified INTERNAL by default; "
                        f"raise a typed taxonomy error instead",
                        f"raise:{raised}:{qual}:{_stmt_ordinal(mod, node)}"))
            elif isinstance(node, ast.ExceptHandler):
                broad = _broad_types(node)
                if not broad or _routes_through_fault(node):
                    continue
                qual = _enclosing_qualname(mod, node.lineno)
                findings.append(Finding(
                    PASS_ID, "broad-swallow", name, qual,
                    node.lineno,
                    f"`except {broad[0]}` swallows without routing "
                    f"through parallel/fault.py — the failure type "
                    f"is erased where retry dispatch needs it",
                    f"swallow:{broad[0]}:{qual}:{_stmt_ordinal(mod, node)}"))
    return findings


def _stmt_ordinal(mod: ModuleInfo, node: ast.AST) -> int:
    """Ordinal of this finding site among same-kind VIOLATION sites in
    its enclosing function — keeps baseline keys stable across
    unrelated line churn while distinguishing multiple sites in one
    function."""
    qual = _enclosing_qualname(mod, node.lineno)
    ordinal = 0
    for other in ast.walk(mod.tree):
        if other is node \
                or getattr(other, "lineno", node.lineno) >= node.lineno:
            continue
        if isinstance(node, ast.Raise) and isinstance(other, ast.Raise):
            if _raised_name(other) not in _BARE:
                continue
        elif isinstance(node, ast.ExceptHandler) \
                and isinstance(other, ast.ExceptHandler):
            if not _broad_types(other) or _routes_through_fault(other):
                continue
        else:
            continue
        if _enclosing_qualname(mod, other.lineno) == qual:
            ordinal += 1
    return ordinal
