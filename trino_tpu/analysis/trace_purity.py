"""trace-purity pass: no host side-effects reachable inside traced code.

Entry points are functions that jax stages out: ``@jax.jit`` /
``@partial(jax.jit, ...)`` / ``@partial(shard_map, ...)`` decorated
defs, and local functions passed into ``jax.jit(f)`` /
``shard_map(f, ...)`` / ``pl.pallas_call(kernel, ...)`` call forms
(the builder idiom of ``device_exchange._exchange_program`` and the
``mesh_query`` programs). From every entry the pass walks resolved
call-graph edges and flags host effects at any reachable function:
span/metrics calls, lock acquisition, ``time.*``, file/socket/
subprocess IO, ``print``, host-RNG, and subscript stores into traced
parameters. The Python body of a jitted function runs only at trace
time, so any such effect silently fires once per compile instead of
once per call — or worse, holds a lock for the duration of a trace
(PR 6's "spans never open inside jit'd code" claim, now checked).

``jit_stats.bump`` is allowlisted: a trace-time counter is the
documented mechanism that makes "repeat shapes do not retrace"
assertable (one bump per cache miss, by design — see jit_stats.py).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import (CallSite, Finding, FunctionInfo, ModuleInfo,
                   ProjectIndex, dotted_chain)

PASS_ID = "trace-purity"

#: decorator / call chains that stage a Python function out to XLA
_JIT_CHAINS = {"jax.jit", "jit", "jax.pmap", "pmap"}
#: batching transforms that WRAP the staged function — the traced body
#: is their first argument (``jax.jit(jax.vmap(f, ...))`` stages f)
_VMAP_CHAINS = {"jax.vmap", "vmap"}
_SHARD_CHAINS = {"shard_map", "jax.experimental.shard_map.shard_map"}
_PALLAS_SUFFIX = "pallas_call"
_PARTIAL_CHAINS = {"partial", "functools.partial"}

#: trace-time effects that are the designed mechanism, not a bug
_ALLOWED_CALLS = {"jit_stats.bump"}


@dataclass
class EntryInfo:
    """One staged-out function and how it was staged."""
    func: FunctionInfo
    kind: str                      # jit | shard_map | pallas
    static_params: Set[str] = field(default_factory=set)


def _static_names(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                return {e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return {kw.value.value}
    return set()


def _stage_kind(chain: Optional[str]) -> Optional[str]:
    if chain is None:
        return None
    if chain in _JIT_CHAINS:
        return "jit"
    if chain in _SHARD_CHAINS or chain.split(".")[-1] == "shard_map":
        return "shard_map"
    if chain.split(".")[-1] == _PALLAS_SUFFIX:
        return "pallas"
    return None


def profiled_entries(index: ProjectIndex) -> Dict[str, List[str]]:
    """Kernel names registered with the compiled-program profiler
    (``telemetry.profiler.instrument("name", ...)`` call forms), keyed
    by name with the registering module(s) as values — the not-blind
    witness that the cost registry actually covers the engine's jit
    entry points (a renamed wrapper or dropped instrument() call would
    silently blind EXPLAIN ANALYZE VERBOSE and the bench flight
    recorder)."""
    out: Dict[str, List[str]] = {}
    # registration FACADES (round 17): a function whose body forwards
    # its own parameter as instrument()'s name — e.g. exec/batched.py
    # ``_batched_kernel(name, cfg, build_lane)`` wrapping every masked
    # agg/join kernel in ``instrument(name, jit(vmap(...)))``.  Calls
    # to such a facade with a CONSTANT name register that name: one-hop
    # dataflow, so the floor test still pins the literal kernel names
    # instead of going blind behind the helper.
    facades: Dict[str, int] = {}
    for mod_name in sorted(index.modules):
        mod = index.modules[mod_name]
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            params = [a.arg for a in node.args.args]
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                chain = dotted_chain(call.func)
                if chain is None \
                        or chain.split(".")[-1] != "instrument":
                    continue
                if call.args and isinstance(call.args[0], ast.Name) \
                        and call.args[0].id in params:
                    facades[node.name] = params.index(call.args[0].id)
    for mod_name in sorted(index.modules):
        mod = index.modules[mod_name]
        # walk the whole module tree: most registrations are module-
        # level rebinds (`kernel = instrument("name", kernel, ...)`),
        # which live outside any FunctionInfo
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_chain(node.func)
            if chain is None:
                continue
            leaf = chain.split(".")[-1]
            pos = 0 if leaf == "instrument" else facades.get(leaf)
            if pos is None:
                continue
            if len(node.args) > pos \
                    and isinstance(node.args[pos], ast.Constant) \
                    and isinstance(node.args[pos].value, str):
                out.setdefault(node.args[pos].value,
                               []).append(mod_name)
    return out


def recording_sites(index: ProjectIndex) -> Dict[str, List[str]]:
    """Call sites of the history-based-statistics write path
    (``record_query`` / ``record_actuals`` on the runtime stats store),
    keyed by called chain with the calling function ids as values —
    the not-blind witness that actuals recording exists in the index
    AND (asserted in tests) stays outside every jit-reachable function:
    a store write that migrated inside traced code would fire once per
    compile instead of once per query, silently freezing history."""
    out: Dict[str, List[str]] = {}
    for mod_name in sorted(index.modules):
        mod = index.modules[mod_name]
        for qual in sorted(mod.functions):
            info = mod.functions[qual]
            for call in info.calls:
                last = call.chain.split(".")[-1]
                if last in ("record_query", "record_actuals"):
                    out.setdefault(call.chain, []).append(info.id)
    return out


def jit_reachable(index: ProjectIndex) -> Set[str]:
    """Every function id reachable from a staged-out entry point over
    resolved call edges — the set the trace-purity findings walk, and
    the set the stats-store write path must stay OUT of."""
    entries = jit_entries(index)
    reached: Set[str] = set()
    for fid in sorted(entries):
        stack = [fid]
        while stack:
            cur = stack.pop()
            if cur in reached:
                continue
            reached.add(cur)
            func = index.functions.get(cur)
            if func is None:
                continue
            for call in func.calls:
                if call.chain in _ALLOWED_CALLS:
                    continue
                if call.target and call.target in index.functions:
                    stack.append(call.target)
    return reached


def jit_entries(index: ProjectIndex) -> Dict[str, EntryInfo]:
    """Every staged-out function in the project, keyed by function id.
    Shared with the recompile pass (traced-branch detection needs the
    same entry set plus each entry's static parameter names)."""
    entries: Dict[str, EntryInfo] = {}

    def add(func: Optional[FunctionInfo], kind: str,
            statics: Set[str]):
        if func is not None and func.id not in entries:
            entries[func.id] = EntryInfo(func, kind, statics)

    for mod_name in sorted(index.modules):
        mod = index.modules[mod_name]
        for qual in sorted(mod.functions):
            info = mod.functions[qual]
            # decorator forms
            for dec in info.decorators:
                chain = index.decorator_chain(dec)
                kind = _stage_kind(chain)
                statics: Set[str] = set()
                if kind is None and isinstance(dec, ast.Call) \
                        and chain in _PARTIAL_CHAINS and dec.args:
                    kind = _stage_kind(dotted_chain(dec.args[0]))
                if kind is not None and isinstance(dec, ast.Call):
                    statics = _static_names(dec)
                if kind is not None:
                    add(info, kind, statics)
            # call forms: jax.jit(f) / shard_map(f, ...) /
            # pl.pallas_call(kernel, ...) / jax.jit(jax.vmap(f, ...))
            for call in info.calls:
                kind = _stage_kind(call.chain)
                if kind is None or not call.node.args:
                    continue
                staged = call.node.args[0]
                # unwrap batching transforms: the vmapped callable IS
                # the traced body (its Python code runs at trace time)
                while isinstance(staged, ast.Call) and staged.args \
                        and dotted_chain(staged.func) is not None \
                        and dotted_chain(staged.func).split(".")[-1] \
                        in {c.split(".")[-1] for c in _VMAP_CHAINS}:
                    staged = staged.args[0]
                arg_chain = dotted_chain(staged)
                if arg_chain is None:
                    continue
                target = index.resolve(mod, info, arg_chain)
                if target in index.functions:
                    add(index.functions[target], kind,
                        _static_names(call.node))
    return entries


# -- impurity tables -----------------------------------------------------

_IO_EXACT = {"open", "input"}
_IO_PREFIXES = ("os.", "socket.", "subprocess.", "shutil.", "io.")
_TELEMETRY_LASTS = {"span", "counter", "gauge", "histogram",
                    "gauge_fn", "observe"}


def _classify_call(chain: str) -> Optional[Tuple[str, str]]:
    """(rule, description) when the called chain is a host effect."""
    if chain in _ALLOWED_CALLS:
        return None
    parts = chain.split(".")
    last = parts[-1]
    if chain == "print":
        return "host-io", "print() runs once per trace, not per call"
    if parts[0] == "time":
        return "host-time", "time.* reads the host clock at trace time"
    if chain in _IO_EXACT or chain.startswith(_IO_PREFIXES):
        return "host-io", "file/socket/process IO inside traced code"
    if last == "acquire" or (len(parts) > 1
                             and "lock" in parts[-2].lower()):
        return "lock-in-trace", ("lock acquisition inside traced code "
                                 "(held for the whole trace, or never "
                                 "per-call)")
    if last in _TELEMETRY_LASTS and (
            "tracer" in parts or "metrics" in parts
            or parts[0] in ("tracer", "metrics")):
        return "telemetry-in-trace", ("span/metric call inside traced "
                                      "code fires per compile, not per "
                                      "query")
    if parts[0] in ("random",) or chain.startswith("np.random."):
        return "host-rng", "host RNG draws once at trace time"
    return None


def _with_lockish(stmt: ast.With) -> Optional[str]:
    for item in stmt.items:
        chain = dotted_chain(item.context_expr)
        if chain and "lock" in chain.split(".")[-1].lower():
            return chain
    return None


def _param_store_targets(func: FunctionInfo) -> List[ast.AST]:
    """Subscript stores into the function's own parameters —
    ``arr[i] = x`` on a traced array mutates a host buffer at trace
    time (jax arrays reject it; numpy ones silently bake one value
    in)."""
    params = set(func.params)
    hits: List[ast.AST] = []
    for node in ast.walk(func.node):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Subscript) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id in params:
                hits.append(t)
    return hits


def run(index: ProjectIndex) -> List[Finding]:
    entries = jit_entries(index)
    findings: List[Finding] = []
    # BFS per entry over resolved edges; remember one sample path root
    reached_via: Dict[str, str] = {}   # function id -> entry id
    order: List[str] = []
    for fid in sorted(entries):
        stack = [fid]
        while stack:
            cur = stack.pop()
            if cur in reached_via:
                continue
            reached_via[cur] = fid
            order.append(cur)
            func = index.functions.get(cur)
            if func is None:
                continue
            for call in func.calls:
                # an allowlisted call's own body is its business
                # (jit_stats.bump's counter lock is the mechanism)
                if call.chain in _ALLOWED_CALLS:
                    continue
                if call.target and call.target in index.functions:
                    stack.append(call.target)

    seen: Set[Tuple[str, str]] = set()
    for cur in order:
        func = index.functions.get(cur)
        if func is None:
            continue
        entry = entries[reached_via[cur]].func
        via = "" if cur == entry.id \
            else f" (reached from traced entry {entry.qualname})"
        for call in func.calls:
            hit = _classify_call(call.chain)
            if hit is None:
                continue
            rule, why = hit
            key = (cur, f"{rule}:{call.chain}")
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                PASS_ID, rule, func.module, func.qualname, call.line,
                f"`{call.chain}()` inside traced code{via}: {why}",
                f"{call.chain}"))
        for node in ast.walk(func.node):
            if isinstance(node, ast.With):
                chain = _with_lockish(node)
                if chain is None:
                    continue
                key = (cur, f"lock-in-trace:{chain}")
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    PASS_ID, "lock-in-trace", func.module,
                    func.qualname, node.lineno,
                    f"`with {chain}:` inside traced code{via}: the "
                    f"lock is held at trace time only",
                    f"with:{chain}"))
        if cur in entries:
            for t in _param_store_targets(func):
                name = t.value.id  # type: ignore[attr-defined]
                key = (cur, f"param-store:{name}")
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    PASS_ID, "host-mutation", func.module,
                    func.qualname, t.lineno,
                    f"subscript store into traced parameter "
                    f"`{name}` mutates a host buffer at trace time",
                    f"store:{name}"))
    return findings
