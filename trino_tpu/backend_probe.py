"""Backend init hardening shared by the driver entry points.

Round-1 failure mode: the axon TPU client can crash ("Unable to
initialize backend") or HANG on init, and a hang can't be interrupted
in-process. So: probe backend init in a GUARDED subprocess (own process
group, stdout->file, group-killed at the deadline — see subproc.py),
retry a few times for transient chip locks, then fall back to CPU so
the caller still produces its artifact (a compile-check or a benchmark
number) instead of zeroing the round.
"""

from __future__ import annotations

import sys
import time

from .subproc import run_guarded

_PROBE = ("import jax; d = jax.devices(); "
          "print('BACKEND_OK', [str(x) for x in d])")


def ensure_backend(tag: str, attempts: int = 2,
                   probe_timeout: int = 120) -> str:
    """Returns the platform in use: "" (jax default, probe succeeded) or
    "cpu" (fallback pinned)."""
    for i in range(attempts):
        text = run_guarded([sys.executable, "-c", _PROBE],
                           timeout=probe_timeout, tag=f"{tag}-probe")
        if "BACKEND_OK" in text:
            sys.stderr.write(f"{tag}: backend probe ok: {text.strip()}\n")
            return ""
        sys.stderr.write(f"{tag}: backend probe attempt {i + 1} failed:\n"
                         f"{text[-2000:]}\n")
        time.sleep(5 * (i + 1))
    sys.stderr.write(f"{tag}: default backend unusable; falling back to "
                     "CPU so the artifact is still produced\n")
    import jax

    jax.config.update("jax_platforms", "cpu")
    return "cpu"
