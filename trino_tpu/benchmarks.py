"""Hand-built benchmark pipelines (reference analog:
``testing/trino-benchmark/src/main/java/io/trino/benchmark/HandTpchQuery1``)
plus the pure jittable "one device step" used by the compile-check entry.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import types as T
from .block import DevicePage, Page
from .connectors.tpch import TpchConnector
from .exec.driver import Driver
from .expr import Call, InputRef, Literal, PageProcessor
from .expr.functions import days_from_civil_host
from .ops.aggregation import (AggCall, HashAggregationOperator,
                              _group_reduce, _init_states, _state_plan,
                              resolve_agg_type)
from .ops.operator import (FilterProjectOperator, OutputCollectorOperator,
                           TableScanOperator, ValuesOperator)
from .ops.sortkeys import group_operands

D12_2 = T.decimal_type(12, 2)

Q1_COLUMNS = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
              "l_discount", "l_tax", "l_shipdate"]


def q1_expressions(input_types: List[T.Type]):
    rf, ls, qty, price, disc, tax, ship = [
        InputRef(t, i) for i, t in enumerate(input_types)]
    cutoff = days_from_civil_host(1998, 12, 1) - 90
    filt = Call(T.BOOLEAN, "le", (ship, Literal(T.DATE, cutoff)))
    one = Literal(T.BIGINT, 1)
    disc_price_t = T.decimal_type(18, 4)
    disc_price = Call(disc_price_t, "multiply",
                      (price, Call(T.decimal_type(13, 2), "subtract",
                                   (one, disc))))
    charge_t = T.decimal_type(18, 6)
    charge = Call(charge_t, "multiply",
                  (disc_price, Call(T.decimal_type(13, 2), "add",
                                    (one, tax))))
    projections = [rf, ls, qty, price, disc, tax, disc_price, charge]
    aggs = []
    for fn, ch, t in [("sum", 2, D12_2), ("sum", 3, D12_2),
                      ("sum", 6, disc_price_t), ("sum", 7, charge_t),
                      ("avg", 2, D12_2), ("avg", 3, D12_2), ("avg", 4, D12_2),
                      ("count_star", None, None)]:
        aggs.append(AggCall(fn, ch, t, resolve_agg_type(fn, t)))
    return projections, filt, aggs


def build_q1_driver(conn: TpchConnector, schema: str = "tiny",
                    source_pages: Optional[Sequence[Page]] = None,
                    desired_splits: int = 4):
    """q1 as a physical pipeline. With source_pages, scanning is replaced by
    a ValuesOperator so the measurement isolates device execution."""
    meta = conn.metadata()
    table = meta.get_table_handle(schema, "lineitem")
    cols = {c.name: c for c in meta.get_columns(table)}
    scan_cols = [cols[n] for n in Q1_COLUMNS]
    input_types = [c.type for c in scan_cols]
    projections, filt, aggs = q1_expressions(input_types)
    proc = PageProcessor(input_types, projections, filt)
    fp = FilterProjectOperator(proc)
    agg = HashAggregationOperator(proc.output_types, [0, 1], aggs)
    sink = OutputCollectorOperator()
    if source_pages is not None:
        driver = Driver([ValuesOperator(source_pages), fp, agg, sink])
    else:
        scan = TableScanOperator(conn, scan_cols)
        driver = Driver([scan, fp, agg, sink])
        for s in conn.split_manager().get_splits(table, desired_splits):
            driver.add_split(s)
        driver.no_more_splits()
    return driver, sink


def scan_q1_pages(conn: TpchConnector, schema: str = "tiny",
                  desired_splits: int = 4) -> List[Page]:
    return scan_table_pages(conn, schema, "lineitem", Q1_COLUMNS,
                            desired_splits)


Q3_CUSTOMER = ["c_custkey", "c_mktsegment"]
Q3_ORDERS = ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"]
Q3_LINEITEM = ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"]


def scan_table_pages(conn: TpchConnector, schema: str, table: str,
                     columns: Sequence[str],
                     desired_splits: int = 4) -> List[Page]:
    """Pre-generated host pages for a table (measurement isolates device
    execution from data generation)."""
    meta = conn.metadata()
    th = meta.get_table_handle(schema, table)
    cols = {c.name: c for c in meta.get_columns(th)}
    scan_cols = [cols[n] for n in columns]
    pages: List[Page] = []
    for s in conn.split_manager().get_splits(th, desired_splits):
        src = conn.page_source(s, scan_cols)
        while True:
            p = src.get_next_page()
            if p is None:
                break
            pages.append(p)
    return pages


def scan_q3_pages(conn: TpchConnector, schema: str = "tiny",
                  desired_splits: int = 4):
    """(customer, orders, lineitem) page lists for build_q3_drivers."""
    return tuple(
        scan_table_pages(conn, schema, t, cols, desired_splits)
        for t, cols in (("customer", Q3_CUSTOMER),
                        ("orders", Q3_ORDERS),
                        ("lineitem", Q3_LINEITEM)))


def build_q3_drivers(cust_pages: Sequence[Page],
                     ord_pages: Sequence[Page],
                     li_pages: Sequence[Page]):
    """TPC-H q3 as three hand-built pipelines — customer build, orders
    semi-join + build, lineitem probe + aggregation + TopN — the
    join-heavy companion to q1 (reference analog:
    ``testing/trino-benchmark/.../HandTpchQuery6.java`` hand-building
    operator chains around LocalQueryRunner). Returns
    ([driver_a, driver_b, driver_c], sink); run the drivers in order."""
    cutoff = days_from_civil_host(1995, 3, 15)
    from .ops.join import HashBuilderOperator, JoinBridge, \
        LookupJoinOperator
    from .ops.operator import FilterProjectOperator
    from .ops.sort import TopNOperator
    from .ops.sortkeys import SortKey

    # pipeline A: customer -> mktsegment filter -> build(custkey)
    ctypes = [T.BIGINT, T.varchar_type(10)]
    c_key = InputRef(ctypes[0], 0)
    c_seg = InputRef(ctypes[1], 1)
    c_filt = Call(T.BOOLEAN, "eq",
                  (c_seg, Literal(ctypes[1], "BUILDING")))
    proc_c = PageProcessor(ctypes, [c_key], c_filt)
    b1 = JoinBridge()
    da = Driver([ValuesOperator(list(cust_pages)),
                 FilterProjectOperator(proc_c),
                 HashBuilderOperator(proc_c.output_types, [0], b1)])

    # pipeline B: orders -> date filter -> semi join vs customer ->
    # trim to (orderkey, orderdate, shippriority) -> build(orderkey)
    otypes = [T.BIGINT, T.BIGINT, T.DATE, T.BIGINT]
    o_key, o_cust, o_date, o_prio = [
        InputRef(t, i) for i, t in enumerate(otypes)]
    o_filt = Call(T.BOOLEAN, "lt", (o_date, Literal(T.DATE, cutoff)))
    proc_o = PageProcessor(otypes, [o_key, o_cust, o_date, o_prio],
                           o_filt)
    semi = LookupJoinOperator(proc_o.output_types, [1], b1, "semi")
    trim_in = proc_o.output_types
    proc_t = PageProcessor(trim_in, [InputRef(trim_in[0], 0),
                                     InputRef(trim_in[2], 2),
                                     InputRef(trim_in[3], 3)], None)
    b2 = JoinBridge()
    db = Driver([ValuesOperator(list(ord_pages)),
                 FilterProjectOperator(proc_o), semi,
                 FilterProjectOperator(proc_t),
                 HashBuilderOperator(proc_t.output_types, [0], b2)])

    # pipeline C: lineitem -> shipdate filter -> project revenue ->
    # probe join -> group by (orderkey, orderdate, shippriority) ->
    # TopN 10 by revenue desc, orderdate asc
    ltypes = [T.BIGINT, D12_2, D12_2, T.DATE]
    l_key, price, disc, ship = [
        InputRef(t, i) for i, t in enumerate(ltypes)]
    l_filt = Call(T.BOOLEAN, "gt", (ship, Literal(T.DATE, cutoff)))
    one = Literal(T.BIGINT, 1)
    rev_t = T.decimal_type(18, 4)
    revenue = Call(rev_t, "multiply",
                   (price, Call(T.decimal_type(13, 2), "subtract",
                                (one, disc))))
    proc_l = PageProcessor(ltypes, [l_key, revenue], l_filt)
    probe = LookupJoinOperator(proc_l.output_types, [0], b2, "inner")
    # probe output: probe channels + build channels
    jtypes = list(proc_l.output_types) + list(proc_t.output_types)
    aggs = [AggCall("sum", 1, rev_t, resolve_agg_type("sum", rev_t))]
    agg = HashAggregationOperator(jtypes, [0, 3, 4], aggs)
    topn = TopNOperator(agg.output_types,
                        [SortKey(3, ascending=False),
                         SortKey(1, ascending=True)], 10)
    sink = OutputCollectorOperator()
    dc = Driver([ValuesOperator(list(li_pages)),
                 FilterProjectOperator(proc_l), probe, agg, topn, sink])
    return [da, db, dc], sink


def q1_device_step(input_types: List[T.Type]):
    """A single pure jittable device step: fused filter+project+group-
    aggregate over one lineitem batch — the flagship kernel for
    compile-checking (``__graft_entry__.entry``)."""
    projections, filt, aggs = q1_expressions(input_types)
    proc = PageProcessor(input_types, projections, filt)
    out_types = proc.output_types
    kinds = tuple(k for a in aggs for (k, _) in _state_plan(a))

    def step(cols, nulls, valid, luts):
        pcols, pnulls, pvalid = proc._run(cols, nulls, valid, luts)
        key_ops = []
        for c in (0, 1):
            key_ops.extend(group_operands(pcols[c], pnulls[c], out_types[c]))
        key_raws = (pcols[0], pcols[1])
        state_cols = []
        for a in aggs:
            state_cols.extend(_init_states(a, pcols, pnulls, pvalid))
        from .ops.pallas_kernels import pallas_mode

        return _group_reduce(tuple(key_ops), key_raws, tuple(state_cols),
                             pvalid, num_keys=2,
                             num_states=len(state_cols), kinds=kinds,
                             pallas=pallas_mode())

    return proc, step


def q1_example_args(schema: str = "micro"):
    conn = TpchConnector(page_rows=4096)
    pages = scan_q1_pages(conn, schema, 1)
    dp = DevicePage.from_page(pages[0])
    input_types = dp.types
    proc, step = q1_device_step(input_types)
    luts = proc._fill_luts(dp.dictionaries)
    args = (tuple(dp.cols), tuple(dp.nulls), dp.valid, luts)
    return step, args
