"""Hand-built benchmark pipelines (reference analog:
``testing/trino-benchmark/src/main/java/io/trino/benchmark/HandTpchQuery1``)
plus the pure jittable "one device step" used by the compile-check entry.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import types as T
from .block import DevicePage, Page
from .connectors.tpch import TpchConnector
from .exec.driver import Driver
from .expr import Call, InputRef, Literal, PageProcessor
from .expr.functions import days_from_civil_host
from .ops.aggregation import (AggCall, HashAggregationOperator,
                              _init_states, _state_plan, resolve_agg_type)
from .ops.operator import (FilterProjectOperator, OutputCollectorOperator,
                           TableScanOperator, ValuesOperator)
from .ops.sortkeys import group_operands

D12_2 = T.decimal_type(12, 2)

#: jitted-processor reuse across repeated builder calls: PageProcessor
#: wraps a per-instance ``jax.jit``, so building a fresh one per bench
#: repeat would re-trace inside the timed region and pollute the
#: jit-trace deltas the bench reports
_PROC_CACHE: dict = {}


def _cached(key, build):
    hit = _PROC_CACHE.get(key)
    if hit is None:
        hit = _PROC_CACHE[key] = build()
    return hit

Q1_COLUMNS = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
              "l_discount", "l_tax", "l_shipdate"]


def q1_expressions(input_types: List[T.Type]):
    rf, ls, qty, price, disc, tax, ship = [
        InputRef(t, i) for i, t in enumerate(input_types)]
    cutoff = days_from_civil_host(1998, 12, 1) - 90
    filt = Call(T.BOOLEAN, "le", (ship, Literal(T.DATE, cutoff)))
    one = Literal(T.BIGINT, 1)
    disc_price_t = T.decimal_type(18, 4)
    disc_price = Call(disc_price_t, "multiply",
                      (price, Call(T.decimal_type(13, 2), "subtract",
                                   (one, disc))))
    charge_t = T.decimal_type(18, 6)
    charge = Call(charge_t, "multiply",
                  (disc_price, Call(T.decimal_type(13, 2), "add",
                                    (one, tax))))
    projections = [rf, ls, qty, price, disc, tax, disc_price, charge]
    aggs = []
    for fn, ch, t in [("sum", 2, D12_2), ("sum", 3, D12_2),
                      ("sum", 6, disc_price_t), ("sum", 7, charge_t),
                      ("avg", 2, D12_2), ("avg", 3, D12_2), ("avg", 4, D12_2),
                      ("count_star", None, None)]:
        aggs.append(AggCall(fn, ch, t, resolve_agg_type(fn, t)))
    return projections, filt, aggs


def build_q1_driver(conn: TpchConnector, schema: str = "tiny",
                    source_pages: Optional[Sequence[Page]] = None,
                    desired_splits: int = 4, hash_grouping: bool = True,
                    collect_stats: bool = False):
    """q1 as a physical pipeline. With source_pages, scanning is replaced by
    a ValuesOperator so the measurement isolates device execution."""
    meta = conn.metadata()
    table = meta.get_table_handle(schema, "lineitem")
    cols = {c.name: c for c in meta.get_columns(table)}
    scan_cols = [cols[n] for n in Q1_COLUMNS]
    input_types = [c.type for c in scan_cols]

    def build():
        projections, filt, aggs = q1_expressions(input_types)
        return PageProcessor(input_types, projections, filt), aggs

    proc, aggs = _cached(("q1", tuple(map(str, input_types))), build)
    fp = FilterProjectOperator(proc)
    agg = HashAggregationOperator(proc.output_types, [0, 1], aggs,
                                  hash_grouping=hash_grouping)
    sink = OutputCollectorOperator()
    if source_pages is not None:
        driver = Driver([ValuesOperator(source_pages,
                                        coalesce_rows=conn.page_rows),
                         fp, agg, sink],
                        collect_stats=collect_stats)
    else:
        scan = TableScanOperator(conn, scan_cols)
        driver = Driver([scan, fp, agg, sink],
                        collect_stats=collect_stats)
        for s in conn.split_manager().get_splits(table, desired_splits):
            driver.add_split(s)
        driver.no_more_splits()
    return driver, sink


def scan_q1_pages(conn: TpchConnector, schema: str = "tiny",
                  desired_splits: int = 4) -> List[Page]:
    return scan_table_pages(conn, schema, "lineitem", Q1_COLUMNS,
                            desired_splits)


Q3_CUSTOMER = ["c_custkey", "c_mktsegment"]
Q3_ORDERS = ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"]
Q3_LINEITEM = ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"]


def scan_table_pages(conn: TpchConnector, schema: str, table: str,
                     columns: Sequence[str],
                     desired_splits: int = 4) -> List[Page]:
    """Pre-generated host pages for a table (measurement isolates device
    execution from data generation)."""
    meta = conn.metadata()
    th = meta.get_table_handle(schema, table)
    cols = {c.name: c for c in meta.get_columns(th)}
    scan_cols = [cols[n] for n in columns]
    pages: List[Page] = []
    for s in conn.split_manager().get_splits(th, desired_splits):
        src = conn.page_source(s, scan_cols)
        while True:
            p = src.get_next_page()
            if p is None:
                break
            pages.append(p)
    return pages


def scan_q3_pages(conn: TpchConnector, schema: str = "tiny",
                  desired_splits: int = 4):
    """(customer, orders, lineitem) page lists for build_q3_drivers."""
    return tuple(
        scan_table_pages(conn, schema, t, cols, desired_splits)
        for t, cols in (("customer", Q3_CUSTOMER),
                        ("orders", Q3_ORDERS),
                        ("lineitem", Q3_LINEITEM)))


Q18_LINEITEM = ["l_orderkey", "l_quantity"]


def scan_q18_pages(conn: TpchConnector, schema: str = "tiny",
                   desired_splits: int = 4) -> List[Page]:
    return scan_table_pages(conn, schema, "lineitem", Q18_LINEITEM,
                            desired_splits)


def build_q18_driver(li_pages: Sequence[Page],
                     hash_grouping: bool = True,
                     collect_stats: bool = False):
    """The large-group aggregation core of TPC-H q18: GROUP BY
    l_orderkey (cardinality ~ the orders table, i.e. ~n_rows/4 groups —
    the anti-q1) + the HAVING sum(l_quantity) > 300 filter. Exercises
    near-capacity group cardinality per page and the adaptive-partial
    regime where grouping barely reduces rows."""
    from decimal import Decimal

    ltypes = [T.BIGINT, D12_2]
    aggs = [AggCall("sum", 1, D12_2, resolve_agg_type("sum", D12_2))]
    agg = HashAggregationOperator(ltypes, [0], aggs,
                                  hash_grouping=hash_grouping)
    out_t = agg.output_types

    def build():
        having = Call(T.BOOLEAN, "gt",
                      (InputRef(out_t[1], 1),
                       Literal(out_t[1], Decimal("300"))))
        return PageProcessor(out_t, [InputRef(t, i)
                                     for i, t in enumerate(out_t)],
                             having)

    proc = _cached(("q18", tuple(map(str, out_t))), build)
    sink = OutputCollectorOperator()
    driver = Driver([ValuesOperator(list(li_pages),
                                    coalesce_rows=1 << 16), agg,
                     FilterProjectOperator(proc), sink],
                    collect_stats=collect_stats)
    return driver, sink


_STAGE_BUCKETS = (
    ("scan", ("TableScan", "Values", "DeferredPagesSource")),
    ("filter_project", ("FilterProject",)),
    ("agg", ("HashAggregation",)),
    ("join", ("HashBuilder", "LookupJoin")),
    ("exchange", ("Exchange", "MergeExchange", "PartitionedOutput")),
    ("sort", ("TopN", "OrderBy", "GroupedTopN", "Window")),
)


def stage_breakdown(drivers: Sequence[Driver]) -> dict:
    """Per-stage wall-time/compile rollup of collect_stats drivers:
    {"stage_ms": {scan|filter_project|agg|join|exchange|sort|other: ms},
     "compiles": total jit traces attributed to the drivers,
     "exchange_stats": skew stats of any exchange boundary the drivers
     touched (per_dest / retries / skew_ratio / partition_rows)}."""
    ms = {name: 0.0 for name, _ in _STAGE_BUCKETS}
    ms["other"] = 0.0
    compiles = 0
    exchange_stats = []
    for d in drivers:
        d.collect_operator_metrics()
        for st in d.stats:
            bucket = "other"
            for name, prefixes in _STAGE_BUCKETS:
                if any(st.name.startswith(p) for p in prefixes):
                    bucket = name
                    break
            ms[bucket] += st.wall_ns / 1e6
            compiles += st.compile_count
            if st.metrics:
                exchange_stats.append({"operator": st.name, **st.metrics})
    return {"stage_ms": {k: round(v, 1) for k, v in ms.items()},
            "compiles": compiles,
            "exchange_stats": exchange_stats}


def build_q3_drivers(cust_pages: Sequence[Page],
                     ord_pages: Sequence[Page],
                     li_pages: Sequence[Page],
                     hash_grouping: bool = True,
                     collect_stats: bool = False):
    """TPC-H q3 as three hand-built pipelines — customer build, orders
    semi-join + build, lineitem probe + aggregation + TopN — the
    join-heavy companion to q1 (reference analog:
    ``testing/trino-benchmark/.../HandTpchQuery6.java`` hand-building
    operator chains around LocalQueryRunner). Returns
    ([driver_a, driver_b, driver_c], sink); run the drivers in order."""
    cutoff = days_from_civil_host(1995, 3, 15)
    from .ops.join import HashBuilderOperator, JoinBridge, \
        LookupJoinOperator
    from .ops.operator import FilterProjectOperator
    from .ops.sort import TopNOperator
    from .ops.sortkeys import SortKey

    def build_procs():
        # the four q3 expression programs (jitted processors), reused
        # across repeated builder calls — see _cached
        ctypes = [T.BIGINT, T.varchar_type(10)]
        c_key = InputRef(ctypes[0], 0)
        c_seg = InputRef(ctypes[1], 1)
        c_filt = Call(T.BOOLEAN, "eq",
                      (c_seg, Literal(ctypes[1], "BUILDING")))
        proc_c = PageProcessor(ctypes, [c_key], c_filt)
        otypes = [T.BIGINT, T.BIGINT, T.DATE, T.BIGINT]
        o_key, o_cust, o_date, o_prio = [
            InputRef(t, i) for i, t in enumerate(otypes)]
        o_filt = Call(T.BOOLEAN, "lt", (o_date, Literal(T.DATE, cutoff)))
        proc_o = PageProcessor(otypes, [o_key, o_cust, o_date, o_prio],
                               o_filt)
        trim_in = proc_o.output_types
        proc_t = PageProcessor(trim_in, [InputRef(trim_in[0], 0),
                                         InputRef(trim_in[2], 2),
                                         InputRef(trim_in[3], 3)], None)
        ltypes = [T.BIGINT, D12_2, D12_2, T.DATE]
        l_key, price, disc, ship = [
            InputRef(t, i) for i, t in enumerate(ltypes)]
        l_filt = Call(T.BOOLEAN, "gt", (ship, Literal(T.DATE, cutoff)))
        one = Literal(T.BIGINT, 1)
        rev_t = T.decimal_type(18, 4)
        revenue = Call(rev_t, "multiply",
                       (price, Call(T.decimal_type(13, 2), "subtract",
                                    (one, disc))))
        proc_l = PageProcessor(ltypes, [l_key, revenue], l_filt)
        return proc_c, proc_o, proc_t, proc_l, rev_t

    proc_c, proc_o, proc_t, proc_l, rev_t = _cached("q3", build_procs)

    # pipeline A: customer -> mktsegment filter -> build(custkey)
    b1 = JoinBridge()
    da = Driver([ValuesOperator(list(cust_pages),
                                coalesce_rows=1 << 16),
                 FilterProjectOperator(proc_c),
                 HashBuilderOperator(proc_c.output_types, [0], b1)],
                collect_stats=collect_stats)

    # pipeline B: orders -> date filter -> semi join vs customer ->
    # trim to (orderkey, orderdate, shippriority) -> build(orderkey)
    semi = LookupJoinOperator(proc_o.output_types, [1], b1, "semi")
    b2 = JoinBridge()
    db = Driver([ValuesOperator(list(ord_pages),
                                coalesce_rows=1 << 16),
                 FilterProjectOperator(proc_o), semi,
                 FilterProjectOperator(proc_t),
                 HashBuilderOperator(proc_t.output_types, [0], b2)],
                collect_stats=collect_stats)

    # pipeline C: lineitem -> shipdate filter -> project revenue ->
    # probe join -> group by (orderkey, orderdate, shippriority) ->
    # TopN 10 by revenue desc, orderdate asc
    probe = LookupJoinOperator(proc_l.output_types, [0], b2, "inner")
    # probe output: probe channels + build channels
    jtypes = list(proc_l.output_types) + list(proc_t.output_types)
    aggs = [AggCall("sum", 1, rev_t, resolve_agg_type("sum", rev_t))]
    agg = HashAggregationOperator(jtypes, [0, 3, 4], aggs,
                                  hash_grouping=hash_grouping)
    topn = TopNOperator(agg.output_types,
                        [SortKey(3, ascending=False),
                         SortKey(1, ascending=True)], 10)
    sink = OutputCollectorOperator()
    dc = Driver([ValuesOperator(list(li_pages),
                                coalesce_rows=1 << 16),
                 FilterProjectOperator(proc_l), probe, agg, topn, sink],
                collect_stats=collect_stats)
    return [da, db, dc], sink


def q1_device_step(input_types: List[T.Type]):
    """A single pure jittable device step: fused filter+project+group-
    aggregate over one lineitem batch — the flagship kernel for
    compile-checking (``__graft_entry__.entry``). Grouping runs the
    vectorized open-addressing hash table in non-exact mode (duplicate
    groups tolerated like a partial step), which needs no host sync and
    keeps the whole step one pure XLA program; the sort-based
    ``_group_reduce`` remains the oracle."""
    from .ops.hashtable import hash_group_ids, hash_segment_reduce

    projections, filt, aggs = q1_expressions(input_types)
    proc = PageProcessor(input_types, projections, filt)
    out_types = proc.output_types
    kinds = tuple(k for a in aggs for (k, _) in _state_plan(a))

    def step(cols, nulls, valid, luts):
        pcols, pnulls, pvalid = proc._run(cols, nulls, valid, luts)
        key_ops = []
        for c in (0, 1):
            key_ops.extend(group_operands(pcols[c], pnulls[c], out_types[c]))
        key_raws = (pcols[0], pcols[1])
        state_cols = []
        for a in aggs:
            state_cols.extend(_init_states(a, pcols, pnulls, pvalid))
        from .ops.pallas_kernels import pallas_mode

        gid, group_rows, ngroups, _overflow = hash_group_ids(
            tuple(key_ops), pvalid, exact=False)
        return hash_segment_reduce(
            gid, group_rows, ngroups, key_raws,
            (pnulls[0], pnulls[1]), tuple(state_cols), kinds,
            pallas=pallas_mode())

    return proc, step


def q1_example_args(schema: str = "micro"):
    conn = TpchConnector(page_rows=4096)
    pages = scan_q1_pages(conn, schema, 1)
    dp = DevicePage.from_page(pages[0])
    input_types = dp.types
    proc, step = q1_device_step(input_types)
    luts = proc._fill_luts(dp.dictionaries)
    args = (tuple(dp.cols), tuple(dp.nulls), dp.valid, luts)
    return step, args
