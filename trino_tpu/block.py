"""Columnar Page/Block data model.

Reference analog: ``core/trino-spi/src/main/java/io/trino/spi/Page.java`` and
the 69 block classes under ``spi/block/`` (ByteArrayBlock, LongArrayBlock,
VariableWidthBlock, DictionaryBlock, RunLengthEncodedBlock, ...).

TPU-first redesign: a Block is ONE flat array per column (the type's device
storage dtype) plus an optional null mask — no per-width block subclasses;
the dtype carries that. Strings are dictionary codes (int32) with the string
pool held host-side (``Dictionary``), so every device kernel sees only
fixed-width lanes. Arrays may live on host (numpy) or device (jax.Array);
kernels pad to power-of-two bucket sizes so XLA compiles a small, reusable
set of shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Union

import numpy as np

from . import types as T

Array = Union[np.ndarray, "jax.Array"]  # noqa: F821


def padded_size(n: int, minimum: int = 16) -> int:
    """Pad row counts to power-of-two buckets => bounded jit cache size."""
    if n <= minimum:
        return minimum
    return 1 << (n - 1).bit_length()


def _rank_sort_key(v):
    """Total-order key over pool entries: None sorts first at every
    nesting level, so nullable composite pools rank without TypeError."""
    if v is None:
        return (0, 0)
    if isinstance(v, tuple):
        return (1, tuple(_rank_sort_key(x) for x in v))
    return (1, v)


def null_pool_value(t) -> object:
    """The type-homogeneous pool placeholder for NULL lanes."""
    return () if (t.is_array or t.is_map
                  or getattr(t, "is_row", False)) else ""


#: process-unique Dictionary ids for host-side caches.  ``id()`` is NOT
#: a safe cache key across pool lifetimes: once PageProcessors outlive
#:  a query (the round-13 shared-processor cache), a freed pool's
#: address can be reused by a new same-length pool and a stale LUT
#: would silently apply to the wrong values — ``uid`` never aliases.
_dict_uids = __import__("itertools").count(1)


class Dictionary:
    """Host-side string pool. Identity (``id()``) defines code compatibility:
    two blocks share code semantics iff they share the Dictionary object.

    Reference analog: ``spi/block/DictionaryBlock.java`` +
    ``VariableWidthBlock.java`` — but here the pool is a first-class engine
    object because device kernels only ever see codes.
    """

    __slots__ = ("values", "_index", "_sort_rank", "_lock", "uid")

    def __init__(self, values: Sequence[str] = ()):
        import threading

        self.values: list = list(values)
        self._index = {v: i for i, v in enumerate(self.values)}
        self._sort_rank = None
        self._lock = threading.Lock()
        self.uid = next(_dict_uids)

    @classmethod
    def aligned(cls, values: Sequence[str]) -> "Dictionary":
        """Pool whose position i maps to values[i] even when values repeat
        (derived pools from string transforms must stay code-aligned with
        their source). Lookup maps to the first occurrence."""
        import threading

        d = cls.__new__(cls)
        d.values = list(values)
        d._index = {}
        for i, v in enumerate(d.values):
            d._index.setdefault(v, i)
        d._sort_rank = None
        d._lock = threading.Lock()
        d.uid = next(_dict_uids)
        return d

    def __len__(self) -> int:
        return len(self.values)

    def code(self, value: str) -> int:
        """Code for value, adding it to the pool if absent. Thread-safe:
        concurrent scan tasks of a distributed query grow shared
        connector pools (check-then-append must not interleave)."""
        c = self._index.get(value)
        if c is not None:
            return c
        with self._lock:
            c = self._index.get(value)
            if c is None:
                c = len(self.values)
                self.values.append(value)
                self._index[value] = c
                self._sort_rank = None
        return c

    def lookup(self, value: str) -> int:
        """Code for value or -1 if absent (no mutation)."""
        return self._index.get(value, -1)

    def encode(self, strings: Sequence[Optional[str]],
               null_value="") -> np.ndarray:
        """Encode values to codes. NULL lanes get code 0 — they carry an
        arbitrary valid code and MUST be masked by the block's null mask
        (kernels fold the null bit into key comparisons explicitly).
        ``null_value`` is the pool placeholder kept type-homogeneous
        ("" for strings, () for arrays) so rank sorting never compares
        across types."""
        out = np.empty(len(strings), dtype=np.int32)
        for i, s in enumerate(strings):
            if s is None:
                if not self.values:
                    self.code(null_value)  # keep code 0 decodable
                out[i] = 0
            else:
                out[i] = self.code(s)
        return out

    def decode(self, codes: np.ndarray) -> list:
        vals = self.values
        return [vals[c] for c in codes]

    def sort_rank(self) -> np.ndarray:
        """rank[code] = DENSE lexicographic rank of values[code]: equal
        strings get equal rank (aligned pools may repeat values), so device
        comparisons/grouping over ranks match string equality. Lets ORDER
        BY / GROUP BY on strings run on device via rank[codes]."""
        if self._sort_rank is None or len(self._sort_rank) != len(self.values):
            vals = list(self.values)
            if any(v is None or isinstance(v, tuple) for v in vals):
                # composite/nullable pools: python comparisons between
                # None and values (or nested Nones inside tuples) have
                # no order — rank through a None-totalizing key
                order = sorted(range(len(vals)),
                               key=lambda i: _rank_sort_key(vals[i]))
                ranks = np.empty(len(vals), dtype=np.int32)
                r = -1
                prev = object()
                for i in order:
                    k = _rank_sort_key(vals[i])
                    if k != prev:
                        r += 1
                        prev = k
                    ranks[i] = r
                self._sort_rank = ranks
            else:
                # np.asarray on equal-length tuples builds a 2-D array;
                # assigning into an empty object array keeps entries
                # intact
                arr = np.empty(len(vals), dtype=object)
                arr[:] = vals
                _, inverse = np.unique(arr, return_inverse=True)
                self._sort_rank = inverse.astype(np.int32)
        return self._sort_rank


@dataclass
class Block:
    """One column of a Page: flat storage array + optional null mask."""

    type: T.Type
    data: Array                      # shape (n,), dtype == type.storage
    nulls: Optional[Array] = None    # bool, True => NULL; None => no nulls
    dictionary: Optional[Dictionary] = None

    def __post_init__(self):
        if self.type.is_pooled and self.dictionary is None:
            raise ValueError("string block requires a dictionary")

    def __len__(self) -> int:
        return int(self.data.shape[0])

    @property
    def may_have_nulls(self) -> bool:
        return self.nulls is not None

    # -- host/device movement ------------------------------------------------

    def numpy(self) -> "Block":
        if isinstance(self.data, np.ndarray) and (
            self.nulls is None or isinstance(self.nulls, np.ndarray)
        ):
            return self
        nulls = None if self.nulls is None else np.asarray(self.nulls)
        return Block(self.type, np.asarray(self.data), nulls, self.dictionary)

    def nulls_array(self) -> np.ndarray:
        if self.nulls is None:
            return np.zeros(len(self), dtype=bool)
        return np.asarray(self.nulls)

    # -- positional ops (reference: Block.getRegion / copyPositions) ---------

    def region(self, offset: int, length: int) -> "Block":
        nulls = None if self.nulls is None else self.nulls[offset:offset + length]
        return Block(self.type, self.data[offset:offset + length], nulls,
                     self.dictionary)

    def take(self, positions) -> "Block":
        nulls = None if self.nulls is None else self.nulls[positions]
        return Block(self.type, self.data[positions], nulls, self.dictionary)

    def filter(self, keep_mask) -> "Block":
        mask = np.asarray(keep_mask)
        return self.numpy().take(np.nonzero(mask)[0])

    # -- python-value conversion --------------------------------------------

    def to_pylist(self) -> list:
        b = self.numpy()
        data, t = b.data, b.type
        nulls = b.nulls_array() if b.nulls is not None else None
        if t.is_pooled:
            raw = b.dictionary.decode(data)
            if t.is_array:
                # user-visible arrays are lists (pool entries are tuples)
                raw = [None if v is None else list(v) for v in raw]
            elif t.is_map:
                # pool entries are sorted (key, value) pair tuples
                raw = [None if v is None else dict(v) for v in raw]
        elif t.is_decimal:
            raw = [t.from_raw(v) for v in data.tolist()]
        elif t.is_timestamp_tz:
            # zone-aware datetimes: the user-visible form carries the
            # column's rendering zone (device raw is the UTC instant)
            import datetime as _dt

            from .expr.tz import parse_fixed_offset_micros

            fixed = parse_fixed_offset_micros(t.zone)
            if fixed is None:
                from zoneinfo import ZoneInfo

                tzinfo = ZoneInfo(t.zone)
            else:
                tzinfo = _dt.timezone(_dt.timedelta(microseconds=fixed))
            epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
            raw = [(epoch + _dt.timedelta(microseconds=int(v)))
                   .astimezone(tzinfo) for v in data.tolist()]
        elif t == T.BOOLEAN:
            raw = [bool(v) for v in data]
        elif t in (T.DOUBLE, T.REAL):
            raw = [float(v) for v in data]
        else:
            raw = [int(v) for v in data.tolist()]
        if nulls is None:
            return raw
        return [None if n else v for v, n in zip(raw, nulls)]

    @staticmethod
    def from_pylist(type_: T.Type, values: Sequence,
                    dictionary: Optional[Dictionary] = None) -> "Block":
        n = len(values)
        nulls = np.fromiter((v is None for v in values), dtype=bool, count=n)
        has_nulls = bool(nulls.any())
        if type_.is_pooled:
            d = dictionary if dictionary is not None else Dictionary()
            if type_.is_map:
                values = [v if v is None else
                          tuple(sorted(v.items())
                                if isinstance(v, dict) else v)
                          for v in values]
            data = d.encode(values, null_value=null_pool_value(type_))
            return Block(type_, data, nulls if has_nulls else None, d)
        data = np.empty(n, dtype=type_.storage)
        if type_.is_timestamp_tz:
            import datetime as _dt

            epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
            one_us = _dt.timedelta(microseconds=1)
        for i, v in enumerate(values):
            if v is None:
                data[i] = 0
            elif type_.is_decimal:
                data[i] = type_.to_raw(v)
            elif type_.is_timestamp_tz and hasattr(v, "timestamp"):
                data[i] = (v - epoch) // one_us
            else:
                data[i] = v
        return Block(type_, data, nulls if has_nulls else None)


@dataclass
class Page:
    """A batch of rows: one Block per channel (reference: ``spi/Page.java:32``)."""

    blocks: list
    num_rows: int

    def __post_init__(self):
        for b in self.blocks:
            assert len(b) == self.num_rows, \
                f"block length {len(b)} != page rows {self.num_rows}"

    @property
    def channel_count(self) -> int:
        return len(self.blocks)

    def block(self, channel: int) -> Block:
        return self.blocks[channel]

    def region(self, offset: int, length: int) -> "Page":
        return Page([b.region(offset, length) for b in self.blocks], length)

    def take(self, positions) -> "Page":
        positions = np.asarray(positions)
        return Page([b.take(positions) for b in self.blocks], len(positions))

    def filter(self, keep_mask) -> "Page":
        positions = np.nonzero(np.asarray(keep_mask))[0]
        return self.take(positions)

    def select_channels(self, channels: Sequence[int]) -> "Page":
        return Page([self.blocks[c] for c in channels], self.num_rows)

    def to_pydict(self, names: Sequence[str]) -> dict:
        return {n: b.to_pylist() for n, b in zip(names, self.blocks)}

    def to_rows(self) -> list:
        cols = [b.to_pylist() for b in self.blocks]
        return [tuple(c[i] for c in cols) for i in range(self.num_rows)]

    @staticmethod
    def from_pylists(types_: Sequence[T.Type], columns: Sequence[Sequence],
                     dictionaries: Optional[Sequence] = None) -> "Page":
        assert len(types_) == len(columns)
        n = len(columns[0]) if columns else 0
        blocks = []
        for i, (t, col) in enumerate(zip(types_, columns)):
            d = dictionaries[i] if dictionaries else None
            blocks.append(Block.from_pylist(t, col, d))
        return Page(blocks, n)

    @staticmethod
    def concat(pages: Sequence["Page"]) -> "Page":
        if not pages:
            raise ValueError(
                "Page.concat of zero pages: caller must use empty_page(types)")
        pages = [p for p in pages if p.num_rows > 0] or list(pages[:1])
        if len(pages) == 1:
            return pages[0]
        nch = pages[0].channel_count
        blocks = []
        for c in range(nch):
            parts = [p.block(c).numpy() for p in pages]
            t = parts[0].type
            dictionary = parts[0].dictionary
            if t.is_pooled:
                # Re-encode into the first block's dictionary when pools differ.
                unified = []
                for b in parts:
                    if b.dictionary is dictionary:
                        unified.append(b.data)
                    else:
                        remap = dictionary.encode(b.dictionary.values) if len(b.dictionary) else np.empty(0, np.int32)
                        unified.append(remap[b.data] if len(remap) else b.data)
                data = np.concatenate(unified)
            else:
                data = np.concatenate([b.data for b in parts])
            if any(b.nulls is not None for b in parts):
                nulls = np.concatenate([b.nulls_array() for b in parts])
            else:
                nulls = None
            blocks.append(Block(t, data, nulls, dictionary))
        return Page(blocks, sum(p.num_rows for p in pages))


@dataclass
class DevicePage:
    """A page resident on device: padded columns + a live-row mask.

    TPU-first replacement for positional compaction: filtering flips lanes
    off in ``valid`` instead of gathering survivors, so filter+project+agg
    chains stay on device with static shapes; compaction happens only at
    host boundaries (``to_page``) or when an operator chooses to densify.

    - ``cols[i]``: jax array, shape (capacity,), dtype types[i].storage
    - ``nulls[i]``: jax bool array (True = SQL NULL) — always materialized
    - ``valid``: jax bool array — lane holds a live row (row-count mask
      AND any filters applied so far)
    """

    types: list
    cols: list
    nulls: list
    valid: "jax.Array"  # noqa: F821
    dictionaries: list  # Optional[Dictionary] per column

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])

    def count(self) -> int:
        """Live row count (device sync)."""
        return int(np.asarray(self.valid).sum())

    @staticmethod
    def from_page(page: Page, capacity: Optional[int] = None) -> "DevicePage":
        import jax.numpy as jnp

        n = page.num_rows
        cap = capacity if capacity is not None else padded_size(n)
        if cap < n:
            raise ValueError(
                f"DevicePage capacity {cap} < page rows {n}")
        cols, nulls, dicts = [], [], []
        for b in page.blocks:
            b = b.numpy()
            data = np.zeros(cap, dtype=b.type.storage)
            data[:n] = b.data
            nl = np.zeros(cap, dtype=bool)
            if b.nulls is not None:
                nl[:n] = b.nulls
            cols.append(jnp.asarray(data))
            nulls.append(jnp.asarray(nl))
            dicts.append(b.dictionary)
        valid = np.zeros(cap, dtype=bool)
        valid[:n] = True
        return DevicePage([b.type for b in page.blocks], cols, nulls,
                          jnp.asarray(valid), dicts)

    def to_page(self) -> Page:
        """Compact live lanes back to a host Page."""
        keep = np.nonzero(np.asarray(self.valid))[0]
        blocks = []
        for t, c, nl, d in zip(self.types, self.cols, self.nulls,
                               self.dictionaries):
            data = np.asarray(c)[keep]
            nulls = np.asarray(nl)[keep]
            blocks.append(Block(t, data, nulls if nulls.any() else None, d))
        return Page(blocks, len(keep))


def unify_dictionaries(pages, n_channels: int):
    """The one dictionary-pool compatibility rule for co-flowing pages:
    all non-None pools of a channel must be the SAME object (exchange
    boundaries re-encode divergent pools; everything downstream relies
    on identity).  Returns the per-channel pools or raises."""
    dicts = [None] * n_channels
    for p in pages:
        for i, d in enumerate(p.dictionaries):
            if d is not None:
                if dicts[i] is None:
                    dicts[i] = d
                elif dicts[i] is not d:
                    raise T.TrinoError(
                        "dictionary pools differ across pages; exchange "
                        "must unify pools", "GENERIC_INTERNAL_ERROR")
    return dicts


def empty_page(types_: Sequence[T.Type],
               dictionaries: Optional[Sequence] = None) -> Page:
    blocks = []
    for i, t in enumerate(types_):
        d = (dictionaries[i] if dictionaries else None) or (Dictionary() if t.is_pooled else None)
        blocks.append(Block(t, np.empty(0, dtype=t.storage), None, d))
    return Page(blocks, 0)
